"""Token wire codec + sequence packing: ragged strings → rung-shaped
int32 batches on the PR-4 wire machinery.

Two halves, mirroring the image path exactly:

- :class:`TokenCodec` is a :class:`~tpudl.data.codec.WireCodec` — token
  ids ship as uint16 (vocab ≤ 65536, half the wire bytes) or int32, and
  the device prologue is one ``astype(int32)`` fused in front of the
  compiled program like the u8 pixel restore. EXACT by construction:
  ids are integers, the cast is value-preserving, so host decode and
  device prologue agree bitwise at every dtype. The codec registers
  under the name ``"tokens"`` (``resolve_codec`` / ``codec_from_key``
  in tpudl.data.codec), so shard manifests persist it and warm replays
  reconstruct the identical prologue.

- The PACK layer runs in the executor's prepare pool (a ``pack=``
  callable for ``Frame.map_batches`` / ``Dataset``):
  :func:`tokenize_pack` builds the string-column → int32-batch pack fn,
  either rung-padded ragged rows (:func:`pack_ragged`, inference /
  featurize) or a densely packed token stream chunked to ``seq_len``
  rows (:func:`pack_dense`, LM training — pad waste only in the final
  row). The pack fn carries ``cache_token`` = tokenizer fingerprint +
  packing config, which is how tokenization becomes shard-cache /
  DeviceBatchCache key material: epoch 2 of a tokenized fine-tune
  replays resident batches with ZERO re-tokenizations and ZERO wire
  bytes, and a changed vocab or seq_len re-keys the cache instead of
  replaying stale ids.

Padding semantics (TEXT.md): pad id is 0, right-padding only; the
attention story is ``pad_mask(tokens)`` INSIDE the jitted model fn —
computed on device from the shipped ids, so no mask crosses the wire
and the mask op fuses into the one program.
"""

from __future__ import annotations

import os
import time

import numpy as np

from tpudl.compile.buckets import BucketLadder, resolve_ladder
from tpudl.data.codec import CodecError, WireCodec
from tpudl.text.tokenizer import PAD_ID, Tokenizer

__all__ = ["TokenCodec", "pad_mask", "lengths", "pack_ragged",
           "pack_dense", "tokenize_pack"]


def _wire_dtype(requested: str, vocab_size) -> str:
    """Resolve the wire dtype: explicit arg beats ``TPUDL_TEXT_WIRE_DTYPE``
    beats auto (u16 whenever the vocab provably fits — token ids are the
    ONE tensor whose value range is declared up front, so the 2× shrink
    needs no probe)."""
    req = requested or "auto"
    if req == "auto":
        req = os.environ.get("TPUDL_TEXT_WIRE_DTYPE", "") or "auto"
    if req == "auto":
        req = ("u16" if vocab_size is not None
               and int(vocab_size) <= (1 << 16) else "i32")
    if req not in ("u16", "i32"):
        raise CodecError(
            f"unknown token wire dtype {req!r}; known: ['auto', 'i32', "
            "'u16']")
    if req == "u16" and vocab_size is not None \
            and int(vocab_size) > (1 << 16):
        raise CodecError(
            f"u16 token wire cannot carry vocab_size={vocab_size} "
            "(> 65536); use 'i32'")
    return req


class TokenCodec(WireCodec):
    """Integer token ids on the wire — uint16 when the vocab fits
    (2× fewer bytes than the int32 the model consumes), restored on
    device by one fused ``astype(int32)``. Unlike the pixel codecs this
    one also VALIDATES: encode bounds-checks every batch against the
    declared ``vocab_size`` (and the u16 ceiling), so an id produced by
    the wrong tokenizer fails loudly host-side instead of gathering a
    garbage embedding row on device."""

    name = "tokens"

    def __init__(self, *, pad_id: int = PAD_ID, vocab_size=None,
                 wire_dtype: str = "auto"):
        self.pad_id = int(pad_id)
        self.vocab_size = None if vocab_size is None else int(vocab_size)
        self.wire = _wire_dtype(wire_dtype, self.vocab_size)

    def key(self) -> tuple:
        return (self.name, self.pad_id, self.vocab_size, self.wire)

    def encode(self, arr: np.ndarray) -> np.ndarray:
        arr = np.asarray(arr)
        if not np.issubdtype(arr.dtype, np.integer):
            raise CodecError(
                f"tokens codec encodes integer id batches, got {arr.dtype}")
        if arr.size:
            lo, hi = int(arr.min()), int(arr.max())
            if lo < 0:
                raise CodecError(f"token ids must be >= 0 (min {lo})")
            limit = (self.vocab_size if self.vocab_size is not None
                     else (1 << 16) if self.wire == "u16" else None)
            if limit is not None and hi >= limit:
                raise CodecError(
                    f"token id {hi} out of range for vocab_size={limit} "
                    "— wrong tokenizer for this model?")
        return arr.astype(np.uint16 if self.wire == "u16" else np.int32)

    def decode_array(self, arr: np.ndarray) -> np.ndarray:
        return np.asarray(arr).astype(np.int32)

    def prologue(self, x):
        import jax.numpy as jnp

        return x.astype(jnp.int32)

    def dense_nbytes(self, encoded: np.ndarray) -> int:
        return int(encoded.size) * 4  # the int32 the model consumes


def pad_mask(tokens, pad_id: int = PAD_ID):
    """float32 attention mask (1 = real, 0 = pad) computed ON DEVICE
    from the shipped ids — jittable, so calling it first thing inside
    the model fn fuses the mask into the one compiled program and
    nothing mask-shaped ever crosses the wire."""
    import jax.numpy as jnp

    return (tokens != pad_id).astype(jnp.float32)


def lengths(batch, pad_id: int = PAD_ID) -> np.ndarray:
    """Host-side per-row real lengths of a right-padded batch (int32);
    the inverse of what packing erased. Counts non-pad ids — valid
    because packing only ever right-pads with ``pad_id``."""
    return (np.asarray(batch) != pad_id).sum(axis=1).astype(np.int32)


def pack_ragged(seqs, *, buckets="pow2", pad_id: int = PAD_ID,
                max_len=None) -> np.ndarray:
    """Ragged id vectors → one right-padded int32 batch whose seq dim
    snaps to a bucket-ladder rung (the PR-15 discipline applied to the
    SEQUENCE axis): a ragged prompt sweep hits O(log n) compiled
    signatures instead of one per novel length."""
    ladder = resolve_ladder(buckets if buckets is not None else "pow2")
    seqs = [np.asarray(s, dtype=np.int32).reshape(-1) for s in seqs]
    longest = max((len(s) for s in seqs), default=0)
    if max_len is not None:
        longest = min(longest, int(max_len))
    width = max(1, ladder.pick(longest) if ladder is not None else longest)
    out = np.full((len(seqs), width), int(pad_id), dtype=np.int32)
    for i, s in enumerate(seqs):
        s = s[:width]
        out[i, : len(s)] = s
    return out


def pack_dense(seqs, seq_len: int, *, pad_id: int = PAD_ID) -> np.ndarray:
    """Dense LM-training packing: concatenate the id streams and chunk
    into ``seq_len`` rows — pad waste only in the final partial row
    (the separator policy — eos between documents — is the tokenizer
    call's ``eos=True``, upstream of here). Always emits at least one
    row so a batch of empty strings still has the declared shape."""
    seq_len = int(seq_len)
    if seq_len < 1:
        raise ValueError(f"seq_len must be >= 1, got {seq_len}")
    flat = (np.concatenate([np.asarray(s, dtype=np.int32).reshape(-1)
                            for s in seqs])
            if len(seqs) else np.zeros(0, dtype=np.int32))
    n_rows = max(1, -(-int(flat.size) // seq_len))
    out = np.full(n_rows * seq_len, int(pad_id), dtype=np.int32)
    out[: flat.size] = flat
    return out.reshape(n_rows, seq_len)


def tokenize_pack(tokenizer: Tokenizer, *, seq_len=None, buckets="pow2",
                  pad_id: int = PAD_ID, bos: bool = False,
                  eos: bool = False, dense: bool = False):
    """Build the string-column pack fn for ``Frame.map_batches(pack=)``
    / ``Dataset(pack=)`` — tokenize + pack runs on the prepare pool's
    threads, overlapped with device compute like image decode.

    ``dense=True`` (requires ``seq_len``) emits the training layout
    (:func:`pack_dense`); otherwise rows stay 1:1 with input strings,
    right-padded to a ladder rung (:func:`pack_ragged`, capped at
    ``seq_len`` when given).

    The returned fn's ``cache_token`` folds in the tokenizer
    FINGERPRINT and every packing parameter — the shard-cache /
    device-cache key material that makes epoch ≥ 2 a zero-tokenize,
    zero-wire replay (and makes a vocab edit a cache miss, never a
    stale-ids replay)."""
    if dense and seq_len is None:
        raise ValueError("dense packing requires seq_len")
    ladder = resolve_ladder(buckets if buckets is not None else "pow2")

    def pack(col) -> np.ndarray:
        from tpudl.obs import metrics as _m

        t0 = time.perf_counter()
        seqs = tokenizer.encode_batch(list(np.asarray(col, dtype=object)),
                                      bos=bos, eos=eos)
        n_tok = int(sum(len(s) for s in seqs))
        _m.counter("text.tokenize.calls").inc()
        _m.counter("text.tokenize.tokens").inc(n_tok)
        _m.histogram("text.tokenize.seconds").observe(
            time.perf_counter() - t0)
        if dense:
            out = pack_dense(seqs, int(seq_len), pad_id=pad_id)
        else:
            out = pack_ragged(seqs, buckets=ladder, pad_id=pad_id,
                              max_len=seq_len)
        _m.counter("text.pack.rows").inc(int(out.shape[0]))
        pad_tokens = int(out.size) - min(n_tok, int(out.size))
        _m.counter("text.pack.pad_tokens").inc(pad_tokens)
        if out.size:
            _m.gauge("text.pack.fill_pct").set(
                100.0 * (1.0 - pad_tokens / out.size))
        return out

    spec = (ladder.spec if ladder is not None else "off")
    pack.cache_token = (
        f"text.pack:{tokenizer.cache_token}|seq={seq_len}|dense={dense}"
        f"|buckets={spec}|pad={int(pad_id)}|bos={bos}|eos={eos}")
    pack.tokenizer = tokenizer
    return pack
