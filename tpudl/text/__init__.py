"""tpudl.text — tokenizer column codec and sequence packing (TEXT.md).

The subsystem that makes STRING columns first-class pipeline inputs:
deterministic fingerprintable tokenizers (:mod:`tpudl.text.tokenizer`),
the ``"tokens"`` wire codec + prepare-pool packing
(:mod:`tpudl.text.codec`), and one-call LM training feeds
(:mod:`tpudl.text.data`). The ml transformers over this layer live in
:mod:`tpudl.ml.lm`; the SQL UDFs in :mod:`tpudl.udf.text_udf`.

Import discipline: jax-free at import (tokenizer + packing run on the
executor's prepare threads and in ``tools/validate_text.py``); only
``TokenCodec.prologue`` / ``pad_mask`` touch jax, lazily.
"""

from tpudl.text.codec import (TokenCodec, lengths, pack_dense,
                              pack_ragged, pad_mask, tokenize_pack)
from tpudl.text.data import lm_dataset
from tpudl.text.tokenizer import (BOS_ID, EOS_ID, PAD_ID, UNK_ID,
                                  ByteTokenizer, Tokenizer,
                                  WordTokenizer, load_vocab,
                                  tokenizer_from_spec)

__all__ = [
    "PAD_ID", "BOS_ID", "EOS_ID", "UNK_ID",
    "Tokenizer", "ByteTokenizer", "WordTokenizer",
    "tokenizer_from_spec", "load_vocab",
    "TokenCodec", "pad_mask", "lengths",
    "pack_ragged", "pack_dense", "tokenize_pack", "lm_dataset",
]
