"""LM dataset assembly: one call from a string column to the full
cached, wire-coded, epoch-replayable training feed.

:func:`lm_dataset` is the text twin of the image path's
``Dataset(frame, ["image"], wire_codec="auto", cache_dir=...)``: it
wires :func:`~tpudl.text.codec.tokenize_pack` (tokenize + dense pack on
the prepare pool) and :class:`~tpudl.text.codec.TokenCodec` (u16 ids on
the wire, int32 restore fused on device) into a
:class:`~tpudl.data.dataset.Dataset` whose cache keys carry the
tokenizer fingerprint. The payoff is the acceptance invariant this PR
pins in tests: epoch 2 of a fine-tune performs ZERO re-tokenizations
(``text.tokenize.calls`` flat) and — with ``device_cache=True`` — ships
ZERO wire bytes (``data.wire.bytes_shipped`` flat), exactly the warm
path images got in PRs 4/12.
"""

from __future__ import annotations

from tpudl.text.codec import TokenCodec, tokenize_pack
from tpudl.text.tokenizer import Tokenizer

__all__ = ["lm_dataset"]


def lm_dataset(frame, col: str, tokenizer: Tokenizer, *, seq_len: int,
               batch_size: int = 64, eos: bool = True,
               cache_dir: str | None = None, retain: bool = False,
               device_cache: bool = False, mesh=None):
    """A :class:`~tpudl.data.dataset.Dataset` of densely packed
    ``[rows, seq_len]`` int32 LM-training batches over ``frame[col]``.

    Each batch tokenizes ``batch_size`` strings (``eos=True`` puts the
    document separator between them) and packs the id stream into
    ``seq_len`` rows — pad waste only in each batch's final row — then
    wire-encodes via :class:`TokenCodec` (uint16 when the vocab fits).
    Feed a :class:`~tpudl.zoo.transformer.TinyCausalLM` loss via
    ``ds.wrap(jax.jit(...))`` or consume host-side with
    ``ds.device_restore``; epoch replay semantics (shard cache,
    ``retain``, HBM residency) are the Dataset's own.
    """
    from tpudl.data.dataset import Dataset

    pack = tokenize_pack(tokenizer, seq_len=int(seq_len), dense=True,
                         eos=eos)
    codec = TokenCodec(vocab_size=tokenizer.vocab_size)
    return Dataset(frame, [col], batch_size=batch_size, wire_codec=codec,
                   cache_dir=cache_dir, pack=pack, retain=retain,
                   device_cache=device_cache, mesh=mesh)
