"""Deterministic, fingerprintable tokenizers for string columns.

The text subsystem's ground rule (TEXT.md): tokenization is CACHE-KEY
MATERIAL. A tokenized Dataset's shard cache and HBM residency replay
across epochs and processes, so two runs may only share shards when
their token ids mean the same thing — which demands a tokenizer whose
identity is (a) deterministic (no dict-order, no hash-seed, no
environment dependence) and (b) summarizable as one short string. Every
tokenizer here answers ``fingerprint``: the sha1 of its canonical spec
JSON (sorted keys, no whitespace), and round-trips through
``spec()`` / :func:`tokenizer_from_spec` and through an on-disk vocab
manifest (``save`` / :func:`load_vocab`) that ``tools/validate_text.py``
audits — format, schema, and a recomputed-fingerprint match.

Import discipline: stdlib + numpy only (the validator imports nothing
from here but mirrors the fingerprint math; the prepare pool runs
``encode`` host-side with no jax in sight).

Two concrete tokenizers cover the judged workloads:

- :class:`ByteTokenizer` — UTF-8 bytes shifted past the specials;
  vocab 260, lossless round-trip, zero build cost. The LM bench family
  and the examples ride it.
- :class:`WordTokenizer` — a corpus-built word/punct vocab, sorted by
  (-count, token) so the SAME corpus always yields the SAME ids; OOV
  maps to ``<unk>``. Lossy decode (single-space join), documented.

Specials are fixed across modes: pad=0, bos=1, eos=2, unk=3 — pad MUST
be 0 so a right-padded int32 batch is also the attention mask's zero
set (tpudl.text.codec.pad_mask) and packed buffers can be np.zeros.
"""

from __future__ import annotations

import hashlib
import json
import os
import re

import numpy as np

__all__ = [
    "PAD_ID", "BOS_ID", "EOS_ID", "UNK_ID", "N_SPECIALS",
    "VOCAB_FORMAT", "Tokenizer", "ByteTokenizer", "WordTokenizer",
    "tokenizer_from_spec", "load_vocab", "spec_fingerprint",
]

PAD_ID = 0
BOS_ID = 1
EOS_ID = 2
UNK_ID = 3
N_SPECIALS = 4

VOCAB_FORMAT = "tpudl-vocab-v1"

_WORD_RE = re.compile(r"\w+|[^\w\s]")


def spec_fingerprint(spec: dict) -> str:
    """sha1 over the canonical JSON of a tokenizer spec — THE
    fingerprint definition, shared verbatim by ``tools/validate_text.py``
    (which recomputes it from a manifest without importing tpudl).
    Canonical = sorted keys, compact separators, ensure_ascii: every
    byte of the digest input is pinned."""
    blob = json.dumps(spec, sort_keys=True, separators=(",", ":"),
                      ensure_ascii=True)
    return hashlib.sha1(blob.encode("utf-8")).hexdigest()


class Tokenizer:
    """Deterministic text ↔ int32-ids contract.

    Subclasses implement ``_encode_one`` / ``_decode_ids`` and
    ``spec()``; everything identity-shaped (fingerprint, cache token,
    manifest save) lives here so no subclass can drift from the
    canonical form the validator audits."""

    mode = "abstract"

    # -- identity ----------------------------------------------------------
    def spec(self) -> dict:  # pragma: no cover - abstract
        raise NotImplementedError

    @property
    def vocab_size(self) -> int:  # pragma: no cover - abstract
        raise NotImplementedError

    @property
    def fingerprint(self) -> str:
        return spec_fingerprint(self.spec())

    @property
    def cache_token(self) -> str:
        """Shard-cache identity (`data.dataset._callable_token` honors
        this attr on pack callables built over a tokenizer)."""
        return f"text.tok:{self.mode}:{self.fingerprint}"

    # -- encode / decode ---------------------------------------------------
    def _encode_one(self, text: str) -> list:  # pragma: no cover
        raise NotImplementedError

    def _decode_ids(self, ids: list) -> str:  # pragma: no cover
        raise NotImplementedError

    def encode(self, text, *, bos: bool = False,
               eos: bool = False) -> np.ndarray:
        """One string → int32 id vector (never padded here — padding
        and rung-snapping belong to the codec/pack layer)."""
        ids = self._encode_one("" if text is None else str(text))
        if bos:
            ids = [BOS_ID] + ids
        if eos:
            ids = ids + [EOS_ID]
        return np.asarray(ids, dtype=np.int32)

    def encode_batch(self, texts, *, bos: bool = False,
                     eos: bool = False) -> list:
        return [self.encode(t, bos=bos, eos=eos) for t in texts]

    def decode(self, ids) -> str:
        """ids → text, specials dropped; trailing pad is how a packed
        row carries its length, so decode is pad-blind by design."""
        ids = [int(i) for i in np.asarray(ids).reshape(-1)
               if int(i) >= N_SPECIALS]
        return self._decode_ids(ids)

    def decode_batch(self, batch) -> list:
        return [self.decode(row) for row in np.asarray(batch)]

    # -- manifest ----------------------------------------------------------
    def save(self, path: str) -> str:
        """Write the vocab manifest (atomic tmp + rename — a killed
        writer never leaves a half manifest for load_vocab/the
        validator to trip on)."""
        doc = dict(self.spec())
        doc["format"] = VOCAB_FORMAT
        doc["fingerprint"] = self.fingerprint
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, sort_keys=True, indent=1)
        os.replace(tmp, path)
        return path

    def __repr__(self):
        return (f"{type(self).__name__}(vocab={self.vocab_size}, "
                f"fingerprint={self.fingerprint[:12]})")


class ByteTokenizer(Tokenizer):
    """UTF-8 bytes shifted past the 4 specials — vocab 260, lossless,
    build-free; the deterministic default for benches and examples."""

    mode = "byte"

    def __init__(self, *, lowercase: bool = False):
        self.lowercase = bool(lowercase)

    @property
    def vocab_size(self) -> int:
        return N_SPECIALS + 256

    def spec(self) -> dict:
        return {"mode": self.mode, "lowercase": self.lowercase,
                "specials": {"pad": PAD_ID, "bos": BOS_ID,
                             "eos": EOS_ID, "unk": UNK_ID}}

    def _encode_one(self, text: str) -> list:
        if self.lowercase:
            text = text.lower()
        return [b + N_SPECIALS for b in text.encode("utf-8")]

    def _decode_ids(self, ids: list) -> str:
        return bytes(i - N_SPECIALS for i in ids
                     if N_SPECIALS <= i < N_SPECIALS + 256).decode(
                         "utf-8", errors="replace")


class WordTokenizer(Tokenizer):
    """Corpus-built word/punctuation vocab with deterministic ids.

    ``build`` sorts candidates by (-count, token) — a pure function of
    the corpus multiset, so re-building from the same texts always
    yields the same vocab (and the same fingerprint). Decode joins with
    single spaces: LOSSY by declaration (whitespace is not modeled)."""

    mode = "word"

    def __init__(self, tokens, *, lowercase: bool = True):
        self.lowercase = bool(lowercase)
        self.tokens = [str(t) for t in tokens]
        if len(set(self.tokens)) != len(self.tokens):
            raise ValueError("vocab tokens must be unique")
        self._ids = {t: i + N_SPECIALS for i, t in enumerate(self.tokens)}

    @classmethod
    def build(cls, texts, *, size: int = 1024,
              lowercase: bool = True) -> "WordTokenizer":
        counts: dict = {}
        for t in texts:
            t = "" if t is None else str(t)
            if lowercase:
                t = t.lower()
            for w in _WORD_RE.findall(t):
                counts[w] = counts.get(w, 0) + 1
        ordered = sorted(counts, key=lambda w: (-counts[w], w))
        return cls(ordered[: max(0, int(size))], lowercase=lowercase)

    @property
    def vocab_size(self) -> int:
        return N_SPECIALS + len(self.tokens)

    def spec(self) -> dict:
        return {"mode": self.mode, "lowercase": self.lowercase,
                "tokens": list(self.tokens),
                "specials": {"pad": PAD_ID, "bos": BOS_ID,
                             "eos": EOS_ID, "unk": UNK_ID}}

    def _encode_one(self, text: str) -> list:
        if self.lowercase:
            text = text.lower()
        return [self._ids.get(w, UNK_ID) for w in _WORD_RE.findall(text)]

    def _decode_ids(self, ids: list) -> str:
        n = len(self.tokens)
        return " ".join(self.tokens[i - N_SPECIALS] for i in ids
                        if N_SPECIALS <= i < N_SPECIALS + n)


def tokenizer_from_spec(spec: dict) -> Tokenizer:
    """Inverse of ``Tokenizer.spec()`` — how a persisted vocab manifest
    (or a serve registry entry) becomes a live tokenizer again."""
    mode = spec.get("mode")
    if mode == "byte":
        return ByteTokenizer(lowercase=bool(spec.get("lowercase", False)))
    if mode == "word":
        return WordTokenizer(spec.get("tokens", ()),
                             lowercase=bool(spec.get("lowercase", True)))
    raise ValueError(f"unknown tokenizer mode {mode!r} "
                     "(known: ['byte', 'word'])")


def load_vocab(path: str) -> Tokenizer:
    """Load + VERIFY a vocab manifest: format tag, spec round-trip, and
    a recomputed fingerprint match — a hand-edited vocab whose ids
    silently shifted must fail here, not corrupt a warm cache."""
    with open(path) as f:
        doc = json.load(f)
    if doc.get("format") != VOCAB_FORMAT:
        raise ValueError(
            f"{path}: not a {VOCAB_FORMAT} manifest "
            f"(format={doc.get('format')!r})")
    want = doc.get("fingerprint")
    spec = {k: v for k, v in doc.items()
            if k not in ("format", "fingerprint")}
    tok = tokenizer_from_spec(spec)
    if want and tok.fingerprint != want:
        raise ValueError(
            f"{path}: fingerprint mismatch (manifest {want[:12]}..., "
            f"recomputed {tok.fingerprint[:12]}...) — the vocab was "
            "edited after it was fingerprinted")
    return tok
