"""Back-compat shim: the compilation cache grew into the
:mod:`tpudl.compile` subsystem (COMPILE.md) — persistent XLA cache +
AOT program store + shape bucketing. Import from ``tpudl.compile``;
this module keeps the original spelling working."""

from __future__ import annotations

from tpudl.compile.cache import enable_compilation_cache

__all__ = ["enable_compilation_cache"]
