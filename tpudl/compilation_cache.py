"""Persistent XLA compilation cache wiring.

The reference pays Spark task-dispatch overhead per stage; our analogous
fixed cost is XLA compilation — ~60-200 s for InceptionV3 through a
tunneled dev chip, paid again every process start. JAX's persistent
compilation cache (serialized executables keyed by HLO+flags+topology)
removes it for repeat runs. This module turns it on with sane defaults;
it is enabled automatically by ``bench.py`` and opt-in elsewhere via
``TPUDL_COMPILE_CACHE_DIR`` (set to a directory, or ``0`` to disable).

Cache safety: entries are keyed by backend+topology, so a cache shared
between the CPU-mesh test runs and the TPU chip never cross-serves.
"""

from __future__ import annotations

import os

__all__ = ["enable_compilation_cache"]

_DEFAULT_DIR = os.path.join(os.path.expanduser("~"), ".cache", "tpudl",
                            "xla_cache")


def enable_compilation_cache(path: str | None = None) -> str | None:
    """Enable JAX's persistent compilation cache at ``path`` (default:
    ``$TPUDL_COMPILE_CACHE_DIR`` or ``~/.cache/tpudl/xla_cache``).
    Returns the cache dir, or None when disabled/unsupported."""
    env = os.environ.get("TPUDL_COMPILE_CACHE_DIR")
    if env == "0":
        return None
    path = path or env or _DEFAULT_DIR
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache everything that took meaningful compile time; tiny
        # programs aren't worth the disk round-trip
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        return path
    except Exception:  # pragma: no cover - old jax or read-only fs
        return None
