"""tpudl — TPU-native deep learning pipelines.

A ground-up jax/XLA/Flax framework with the capability surface of
`spark-deep-learning` (`sparkdl`): see SURVEY.md for the blueprint and the
per-module docstrings for reference anchors. The public API mirrors the
reference's names (ref: python/sparkdl/__init__.py:~L1-40) so a sparkdl
user finds everything under the same spelling, while execution is fused
jitted programs on a TPU mesh.
"""

import importlib
import os as _os

from tpudl.version import __version__

if _os.environ.get("TPUDL_TRACECK", "0") == "1":
    # recompile-storm sentinel (tpudl.testing.traceck): install the
    # jax.jit counting shim BEFORE any product module binds jax.jit
    # into a decorator/partial/local — import order IS the contract
    from tpudl.testing import traceck as _traceck  # noqa: F401

# symbol → defining module. Extended as layers land; __all__ derives from it
# so star-import never advertises a module that does not exist yet.
_LAZY = {
    "Frame": "tpudl.frame",
    "sql": "tpudl.frame",
    "register_udf": "tpudl.udf",
    # L5 product surface (ref: sparkdl/__init__.py __all__)
    "DeepImageFeaturizer": "tpudl.ml",
    "DeepImagePredictor": "tpudl.ml",
    "TFImageTransformer": "tpudl.ml",
    "TFTransformer": "tpudl.ml",
    "KerasTransformer": "tpudl.ml",
    "KerasImageFileTransformer": "tpudl.ml",
    "Pipeline": "tpudl.ml",
    "PipelineModel": "tpudl.ml",
    "TFInputGraph": "tpudl.ingest",
    "KerasImageFileEstimator": "tpudl.ml.estimator",
    "ParamGridBuilder": "tpudl.ml.tuning",
    "CrossValidator": "tpudl.ml.tuning",
    "LogisticRegression": "tpudl.ml",
    "registerKerasImageUDF": "tpudl.udf.keras_image_model",
    "GraphFunction": "tpudl.ingest",
    "IsolatedSession": "tpudl.ingest",
    # preemption-survivable job runtime (JOBS.md)
    "JobSpec": "tpudl.jobs",
    "JobRuntime": "tpudl.jobs",
    "RetryPolicy": "tpudl.jobs",
    # wire-aware dataset subsystem (DATA.md)
    "Dataset": "tpudl.data",
    "U8Codec": "tpudl.data",
    "BF16Codec": "tpudl.data",
    "ShardCache": "tpudl.data",
    # text subsystem: tokenizer codec + LM pipeline stages (TEXT.md)
    "ByteTokenizer": "tpudl.text",
    "WordTokenizer": "tpudl.text",
    "TokenCodec": "tpudl.text",
    "lm_dataset": "tpudl.text",
    "LMFeaturizer": "tpudl.ml",
    "LMGenerator": "tpudl.ml",
    "LMClassifier": "tpudl.ml",
    # long-context / sequence parallelism (TPU-native addition)
    "ring_attention": "tpudl.attention",
    "shard_sequence": "tpudl.attention",
    "flash_attention": "tpudl.pallas_ops",
    "TinyCausalLM": "tpudl.zoo.transformer",
}

__all__ = ["__version__", *_LAZY]


def __getattr__(name):
    # Lazy re-exports: keep `import tpudl` light (no TF, no model zoo) until
    # a symbol is actually used.
    if name in _LAZY:
        return getattr(importlib.import_module(_LAZY[name]), name)
    raise AttributeError(f"module 'tpudl' has no attribute {name!r}")


def __dir__():
    return sorted(__all__)
