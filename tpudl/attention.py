"""Ring attention — sequence/context parallelism over the mesh.

The reference has no long-context capability (SURVEY.md §5.7 records it
absent upstream), but tpudl's charter makes long-context first-class:
sequences too large for one chip's HBM are sharded over a mesh axis and
attention runs as a RING — each device holds its Q shard and the K/V
shards ROTATE around the axis via ``jax.lax.ppermute`` (one hop per
step, riding ICI neighbor links, never materializing the full [S, S]
score matrix or the full K/V on any chip).

Numerics: flash-style online softmax — running max ``m``, normalizer
``l`` and weighted accumulator per Q row are updated as each K/V block
arrives, so the result is bit-consistent with dense softmax(QKᵀ)V up to
float re-association. Causal masking uses global positions derived from
``lax.axis_index``, so it stays correct as blocks rotate.

The implementation is ``shard_map`` over the existing :mod:`tpudl.mesh`
axes — the same mesh that carries data-parallel training; XLA schedules
the ppermute collectives on ICI. Differentiable end-to-end (jax.grad
through shard_map), jit-compatible, size-agnostic from the 8-device CPU
test mesh to a pod slice.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from jax import shard_map  # top-level since jax 0.6 (pyproject floor)

from tpudl import mesh as M

__all__ = ["ring_attention", "attention_reference", "shard_sequence"]


def attention_reference(q, k, v, causal: bool = False):
    """Dense single-device softmax attention oracle: ``softmax(QKᵀ/√d)V``.
    q, k, v: [batch, seq, heads, head_dim]."""
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), bool))
        scores = jnp.where(mask, scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


def shard_sequence(tree, mesh, axis: str = M.DATA_AXIS):
    """Place [B, S, ...] arrays with the SEQUENCE dim sharded over
    ``axis`` — the long-context infeed edge (batch replicated)."""
    def _put(x):
        spec = P(None, axis, *([None] * (x.ndim - 2)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(_put, tree)


def ring_attention(q, k, v, mesh, *, axis: str = M.DATA_AXIS,
                   head_axis: str | None = None,
                   causal: bool = False, use_pallas: bool = False,
                   pallas_block: int = 128,
                   pallas_interpret: bool | None = None):
    """Sequence-parallel attention over ``mesh[axis]``.

    q, k, v: [batch, seq, heads, head_dim] with ``seq`` sharded over
    ``axis`` (``shard_sequence`` produces the right placement; unsharded
    inputs are accepted and constrained). ``seq`` must divide evenly by
    the axis size. Returns [batch, seq, heads, head_dim] with the same
    sequence sharding.

    ``head_axis`` additionally shards the HEADS dim over that mesh axis
    — the tensor-parallel composition (SP ring × TP heads): heads are
    embarrassingly parallel in attention, so the ring body runs
    unchanged on its head shard and no extra collective is needed
    inside; ``heads`` must divide by the axis size.

    Communication: n-1 neighbor ``ppermute`` hops of the local K/V block
    (each hop overlaps the block's score/accumulate compute in XLA's
    schedule); memory: O(S/n) K/V per device, O((S/n)²·n → S·S/n) scores
    peak, never the full matrix.

    ``use_pallas=True`` computes each ring step with the Pallas flash
    kernel (:func:`tpudl.pallas_ops.flash_attention`): forward AND
    backward are tiled kernels (the custom VJP launches flash dq/dk/dv
    kernels from the saved log-sum-exp), so neither direction
    materializes an (S/n)² matrix per device, and strictly-future
    hops/tiles are skipped under causal masking. Partials merge exactly
    via their log-sum-exps (the standard ring/flash-decoding merge).
    ``pallas_interpret`` defaults to auto (interpret off TPU, compiled
    on TPU).
    """
    n = mesh.shape[axis]
    if q.shape[1] % n:
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by ring size {n}")
    if head_axis is not None and q.shape[2] % mesh.shape[head_axis]:
        raise ValueError(
            f"heads {q.shape[2]} not divisible by mesh axis "
            f"{head_axis!r} size {mesh.shape[head_axis]}")
    vary_axes = (axis,) if head_axis is None else (axis, head_axis)
    seq_spec = P(None, axis, head_axis, None)
    if use_pallas:
        return _ring_attention_pallas(q, k, v, mesh, axis, n, seq_spec,
                                      causal, pallas_block,
                                      pallas_interpret, vary_axes)

    def local(qb, kb, vb):
        # qb/kb/vb: [B, S/n, H, D] — this device's blocks
        idx = jax.lax.axis_index(axis)
        s_loc = qb.shape[1]
        scale = 1.0 / jnp.sqrt(qb.shape[-1]).astype(jnp.float32)
        q32 = qb.astype(jnp.float32)
        q_pos = idx * s_loc + jnp.arange(s_loc)

        m = jnp.full(qb.shape[:2] + (qb.shape[2],), -jnp.inf, jnp.float32)
        m = jnp.moveaxis(m, -1, 1)                     # [B, H, Sq]
        l = jnp.zeros_like(m)                          # [B, H, Sq]
        acc = jnp.zeros(
            (qb.shape[0], qb.shape[2], s_loc, qb.shape[3]), jnp.float32)
        # the carry becomes device-varying after one step (it mixes in the
        # rotating K/V); mark the initial values varying so scan's carry
        # types line up under shard_map's varying-axis tracking
        m, l, acc = (_mark_varying(t, vary_axes) for t in (m, l, acc))

        perm = [(i, (i + 1) % n) for i in range(n)]

        def step(carry, s):
            m, l, acc, kc, vc = carry
            # block s originated on device (idx - s) mod n
            src = (idx - s) % n
            k_pos = src * s_loc + jnp.arange(s_loc)
            scores = jnp.einsum("bqhd,bkhd->bhqk", q32,
                                kc.astype(jnp.float32)) * scale
            if causal:
                mask = q_pos[:, None] >= k_pos[None, :]  # [Sq, Sk]
                scores = jnp.where(mask[None, None], scores, -jnp.inf)
            m_new = jnp.maximum(m, scores.max(axis=-1))
            # exp(-inf - -inf) guard: rows with no visible keys yet keep
            # m_new == -inf; make their correction factor 0, not NaN
            corr = jnp.where(jnp.isinf(m), 0.0, jnp.exp(m - m_new))
            p = jnp.exp(scores - m_new[..., None])
            p = jnp.where(jnp.isinf(m_new)[..., None], 0.0, p)
            l = l * corr + p.sum(axis=-1)
            acc = acc * corr[..., None] + jnp.einsum(
                "bhqk,bkhd->bhqd", p, vc.astype(jnp.float32))
            kc, vc = _rotate_unless_last(kc, vc, s, n, axis, perm)
            return (m_new, l, acc, kc, vc), None

        (m, l, acc, _k, _v), _ = jax.lax.scan(
            step, (m, l, acc, kb, vb), jnp.arange(n))
        out = acc / jnp.where(l == 0.0, 1.0, l)[..., None]  # [B,H,Sq,D]
        return jnp.moveaxis(out, 1, 2).astype(qb.dtype)     # [B,Sq,H,D]

    fn = shard_map(local, mesh=mesh,
                   in_specs=(seq_spec, seq_spec, seq_spec),
                   out_specs=seq_spec)
    return fn(q, k, v)


def _ring_attention_pallas(q, k, v, mesh, axis, n, seq_spec, causal,
                           block, interpret, vary_axes=None):
    """Ring loop where each step is one Pallas flash-attention call over
    the local Q shard and the rotating K/V block; partials merge via
    log-sum-exp weights (exact — same math as the in-kernel online
    softmax, applied across blocks)."""
    from tpudl.pallas_ops import _NEG_INF, flash_attention

    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def local(qb, kb, vb):
        idx = jax.lax.axis_index(axis)
        s_loc = qb.shape[1]
        # largest block that divides the shard (min() alone would reject
        # shard lengths like 192 that the plain ring path accepts)
        blk = math.gcd(s_loc, block)
        q_off = idx * s_loc
        o0 = jnp.zeros(qb.shape, jnp.float32)
        lse0 = jnp.full((qb.shape[0], s_loc, qb.shape[2]), _NEG_INF,
                        jnp.float32)
        o0, lse0 = (_mark_varying(t, vary_axes or (axis,))
                    for t in (o0, lse0))
        perm = [(i, (i + 1) % n) for i in range(n)]

        def step(carry, s):
            o, lse, kc, vc = carry
            src = (idx - s) % n

            def live(args):
                kc, vc = args
                return flash_attention(
                    qb, kc, vc, causal=causal, q_offset=q_off,
                    k_offset=src * s_loc, block_q=blk, block_k=blk,
                    interpret=interpret, return_lse=True)

            def future(args):
                return (jnp.zeros(qb.shape, qb.dtype),
                        jnp.full(lse0.shape, _NEG_INF, jnp.float32))

            if causal:
                # a hop whose K block is strictly in this shard's future
                # contributes weight exp(-inf); skip the whole launch
                ob, lb = jax.lax.cond(src <= idx, live, future, (kc, vc))
            else:
                ob, lb = live((kc, vc))
            m = jnp.maximum(lse, lb)
            w_prev, w_blk = jnp.exp(lse - m), jnp.exp(lb - m)
            denom = w_prev + w_blk
            safe = jnp.where(denom == 0.0, 1.0, denom)
            o = (o * w_prev[..., None]
                 + ob.astype(jnp.float32) * w_blk[..., None]) / safe[..., None]
            lse = m + jnp.log(safe)
            kc, vc = _rotate_unless_last(kc, vc, s, n, axis, perm)
            return (o, lse, kc, vc), None

        (o, _lse, _k, _v), _ = jax.lax.scan(
            step, (o0, lse0, kb, vb), jnp.arange(n))
        return o.astype(qb.dtype)

    # check_vma off: pallas_call's out_shape carries no varying-axis
    # annotation, so the tracker cannot type the kernel's outputs
    fn = shard_map(local, mesh=mesh,
                   in_specs=(seq_spec, seq_spec, seq_spec),
                   out_specs=seq_spec, check_vma=False)
    return fn(q, k, v)


def _rotate_unless_last(kc, vc, s, n, axis, perm):
    """Rotate the K/V blocks one ring hop — except on the final scan step,
    whose rotated blocks would be discarded (n-1 hops suffice for n
    blocks; the predicate is the uniform scan counter, so every device
    takes the same branch and the collective stays matched)."""
    if n == 1:
        return kc, vc
    return jax.lax.cond(
        s < n - 1,
        lambda kv: (jax.lax.ppermute(kv[0], axis, perm),
                    jax.lax.ppermute(kv[1], axis, perm)),
        lambda kv: kv,
        (kc, vc))


def _mark_varying(t, axes):
    """Mark ``t`` device-varying over ``axes`` (a name or tuple of
    names) under shard_map's varying-axis type tracking (``lax.pcast``
    on current jax; ``pvary`` is the 0.6–0.7 spelling within the
    supported floor)."""
    axes = (axes,) if isinstance(axes, str) else tuple(axes)
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(t, axes, to="varying")
    return jax.lax.pvary(t, axes)  # pragma: no cover - jax 0.6/0.7
