"""Mesh-parallel training step — the NCCL-allreduce equivalent.

The reference's distributed-training capability is HorovodRunner's MPI +
NCCL ring allreduce (SURVEY.md §3.6/§5.8, Databricks distribution). The
TPU-native translation: ONE jitted SPMD program over the mesh — batch
sharded on the ``data`` axis, params replicated — in which XLA lowers
the gradient reduction onto ICI collectives automatically. There is no
hand-written ring: the sharding annotations ARE the communication spec
(scaling-book recipe: pick a mesh, annotate, let XLA insert collectives).
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from tpudl import mesh as M

__all__ = ["make_train_step", "make_eval_step", "with_compute_dtype"]


def with_compute_dtype(loss_fn, dtype):
    """Mixed precision the TPU way: fp32 MASTER params, ``dtype``
    (bf16) compute. Wraps ``loss_fn`` so float32 param leaves are cast
    to ``dtype`` for the forward/backward pass while the optimizer
    updates the fp32 originals.

    Why this exists: training directly in bf16 silently STALLS once
    updates shrink below the parameter's 8-bit-mantissa ULP —
    ``bf16(1.0 + 1e-6) == 1.0``, so SGD steps round to nothing (the
    ResNet50 convergence bench plateaued exactly this way). The cast is
    free on the MXU path (XLA fuses it into the consuming matmul), and
    grads come back fp32 because the masters are fp32.
    """
    import jax.numpy as jnp

    target = jnp.dtype(dtype)

    def cast(leaf):
        return (leaf.astype(target)
                if hasattr(leaf, "dtype") and leaf.dtype == jnp.float32
                else leaf)

    def wrapped(params, *batch):
        return loss_fn(jax.tree.map(cast, params), *batch)

    return wrapped


def make_train_step(loss_fn, optimizer, mesh=None, donate=True,
                    param_shardings=None):
    """Build ``step(params, opt_state, *batch) -> (params, opt_state,
    loss)``, jit-compiled as one SPMD program.

    ``loss_fn(params, *batch) -> scalar`` must be the *global-batch mean*
    loss (the usual formulation): because the mean over a sharded batch
    already contracts over the data axis, the backward pass's reduction
    IS the allreduce — XLA emits the psum over ICI, replacing
    hvd.DistributedOptimizer's NCCL ring.

    ``param_shardings`` (a pytree of NamedSharding matching ``params``)
    overrides the default fully-replicated param constraint — the
    tensor-parallel hook: pass the model's ``param_shardings(mesh)`` and
    params, grads, and optimizer state all stay sharded over the
    ``model`` axis through the whole step (grads inherit the param
    sharding through AD; XLA keeps the update local to each shard).
    """

    def step(params, opt_state, *batch):
        if mesh is not None:
            batch = tuple(
                jax.lax.with_sharding_constraint(
                    b, NamedSharding(mesh, P(M.DATA_AXIS,
                                             *([None] * (b.ndim - 1)))))
                for b in batch)
            params = (jax.lax.with_sharding_constraint(params,
                                                       param_shardings)
                      if param_shardings is not None else
                      jax.lax.with_sharding_constraint(
                          params, NamedSharding(mesh, P())))
        loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    donate_argnums = (0, 1) if donate else ()
    return jax.jit(step, donate_argnums=donate_argnums)


def make_eval_step(apply_fn, mesh=None):
    """Build ``eval(params, *batch) -> outputs`` sharded like the train
    step (for validation passes between epochs)."""

    def step(params, *batch):
        if mesh is not None:
            batch = tuple(
                jax.lax.with_sharding_constraint(
                    b, NamedSharding(mesh, P(M.DATA_AXIS,
                                             *([None] * (b.ndim - 1)))))
                for b in batch)
        return apply_fn(params, *batch)

    return jax.jit(step)
