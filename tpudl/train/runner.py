"""HorovodRunner contract + the training loop that owns it.

Rebuild of the L7 capability surface (SURVEY.md §3.6): the Databricks
``sparkdl.HorovodRunner(np=N).run(train_fn, **kwargs)`` API — MPI gang
launch + NCCL allreduce — re-owned as SPMD over a jax mesh:

- ``np > 0``: data-parallel mesh over the first ``np`` local devices
  (the reference's N distributed GPU ranks → N TPU chips on the slice).
- ``np < 0``: |np|-device debug mesh, mirroring HorovodRunner's
  negative-np local-mode debugging contract (runs on whatever local
  devices exist; under the CPU simulation flag this is a real multi-
  device mesh on one host).

Differences owned deliberately (NOT ported): there are no per-rank
processes and no hvd.* mutable global — ``train_fn`` receives a
:class:`TrainContext` as its first argument and is executed ONCE as an
SPMD program driver. Rank-0-only conventions collapse: in SPMD the
driver *is* logically rank 0 (``ctx.rank == 0`` is kept for code that
checks it). Gang semantics match TPU reality (§5.3): a failure kills the
whole program; ``max_restarts`` re-launches ``train_fn`` which resumes
from the last checkpoint.
"""

from __future__ import annotations

import functools
import logging
import time

import jax
import numpy as np

from tpudl import distributed as D
from tpudl import mesh as M
from tpudl.jobs.retry import RetryPolicy, is_fatal
from tpudl.obs import attribution as _attr
from tpudl.obs import flight as _obs_flight
from tpudl.obs import metrics as _obs_metrics
from tpudl.obs import tracer as _obs_tracer
from tpudl.obs import watchdog as _obs_watchdog
from tpudl.testing import faults as _faults
from tpudl.train.checkpoint import CheckpointManager
from tpudl.train.step import make_train_step

__all__ = ["HorovodRunner", "TrainContext", "Trainer", "Preempted",
           "RestartsExhausted"]

log = logging.getLogger("tpudl.train")


class Preempted(Exception):
    """Cooperative-stop signal: ``Trainer.fit(stop=...)`` saw the stop
    flag, force-saved a checkpoint at ``step`` and unwound. Marked
    ``tpudl_fatal`` so NO retry layer (gang restart, RetryPolicy, trial
    retry) fights the preemption — the job runtime (tpudl.jobs) catches
    it and turns it into an orderly preempted-resumable exit."""

    tpudl_fatal = True

    def __init__(self, step: int, saved: bool = True):
        super().__init__(f"preempted at step {step}"
                         + ("" if saved else " (no checkpoint dir — "
                            "state NOT saved)"))
        self.step = int(step)
        self.saved = bool(saved)


class RestartsExhausted(RuntimeError):
    """The gang-restart budget ran out. Carries the LAST cause (also
    chained as ``__cause__``) so the terminal error names why the gang
    kept dying, not just that it did. Subclasses RuntimeError — and
    embeds the cause's message — for compatibility with callers that
    matched the previously re-raised original."""

    def __init__(self, attempts: int, last_cause: BaseException):
        super().__init__(
            f"gang restart budget exhausted after {attempts} attempt(s); "
            f"last cause: {type(last_cause).__name__}: {last_cause}")
        self.attempts = int(attempts)
        self.last_cause = last_cause


def _restart_backoff_base_s() -> float:
    import os

    try:
        return float(os.environ.get("TPUDL_TRAIN_RESTART_BACKOFF_S",
                                    "") or 0.1)
    except ValueError:
        return 0.1


@functools.lru_cache(maxsize=1)
def _owning_identity():
    """The ONE cached jitted identity program ``Trainer.fit``'s
    ``_own`` runs to take ownership of an already-mesh-sharded tree
    without a host gather. A fresh ``jax.jit(lambda t: t)`` at the
    call site would be a fresh fn identity — a retrace per fit
    (jit-cache-churn); jit's own cache then keys per tree structure."""
    return jax.jit(lambda t: t)


class TrainContext:
    """What a ``train_fn`` gets instead of the hvd.* globals."""

    def __init__(self, mesh, checkpoint_dir=None, save_every=100):
        self.mesh = mesh
        self.checkpoint_dir = checkpoint_dir
        self.save_every = save_every
        self.attempt = 0  # restart count, set by the runner

    # hvd-parity accessors
    @property
    def size(self) -> int:
        return self.mesh.shape[M.DATA_AXIS]

    @property
    def rank(self) -> int:
        return 0  # SPMD driver == logical rank 0 (see module docstring)

    # mesh edges
    def shard_batch(self, tree):
        return M.shard_batch(tree, self.mesh)

    def replicate(self, tree):
        return M.replicate(tree, self.mesh)

    def checkpoints(self, subdir: str | None = None) -> CheckpointManager | None:
        if self.checkpoint_dir is None:
            return None
        d = self.checkpoint_dir if subdir is None else f"{self.checkpoint_dir}/{subdir}"
        return CheckpointManager(d, save_every=self.save_every)

    def trainer(self, loss_fn, optimizer, **kw) -> "Trainer":
        kw.setdefault("checkpoint_dir", self.checkpoint_dir)
        kw.setdefault("save_every", self.save_every)
        return Trainer(loss_fn, optimizer, mesh=self.mesh, **kw)


class HorovodRunner:
    """``HorovodRunner(np=2).run(train_fn)`` — the reference's public
    training entry point, mesh-native."""

    def __init__(self, np: int = -1, *, checkpoint_dir: str | None = None,
                 save_every: int = 100, max_restarts: int = 0,
                 devices=None, retry_policy: RetryPolicy | None = None):
        self._np = int(np)
        self.checkpoint_dir = checkpoint_dir
        self.save_every = save_every
        self.max_restarts = int(max_restarts)
        self._devices = devices
        # the shared RetryPolicy governs restart PACING + classification
        # (max_restarts stays the budget): exponential backoff + jitter
        # between re-launches replaces the old immediate unbounded-rate
        # re-spawn — a gang dying in a tight loop no longer hammers the
        # backend while it is down (TPUDL_TRAIN_RESTART_BACKOFF_S base)
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=self.max_restarts + 1,
            backoff_s=_restart_backoff_base_s(), max_backoff_s=30.0,
            transient="all")

    def _build_mesh(self):
        devs = list(self._devices) if self._devices else jax.devices()
        n = abs(self._np) if self._np != 0 else len(devs)
        if n > len(devs):
            raise ValueError(
                f"HorovodRunner(np={self._np}) needs {n} devices, have "
                f"{len(devs)} ({devs[0].platform})")
        # TPUDL_MESH_MODEL>1 folds the same n devices into a 2-D
        # (data, model) grid — np keeps meaning TOTAL chips (the
        # reference's contract), the model axis comes out of it
        n_model = M.model_axis_size()
        if n % n_model:
            raise ValueError(
                f"HorovodRunner(np={self._np}): {n} devices do not "
                f"divide into TPUDL_MESH_MODEL={n_model} model shards")
        return M.build_mesh(n_data=n // n_model, n_model=n_model,
                            devices=devs[:n])

    def run(self, main, **kwargs):
        """Run ``main(ctx, **kwargs)`` over the mesh; on exception,
        re-launch up to ``max_restarts`` times (gang restart semantics —
        main must resume from its checkpoints; Trainer does)."""
        mesh = self._build_mesh()
        ctx = TrainContext(mesh, self.checkpoint_dir, self.save_every)
        attempt = 0
        while True:
            ctx.attempt = attempt
            try:
                with _obs_tracer.span("train.run", attempt=attempt,
                                      mesh_size=ctx.size):
                    with M.use_mesh(mesh):
                        return main(ctx, **kwargs)
            except Exception as e:
                if is_fatal(e) or not self.retry_policy.is_transient(e):
                    # a Preempted unwind (or a classified-permanent
                    # failure) is an orderly stop, not a gang death:
                    # restarting would fight the scheduler/caller
                    raise
                attempt += 1
                # the step the gang died at (train.last_step gauge, set
                # by Trainer.fit's finally) + the triggering exception
                # go into the flight recorder: max_restarts exhaustion
                # then explains WHY, not just how often (the
                # train.restarts counter alone couldn't)
                last_step = _obs_metrics.gauge("train.last_step").value
                _obs_flight.get_recorder().record_restart(
                    attempt, e, step=last_step,
                    max_restarts=self.max_restarts)
                if attempt > self.max_restarts:
                    _obs_flight.record_error(
                        "train.exhausted", e, attempts=attempt,
                        max_restarts=self.max_restarts, step=last_step)
                    raise RestartsExhausted(attempt, e) from e
                # restart count is a first-class metric (a silently
                # restarting gang looks healthy in logs-only setups);
                # pacing via the shared policy: exponential backoff +
                # jitter, published so a backing-off gang is visible
                _obs_metrics.counter("train.restarts").inc()
                self.retry_policy.record("train.restart", e,
                                         attempt=attempt)
                delay = self.retry_policy.backoff_s(attempt)
                _obs_metrics.histogram(
                    "train.restart_backoff_s").observe(delay)
                log.exception(
                    "train_fn failed; gang restart %d/%d from last "
                    "checkpoint in %.2fs", attempt, self.max_restarts,
                    delay)
                if delay > 0:
                    # tpudl: ignore[adhoc-retry] — the pacing COMES
                    # from the shared RetryPolicy (recorded above);
                    # this sleep is the gang-restart boundary itself
                    time.sleep(delay)


class Trainer:
    """Step-loop engine: sharded batches → one jitted SPMD step, periodic
    orbax checkpoints, resume, throughput metrics.

    ``data_fn(step) -> tuple_of_host_arrays`` must be stateless in
    ``step`` (index-addressable), which makes the data cursor exactly the
    step counter — resume is then correct by construction.
    """

    def __init__(self, loss_fn, optimizer, *, mesh=None,
                 checkpoint_dir=None, save_every=100, log_every=0,
                 param_shardings=None):
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh
        self.checkpoint_dir = checkpoint_dir
        self.save_every = save_every
        self.log_every = log_every
        # tensor parallelism through the standard Trainer: a pytree of
        # NamedSharding matching params (e.g. TinyCausalLM
        # .param_shardings(mesh)) — params and optimizer state then live
        # SHARDED over the model axis for the whole fit, checkpoints
        # included (orbax round-trips the shardings via `like`)
        self.param_shardings = param_shardings
        if param_shardings is not None and mesh is None:
            raise ValueError(
                "param_shardings without mesh= would be silently ignored "
                "— pass the mesh the shardings were built on")
        self.history: list[dict] = []
        # one compiled SPMD program per Trainer: rebuilding the jit wrapper
        # per fit() would retrace+recompile every call (loss_fn/optimizer/
        # mesh are fixed at construction, so the program is too)
        self._step_fn = make_train_step(loss_fn, optimizer, mesh,
                                        param_shardings=param_shardings)

    def fit(self, params, data_fn, steps: int, *,  # tpudl: hot-path
            opt_state=None, stop=None):
        """Train for ``steps`` total steps (resuming included). Returns
        (params, opt_state, history).

        ``stop`` (optional zero-arg callable → bool) is the cooperative
        preemption check, polled at every step boundary: when it turns
        truthy the trainer force-saves a checkpoint AT THE CURRENT STEP
        (when a ``checkpoint_dir`` is configured) and raises
        :class:`Preempted` — the checkpoint-then-exit half of the job
        runtime's SIGTERM contract (JOBS.md), with resume rework bounded
        at zero steps on the graceful path (≤ ``save_every`` when the
        save itself is lost)."""
        self.history = []  # per-fit; stale entries would misreport results

        # own the buffers: the step donates params/opt_state, and device_put
        # may alias the caller's arrays — donating an alias would delete the
        # caller's data out from under them. Host arrays are copied
        # host-side; mesh-spanning device trees are copied by a jitted
        # identity (fresh output buffers, SAME shardings — an np.asarray
        # here would gather a TP-sharded state to host, losing its
        # layout and failing outright on multi-host non-addressable
        # shards).
        mesh_devices = (set(self.mesh.devices.flat)
                        if self.mesh is not None else None)

        def _spans_mesh(x):
            sh = getattr(x, "sharding", None)
            return (sh is not None and mesh_devices is not None
                    and sh.device_set == mesh_devices)

        def _own(tree):
            if all(_spans_mesh(leaf) for leaf in jax.tree.leaves(tree)):
                return _owning_identity()(tree)
            return jax.tree.map(np.asarray, tree)

        params = _own(params)
        if opt_state is not None:
            opt_state = _own(opt_state)
        if self.mesh is not None and not all(
                _spans_mesh(leaf) for leaf in jax.tree.leaves(params)):
            if self.param_shardings is not None:
                # typed refusal BEFORE any transfer when the per-device
                # share exceeds TPUDL_DATA_HBM_BUDGET_MB (the "widen the
                # model axis" signal, same rail as zoo shard_params)
                M.require_hbm_fit(params, self.param_shardings,
                                  what="model-sharded params")
                params = jax.tree.map(jax.device_put, params,
                                      self.param_shardings)
                # an opt_state built from SHARDED params gets sharded
                # moment buffers for free
            else:
                params = M.replicate(params, self.mesh)
        fresh_opt = opt_state is None
        if fresh_opt:
            opt_state = self.optimizer.init(params)
        if self.mesh is not None and any(
                not _spans_mesh(leaf)
                for leaf in jax.tree.leaves(opt_state)):
            # optax states mix param-shaped buffers with FRESH scalars
            # (adam's `count`) that land on one default device — a
            # mixed-device jit call is an error. Param-shaped leaves get
            # the sharding the optimizer WOULD give them when built from
            # the placed params (so a caller-passed host state on the TP
            # path comes back model-SHARDED, not replicated — replicated
            # fp32 moments defeat the point of TP); everything else is
            # replicated. A freshly-built state is its own template;
            # otherwise the template is derived structurally from
            # param_shardings WITHOUT materializing a second opt state.
            # Zero-allocation routes that DON'T work (tried,
            # review-caught): eval_shape loses shardings entirely, and
            # AOT output_shardings of optimizer.init come back
            # replicated/single-device (XLA leaves trivial zeros_like
            # outputs unconstrained). What does: optax embeds the
            # params PYTREE verbatim in its moment subtrees, so a state
            # leaf whose path ends with a param's full path (and
            # matches its shape) takes that param's sharding; scalars
            # and everything else replicate.
            if fresh_opt or self.param_shardings is None:
                # each leaf's own sharding (None for host leaves)
                template = jax.tree.map(
                    lambda leaf: getattr(leaf, "sharding", None),
                    opt_state)
            else:
                from jax.tree_util import (tree_flatten_with_path,
                                           tree_map_with_path)

                sh_flat = tree_flatten_with_path(self.param_shardings)[0]
                p_flat = tree_flatten_with_path(params)[0]
                suffix = {tuple(str(k) for k in path): (sh, leaf.shape)
                          for (path, sh), (_p, leaf)
                          in zip(sh_flat, p_flat)}
                struct = jax.eval_shape(self.optimizer.init, params)

                def _sh_for(path, leaf):
                    keys = tuple(str(k) for k in path)
                    # + 1: the EMPTY suffix must be tried too — a
                    # bare-leaf params tree has path (), and any state
                    # leaf whose shape matches it is its moment
                    for start in range(len(keys) + 1):
                        hit = suffix.get(keys[start:])
                        if hit and hit[1] == leaf.shape:
                            return hit[0]
                    return None

                template = tree_map_with_path(_sh_for, struct)

            def _sharding_spans(sh):
                try:
                    return (sh is not None
                            and sh.device_set == mesh_devices)
                except Exception:  # AbstractMesh shardings
                    return False

            def _place_like(x, ref_sh):
                if _spans_mesh(x):
                    return x
                target = (ref_sh if _sharding_spans(ref_sh)
                          else M.replicated(self.mesh))
                return jax.device_put(np.asarray(x), target)

            opt_state = jax.tree.map(_place_like, opt_state, template)
            del template

        start = 0
        mgr = None
        if self.checkpoint_dir is not None:
            mgr = CheckpointManager(self.checkpoint_dir,
                                    save_every=self.save_every)
            # `like` is built AFTER placement, so restored arrays come
            # back with the same (possibly TP-sharded) shardings
            like = {"params": params, "opt_state": opt_state,
                    "step": np.asarray(0, np.int64)}
            t_ck = time.perf_counter()
            restored = mgr.restore(like=like)
            if restored is not None:
                _obs_metrics.histogram(
                    "train.checkpoint_restore_seconds").observe(
                        time.perf_counter() - t_ck)
                params = restored["params"]
                opt_state = restored["opt_state"]
                start = int(restored["step"])
                log.info("resumed from checkpoint at step %d", start)
            # the pre-restore placed buffers (still referenced by `like`)
            # would otherwise pin ~2x params+opt HBM for the whole fit
            del like, restored

        step_fn = self._step_fn

        # Multi-host: data_fn returns THIS host's slice of the global
        # batch (use tpudl.distributed.host_shard to pick the host's
        # files); slices assemble into one globally-sharded array whose
        # collectives ride ICI/DCN (SURVEY.md §5.8 input data plane).
        # Single host, multi-device: plain shard_batch. A 1-wide data
        # axis needs no explicit sharding: host arrays go straight into
        # the jitted step, whose own arg transfer pipelines (an explicit
        # per-step device_put serializes on tunneled backends).
        multi_host = self.mesh is not None and D.process_count() > 1
        shard_inputs = (self.mesh is not None
                        and self.mesh.shape[M.DATA_AXIS] > 1)
        t0 = time.perf_counter()
        examples = 0
        executed = 0  # steps actually run (a failed run must not
        loss = None   # report the PLANNED count to the registry)
        # per-step loop time (dispatch cadence: async device dispatch
        # returns early, so this is the host loop's view — the honest
        # wall denominator is examples_per_sec in history) and
        # checkpoint save durations, published run-wide
        step_hist = _obs_metrics.histogram("train.step_seconds")
        ckpt_hist = _obs_metrics.histogram("train.checkpoint_save_seconds")
        # watchdog heartbeat: one beat per step — a wedged data_fn or a
        # hung device dispatch flags a stall naming the step it froze
        # at; train.last_step feeds the runner's restart forensics
        step_gauge = _obs_metrics.gauge("train.last_step")
        hb = _obs_watchdog.heartbeat("train.fit", steps=steps,
                                     start=start)
        try:
            for step in range(start, steps):
                if stop is not None and stop():
                    # checkpoint-then-exit: the state BEFORE this step
                    # is saved at `step` (steps 0..step-1 completed), so
                    # an identical relaunch resumes with zero re-work
                    if mgr is not None:
                        t_ck = time.perf_counter()
                        mgr.save(step, {"params": params,
                                        "opt_state": opt_state,
                                        "step": np.asarray(step, np.int64)},
                                 force=True)
                        ckpt_hist.observe(time.perf_counter() - t_ck)
                    raise Preempted(step, saved=mgr is not None)
                # step + examples ride the beat: the live status plane
                # (obs top) shows training progress from the heartbeat
                # info without a second instrumentation channel
                hb.beat(step=step, examples=examples)
                # fault point for the preemption suite: a FaultPlan can
                # SIGTERM-to-self or raise at an exact step (unarmed:
                # one global None-check)
                _faults.fire("train.step", step=step)
                t_step = time.perf_counter()
                batch = data_fn(step)
                if not isinstance(batch, tuple):
                    batch = (batch,)
                if multi_host:
                    batch = tuple(
                        # tpudl: ignore[hot-sync] — data_fn yields HOST
                        # arrays; this asarray is the H2D staging copy
                        # of the local shard, not a device round-trip
                        D.global_batch(np.asarray(b), self.mesh)
                        for b in batch)
                elif shard_inputs:
                    # ONE batched async transfer for the whole step
                    # tuple (mesh.transfer_batch underneath — the same
                    # edge the frame executor and the estimator use)
                    batch = M.shard_batch(batch, self.mesh)
                params, opt_state, loss = step_fn(params, opt_state, *batch)
                step_hist.observe(time.perf_counter() - t_step)
                step_gauge.set(step + 1)
                executed += 1
                examples += int(np.shape(batch[0])[0])
                # attribution: training rows consumed under the
                # caller's scope — fit publishes on the calling thread,
                # so the contextvar needs no explicit carry here
                _attr.charge("rows_in", int(np.shape(batch[0])[0]))
                done = step + 1
                if mgr is not None and done < steps:
                    t_ck = time.perf_counter()
                    if mgr.maybe_save(done, {"params": params,
                                             "opt_state": opt_state,
                                             "step": np.asarray(done, np.int64)}):
                        ckpt_hist.observe(time.perf_counter() - t_ck)
                        log.debug("checkpoint at step %d", done)
                if self.log_every and done % self.log_every == 0:
                    dt = time.perf_counter() - t0
                    # tpudl: ignore[hot-sync] — opt-in loss logging:
                    # the fetch is the feature, paid once per
                    # log_every steps and off by default
                    l = float(jax.device_get(loss))
                    self.history.append(
                        {"step": done, "loss": l,
                         "examples_per_sec": examples / max(dt, 1e-9)})
                    log.info("step %d loss %.5f (%.1f ex/s)", done, l,
                             examples / max(dt, 1e-9))
            if loss is not None and (not self.history
                                     or self.history[-1]["step"] != steps):
                dt = time.perf_counter() - t0
                self.history.append(
                    {"step": steps,
                     # tpudl: ignore[hot-sync] — after the last step:
                     # the run's final loss fetch, no pipeline behind it
                     "loss": float(jax.device_get(loss)),
                     "examples_per_sec": examples / max(dt, 1e-9)})
            if mgr is not None and steps > start:
                t_ck = time.perf_counter()
                mgr.save(steps, {"params": params, "opt_state": opt_state,
                                 "step": np.asarray(steps, np.int64)}, force=True)
                ckpt_hist.observe(time.perf_counter() - t_ck)
        finally:
            hb.__exit__(None, None, None)
            if mgr is not None:
                mgr.close()
            _obs_metrics.counter("train.steps").inc(executed)
            _obs_metrics.counter("train.examples").inc(examples)
            _obs_metrics.get_registry().maybe_flush()
        return params, opt_state, self.history
