"""Distributed training layer (SURVEY.md §2.1 L7 / §5.8).

HorovodRunner's MPI+NCCL contract re-owned as SPMD over the jax mesh:
see :mod:`tpudl.train.runner` (Runner/Trainer), :mod:`tpudl.train.step`
(the allreduce-equivalent jitted step), :mod:`tpudl.train.checkpoint`
(atomic checksummed checkpoint/resume — first-class, unlike the
reference). ``Preempted``/``RestartsExhausted`` are the typed edges the
job runtime (tpudl.jobs, JOBS.md) builds its preemption contract on.
"""

from tpudl.train.checkpoint import CheckpointManager
from tpudl.train.runner import (HorovodRunner, Preempted,
                                RestartsExhausted, TrainContext, Trainer)
from tpudl.train.step import (make_eval_step, make_train_step,
                              with_compute_dtype)

__all__ = [
    "HorovodRunner",
    "TrainContext",
    "Trainer",
    "CheckpointManager",
    "Preempted",
    "RestartsExhausted",
    "make_train_step",
    "make_eval_step",
    "with_compute_dtype",
]
