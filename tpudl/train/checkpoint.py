"""Checkpoint / resume — first-class, unlike the reference.

SURVEY.md §5.4: the reference only *consumes* checkpoints
(TFInputGraph.fromCheckpoint) and returns final HDF5 blobs; there is no
periodic checkpoint/resume loop anywhere in its tree. Here it is a core
subsystem: atomic checksummed snapshots of the whole training state
(params + opt_state + step + data cursor), periodic saves, latest-wins
restore — the substrate for the Runner's fault recovery (§5.3: SPMD
programs die together; recovery is restart-from-last-checkpoint) and
the job runtime's resume state (JOBS.md).

Durability contract (the shard-manifest contract, applied to model
state — a checkpoint a preempted run will bet its resume on must be
trustworthy the way the prepared-batch cache is):

- **atomic writes** — each step is ONE ``ckpt-<step>.npz`` written to
  a temp name and ``os.replace``d into place, then indexed in
  ``ckpt-manifest.json`` (itself tmp+rename). A kill at ANY byte
  leaves either the previous state or the new one, never a torn file
  that parses;
- **checksums** — the manifest records crc32 + byte size per
  checkpoint; ``restore`` verifies before trusting;
- **corruption → fall back, not crash** — a truncated/bit-flipped/
  unparseable newest checkpoint is dropped (``train.checkpoint.corrupt``
  counter + a flight-recorder error sample) and ``restore()`` falls
  back to the newest VALID step; only when no step survives does it
  return None (fresh start — the honest answer).

Leaves are stored as raw bytes + (shape, dtype) metadata rather than
native ``.npy`` entries: ``np.save`` silently degrades non-builtin
dtypes (bfloat16 → V2 void), and a checkpoint that changes dtype on
round-trip is corruption with extra steps. ``restore(like=...)`` puts
each leaf back onto the `like` leaf's sharding, so TP-sharded state
comes back device-sharded (not gathered). Scope: ``save`` gathers
single-host sharded leaves to host bytes; state spanning
NON-addressable devices (multi-host) is refused with a clear error —
gather it (``multihost_utils.process_allgather``) before saving.
"""

from __future__ import annotations

import io
import json
import os
import threading

import jax
import numpy as np

# the ONE chunked-crc32 helper (tools/validate_job.py keeps its own
# copy on purpose: validators stay stdlib-pure, importing no tpudl)
from tpudl.data.shards import _crc32_file
from tpudl.testing import tsan as _tsan

__all__ = ["CheckpointManager", "CheckpointCorruption", "as_numpy_state"]

MANIFEST_NAME = "ckpt-manifest.json"
MANIFEST_SCHEMA = "tpudl-checkpoint-manifest"
MANIFEST_VERSION = 1
PAYLOAD_VERSION = 1


class CheckpointCorruption(Exception):
    """A checkpoint failed its integrity check (restore() converts it
    into a fallback to the next-newest valid step)."""


def _resolve_dtype(name: str) -> np.dtype:
    """dtype by saved name, including the ml_dtypes extended set
    (bfloat16, float8_*) numpy alone cannot construct by string."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _path_components(path) -> list:
    """One tree_flatten_with_path key path → JSON-able components
    (enough to rebuild nested dict/list states for like-less restore;
    exotic containers round-trip through ``like=`` instead)."""
    comps = []
    for k in path:
        if hasattr(k, "key"):
            comps.append({"t": "key", "k": str(k.key)})
        elif hasattr(k, "idx"):
            comps.append({"t": "idx", "i": int(k.idx)})
        elif hasattr(k, "name"):
            comps.append({"t": "attr", "k": str(k.name)})
        else:  # pragma: no cover - future key kinds
            comps.append({"t": "key", "k": str(k)})
    return comps


class CheckpointManager:
    """Atomic checksummed store of the {params, opt_state, step, ...}
    training-state pytree under one directory."""

    def __init__(self, directory: str, *, save_every: int = 100,
                 max_to_keep: int = 3):
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self.save_every = int(save_every)
        self.max_to_keep = int(max_to_keep)
        self._lock = _tsan.named_lock("train.checkpoint.manifest")
        self._manifest: dict[str, dict] = {}
        self._load_manifest()

    # -- manifest ----------------------------------------------------------
    def _manifest_path(self) -> str:
        return os.path.join(self._dir, MANIFEST_NAME)

    def _file_for(self, step: int) -> str:
        return os.path.join(self._dir, f"ckpt-{int(step):08d}.npz")

    def _load_manifest(self) -> None:
        try:
            with open(self._manifest_path()) as f:
                m = json.load(f)
            if (isinstance(m, dict) and m.get("schema") == MANIFEST_SCHEMA
                    and isinstance(m.get("checkpoints"), dict)):
                self._manifest = m["checkpoints"]
            else:
                self._manifest = {}
        except (OSError, json.JSONDecodeError):
            self._manifest = {}

    def _write_manifest_locked(self) -> None:
        """Raises OSError on failure: ``save()`` must not report a
        checkpoint durable-and-indexed when the index write was lost —
        an unindexed file is only reachable through the orphan scan,
        which cannot size/crc-verify it. Maintenance callers (prune,
        corrupt-drop) tolerate the failure themselves."""
        m = {"schema": MANIFEST_SCHEMA, "version": MANIFEST_VERSION,
             "checkpoints": self._manifest}
        tmp = self._manifest_path() + f".tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(m, f)
            os.replace(tmp, self._manifest_path())
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    # -- write -------------------------------------------------------------
    def save(self, step: int, state: dict, *, force: bool = False) -> bool:
        """Save if ``step`` hits the cadence (or ``force``). Blocking
        and durable-before-return is deliberate: resume-equivalence
        (and the job runtime's bounded-rework contract) require the
        write to be on disk before the step counter advances."""
        if not force and (self.save_every <= 0
                          or step % self.save_every != 0):
            return False
        leaves = jax.tree_util.tree_flatten_with_path(state)[0]
        meta = {"version": PAYLOAD_VERSION, "step": int(step),
                "leaves": []}
        entries: dict[str, np.ndarray] = {}
        for i, (path, leaf) in enumerate(leaves):
            if getattr(leaf, "is_fully_addressable", True) is False:
                # multi-host sharded state: np.asarray would raise an
                # opaque RuntimeError mid-save. Name the gap instead —
                # this store checkpoints host-visible state; gather
                # (multihost_utils.process_allgather) before saving
                raise NotImplementedError(
                    f"CheckpointManager.save: leaf "
                    f"{jax.tree_util.keystr(path)} spans non-"
                    "addressable devices (multi-host sharding); gather "
                    "it host-side before checkpointing")
            # NOT ascontiguousarray: it silently promotes 0-d scalars
            # to shape (1,); tobytes() already yields C-order bytes for
            # any layout
            arr = np.asarray(leaf)
            entries[f"leaf_{i:05d}"] = np.frombuffer(
                arr.tobytes(), dtype=np.uint8)
            meta["leaves"].append({
                "key": jax.tree_util.keystr(path),
                "path": _path_components(path),
                "shape": list(arr.shape), "dtype": str(arr.dtype)})
        entries["__meta__"] = np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8)
        out = self._file_for(step)
        tmp = out + f".tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            with open(tmp, "wb") as f:
                np.savez(f, **entries)
                f.flush()
                os.fsync(f.fileno())
            crc = _crc32_file(tmp)
            nbytes = os.stat(tmp).st_size
            os.replace(tmp, out)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        with self._lock:
            self._manifest[str(int(step))] = {
                "file": os.path.basename(out), "crc32": crc,
                "nbytes": nbytes, "n_leaves": len(leaves)}
            self._write_manifest_locked()
            self._prune_locked()
        return True

    def maybe_save(self, step: int, state: dict) -> bool:
        return self.save(step, state)

    def _prune_locked(self) -> None:
        steps = sorted(int(s) for s in self._manifest)
        for s in steps[: max(0, len(steps) - self.max_to_keep)]:
            entry = self._manifest.pop(str(s), None)
            if entry:
                try:
                    os.unlink(os.path.join(self._dir, entry["file"]))
                except OSError:
                    pass
        if len(steps) > self.max_to_keep:
            try:
                self._write_manifest_locked()
            except OSError:
                # stale manifest entries point at unlinked files; the
                # restore path already treats those as corrupt + drops
                pass

    # -- read --------------------------------------------------------------
    def _candidate_steps(self) -> list[int]:
        """Known steps, newest first: manifest entries plus any orphan
        ``ckpt-*.npz`` a crash left un-indexed (file replaced, manifest
        write lost — the file is durable, so it is a candidate)."""
        with self._lock:
            steps = {int(s) for s in self._manifest}
        try:
            for name in os.listdir(self._dir):
                if name.startswith("ckpt-") and name.endswith(".npz"):
                    try:
                        steps.add(int(name[5:-4]))
                    except ValueError:
                        pass
        except OSError:
            pass
        return sorted(steps, reverse=True)

    def latest_step(self) -> int | None:
        steps = self._candidate_steps()
        return steps[0] if steps else None

    def _load_verified(self, step: int) -> dict:
        """Parse + verify one checkpoint file → {meta, arrays} or raise
        CheckpointCorruption."""
        path = self._file_for(step)
        with self._lock:
            entry = self._manifest.get(str(int(step)))
        try:
            size = os.stat(path).st_size
        except OSError as e:
            raise CheckpointCorruption(f"missing {path}") from e
        if entry is not None:
            if size != entry["nbytes"]:
                raise CheckpointCorruption(
                    f"{path}: size {size} != manifest {entry['nbytes']} "
                    "(truncated or partial write)")
            if _crc32_file(path) != entry["crc32"]:
                raise CheckpointCorruption(
                    f"{path}: crc32 mismatch (bit rot or torn write)")
        try:
            with open(path, "rb") as f:
                blob = f.read()
            z = np.load(io.BytesIO(blob), allow_pickle=False)
            meta = json.loads(bytes(z["__meta__"]).decode())
            arrays = []
            for i, lf in enumerate(meta["leaves"]):
                dt = _resolve_dtype(lf["dtype"])
                buf = z[f"leaf_{i:05d}"]
                want = int(np.prod(lf["shape"], dtype=np.int64)) * dt.itemsize
                if buf.nbytes != want:
                    raise CheckpointCorruption(
                        f"{path}: leaf {i} has {buf.nbytes} bytes, "
                        f"expected {want}")
                arrays.append(np.frombuffer(
                    buf.tobytes(), dtype=dt).reshape(lf["shape"]))
        except CheckpointCorruption:
            raise
        except Exception as e:  # zip/json/npy damage of any shape
            raise CheckpointCorruption(f"{path}: unreadable ({e!r})") from e
        return {"meta": meta, "arrays": arrays}

    def _drop(self, step: int, reason: str) -> None:
        from tpudl.obs import flight as _flight
        from tpudl.obs import metrics as _metrics

        _metrics.counter("train.checkpoint.corrupt").inc()
        _flight.record_error("train.checkpoint.corrupt", reason,
                             step=int(step), dir=self._dir)
        with self._lock:
            if self._manifest.pop(str(int(step)), None) is not None:
                try:
                    self._write_manifest_locked()
                except OSError:
                    pass  # in-memory drop still prevents re-reads
        try:
            os.unlink(self._file_for(step))
        except OSError:
            pass

    def restore(self, step: int | None = None, *, like: dict | None = None):
        """Restore the state pytree at ``step`` (default: the newest
        VALID step — a corrupt newest checkpoint falls back to its
        predecessor instead of crashing the resume). ``like`` provides
        the target structure/shardings: each restored leaf is placed
        onto the corresponding ``like`` leaf's sharding, so TP-sharded
        state comes back device-sharded. Returns None when nothing
        restorable exists."""
        if step is not None:
            payload = self._load_verified(step)  # explicit step: raise
            return self._rebuild(payload, like)
        for cand in self._candidate_steps():
            try:
                payload = self._load_verified(cand)
            except CheckpointCorruption as e:
                self._drop(cand, repr(e))
                continue
            return self._rebuild(payload, like)
        return None

    def _rebuild(self, payload: dict, like: dict | None):
        meta, arrays = payload["meta"], payload["arrays"]
        if like is not None:
            flat, treedef = jax.tree_util.tree_flatten(like)
            keys = [jax.tree_util.keystr(p) for p, _ in
                    jax.tree_util.tree_flatten_with_path(like)[0]]
            saved = [lf["key"] for lf in meta["leaves"]]
            if keys != saved:
                raise ValueError(
                    f"checkpoint structure does not match `like`: saved "
                    f"leaves {saved[:4]}... vs target {keys[:4]}...")
            placed = []
            for ref, arr in zip(flat, arrays):
                sharding = getattr(ref, "sharding", None)
                if sharding is not None:
                    placed.append(jax.device_put(arr, sharding))
                elif hasattr(ref, "devices"):  # jax array, default place
                    placed.append(jax.device_put(arr))
                else:
                    placed.append(np.array(arr))  # writable host copy
            return jax.tree_util.tree_unflatten(treedef, placed)
        # like-less restore: rebuild nested dict/list containers from
        # the recorded path components (attr paths degrade to dict keys
        # — pass `like=` for exotic containers, as the Trainer does)
        root: dict | list | None = None

        def _place(container, comps, value):
            head, rest = comps[0], comps[1:]
            key = head["k"] if head["t"] in ("key", "attr") else head["i"]
            if not rest:
                if isinstance(container, list):
                    while len(container) <= key:
                        container.append(None)
                container[key] = value
                return
            nxt_is_idx = rest[0]["t"] == "idx"
            if isinstance(container, list):
                while len(container) <= key:
                    container.append(None)
                if container[key] is None:
                    container[key] = [] if nxt_is_idx else {}
                _place(container[key], rest, value)
            else:
                child = container.setdefault(
                    key, [] if nxt_is_idx else {})
                _place(child, rest, value)

        for lf, arr in zip(meta["leaves"], arrays):
            comps = lf["path"]
            if not comps:
                return np.array(arr)  # bare-leaf state
            if root is None:
                root = [] if comps[0]["t"] == "idx" else {}
            _place(root, comps, np.array(arr))
        return root

    # -- maintenance -------------------------------------------------------
    def validate(self) -> list[str]:
        """Integrity errors across every known step (the audit path
        ``tools/validate_job.py`` drives); empty = clean."""
        errs = []
        for s in self._candidate_steps():
            try:
                self._load_verified(s)
            except CheckpointCorruption as e:
                errs.append(str(e))
        return errs

    def close(self):
        pass  # every save is already durable; kept for API compat

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()




def as_numpy_state(state: dict) -> dict:
    """Device pytree → host numpy (for handing across process restarts)."""
    return jax.tree.map(lambda x: np.asarray(x), state)
