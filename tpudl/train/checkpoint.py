"""Checkpoint / resume — first-class, unlike the reference.

SURVEY.md §5.4: the reference only *consumes* checkpoints
(TFInputGraph.fromCheckpoint) and returns final HDF5 blobs; there is no
periodic checkpoint/resume loop anywhere in its tree. Here it is a core
subsystem: orbax-backed sharded checkpoints of the whole training state
(params + opt_state + step + data cursor), periodic saves, latest-wins
restore — the substrate for the Runner's fault recovery (§5.3: SPMD
programs die together; recovery is restart-from-last-checkpoint).
"""

from __future__ import annotations

import os

import jax
import numpy as np

__all__ = ["CheckpointManager"]


class CheckpointManager:
    """Thin veneer over orbax's CheckpointManager holding the
    {params, opt_state, step, cursor} training-state pytree."""

    def __init__(self, directory: str, *, save_every: int = 100,
                 max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self.save_every = int(save_every)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
        )

    # -- write -------------------------------------------------------------
    def save(self, step: int, state: dict, *, force: bool = False) -> bool:
        """Save if ``step`` hits the cadence (or ``force``). Blocking save
        is deliberate: resume-equivalence tests require the write to be
        durable before the step counter advances."""
        import orbax.checkpoint as ocp

        if not force and (self.save_every <= 0
                          or step % self.save_every != 0):
            return False
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        self._mgr.wait_until_finished()
        return True

    def maybe_save(self, step: int, state: dict) -> bool:
        return self.save(step, state)

    # -- read --------------------------------------------------------------
    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, step: int | None = None, *, like: dict | None = None):
        """Restore the state pytree at ``step`` (default latest). ``like``
        provides the target structure/shardings (orbax restores device-
        sharded arrays directly when given abstract targets)."""
        import orbax.checkpoint as ocp

        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        if like is not None:
            abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, like)
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(abstract))
        return self._mgr.restore(step)

    def close(self):
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def as_numpy_state(state: dict) -> dict:
    """Device pytree → host numpy (for handing across process restarts)."""
    return jax.tree.map(lambda x: np.asarray(x), state)
