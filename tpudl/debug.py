"""Numerical-debug hooks — SURVEY.md §5.2's moral equivalents.

The reference has no sanitizers (thread-safety by frozen-protobuf
avoidance); the survey prescribes the JAX-native analogues for the
rebuild: ``jax_debug_nans`` for device-side NaN provenance and
``checkify`` for value checks inside jitted programs. Host-side input
checking lives in ``Frame.map_batches(check_finite=True)`` (the input
pipeline is host-side; a numpy check there is free and catches bad rows
before they poison a fused device program).
"""

from __future__ import annotations

import contextlib

import jax

__all__ = ["debug_nans", "checkify_fn"]


@contextlib.contextmanager
def debug_nans(enable: bool = True):
    """Within the block, any NaN produced by a jitted program raises with
    the op that made it (re-runs un-jitted on failure — debugging tool,
    not a production mode)."""
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", bool(enable))
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


def checkify_fn(fn, *, nan: bool = True, div: bool = True,
                oob: bool = True):
    """Wrap a jax-traceable ``fn`` with ``checkify`` error instrumentation
    (NaN production, division, out-of-bounds indexing — the survey's
    bounds checks for the input pipeline). The wrapper is jittable; the
    first error raises ``jax.experimental.checkify.JaxRuntimeError`` at
    call time instead of silently propagating garbage."""
    from jax.experimental import checkify

    errors = set()
    if nan:
        errors |= checkify.nan_checks
    if div:
        errors |= checkify.div_checks
    if oob:
        errors |= checkify.index_checks
    checked = checkify.checkify(fn, errors=errors)

    def wrapper(*args, **kwargs):
        err, out = checked(*args, **kwargs)
        checkify.check_error(err)
        return out

    return wrapper
