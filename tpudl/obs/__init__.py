"""Observability: host-span tracing, process-wide metrics, device traces.

SURVEY.md §5.1/§5.5: the reference had NO first-party tracing or metrics
(observability was inherited from the Spark UI). This package is the
run-wide subsystem that replaces it (OBSERVABILITY.md is the operator
guide), three pillars:

- :mod:`tpudl.obs.tracer` — host-span tracer: ``obs.span("stage")``
  records thread-aware wall-clock spans into a bounded ring, exportable
  as Chrome trace-event JSON;
- :mod:`tpudl.obs.metrics` — process-wide metrics registry: thread-safe
  counters/gauges/bounded-histograms with ``snapshot()`` and an opt-in
  JSONL sink (``TPUDL_METRICS_FILE``);
- :mod:`tpudl.obs.trace` — jax.profiler capture + trace-viewer parsing,
  and the host/device MERGE: ``python -m tpudl.obs trace <dir>`` renders
  host spans and XLA device lanes on one timeline with a combined
  summary (device busy %, host stage totals, overlap).

Per-run executor reports (:class:`PipelineReport`) live in
:mod:`tpudl.obs.pipeline`, kept in a bounded ring keyed by run id.

The black-box layer (OBSERVABILITY.md "Failure forensics"):

- :mod:`tpudl.obs.flight` — always-on bounded flight recorder;
  ``obs.dump()`` (or an unhandled exception / SIGTERM / SIGQUIT after
  ``obs.flight.install()``) writes a self-contained
  ``tpudl-dump-<pid>.json.gz``;
- :mod:`tpudl.obs.watchdog` — heartbeat registry + stall daemon
  (``TPUDL_WATCHDOG_STALL_S``); stalls snapshot every thread's stack
  into the recorder and bump ``obs.watchdog.stalls``;
- :mod:`tpudl.obs.doctor` — ``python -m tpudl.obs doctor <dump|dir>``
  merges per-host dumps and classifies the failure.

The live ops plane (OBSERVABILITY.md "Live ops plane"):

- :mod:`tpudl.obs.roofline` — per-run roofline attribution:
  ``obs.analyze_roofline()`` decomposes achieved vs achievable
  throughput across prepare/wire/dispatch/d2h, publishes
  ``obs.roofline.*`` gauges, and the knob advisor recommends concrete
  ``fuse_steps``/``prefetch_depth``/``prepare_workers``/``wire_codec``
  settings with predicted gain;
- :mod:`tpudl.obs.live` — every instrumented process writes an atomic
  ``tpudl-status-<pid>.json`` (``TPUDL_STATUS_DIR``);
  ``python -m tpudl.obs top <dir>`` renders the refreshing live view.

The attribution plane (OBSERVABILITY.md "Attribution plane"):

- :mod:`tpudl.obs.attribution` — ``obs.scope(tenant=..., job=...,
  run=...)`` tags every publish on the calling thread (carried across
  the executor/serve/HPO pools), and the bounded per-scope resource
  ledger answers WHO used the bytes/rows/tokens/seconds; per-scope
  sums + ``unattributed`` reconcile EXACTLY against the global
  counters (``python -m tpudl.obs ledger <dir>`` offline).
"""

from __future__ import annotations

from tpudl.obs.attribution import (Scope, carry, charge, current_scope,
                                   get_ledger, ledger_snapshot,
                                   ledger_totals, reconcile,
                                   reset_ledger, scope)
from tpudl.obs.flight import dump, get_recorder, record_error
from tpudl.obs.live import (ensure_status_writer, start_status_writer,
                            stop_status_writer, write_status)
from tpudl.obs.roofline import RooflineReport, advise, autotune_seed
from tpudl.obs.roofline import analyze as analyze_roofline
from tpudl.obs.metrics import (Meter, counter, flush_metrics, gauge,
                               get_registry, histogram, snapshot, timed)
from tpudl.obs.watchdog import heartbeat, start_watchdog
from tpudl.obs.pipeline import (PipelineReport, get_pipeline_report,
                                last_pipeline_report, pipeline_reports,
                                set_last_pipeline)
from tpudl.obs.trace import (load_host_trace_events, load_trace_events,
                             merge_trace_events, named_scope, profile,
                             summarize_device_trace, summarize_merged)
from tpudl.obs.tracer import export_chrome_trace, get_tracer, span

__all__ = [
    # attribution plane (scoped ledgers)
    "Scope", "scope", "current_scope", "carry", "charge",
    "get_ledger", "reset_ledger", "ledger_snapshot", "ledger_totals",
    "reconcile",
    # tracer
    "span", "get_tracer", "export_chrome_trace",
    # metrics
    "counter", "gauge", "histogram", "snapshot", "flush_metrics",
    "get_registry", "timed", "Meter",
    # device traces + merge
    "profile", "named_scope", "load_trace_events",
    "summarize_device_trace", "load_host_trace_events",
    "merge_trace_events", "summarize_merged",
    # per-run pipeline reports
    "PipelineReport", "last_pipeline_report", "set_last_pipeline",
    "pipeline_reports", "get_pipeline_report",
    # failure forensics (flight recorder + watchdog)
    "dump", "get_recorder", "record_error", "heartbeat",
    "start_watchdog",
    # live ops plane (roofline + status files)
    "RooflineReport", "analyze_roofline", "advise",
    "ensure_status_writer", "start_status_writer",
    "stop_status_writer", "write_status",
]
