"""Windowed SLO engine for the serve plane.

Lifetime histograms answer "how has this process done since boot";
an operator paging on latency needs "how are we doing RIGHT NOW". This
module keeps a bounded ring of ``(monotonic_t, latency_ms)`` stamps —
one per completed request — and computes everything over SLIDING
windows:

- **short window** (``TPUDL_SERVE_SLO_WINDOW_S``, default 30 s):
  recent p50/p99, availability against the configured objective, and
  the fast burn rate;
- **long window** (10× short, the classic multi-window pairing): the
  slow burn rate that filters one-spike noise — page when BOTH burn,
  investigate when only the short one does.

**Burn rate** is budget language: a p99 objective grants a 1% error
budget (1 - 0.99). ``burn = (fraction of windowed requests over
``TPUDL_SERVE_SLO_P99_MS``) / 0.01`` — burn 1.0 means spending budget
exactly as fast as it accrues; 10.0 means a day's budget in ~2.4 h.

**Tail exemplars**: a completed request slower than
``TPUDL_SERVE_SLO_TAIL_K`` × the cached windowed median is captured
into the flight recorder's error ring with its full segment breakdown
(queue_wait/batching/prefill/decode, from :mod:`tpudl.serve.reqtrace`)
— the forensic record ``obs doctor``'s ``slo_burn`` rule aggregates to
name WHERE tail time goes.

Discipline: one instance lock (``obs.slo.engine``, locks.py) covers
the stamp ring and cached median; gauges (``serve.slo.*``) and the
exemplar error-ring write happen OUTSIDE it, and gauge publication is
throttled so the per-request hot cost stays a lock + append.
"""

from __future__ import annotations

import os
import time

from collections import deque

from tpudl.obs import metrics as _metrics
from tpudl.obs.metrics import percentile as _percentile
from tpudl.testing import tsan as _tsan

__all__ = ["SloEngine", "get_slo_engine", "reset_slo_engine",
           "ERROR_BUDGET"]

# a p99 objective tolerates 1% of requests over target — the error
# budget every burn rate is normalized against
ERROR_BUDGET = 0.01

# stamp ring bound (matches the histogram sample cap: windows are
# honest up to this many requests per long window)
_RING_CAP = 4096

# gauge publication throttle: windows move slowly; per-request gauge
# math would be pure overhead
_PUBLISH_EVERY_S = 0.25

# tail of short-window samples exported in the status section so a
# multi-process `obs top` can merge a REAL fleet p99 (bounded: the
# status file stays a HUD, not a dump)
_STATUS_SAMPLE_TAIL = 64


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class SloEngine:
    """Sliding-window latency objective tracker for one process's
    serve plane. ``record()`` per completed request (hot path);
    ``compute()``/``status_section()`` for readers; ``publish()`` for
    the ``serve.slo.*`` gauges."""

    def __init__(self):
        self.target_ms = _env_float("TPUDL_SERVE_SLO_P99_MS", 500.0)
        self.window_s = max(1.0,
                            _env_float("TPUDL_SERVE_SLO_WINDOW_S", 30.0))
        self.long_window_s = 10.0 * self.window_s
        self.tail_k = max(1.0, _env_float("TPUDL_SERVE_SLO_TAIL_K", 4.0))
        self._lock = _tsan.named_lock("obs.slo.engine")
        self._stamps: deque = deque(maxlen=_RING_CAP)
        self._median_ms: float | None = None  # cached (exemplar gate)
        self._next_publish = 0.0

    # -- hot path ----------------------------------------------------------
    def record(self, req) -> None:
        """One completed request: append its stamp, capture a tail
        exemplar if it dwarfs the cached windowed median, maybe
        publish. The lock covers only the append + median read."""
        if req.latency_s is None:
            return
        lat_ms = float(req.latency_s) * 1000.0
        now = time.monotonic()
        with self._lock:
            self._stamps.append((now, lat_ms))
            median = self._median_ms
        if median and lat_ms > self.tail_k * median:
            self._exemplar(req, lat_ms, median)
        self.publish(now=now)

    def _exemplar(self, req, lat_ms: float, median: float) -> None:
        # the error ring is the forensic store: descriptors only —
        # trace id, segment milliseconds, never prompt content
        from tpudl.obs import flight as _flight

        trace = getattr(req, "trace", None)
        segs = trace.segments() if trace is not None else None
        ctx = {
            "trace_id": trace.trace_id if trace is not None else None,
            "model": str(req.model),
            "latency_ms": round(lat_ms, 3),
            "window_median_ms": round(median, 3),
            "tail_k": self.tail_k,
        }
        dominant = None
        if segs:
            for name, v in segs.items():
                ctx[f"{name}_ms"] = round(v * 1000.0, 3)
            dominant = max(segs.items(), key=lambda kv: kv[1])[0]
        ctx["dominant_segment"] = dominant
        _flight.record_error(
            "serve.slo.exemplar",
            f"tail request {lat_ms:.0f}ms > {self.tail_k:g}x windowed "
            f"median {median:.0f}ms"
            + (f" (dominant segment: {dominant})" if dominant else ""),
            **ctx)
        _metrics.counter("serve.slo.exemplars").inc()

    # -- window math -------------------------------------------------------
    def _windowed(self, now: float):
        """Short- and long-window latency lists (arrival order), under
        the caller's lock."""
        t_short = now - self.window_s
        t_long = now - self.long_window_s
        short: list = []
        long_: list = []
        for t, ms in self._stamps:
            if t >= t_long:
                long_.append(ms)
                if t >= t_short:
                    short.append(ms)
        return short, long_

    @staticmethod
    def _burn(window: list, target_ms: float):
        if not window:
            return None
        over = sum(1 for ms in window if ms > target_ms)
        return (over / len(window)) / ERROR_BUDGET

    def compute(self, now: float | None = None) -> dict:
        """The full windowed view (and refresh of the cached median).
        Pure host math — safe from any thread."""
        now = time.monotonic() if now is None else now
        with self._lock:
            short, long_ = self._windowed(now)
            short_sorted = sorted(short)
            self._median_ms = _percentile(short_sorted, 0.50)
        n = len(short)
        avail = (sum(1 for ms in short if ms <= self.target_ms) / n
                 if n else None)
        return {
            "target_ms": self.target_ms,
            "window_s": self.window_s,
            "long_window_s": self.long_window_s,
            "window_n": n,
            "window_qps": round(n / self.window_s, 3),
            "window_p50_ms": _percentile(short_sorted, 0.50),
            "window_p99_ms": _percentile(short_sorted, 0.99),
            "availability": avail,
            "burn_short": self._burn(short, self.target_ms),
            "burn_long": self._burn(long_, self.target_ms),
            "window_samples_ms": [round(ms, 3)
                                  for ms in short[-_STATUS_SAMPLE_TAIL:]],
        }

    # -- publication -------------------------------------------------------
    def publish(self, force: bool = False,
                now: float | None = None) -> dict | None:
        """Refresh the ``serve.slo.*`` gauges (throttled unless
        ``force``); returns the computed view when it ran."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if not force and now < self._next_publish:
                return None
            self._next_publish = now + _PUBLISH_EVERY_S
        view = self.compute(now)
        # gauges OUTSIDE the engine lock (locks.py rank discipline)
        _metrics.gauge("serve.slo.target_ms").set(self.target_ms)
        if view["window_n"]:
            _metrics.gauge("serve.slo.window_p50_ms").set(
                view["window_p50_ms"])
            _metrics.gauge("serve.slo.window_p99_ms").set(
                view["window_p99_ms"])
            _metrics.gauge("serve.slo.availability").set(
                view["availability"])
            _metrics.gauge("serve.slo.burn_short").set(
                view["burn_short"])
        if view["burn_long"] is not None:
            _metrics.gauge("serve.slo.burn_long").set(view["burn_long"])
        return view

    def status_section(self) -> dict | None:
        """The ``serve.slo`` block for the live status file (``None``
        until the first request — no empty sections in the HUD)."""
        with self._lock:
            empty = not self._stamps
        if empty:
            return None
        return self.compute()


_ENGINE = SloEngine()


def get_slo_engine() -> SloEngine:
    return _ENGINE


def reset_slo_engine() -> SloEngine:
    """Fresh engine re-reading the env (tests monkeypatch
    ``TPUDL_SERVE_SLO_*`` then reset)."""
    global _ENGINE
    _ENGINE = SloEngine()
    return _ENGINE
