"""Attribution plane: scoped resource ledgers for multi-tenant obs.

Every counter in :mod:`tpudl.obs.metrics` is process-global — a serve
loop and a fine-tune sharing the process are indistinguishable in
``obs.snapshot()``. This module adds the WHO axis (OBSERVABILITY.md
"Attribution plane", the substrate ROADMAP items 5 and 3 dispatch on):

- **Scope** — ``obs.scope(tenant=..., job=..., run=...)`` establishes
  a contextvar-propagated attribution scope on the calling thread
  (``job=`` accepts a :class:`tpudl.jobs.spec.JobSpec` and uses its
  fingerprint — PR-7 identity, not object identity). Scopes nest and
  MERGE: an inner ``scope(run=...)`` keeps the outer tenant/job.
- **carry(fn)** — contextvars do NOT cross ``ThreadPoolExecutor``
  boundaries; the executor's prepare pool and dispatch window, the
  serve loop's per-request path, and the HPO trial pool all wrap their
  submissions so a worker thread's publishes land in the SUBMITTING
  scope (pinned by tests/test_obs_attribution.py's interleaved runs).
- **ScopeLedger** — bounded per-scope running aggregates (exact, not
  sampled): rows/tokens in+out, wire bytes shipped, HBM bytes resident
  + peak, dispatch/compile seconds, retry/degradation counts, serve
  completions and SLO samples. LRU-bounded at ``TPUDL_OBS_SCOPES``
  scopes under ONE registered named lock (``obs.attribution.ledger``,
  locks.py); an evicted scope folds its totals into the explicit
  ``unattributed`` bucket (and files ``attribution.scopes_evicted``)
  so eviction never loses bytes.

**The reconciliation invariant is the correctness contract**: every
ledger charge is paired with the exact site that increments the
corresponding GLOBAL counter, with the same amount — no scope active
means the charge lands in ``unattributed`` — so per-scope sums plus
``unattributed`` equal the global counters at all times
(:func:`reconcile`; offline: ``python -m tpudl.obs ledger <dir>``).

Lock discipline: the ledger lock is a leaf for metrics purposes —
charges never publish under it; the eviction counter and every gauge
publish AFTER release (tpudl/analysis/locks.py).
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import time
from collections import OrderedDict

from tpudl.obs import metrics as _metrics
from tpudl.testing import tsan as _tsan

__all__ = ["Scope", "scope", "current_scope", "carry", "ScopeLedger",
           "get_ledger", "reset_ledger", "charge", "ledger_snapshot",
           "ledger_totals", "reconcile", "status_section",
           "totals_of", "reconcile_snapshot",
           "LEDGER_FIELDS", "RECONCILED"]

# every per-scope aggregate the ledger tracks (one dict key per field;
# floats throughout — bytes/counts stay integral in practice)
LEDGER_FIELDS = ("rows_in", "rows_out", "tokens_in", "tokens_out",
                 "wire_bytes", "hbm_bytes", "hbm_peak_bytes",
                 "dispatch_s", "compile_s", "retries", "degradations",
                 "serve_completed", "slo_samples")

# the reconciliation contract: ledger field → the global metric it must
# sum to (kind matters: a gauge compares against .value, a counter
# against .value, a histogram against .count). hbm_peak_bytes and the
# purely-attributed fields (rows/tokens/dispatch_s) have no global
# counterpart and are excluded by construction.
RECONCILED = (
    ("wire_bytes", "data.wire.bytes_shipped", "counter"),
    ("hbm_bytes", "data.hbm.bytes_resident", "gauge"),
    ("compile_s", "compile.aot_s", "counter"),
    ("retries", "retry.attempts", "counter"),
    ("degradations", "frame.degraded.rungs", "counter"),
    ("serve_completed", "serve.completed", "counter"),
    ("slo_samples", "serve.latency_ms", "histogram"),
)

_SCOPE_VAR: contextvars.ContextVar = contextvars.ContextVar(
    "tpudl_obs_scope", default=None)

# seconds accumulate float dt in thread-arrival order; the global
# counter and the ledger may sum the same dts in DIFFERENT orders, so
# float rounding can differ in the last ulps — everything else (bytes,
# rows, counts) must match exactly
_SECONDS_RTOL = 1e-9


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class Scope:
    """One attribution identity: ``tenant`` / ``job`` / ``run``
    (any subset). Immutable; ``key`` is the stable ledger key."""

    __slots__ = ("tenant", "job", "run", "key")

    def __init__(self, tenant=None, job=None, run=None):
        if job is not None and not isinstance(job, str):
            # a JobSpec (tpudl.jobs.spec) attributes by its PR-7
            # fingerprint — the identity resume/retry already key on
            fp = getattr(job, "fingerprint", None)
            job = fp()[:12] if callable(fp) else str(job)
        object.__setattr__(self, "tenant",
                           str(tenant) if tenant is not None else None)
        object.__setattr__(self, "job", job)
        object.__setattr__(self, "run",
                           str(run) if run is not None else None)
        parts = [f"{k}={v}" for k, v in (("tenant", self.tenant),
                                         ("job", self.job),
                                         ("run", self.run))
                 if v is not None]
        object.__setattr__(self, "key", "|".join(parts) or None)

    def __setattr__(self, name, value):
        raise AttributeError("Scope is immutable")

    def merged(self, tenant=None, job=None, run=None) -> "Scope":
        """A child scope: unset fields inherit from this one."""
        child = Scope(tenant=tenant, job=job, run=run)
        return Scope(
            tenant=child.tenant if child.tenant is not None else self.tenant,
            job=child.job if child.job is not None else self.job,
            run=child.run if child.run is not None else self.run)

    def __repr__(self):
        return f"Scope({self.key or 'unattributed'})"


def current_scope() -> Scope | None:
    """The attribution scope active on this thread (None = charges go
    to the ``unattributed`` bucket)."""
    return _SCOPE_VAR.get()


@contextlib.contextmanager
def scope(tenant=None, job=None, run=None):
    """Enter an attribution scope on the calling thread. Nested scopes
    merge (inner unset fields inherit); ``job=`` accepts a JobSpec."""
    cur = _SCOPE_VAR.get()
    new = (cur.merged(tenant=tenant, job=job, run=run) if cur is not None
           else Scope(tenant=tenant, job=job, run=run))
    token = _SCOPE_VAR.set(new)
    try:
        yield new
    finally:
        _SCOPE_VAR.reset(token)


def carry(fn):
    """Bind the CURRENT scope to ``fn`` for execution on another
    thread: ``pool.submit(carry(fn), ...)`` makes the worker's charges
    land in the submitter's scope (a contextvar does not cross the
    pool boundary by itself). Capture happens NOW, at wrap time —
    wrap at the submit site, not at pool construction."""
    captured = _SCOPE_VAR.get()
    if captured is None:
        return fn

    def bound(*args, **kw):
        token = _SCOPE_VAR.set(captured)
        try:
            return fn(*args, **kw)
        finally:
            _SCOPE_VAR.reset(token)

    return bound


def _zero_row() -> dict:
    return {f: 0.0 for f in LEDGER_FIELDS}


class ScopeLedger:
    """LRU-bounded scope → running-aggregates table plus the explicit
    ``unattributed`` bucket. One instance lock covers the table; every
    metric publish happens outside it."""

    def __init__(self):
        self.cap = max(1, _env_int("TPUDL_OBS_SCOPES", 64))
        self._lock = _tsan.named_lock("obs.attribution.ledger")
        self._scopes: OrderedDict[str, dict] = OrderedDict()
        self._unattributed = _zero_row()
        self._evicted = 0

    # -- hot path ----------------------------------------------------------
    def charge(self, field: str, amount: float = 1.0, *,
               key: object = current_scope, create: bool = True):
        """Add ``amount`` (negative = credit) to one scope's ``field``.

        ``key`` defaults to the calling context's scope; pass an
        explicit key string to charge a REMEMBERED owner (the HBM
        credit path), or ``None`` for unattributed. ``create=False``
        routes a charge for an absent key to ``unattributed`` instead
        of resurrecting an evicted scope (a credit against a folded
        scope must land where its debits went). Returns the key
        actually charged (None = unattributed) — HBM call sites store
        it on the cache entry for the eventual credit."""
        if field not in self._unattributed:
            raise KeyError(f"unknown ledger field {field!r}")
        if key is current_scope:
            sc = _SCOPE_VAR.get()
            key = sc.key if sc is not None else None
        amount = float(amount)
        evicted_key = None
        with self._lock:
            if key is None:
                row = self._unattributed
            else:
                row = self._scopes.get(key)
                if row is None:
                    if not create:
                        row, key = self._unattributed, None
                    else:
                        if len(self._scopes) >= self.cap:
                            evicted_key, old = self._scopes.popitem(
                                last=False)
                            self._fold_locked(old)
                        row = self._scopes[key] = _zero_row()
                else:
                    self._scopes.move_to_end(key)
            row[field] += amount
            if field == "hbm_bytes":
                row["hbm_peak_bytes"] = max(row["hbm_peak_bytes"],
                                            row["hbm_bytes"])
        if evicted_key is not None:
            # publish OUTSIDE the ledger lock (locks.py discipline)
            _metrics.counter("attribution.scopes_evicted").inc()
        return key

    def _fold_locked(self, row: dict) -> None:
        """Fold an evicted scope's totals into ``unattributed`` so the
        reconciliation invariant survives eviction (peak folds by max:
        it is a high-water mark, not a conserved quantity)."""
        self._evicted += 1
        for f, v in row.items():
            if f == "hbm_peak_bytes":
                self._unattributed[f] = max(self._unattributed[f],
                                            row["hbm_peak_bytes"])
            else:
                self._unattributed[f] += v

    # -- readers -----------------------------------------------------------
    def snapshot(self) -> dict:
        """Deep plain-dict view: ``{"scopes": {key: row},
        "unattributed": row, "evicted": n, "cap": n}``."""
        with self._lock:
            scopes = {k: dict(v) for k, v in self._scopes.items()}
            una = dict(self._unattributed)
            evicted = self._evicted
        return {"scopes": scopes, "unattributed": una,
                "evicted": evicted, "cap": self.cap}

    def totals(self) -> dict:
        """Per-field sums across every scope PLUS unattributed — the
        left-hand side of the reconciliation invariant."""
        return totals_of(self.snapshot())

    def reconcile(self, metrics: dict | None = None) -> dict:
        """Check the invariant against a metrics snapshot (default: the
        live registry). Returns ``{"ok": bool, "checks": [...]}`` with
        one entry per RECONCILED pair; a global metric that was never
        created reads as 0 (a ledger that charged anyway is a bug)."""
        if metrics is None:
            metrics = _metrics.snapshot()
        return reconcile_snapshot(self.snapshot(), metrics)


def totals_of(snap: dict) -> dict:
    """Per-field sums over a PLAIN ledger snapshot (live or parsed from
    a dump/status artifact) — the offline ``python -m tpudl.obs
    ledger`` path and the live :meth:`ScopeLedger.totals` share this
    math. ``hbm_peak_bytes`` is a high-water mark, not conserved, so it
    is excluded from the scope sum."""
    out = {f: float((snap.get("unattributed") or {}).get(f) or 0.0)
           for f in LEDGER_FIELDS}
    for row in (snap.get("scopes") or {}).values():
        for f in LEDGER_FIELDS:
            if f != "hbm_peak_bytes":
                out[f] += float(row.get(f) or 0.0)
    return out


def reconcile_snapshot(snap: dict, metrics: dict) -> dict:
    """The invariant check on plain dicts: one entry per RECONCILED
    pair, comparing the snapshot's totals to the metrics snapshot (a
    histogram reconciles against its ``count``; a metric that was never
    created reads 0 — a ledger that charged anyway is a bug)."""
    totals = totals_of(snap)
    checks = []
    ok = True
    for field, name, kind in RECONCILED:
        entry = (metrics or {}).get(name) or {}
        glob = float(entry.get("count" if kind == "histogram"
                               else "value") or 0.0)
        led = totals[field]
        if field.endswith("_s"):
            good = abs(led - glob) <= _SECONDS_RTOL * max(
                1.0, abs(led), abs(glob))
        else:
            good = led == glob
        ok = ok and good
        checks.append({"field": field, "metric": name,
                       "ledger": led, "global": glob, "ok": good})
    return {"ok": ok, "checks": checks}


_LEDGER = ScopeLedger()


def get_ledger() -> ScopeLedger:
    return _LEDGER


def reset_ledger() -> ScopeLedger:
    """Fresh ledger re-reading ``TPUDL_OBS_SCOPES`` (tests monkeypatch
    then reset — the SloEngine pattern). Also clears the status
    section's rate state so a reset never yields negative rates."""
    global _LEDGER
    _LEDGER = ScopeLedger()
    _RATE_STATE.clear()
    return _LEDGER


def charge(field: str, amount: float = 1.0, *,
           key: object = current_scope, create: bool = True):
    return _LEDGER.charge(field, amount, key=key, create=create)


def ledger_snapshot() -> dict:
    return _LEDGER.snapshot()


def ledger_totals() -> dict:
    return _LEDGER.totals()


def reconcile(metrics: dict | None = None) -> dict:
    return _LEDGER.reconcile(metrics)


# -- the 1 Hz status section ----------------------------------------------
# per-scope (ts, rows_out, tokens_out) from the previous tick — the
# _HBM_RATE_STATE pattern (live.py): one writer (the status thread), so
# a plain dict suffices
_RATE_STATE: dict = {}


def status_section() -> dict | None:
    """The ``ledger`` block for the live status file (None until the
    first charge — no empty sections in the HUD). Adds per-tick
    ``rows_s``/``tokens_s`` rates and each scope's ``hbm_share`` of
    the resident total."""
    snap = _LEDGER.snapshot()
    if not snap["scopes"] and not any(snap["unattributed"].values()):
        return None
    now = time.monotonic()
    resident = sum(r["hbm_bytes"] for r in snap["scopes"].values())
    resident += snap["unattributed"]["hbm_bytes"]
    for k in list(_RATE_STATE):
        if k is not None and k not in snap["scopes"]:
            del _RATE_STATE[k]  # evicted/reset scopes drop rate state
    for k, row in list(snap["scopes"].items()) + [
            (None, snap["unattributed"])]:
        prev = _RATE_STATE.get(k)
        rows = row["rows_in"] + row["rows_out"]
        toks = row["tokens_in"] + row["tokens_out"]
        if prev is not None and now > prev[0]:
            dt = now - prev[0]
            row["rows_s"] = round(max(0.0, rows - prev[1]) / dt, 3)
            row["tokens_s"] = round(max(0.0, toks - prev[2]) / dt, 3)
        else:
            row["rows_s"] = None
            row["tokens_s"] = None
        _RATE_STATE[k] = (now, rows, toks)
        row["hbm_share"] = (round(row["hbm_bytes"] / resident, 4)
                            if resident > 0 else 0.0)
    return snap
