"""Live run monitor: atomic per-process status files + ``obs top``.

The forensics layer (flight recorder / doctor) answers "why did it
die"; this module answers "what is it doing RIGHT NOW". Every
instrumented process — anything that registers a watchdog heartbeat:
``Frame.map_batches``, ``Trainer.fit``, estimator trials, UDF calls,
HPO trials — periodically writes ONE self-contained status file,

    <TPUDL_STATUS_DIR>/tpudl-status-<pid>.json

assembled from the instrumentation that already exists (the pipeline-
report ring, the heartbeat registry, the metrics registry, and the
roofline model's current verdict). Writes are atomic (tmp + rename in
the same directory), so a reader NEVER sees a torn file — the
``tools/validate_status.py`` contract. File-based on purpose: no
sockets, nothing to connect to, attachable after the fact, and a
crashed process leaves its last status behind as evidence.

``python -m tpudl.obs top <dir>`` renders a refreshing terminal view of
every status file in the directory: active runs with per-stage
throughput, queue depths, rows done/total + ETA, heartbeat ages, and
the roofline/advisor verdict. ``--once`` prints a single frame (CI,
piping, tests).

Overhead: the writer is one daemon thread at ``TPUDL_STATUS_INTERVAL_S``
(default 1 s) cadence; the executor hot path pays only the one-time
``ensure_status_writer()`` flag check when a heartbeat registers. The
<5% executor-overhead guard in tests/test_obs_live.py pins it, same as
the recorder's.
"""

from __future__ import annotations

import atexit
import json
import os
import socket
import sys
import threading
import time

from tpudl.obs.metrics import _env_float
from tpudl.testing import tsan as _tsan

__all__ = ["collect_status", "write_status", "ensure_status_writer",
           "start_status_writer", "stop_status_writer", "status_path",
           "read_statuses", "render", "SCHEMA", "VERSION",
           "STATUS_PREFIX"]

SCHEMA = "tpudl-status"
VERSION = 1
STATUS_PREFIX = "tpudl-status-"

_METRIC_PREFIXES = ("train.", "hpo.", "udf.", "estimator.",
                    "obs.watchdog.", "obs.roofline.",
                    "frame.map_batches.", "frame.degraded.", "retry.",
                    "data.hbm.", "data.wire.", "compile.", "serve.",
                    "attribution.")


def _status_dir() -> str | None:
    return os.environ.get("TPUDL_STATUS_DIR") or None


def _interval_s() -> float:
    return max(0.05, _env_float("TPUDL_STATUS_INTERVAL_S", 1.0))


def status_path(status_dir: str, pid: int | None = None) -> str:
    return os.path.join(status_dir,
                        f"{STATUS_PREFIX}{pid or os.getpid()}.json")


# -- assembly ----------------------------------------------------------------

def _run_entry(report: dict) -> dict:
    """One pipeline report → the status file's condensed run entry."""
    rows_total = report.get("rows")
    rows_done = int(report.get("rows_done") or 0)
    finished = bool(report.get("finished"))
    wall = (report.get("wall_seconds") if finished
            else report.get("age_s")) or 0.0
    rate = rows_done / wall if wall > 0 else None
    eta = None
    if (not finished and rate and rows_total
            and rows_total > rows_done):
        eta = (rows_total - rows_done) / rate
    entry = {
        "run_id": report.get("run_id"),
        "rows_total": rows_total,
        "rows_done": rows_done,
        "finished": finished,
        "wall_s": round(wall, 3),
        "rows_per_sec": round(rate, 2) if rate else None,
        "eta_s": round(eta, 1) if eta is not None else None,
        "stage_seconds": report.get("stage_seconds") or {},
        "overlap_efficiency": report.get("overlap_efficiency"),
        "queue_depth_mean": report.get("queue_depth_mean"),
        "config": {k: report.get(k) for k in (
            "executor", "batch_size", "fuse_steps", "prefetch_depth",
            "prepare_workers", "wire_codec", "batch_cache",
            "device_cache", "degraded_to", "recovered_batches", "mesh")
            if report.get(k) is not None},
    }
    if rows_total:
        entry["pct"] = round(100.0 * rows_done / rows_total, 1)
    return entry


def collect_status(roofline: bool = True) -> dict:
    """Assemble one status payload from the live registries. Never
    raises — a section that fails to assemble is recorded as absent
    (the observer must not take down the observed)."""
    payload = {
        "schema": SCHEMA,
        "version": VERSION,
        "ts": time.time(),
        "pid": os.getpid(),
        "host": socket.gethostname(),
        "argv": [os.path.basename(sys.argv[0] or "python")]
        + sys.argv[1:3],
        "interval_s": _interval_s(),
        "alive": True,
        "runs": [],
        "heartbeats": {},
        "metrics": {},
        "roofline": None,
    }
    try:
        from tpudl.obs import pipeline as _pipeline

        reports = list(_pipeline.pipeline_reports().values())
        # every unfinished run, plus the newest finished one (context
        # for "what just happened" when the process idles between runs)
        active = [r for r in reports if not r.get("finished")]
        done = [r for r in reports if r.get("finished")]
        keep = active + (done[-1:] if done else [])
        payload["runs"] = [_run_entry(r) for r in keep]
        if roofline:
            newest = (active or done)[-1] if (active or done) else None
            if newest:
                from tpudl.obs import roofline as _roofline

                # allow_probe=False: the status thread reads the
                # CACHED wire figure but never issues a device op (or
                # drags jax into a host-only process) itself
                rr = _roofline.analyze(newest, publish=False,
                                       allow_probe=False)
                if rr is not None:
                    payload["roofline"] = rr.to_dict()
    # tpudl: ignore[swallowed-except] — 1 Hz status thread: a broken
    # contributor drops its section, never the whole status file
    except Exception:
        pass
    try:
        from tpudl.obs import watchdog as _watchdog

        payload["heartbeats"] = _watchdog.get_registry().describe()
    # tpudl: ignore[swallowed-except] — 1 Hz status thread: a broken
    # contributor drops its section, never the whole status file
    except Exception:
        pass
    try:
        from tpudl.obs import metrics as _metrics

        # filtered AT the registry (ISSUE 20): the 1 Hz writer copies
        # only the sections it ships instead of snapshotting the whole
        # table and discarding most of it — the <5% overhead guard's
        # margin lives here
        payload["metrics"] = _metrics.snapshot(prefix=_METRIC_PREFIXES)
        hbm = _hbm_section(payload["metrics"], payload["ts"])
        if hbm is not None:
            payload["hbm"] = hbm
        comp = _compile_section(payload["metrics"])
        if comp is not None:
            payload["compile"] = comp
        srv = _serve_section(payload["metrics"])
        if srv is not None:
            payload["serve"] = srv
    # tpudl: ignore[swallowed-except] — 1 Hz status thread: a broken
    # contributor drops its section, never the whole status file
    except Exception:
        pass
    try:
        from tpudl.obs import attribution as _attr

        led = _attr.status_section()
        if led is not None:
            payload["ledger"] = led
    # tpudl: ignore[swallowed-except] — 1 Hz status thread: a broken
    # contributor drops its section, never the whole status file
    except Exception:
        pass
    return payload


# hits/s needs a delta: the writer ticks at a fixed cadence, so one
# (ts, hits) pair of module state per process is enough — no lock
# (the 1 Hz writer is the only caller; a torn read worst-cases one
# frame's rate to None)
_HBM_RATE_STATE: dict = {}


def _hbm_section(metrics: dict, now: float) -> dict | None:
    """The status file's HBM residency line (ISSUE 12): bytes resident
    vs budget, hit/miss/eviction totals, and a hits/s rate — a
    budget-thrashing job (evictions climbing, hit rate sagging) is
    visible LIVE instead of only in post-hoc counters. None when the
    device cache never armed in this process."""
    def val(name):
        entry = metrics.get(name) or {}
        v = entry.get("value")
        return v if isinstance(v, (int, float)) else None

    resident = val("data.hbm.bytes_resident")
    if resident is None:
        return None
    budget = val("data.hbm.budget_bytes")
    hits = val("data.hbm.hits") or 0
    out = {
        "bytes_resident": int(resident),
        "budget_bytes": int(budget) if budget else None,
        "budget_pct": (round(100.0 * resident / budget, 1)
                       if budget else None),
        "hits": int(hits),
        "misses": int(val("data.hbm.misses") or 0),
        "evictions": int(val("data.hbm.evictions") or 0),
        "hits_per_s": None,
    }
    prev = _HBM_RATE_STATE.get("tick")
    _HBM_RATE_STATE["tick"] = (now, hits)
    if prev and now > prev[0]:
        out["hits_per_s"] = round(
            max(0.0, hits - prev[1]) / (now - prev[0]), 1)
    return out


def _compile_section(metrics: dict) -> dict | None:
    """The status file's compile line (ISSUE 15): AOT program-store
    hit rate, programs restored/compiled, seconds spent in AOT work,
    bucket pad rows, and whether the persistent cache ever disabled —
    a fleet cold-starting (misses climbing, nothing restored, or
    cache_disabled > 0) is visible LIVE. None when no compile metric
    ever published in this process."""
    def val(name):
        entry = metrics.get(name) or {}
        v = entry.get("value")
        return v if isinstance(v, (int, float)) else None

    hits = val("compile.hits")
    misses = val("compile.misses")
    if hits is None and misses is None \
            and val("compile.programs_restored") is None \
            and val("compile.cache_disabled") is None:
        return None
    return {
        "hits": int(hits or 0),
        "misses": int(misses or 0),
        "programs_restored": int(val("compile.programs_restored") or 0),
        "programs_compiled": int(val("compile.programs_compiled") or 0),
        "aot_s": round(val("compile.aot_s") or 0.0, 3),
        "bucket_pad_rows": int(val("compile.bucket_pad_rows") or 0),
        "cache_disabled": int(val("compile.cache_disabled") or 0),
    }


def _serve_section(metrics: dict) -> dict | None:
    """The status file's serve line (ISSUE 17): offered vs rejected
    load, queue depth against its cap, slot occupancy, sustained token
    rate and the latency SLO percentiles — a saturating server (depth
    at cap, rejects climbing) or a TTFT regression is visible LIVE.
    None when no serve metric ever published in this process."""
    def val(name):
        entry = metrics.get(name) or {}
        v = entry.get("value")
        return v if isinstance(v, (int, float)) else None

    def pct(name, q):
        v = (metrics.get(name) or {}).get(q)
        return v if isinstance(v, (int, float)) else None

    if val("serve.requests") is None and val("serve.queue_cap") is None:
        return None
    # the WINDOWED view (ISSUE 18): recent p50/p99/burn from the SLO
    # engine, so `obs top` answers "how are we doing NOW", not "since
    # boot"; the lifetime percentiles stay as the fallback
    try:
        from tpudl.obs import slo as _slo

        slo_section = _slo.get_slo_engine().status_section()
    # tpudl: ignore[swallowed-except] — status writer daemon: a broken
    # SLO engine must cost the slo block, never the whole status file
    except Exception:
        slo_section = None
    return {
        "requests": int(val("serve.requests") or 0),
        "rejects": int(val("serve.rejects") or 0),
        "completed": int(val("serve.completed") or 0),
        "deadline_sheds": int(val("serve.deadline_sheds") or 0),
        "queue_depth": int(val("serve.queue_depth") or 0),
        "queue_cap": int(val("serve.queue_cap") or 0),
        "occupancy": (round(val("serve.batch_occupancy"), 3)
                      if val("serve.batch_occupancy") is not None
                      else None),
        "tokens_per_s": (round(val("serve.tokens_per_s"), 1)
                         if val("serve.tokens_per_s") is not None
                         else None),
        "p50_ms": pct("serve.latency_ms", "p50"),
        "p99_ms": pct("serve.latency_ms", "p99"),
        "models": int(val("serve.models") or 0),
        "slo": slo_section,
    }


def write_status(status_dir: str | None = None,
                 payload: dict | None = None) -> str | None:
    """Write one atomic status file; returns its path (None on failure
    or when no directory is configured). tmp + ``os.replace`` in the
    SAME directory — a reader sees the old complete file or the new
    complete file, never bytes in between."""
    status_dir = status_dir or _status_dir()
    if not status_dir:
        return None
    try:
        payload = payload if payload is not None else collect_status()
        os.makedirs(status_dir, exist_ok=True)
        path = status_path(status_dir)
        tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
        with open(tmp, "w") as f:
            f.write(json.dumps(payload, default=str))
        os.replace(tmp, path)
        return path
    except Exception:
        return None


# -- the writer daemon -------------------------------------------------------

class StatusWriter:
    """Daemon thread: one atomic status write per interval while the
    process lives; the final write (atexit or ``stop``) flips
    ``alive: false`` so ``obs top`` shows a clean exit instead of a
    stale age."""

    def __init__(self, status_dir: str, interval: float | None = None):
        self.status_dir = status_dir
        self.interval = float(interval if interval is not None
                              else _interval_s())
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpudl-status")
        self._thread.start()
        return self

    def _run(self):
        write_status(self.status_dir)  # first frame immediately
        while not self._stop.wait(self.interval):
            write_status(self.status_dir)

    def stop(self, final: bool = True):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None
        if final:
            payload = collect_status()
            payload["alive"] = False
            write_status(self.status_dir, payload)


_WRITER: StatusWriter | None = None
_WRITER_LOCK = _tsan.named_lock("obs.live.writer")
_CHECKED = False  # fast path: ensure() is called per heartbeat


def ensure_status_writer() -> "StatusWriter | None":
    """Lazily start the process-wide writer when ``TPUDL_STATUS_DIR``
    is set. Called by the heartbeat registrar, so ANY instrumented
    layer (executor, trainer, estimator, UDFs, HPO) starting work makes
    the process monitorable without its own plumbing. The post-start
    cost is one module-flag read."""
    global _CHECKED
    if _CHECKED:
        return _WRITER
    d = _status_dir()
    if d is None:
        # no flag-latch on the None path: an operator can export the
        # env var mid-process and the next run picks it up
        return None
    with _WRITER_LOCK:
        if _WRITER is None:
            _start_locked(d, None)
        _CHECKED = True
        return _WRITER


def start_status_writer(status_dir: str | None = None,
                        interval: float | None = None) -> StatusWriter:
    """Start (or return) the process-wide writer. Explicit args win
    over the env knobs."""
    global _CHECKED
    with _WRITER_LOCK:
        if _WRITER is None:
            _start_locked(status_dir or _status_dir() or os.getcwd(),
                          interval)
        _CHECKED = True
        return _WRITER


def _start_locked(status_dir: str, interval):
    global _WRITER
    _WRITER = StatusWriter(status_dir, interval).start()
    atexit.register(_atexit_stop)


def _atexit_stop():
    w = _WRITER
    if w is not None:
        w.stop(final=True)


def stop_status_writer():
    """Stop and forget the writer (tests)."""
    global _WRITER, _CHECKED
    with _WRITER_LOCK:
        if _WRITER is not None:
            # tpudl: ignore[lock-held-blocking] — stop(final=False)
            # only joins the 1 Hz writer thread (timeout=2.0); the
            # probe-reaching path the analyzer sees is final=True's
            # collect_status, whose roofline read uses the CACHED wire
            # probe (roofline.py: never re-probed from the status
            # thread)
            _WRITER.stop(final=False)
            _WRITER = None
        _CHECKED = False


# -- the reader / renderer (``obs top``) -------------------------------------

def read_statuses(status_dir: str) -> list[dict]:
    """Parse every status file under ``status_dir`` (newest-written
    first). A half-readable file is skipped, not fatal — the atomic-
    write contract means that only happens for foreign files."""
    out = []
    try:
        names = sorted(os.listdir(status_dir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith(STATUS_PREFIX)
                and name.endswith(".json")):
            continue
        path = os.path.join(status_dir, name)
        try:
            with open(path) as f:
                payload = json.load(f)
            payload["_path"] = path
            out.append(payload)
        except (OSError, json.JSONDecodeError):
            continue
    out.sort(key=lambda p: -(p.get("ts") or 0))
    return out


def _bar(pct: float | None, width: int = 20) -> str:
    if pct is None:
        return "?" * width
    filled = int(width * min(100.0, max(0.0, pct)) / 100.0)
    return "#" * filled + "." * (width - filled)


def _fmt_age(s: float) -> str:
    if s < 120:
        return f"{s:.1f}s"
    return f"{s / 60:.1f}m"


def _fleet_serve_line(serves: list[dict]) -> str:
    """One merged aggregate over every process's serve section (the
    doctor's per-host-merge treatment applied to ``obs top``): summed
    load, worst queue depth, and a REAL merged windowed p99 — computed
    over the concatenation of each process's exported window sample
    tail, not a max-of-p99s (which would overstate a balanced fleet)."""
    from tpudl.obs.metrics import percentile as _pct

    requests = sum(int(s.get("requests") or 0) for s in serves)
    completed = sum(int(s.get("completed") or 0) for s in serves)
    rejects = sum(int(s.get("rejects") or 0) for s in serves)
    depth = max(int(s.get("queue_depth") or 0) for s in serves)
    slos = [s.get("slo") or {} for s in serves]
    qps = sum(float(sl.get("window_qps") or 0.0) for sl in slos)
    samples: list = []
    for sl in slos:
        samples.extend(x for x in (sl.get("window_samples_ms") or [])
                       if isinstance(x, (int, float)))
    line = (f"fleet serve ({len(serves)} procs): req {requests}"
            f"  done {completed}  rejects {rejects}"
            f"  queue max {depth}")
    if qps:
        line += f"  qps {qps:.1f}"
    merged_p99 = _pct(sorted(samples), 0.99)
    if merged_p99 is not None:
        line += f"  w_p99 {merged_p99:.0f}ms"
    burns = [sl.get("burn_short") for sl in slos
             if isinstance(sl.get("burn_short"), (int, float))]
    if burns:
        line += f"  burn {max(burns):.1f}x"
    return line


def _fmt_bytes(n: float) -> str:
    n = float(n)
    if abs(n) >= 2**30:
        return f"{n / 2**30:.2f}GB"
    if abs(n) >= 2**20:
        return f"{n / 2**20:.1f}MB"
    if abs(n) >= 2**10:
        return f"{n / 2**10:.1f}KB"
    return f"{n:.0f}B"


def _ledger_rows(led: dict) -> list[tuple]:
    """(key, row) pairs worth rendering: every named scope plus the
    unattributed bucket when it actually carries charges."""
    rows = list((led.get("scopes") or {}).items())
    una = led.get("unattributed") or {}
    if any(una.get(f) for f in ("rows_in", "rows_out", "tokens_in",
                                "tokens_out", "wire_bytes", "hbm_bytes",
                                "serve_completed")):
        rows.append(("(unattributed)", una))
    return rows


def _ledger_line(key: str, row: dict) -> str:
    """One ``obs top`` attribution row: who, rows/s, tokens/s, HBM
    share, wire bytes — the ISSUE 20 per-tenant surface."""
    line = f"    {key:<24}"
    rs, ts = row.get("rows_s"), row.get("tokens_s")
    line += (f" {rs:.1f} rows/s" if isinstance(rs, (int, float))
             else " - rows/s")
    line += (f"  {ts:.1f} tok/s" if isinstance(ts, (int, float))
             else "  - tok/s")
    share = row.get("hbm_share")
    if row.get("hbm_bytes") or share:
        line += (f"  hbm {_fmt_bytes(row.get('hbm_bytes') or 0)}"
                 + (f" ({100 * share:.0f}%)"
                    if isinstance(share, (int, float)) and share > 0
                    else ""))
    if row.get("wire_bytes"):
        line += f"  wire {_fmt_bytes(row['wire_bytes'])}"
    if row.get("serve_completed"):
        line += f"  served {row['serve_completed']:.0f}"
    return line


def _fleet_ledger_lines(ledgers: list[dict]) -> list[str]:
    """Per-tenant rows merged across every process's ledger section
    (the ``_fleet_serve_line`` treatment for attribution): additive
    fields and per-proc rates SUM; the HBM share is recomputed over
    the merged resident total."""
    merged: dict[str, dict] = {}
    evicted = 0
    for led in ledgers:
        evicted += int(led.get("evicted") or 0)
        for key, row in _ledger_rows(led):
            at = merged.setdefault(key, {})
            for f, v in row.items():
                if not isinstance(v, (int, float)):
                    continue
                if f == "hbm_share":
                    continue  # recomputed below, shares don't add
                at[f] = at.get(f, 0.0) + v
    resident = sum(r.get("hbm_bytes") or 0 for r in merged.values())
    lines = [f"fleet tenants ({len(ledgers)} procs, "
             f"{len(merged)} scopes"
             + (f", {evicted} evicted" if evicted else "") + "):"]
    for key, row in sorted(merged.items()):
        row["hbm_share"] = ((row.get("hbm_bytes") or 0) / resident
                            if resident > 0 else 0.0)
        lines.append(_ledger_line(key, row))
    return lines


def render(statuses: list[dict], now: float | None = None) -> str:
    """One text frame over parsed status payloads — pure (testable)."""
    now = now if now is not None else time.time()
    lines = [f"tpudl obs top — {len(statuses)} process(es) — "
             f"{time.strftime('%H:%M:%S', time.localtime(now))}"]
    if not statuses:
        lines.append("  (no tpudl-status-*.json files yet)")
    serves = [st.get("serve") for st in statuses if st.get("serve")]
    if len(serves) >= 2:
        lines.append(_fleet_serve_line(serves))
    ledgers = [st.get("ledger") for st in statuses if st.get("ledger")]
    if len(ledgers) >= 2:
        lines.extend(_fleet_ledger_lines(ledgers))
    for st in statuses:
        age = now - (st.get("ts") or now)
        stale_after = 3 * float(st.get("interval_s") or 1.0) + 2.0
        state = ("EXITED" if not st.get("alive", True)
                 else ("STALE" if age > stale_after else "live"))
        lines.append(
            f"\npid {st.get('pid')} [{state}] "
            f"{' '.join(st.get('argv') or [])}  "
            f"(written {_fmt_age(age)} ago on {st.get('host')})")
        for run in st.get("runs") or []:
            pct = run.get("pct")
            state_r = "done" if run.get("finished") else "RUNNING"
            rate = run.get("rows_per_sec")
            eta = run.get("eta_s")
            lines.append(
                f"  run {run.get('run_id')} [{state_r}] "
                f"rows {run.get('rows_done')}/{run.get('rows_total')}"
                + (f" ({pct:.0f}%)" if pct is not None else "")
                + f" |{_bar(pct)}|"
                + (f" {rate:.1f} rows/s" if rate else "")
                + (f" ETA {_fmt_age(eta)}" if eta is not None else "")
                # mesh topology on the run line (ISSUE 16): a glance
                # distinguishes an 8x1 data-parallel run from a 4x2
                # tensor-parallel one without digging into the knobs
                + (" mesh={}".format("x".join(
                    str((run.get("config") or {})["mesh"].get(a, 1))
                    for a in ("data", "model")))
                   if (run.get("config") or {}).get("mesh") else "")
                # fault containment: a run surviving on a degraded rung
                # is loud here — same field the PipelineReport carries
                + (f" DEGRADED->{(run.get('config') or {})['degraded_to']}"
                   if (run.get("config") or {}).get("degraded_to")
                   else ""))
            ss = run.get("stage_seconds") or {}
            if ss:
                stages = "  ".join(f"{k} {v:.2f}s" for k, v
                                   in sorted(ss.items(), key=lambda kv:
                                             -kv[1]))
                lines.append(f"      stages: {stages}")
            cfg = run.get("config") or {}
            if cfg:
                knobs = " ".join(f"{k}={v}" for k, v
                                 in sorted(cfg.items()))
                lines.append(f"      knobs:  {knobs}")
        hbs = st.get("heartbeats") or {}
        if hbs:
            parts = []
            for name, hb in sorted(hbs.items()):
                inflight = hb.get("in_flight") or {}
                suspect = (" [" + ",".join(
                    f"{k}:{v.get('age_s')}s" for k, v
                    in inflight.items()) + "]") if inflight else ""
                flag = " STALLED" if hb.get("stalled") else ""
                parts.append(f"{name} {hb.get('age_s')}s"
                             f"{suspect}{flag}")
            lines.append("  heartbeats: " + "; ".join(parts))
        hbm = st.get("hbm") or {}
        if hbm.get("bytes_resident") is not None:
            mb = hbm["bytes_resident"] / 2**20
            budget = hbm.get("budget_bytes")
            pct = hbm.get("budget_pct")
            rate = hbm.get("hits_per_s")
            line = f"  hbm:        {mb:.1f}"
            if budget:
                line += f"/{budget / 2**20:.1f} MB resident"
                if pct is not None:
                    line += f" ({pct:.0f}%)"
            else:
                line += " MB resident"
            line += f"  hits {hbm.get('hits', 0)}"
            if rate is not None:
                line += f" ({rate:.1f}/s)"
            if hbm.get("evictions"):
                line += f"  evictions {hbm['evictions']}"
            lines.append(line)
        comp = st.get("compile") or {}
        if comp:
            line = (f"  compile:    hits {comp.get('hits', 0)}"
                    f"  misses {comp.get('misses', 0)}")
            if comp.get("programs_restored"):
                line += f"  restored {comp['programs_restored']}"
            if comp.get("programs_compiled"):
                line += f"  aot {comp['programs_compiled']}"
            if comp.get("aot_s"):
                line += f" ({comp['aot_s']:.1f}s)"
            if comp.get("bucket_pad_rows"):
                line += f"  pad_rows {comp['bucket_pad_rows']}"
            if comp.get("cache_disabled"):
                line += (f"  CACHE-DISABLED "
                         f"x{comp['cache_disabled']}")
            lines.append(line)
        srv = st.get("serve") or {}
        if srv:
            line = (f"  serve:      req {srv.get('requests', 0)}"
                    f"  done {srv.get('completed', 0)}"
                    f"  queue {srv.get('queue_depth', 0)}"
                    f"/{srv.get('queue_cap', 0)}")
            if srv.get("rejects"):
                line += f"  rejects {srv['rejects']}"
            if srv.get("deadline_sheds"):
                line += f"  sheds {srv['deadline_sheds']}"
            if srv.get("occupancy") is not None:
                line += f"  occ {100 * srv['occupancy']:.0f}%"
            if srv.get("tokens_per_s") is not None:
                line += f"  tok/s {srv['tokens_per_s']:.1f}"
            slo = srv.get("slo") or {}
            if slo.get("window_p99_ms") is not None:
                # the WINDOWED truth (last window_s seconds), not the
                # lifetime histogram — "now", the number you page on
                line += (f"  w_p50 {slo['window_p50_ms']:.0f}ms"
                         f"  w_p99 {slo['window_p99_ms']:.0f}ms")
                if slo.get("burn_short") is not None:
                    line += f"  burn {slo['burn_short']:.1f}x"
            elif srv.get("p99_ms") is not None:
                line += f"  p99 {srv['p99_ms']:.0f}ms"
            if srv.get("models", 0) > 1:
                line += f"  models {srv['models']}"
            lines.append(line)
        led = st.get("ledger") or {}
        led_rows = _ledger_rows(led)
        if led_rows:
            head = f"  tenants:    {len(led.get('scopes') or {})} scope(s)"
            if led.get("evicted"):
                head += f"  evicted {led['evicted']}"
            lines.append(head)
            for key, row in led_rows:
                lines.append(_ledger_line(key, row))
        rl = st.get("roofline") or {}
        if rl.get("verdict"):
            lines.append(f"  roofline:   {rl['verdict']}")
            attr = rl.get("gap_attribution") or {}
            if attr:
                shares = "  ".join(
                    f"{k} {100 * v:.0f}%" for k, v in sorted(
                        attr.items(), key=lambda kv: -kv[1]) if v)
                lines.append(f"  gap:        {shares}")
        m = st.get("metrics") or {}
        stalls = (m.get("obs.watchdog.stalls") or {}).get("value")
        step = (m.get("train.last_step") or {}).get("value")
        bits = []
        if step is not None:
            bits.append(f"train.last_step {step:.0f}")
        if stalls:
            bits.append(f"watchdog stalls {stalls:.0f}")
        if bits:
            lines.append("  metrics:    " + "  ".join(bits))
    return "\n".join(lines)


def top_main(status_dir: str, once: bool = False,
             interval: float = 2.0, out=None) -> int:
    """The ``obs top`` loop. ``--once`` prints a single frame and
    returns 2 when the directory holds no status files (scriptable
    "is anything running here"); the live loop keeps waiting for
    processes to appear and exits 0 on Ctrl-C."""
    out = out or sys.stdout
    while True:
        try:
            statuses = read_statuses(status_dir)
            frame = render(statuses)
            if once:
                print(frame, file=out)
                return 0 if statuses else 2
            # clear + home, then the frame (plain ANSI — no curses dep)
            print("\x1b[2J\x1b[H" + frame, file=out, flush=True)
            # tpudl: ignore[adhoc-retry] — the interactive top refresh
            # cadence, not a retry: nothing failed, nothing backs off
            time.sleep(max(0.2, interval))
        except KeyboardInterrupt:  # pragma: no cover - interactive
            return 0
