"""Flight recorder: the always-on black box that explains a dead run.

BENCH_r05.json ended rc=124 with nothing but an stderr tail — a hung
infeed and a decode-error storm were indistinguishable from a slow run.
This module is the post-mortem layer of :mod:`tpudl.obs`
(OBSERVABILITY.md "Failure forensics"): a process-wide
:class:`FlightRecorder` keeps bounded rings of recent evidence —

- **batch descriptors** (shapes/dtypes/cheap fingerprints — NEVER the
  data) published by the frame executor per prepared batch;
- **errors** (decode failures, shard corruption, train restarts, any
  layer's ``record_error``) with type/message/context;
- **stall events** from :mod:`tpudl.obs.watchdog`, each carrying a
  snapshot of every Python thread's stack at detection time;
- **metric ticks** (periodic registry snapshots, so a dump shows the
  trajectory, not just the final totals).

``dump()`` assembles those rings plus everything the rest of obs
already holds — the span-ring tail, the pipeline-report ring, the full
metrics snapshot — and an env/backend/config snapshot into ONE
self-contained ``tpudl-dump-<pid>.json.gz``, written atomically
(tmp + ``os.replace``). In distributed runs each process writes its own
file keyed by ``jax.process_index()``
(``tpudl-dump-host<idx>-<pid>.json.gz``);
``python -m tpudl.obs doctor <dir>`` merges and classifies them
offline (:mod:`tpudl.obs.doctor`).

``install()`` arms the automatic triggers: unhandled exceptions
(``sys.excepthook`` chain), SIGTERM/SIGQUIT (prior handlers are chained
afterwards, default signal semantics preserved), and — opt-in via
``TPUDL_FAULTHANDLER=1`` — the stdlib ``faulthandler`` writing native-
crash Python stacks to ``tpudl-fault-<pid>.log`` next to the dumps, so
a libtpu/XLA segfault still leaves evidence.

Hot-loop discipline: recording is a lock + a deque append of a small
dict; jax is never imported here (``sys.modules`` probe only), so
host-only pipelines stay light and the recorder can stay on in
production (the executor overhead guard in tests/test_obs_flight.py
pins recorder+watchdog at <5%).
"""

from __future__ import annotations

import gzip
import itertools
import json
import os
import signal
import sys
import threading
import time
import traceback
import zlib
from collections import deque

from tpudl.testing import tsan as _tsan

__all__ = ["FlightRecorder", "get_recorder", "record_error",
           "record_batch", "record_request", "dump", "install",
           "DUMP_SCHEMA", "DUMP_VERSION", "dump_path_for"]

DUMP_SCHEMA = "tpudl-flight-dump"
# v3: + "ledger" (attribution snapshot + reconciliation verdict) so the
# doctor can name the dominant scope at death and the offline
# `python -m tpudl.obs ledger` reconciliation has its right-hand side
DUMP_VERSION = 3

_DUMP_SEQ = itertools.count()  # tmp-name uniqueness across dump writers

# ring bounds (env-overridable at recorder construction)
_DEFAULT_BATCHES = 32
_DEFAULT_ERRORS = 64
_DEFAULT_STALLS = 16
_DEFAULT_TICKS = 32
_DEFAULT_REQUESTS = 64
_DEFAULT_SPAN_TAIL = 512
# env prefixes worth keeping in a dump — a full os.environ copy could
# leak credentials into an artifact that gets attached to bug reports
_ENV_PREFIXES = ("TPUDL_", "JAX_", "XLA_", "TF_", "LIBTPU_", "TPU_")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _jax_info() -> dict:
    """Backend/process facts WITHOUT importing jax: a dump from a
    host-only pipeline (or a dying interpreter) must not trigger a
    backend bring-up. Every probe is best-effort — a wedged runtime
    may fail any of these calls."""
    jax = sys.modules.get("jax")
    info: dict = {"jax_loaded": jax is not None}
    if jax is None:
        return info
    try:
        info["version"] = getattr(jax, "__version__", None)
    # tpudl: ignore[swallowed-except] — best-effort probe of a possibly
    # wedged runtime; a missing key IS the evidence
    except Exception:
        pass
    for key, fn in (("process_index", "process_index"),
                    ("process_count", "process_count"),
                    ("device_count", "device_count")):
        try:
            info[key] = int(getattr(jax, fn)())
        # tpudl: ignore[swallowed-except] — best-effort probe of a
        # possibly wedged runtime; a missing key IS the evidence
        except Exception:
            pass
    try:
        info["backend"] = jax.default_backend()
    # tpudl: ignore[swallowed-except] — best-effort probe of a possibly
    # wedged runtime; a missing key IS the evidence
    except Exception:
        pass
    return info


def process_index() -> int:
    """This process's index in the gang (0 single-host), without
    importing jax."""
    return int(_jax_info().get("process_index", 0) or 0)


def batch_fingerprint(arrays) -> str | None:
    """Cheap content identity of one prepared batch: crc32 over the
    first KB of each column's raw bytes + total size. Identifies a
    repeating/poisoned batch across dumps without ever storing pixel
    data (the descriptor contract: shapes/dtypes/fingerprints, never
    values). None when a column can't expose raw bytes (object
    arrays)."""
    try:
        crc = 0
        total = 0
        for arr in arrays:
            dt = getattr(arr, "dtype", None)
            if dt is None or dt == object:
                return None
            total += int(arr.nbytes)
            if getattr(arr, "flags", None) is not None \
                    and arr.flags.c_contiguous:
                # reshape of a contiguous array is a VIEW; tobytes on
                # the 256-element slice is O(1KB) no matter the batch
                head_bytes = arr.reshape(-1)[:256].tobytes()
            else:
                # non-contiguous (strided/transposed pack output):
                # reshape would copy the WHOLE array — sample via the
                # flat iterator instead (256 element reads, no copy)
                import numpy as _np

                head_bytes = _np.asarray(
                    [x for _, x in zip(range(256), arr.flat)],
                    dtype=arr.dtype).tobytes()
            crc = zlib.crc32(head_bytes, crc)
        return f"{crc & 0xFFFFFFFF:08x}-{total}"
    except Exception:
        return None


class FlightRecorder:
    """Bounded in-memory black box + atomic gzip dump writer."""

    def __init__(self):
        self._lock = _tsan.named_lock("obs.flight.recorder")
        self._batches: deque = deque(
            maxlen=max(1, _env_int("TPUDL_FLIGHT_BATCHES",
                                   _DEFAULT_BATCHES)))
        self._errors: deque = deque(
            maxlen=max(1, _env_int("TPUDL_FLIGHT_ERRORS", _DEFAULT_ERRORS)))
        self._stalls: deque = deque(
            maxlen=max(1, _env_int("TPUDL_FLIGHT_STALLS", _DEFAULT_STALLS)))
        self._ticks: deque = deque(
            maxlen=max(1, _env_int("TPUDL_FLIGHT_TICKS", _DEFAULT_TICKS)))
        self._requests: deque = deque(
            maxlen=max(1, _env_int("TPUDL_FLIGHT_REQUESTS",
                                   _DEFAULT_REQUESTS)))
        self._restarts: list = []  # train gang restarts: small + precious,
        self._events: deque = deque(maxlen=64)  # lifecycle breadcrumbs
        self._installed = False    # never ring-evicted
        self._prev_excepthook = None
        self._prev_signal: dict = {}
        self._fault_file = None
        self.dumped_paths: list[str] = []

    # -- recording (hot-path safe) ----------------------------------------
    def record_batch(self, stage: str, index: int, arrays, **info):
        """One prepared batch's descriptor: shapes/dtypes/fingerprint
        only. Called by the frame executor per batch — must stay a
        dict-build + deque append."""
        try:
            desc = {"ts": time.time(), "stage": str(stage),
                    "index": int(index),
                    "shapes": [list(getattr(a, "shape", ())) for a in arrays],
                    "dtypes": [str(getattr(a, "dtype", type(a).__name__))
                               for a in arrays],
                    "fingerprint": batch_fingerprint(arrays)}
            desc.update(info)
        # tpudl: ignore[swallowed-except] — per-batch hot-path hook:
        # the observer must never take down the pipeline, and there is
        # no cheaper breadcrumb channel than this recorder itself
        except Exception:
            return
        with self._lock:
            if _tsan.ENABLED:
                _tsan.check_guarded("obs.flight.recorder",
                                    "flight-recorder batch ring",
                                    lock=self._lock)
            self._batches.append(desc)

    def record_error(self, kind: str, error, **ctx):
        """One failure event (decode error, shard corruption, restart
        cause ...). ``error`` may be an exception or a message string;
        context keys must be JSON-scalar."""
        if isinstance(error, BaseException):
            entry = {"type": type(error).__name__,
                     "message": str(error)[:500]}
        else:
            entry = {"type": None, "message": str(error)[:500]}
        entry.update({"ts": time.time(), "kind": str(kind)})
        for k, v in ctx.items():
            entry[k] = v if isinstance(
                v, (int, float, str, bool, type(None))) else repr(v)[:200]
        with self._lock:
            self._errors.append(entry)

    def record_restart(self, attempt: int, error, step: float | None = None,
                       max_restarts: int | None = None):
        """One gang restart: the triggering exception + the step count
        at failure, so ``max_restarts`` exhaustion explains WHY (the
        ``train.restarts`` counter only says how often)."""
        entry = {"ts": time.time(), "attempt": int(attempt),
                 "step": step, "max_restarts": max_restarts,
                 "error_type": type(error).__name__
                 if isinstance(error, BaseException) else None,
                 "error": str(error)[:500],
                 "traceback": "".join(traceback.format_exception(
                     error))[-2000:]
                 if isinstance(error, BaseException) else None}
        with self._lock:
            self._restarts.append(entry)
            del self._restarts[:-64]  # bounded even under a crash loop
        self.record_error("train.restart", error, attempt=attempt,
                          step=step)

    def record_request(self, rec: dict):
        """One TERMINAL serve request's descriptor (trace id, segment
        milliseconds, outcome — built by
        :func:`tpudl.serve.reqtrace.request_record`; NEVER prompt
        content, per the validate_dump contract). Serve hot path: must
        stay a lock + deque append."""
        with self._lock:
            self._requests.append(rec)

    def record_stall(self, stall: dict):
        """Filed by the watchdog: one no-progress event with thread
        stacks at detection time."""
        with self._lock:
            self._stalls.append(stall)

    def record_event(self, kind: str, **fields):
        """Small lifecycle breadcrumb (distributed init, install,
        dump)."""
        entry = {"ts": time.time(), "kind": str(kind)}
        entry.update(fields)
        with self._lock:
            self._events.append(entry)

    def record_metrics_tick(self):
        """Periodic registry snapshot into the tick ring (the watchdog
        calls this per scan): a dump then shows the metric TRAJECTORY —
        e.g. decode_errors exploding in the last 30s — not just the
        final totals."""
        try:
            from tpudl.obs import metrics as _m

            snap = _m.snapshot()
        # tpudl: ignore[swallowed-except] — periodic tick: a broken
        # metrics registry just means a sparser trajectory in the dump
        except Exception:
            return
        with self._lock:
            self._ticks.append({"ts": time.time(), "metrics": snap})

    # -- dump assembly ------------------------------------------------------
    def snapshot(self, reason: str = "manual", error=None) -> dict:
        """The full dump payload as a plain dict (the schema
        ``tools/validate_dump.py`` audits)."""
        jinfo = _jax_info()
        payload: dict = {
            "schema": DUMP_SCHEMA,
            "version": DUMP_VERSION,
            "reason": str(reason),
            "ts": time.time(),
            "pid": os.getpid(),
            "process_index": int(jinfo.get("process_index", 0) or 0),
            "process_count": int(jinfo.get("process_count", 1) or 1),
            "argv": list(sys.argv),
            "python": sys.version.split()[0],
            "backend": jinfo,
            "env": {k: v for k, v in sorted(os.environ.items())
                    if k.startswith(_ENV_PREFIXES)},
        }
        if error is not None:
            if isinstance(error, BaseException):
                payload["error"] = {
                    "type": type(error).__name__,
                    "message": str(error)[:2000],
                    "traceback": "".join(
                        traceback.format_exception(error))[-8000:]}
            else:
                payload["error"] = {"type": None,
                                    "message": str(error)[:2000]}
        else:
            payload["error"] = None
        with self._lock:
            payload["batches"] = list(self._batches)
            payload["errors"] = list(self._errors)
            payload["stalls"] = list(self._stalls)
            payload["metric_ticks"] = list(self._ticks)
            payload["requests"] = list(self._requests)
            payload["restarts"] = list(self._restarts)
            payload["events"] = list(self._events)
        # the rest of obs contributes its own rings (each best-effort:
        # a dump from a dying interpreter takes what it can get)
        try:
            from tpudl.obs import metrics as _m

            payload["metrics"] = _m.snapshot()
        # tpudl: ignore[swallowed-except] — dying-interpreter dump
        # takes what it can get; the empty default marks the gap
        except Exception:
            payload["metrics"] = {}
        try:
            from tpudl.obs import pipeline as _p

            payload["pipeline_reports"] = _p.pipeline_reports()
        # tpudl: ignore[swallowed-except] — dying-interpreter dump
        # takes what it can get; the empty default marks the gap
        except Exception:
            payload["pipeline_reports"] = {}
        try:
            from tpudl.obs import tracer as _t

            spans = _t.get_tracer().spans()[-_env_int(
                "TPUDL_FLIGHT_SPANS", _DEFAULT_SPAN_TAIL):]
            payload["spans"] = [
                {"name": s.name, "ts_us": s.ts_us, "dur_us": s.dur_us,
                 "tid": s.tid, "thread": s.thread_name,
                 "attrs": dict(s.attrs) if s.attrs else None}
                for s in spans]
        # tpudl: ignore[swallowed-except] — dying-interpreter dump
        # takes what it can get; the empty default marks the gap
        except Exception:
            payload["spans"] = []
        try:
            from tpudl.obs import watchdog as _w

            payload["heartbeats"] = _w.get_registry().describe()
        # tpudl: ignore[swallowed-except] — dying-interpreter dump
        # takes what it can get; the empty default marks the gap
        except Exception:
            payload["heartbeats"] = {}
        try:
            from tpudl.obs import attribution as _attr

            led = _attr.ledger_snapshot()
            # the verdict is computed against THIS dump's metrics copy,
            # so the pair in the artifact is self-consistent even if
            # counters kept moving after the snapshot above
            led["reconcile"] = _attr.reconcile(payload.get("metrics")
                                               or None)
            payload["ledger"] = led
        # tpudl: ignore[swallowed-except] — dying-interpreter dump
        # takes what it can get; the None default marks the gap
        except Exception:
            payload["ledger"] = None
        return payload

    def dump(self, reason: str = "manual", error=None,
             path: str | None = None,
             timeout: float | None = None) -> str | None:
        """Write one self-contained gzip dump atomically; returns the
        path, or None when even best-effort writing failed (a dying
        process must never die HARDER because of its black box).

        ``timeout`` assembles the dump on a worker thread and gives up
        after that many seconds — REQUIRED from signal handlers: the
        handler runs on the main thread between bytecodes, and if the
        signal interrupted a frame that holds one of the obs locks
        (a record_batch on the executor hot path, a metric update), an
        inline snapshot would self-deadlock on that lock forever. The
        worker blocks instead; on timeout the handler proceeds without
        the dump (the daemon thread may still finish and write the
        file later — the write stays atomic either way)."""
        if timeout is not None:
            result: dict = {}
            t = threading.Thread(
                target=lambda: result.update(
                    path=self._dump_inner(reason, error, path)),
                daemon=True, name="tpudl-flight-dump")
            t.start()
            t.join(timeout)
            return result.get("path")
        return self._dump_inner(reason, error, path)

    def _dump_inner(self, reason: str, error, path: str | None,
                    ) -> str | None:
        tmp = None
        try:
            payload = self.snapshot(reason=reason, error=error)
            out = path or dump_path_for(
                payload["process_index"], payload["process_count"])
            # unique per writer: an abandoned timeout-dump worker may
            # still be finishing when a second dump runs — pid alone
            # would collide their tmp files and fail both replaces
            tmp = (f"{out}.tmp.{os.getpid()}.{threading.get_ident()}"
                   f".{next(_DUMP_SEQ)}")
            with gzip.open(tmp, "wt", encoding="utf-8") as f:
                json.dump(payload, f, default=str)
            os.replace(tmp, out)
            with self._lock:
                self.dumped_paths.append(out)
            self.record_event("dump", reason=str(reason), path=out)
            return out
        except Exception:
            if tmp is not None:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
            return None

    # -- triggers -----------------------------------------------------------
    def install(self, dump_dir: str | None = None,
                signals=(signal.SIGTERM,
                         getattr(signal, "SIGQUIT", None)),
                excepthook: bool = True) -> "FlightRecorder":
        """Arm automatic dumping. Idempotent; prior handlers are
        CHAINED, not replaced — after the dump the previous Python
        handler runs, and a default-disposition signal is re-raised
        with its default handler restored, so exit codes and driver
        semantics are preserved.

        ``TPUDL_FAULTHANDLER=1`` additionally enables the stdlib
        ``faulthandler`` on fatal native signals (SIGSEGV/SIGABRT/...),
        writing Python stacks to ``tpudl-fault-<pid>.log`` in the dump
        directory — libtpu/XLA crashes happen below the interpreter,
        where no excepthook can run."""
        if dump_dir:
            os.environ["TPUDL_FLIGHT_DIR"] = str(dump_dir)
        if self._installed:
            return self
        self._installed = True
        if excepthook:
            self._prev_excepthook = sys.excepthook

            def hook(exc_type, exc, tb):
                # top of a unwound stack: no obs lock can still be
                # held by this thread, so an inline dump is safe here
                self.dump(reason="exception", error=exc)
                (self._prev_excepthook or sys.__excepthook__)(
                    exc_type, exc, tb)

            sys.excepthook = hook
        for sig in signals:
            if sig is None:
                continue
            try:
                prev = signal.getsignal(sig)

                # tpudl: ignore[signal-handler, signal-lock] — THE
                # forensics handler: dump() assembles on a bounded
                # WORKER thread (timeout=10) so an interrupted frame
                # holding an obs lock can't deadlock it (the worker,
                # not the handler frame, takes the recorder/metrics/
                # report locks), then chains/re-raises for default
                # exit semantics
                def handler(signum, frame, _prev=prev):
                    self.dump(reason=f"signal:{signum}", timeout=10.0)
                    if callable(_prev):
                        _prev(signum, frame)
                    elif _prev != signal.SIG_IGN:
                        # restore + re-raise: default semantics (process
                        # death, correct exit status) preserved
                        signal.signal(signum, signal.SIG_DFL)
                        os.kill(os.getpid(), signum)

                signal.signal(sig, handler)
                self._prev_signal[sig] = prev
            except (ValueError, OSError):
                pass  # not the main thread / exotic platform
        if os.environ.get("TPUDL_FAULTHANDLER", "0") == "1":
            try:
                import faulthandler

                fault_path = os.path.join(
                    _dump_dir(), f"tpudl-fault-{os.getpid()}.log")
                self._fault_file = open(fault_path, "w")  # noqa: SIM115
                # fd must stay open for the process lifetime: the
                # handler writes from the crashed state
                faulthandler.enable(file=self._fault_file,
                                    all_threads=True)
                self.record_event("faulthandler", path=fault_path)
            # tpudl: ignore[swallowed-except] — opt-in extra: an
            # unwritable fault log must not break install(); the reset
            # to None records that it is off
            except Exception:
                self._fault_file = None
        self.record_event("install")
        return self

    # -- tests --------------------------------------------------------------
    def reset(self):
        """Drop recorded evidence (tests; the trigger installation
        stays)."""
        with self._lock:
            for ring in (self._batches, self._errors, self._stalls,
                         self._ticks, self._requests, self._events):
                ring.clear()
            del self._restarts[:]
            del self.dumped_paths[:]


def _dump_dir() -> str:
    d = os.environ.get("TPUDL_FLIGHT_DIR") or os.getcwd()
    try:
        os.makedirs(d, exist_ok=True)
    except OSError:
        d = os.getcwd()
    return d


def dump_path_for(proc_index: int = 0, proc_count: int = 1) -> str:
    """The per-process dump file path: single-host runs get
    ``tpudl-dump-<pid>.json.gz``; gang members key by process index
    (``tpudl-dump-host<idx>-<pid>.json.gz``) so every host's black box
    lands distinctly in a shared dir for the doctor to merge."""
    name = (f"tpudl-dump-host{int(proc_index)}-{os.getpid()}.json.gz"
            if int(proc_count) > 1
            else f"tpudl-dump-{os.getpid()}.json.gz")
    return os.path.join(_dump_dir(), name)


_RECORDER = FlightRecorder()


def get_recorder() -> FlightRecorder:
    return _RECORDER


def record_error(kind: str, error, **ctx):
    _RECORDER.record_error(kind, error, **ctx)


def record_batch(stage: str, index: int, arrays, **info):
    _RECORDER.record_batch(stage, index, arrays, **info)


def record_request(rec: dict):
    _RECORDER.record_request(rec)


def dump(reason: str = "manual", error=None, path: str | None = None,
         timeout: float | None = None) -> str | None:
    """``obs.dump()`` — write the black box now (explicit trigger).
    Pass ``timeout`` when calling from a signal handler (see
    :meth:`FlightRecorder.dump`)."""
    return _RECORDER.dump(reason=reason, error=error, path=path,
                          timeout=timeout)


def install(dump_dir: str | None = None, **kw) -> FlightRecorder:
    """``obs.flight.install()`` — arm exception/signal dumping (see
    :meth:`FlightRecorder.install`)."""
    return _RECORDER.install(dump_dir=dump_dir, **kw)
