"""Per-run pipeline reports: stage times, gauges, and the report ring.

``PipelineReport`` is ONE ``Frame.map_batches`` run's stage accounting
(PIPELINE.md has the reading guide). This module also owns the ring of
recent reports — keyed by run id, bounded at ``TPUDL_PIPELINE_RING``
(default 16) — which replaces the old single racy ``_LAST_PIPELINE``
global: two concurrent runs (HPO trials in threads) each keep their own
retrievable, internally-consistent report, and
``last_pipeline_report()`` stays the newest entry for every existing
caller. On ``finish()`` a report ALSO publishes its totals into the
process-wide metrics registry (:mod:`tpudl.obs.metrics`), so run-level
stage seconds accumulate across a whole process alongside every other
layer's metrics.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import time
from collections import deque

from tpudl.obs import metrics as _metrics
from tpudl.obs import tracer as _tracer
from tpudl.testing import tsan as _tsan

__all__ = ["PipelineReport", "last_pipeline_report", "set_last_pipeline",
           "pipeline_reports", "get_pipeline_report"]

# per-gauge retained samples; running aggregates keep mean/max exact
# over ALL samples (a long streaming run must not grow without bound)
GAUGE_SAMPLE_CAP = 4096

_run_counter = itertools.count()


def _next_run_id() -> str:
    return f"{os.getpid()}-{next(_run_counter)}"


class PipelineReport:
    """Per-stage wall time + gauges for ONE ``Frame.map_batches`` run.

    The stage-time model (PIPELINE.md has the reading guide):

    - ``prepare``: worker-thread seconds in decode/pack (summed across
      the prepare pool — N workers can make this exceed wall time);
    - ``h2d``: the explicit pad + sharded-transfer ENQUEUE on the mesh
      path (``mesh.transfer_batch`` is async since ISSUE 11 — the
      copies themselves ride under later dispatches, so this stage
      measures the enqueue/pad cost, not the wire; on the mesh=None
      tunnel path the transfer rides the dispatch, see map_batches);
    - ``dispatch``: seconds in ``fn(...)`` — on the serial path these
      are consumer-thread seconds (enqueue only for async device fns,
      enqueue+compute for host fns); under the D-deep async dispatch
      window they are POOL-SUMMED across the dispatch threads and may
      exceed wall time (like ``prepare``) — the consumer-visible cost
      is ``dispatch_wait``;
    - ``dispatch_wait``: consumer seconds blocked on the in-flight
      dispatch window (async executor only) — the UNHIDDEN dispatch
      residue, the round-trip time depth D failed to hide (the
      ``infeed_wait`` analogue of the dispatch side; the roofline model
      reads this, not the pool-summed ``dispatch``, when present);
    - ``d2h``: device→host fetch time (windowed drain + the acc-mode
      final fetch — the copies themselves start at dispatch, so this
      measures only the unoverlapped tail);
    - ``infeed_wait``: consumer seconds blocked on the infeed queue —
      the UNHIDDEN remainder of prepare, and the numerator of
      ``overlap_efficiency``.

    Gauges (``gauge``) keep a bounded ring of samples (last
    ``GAUGE_SAMPLE_CAP``) plus running count/sum/max, so the reported
    mean/max stay exact over ALL samples at O(cap) memory
    (``queue_depth`` is sampled at each consumer take: depth K means the
    pool is keeping the device fed). Thread-safe: prepare workers and
    the consumer thread write concurrently.

    Each stage() block also lands on the host-span tracer (named
    ``frame.<stage>``, tagged with this run's id), so an exported host
    trace shows the executor's stages on the merged timeline.
    """

    def __init__(self):
        self.run_id = _next_run_id()
        self.stages: dict[str, float] = {}
        self.calls: dict[str, int] = {}
        self.gauges: dict[str, _metrics.Histogram] = {}
        self.wall_seconds = 0.0
        self.config: dict = {}
        # live progress (fed by the executor's dispatch loop, which
        # knows batch counts — the live monitor and its ETA read these
        # instead of inferring progress from counters); rows_total
        # arrives via config["rows"], rows_done via progress()
        self.rows_done = 0
        self.finished = False
        self._t0 = time.perf_counter()
        # the executor's watchdog heartbeat (set by map_batches): every
        # stage ENTRY beats it with the stage name, so a freeze inside
        # any stage leaves "last progress = entering <stage>" as the
        # stall's suspect (tpudl.obs.watchdog)
        self.heartbeat = None
        self._lock = _tsan.named_lock("obs.pipeline.report")

    @contextlib.contextmanager
    def stage(self, name: str):
        # enter/exit (not a bare beat): the stage stays IN FLIGHT on
        # the heartbeat until it returns, so a freeze inside dispatch
        # is still the suspect after prepare workers beat afterwards
        hb = self.heartbeat
        if hb is not None:
            hb.stage_enter(name)
        with _tracer.span(f"frame.{name}", run=self.run_id):
            t0 = time.perf_counter()
            try:
                yield
            except BaseException as e:
                # fault-taxonomy hook (tpudl.frame.supervisor): tag the
                # escaping exception with the INNERMOST stage it left —
                # outer stage blocks see the tag set and keep it, so a
                # mesh-transfer fault inside prepare's nested h2d block
                # classifies as a transfer fault, not a prepare one
                if getattr(e, "tpudl_stage", None) is None:
                    try:
                        e.tpudl_stage = name
                    # tpudl: ignore[swallowed-except] — exceptions with
                    # __slots__/immutable attrs just stay untagged; the
                    # classifier falls back to type/message anchoring
                    except Exception:
                        pass
                raise
            finally:
                self.add(name, time.perf_counter() - t0)
                if hb is not None:
                    hb.stage_exit(name)

    def add(self, name: str, seconds: float):
        with self._lock:
            self.stages[name] = self.stages.get(name, 0.0) + seconds
            self.calls[name] = self.calls.get(name, 0) + 1

    def count(self, name: str, k: int = 1):
        with self._lock:
            self.calls[name] = self.calls.get(name, 0) + k

    def progress(self, rows: int):
        """``rows`` more rows finished dispatching — the executor calls
        this per handled batch so the run's rows_done/rows_total pair is
        authoritative (ETA = remaining rows / observed rate)."""
        with self._lock:
            self.rows_done += int(rows)

    def gauge(self, name: str, value):
        with self._lock:
            h = self.gauges.get(name)
            if h is None:
                # one authority for "bounded samples + exact running
                # aggregates": the registry's Histogram (unregistered —
                # these samples are per-run, not process-wide)
                h = self.gauges[name] = _metrics.Histogram(
                    cap=GAUGE_SAMPLE_CAP)
        h.observe(value)

    def dispatch_overlap_s(self) -> float | None:
        """Dispatch seconds HIDDEN from the consumer by the in-flight
        window: pool-summed ``dispatch`` minus the consumer's
        ``dispatch_wait``. On the async executor this is the round-trip
        time that rode under other dispatches — the ROADMAP-2 win as
        one number (published as the ``frame.dispatch.overlap_s``
        gauge). None for serial runs (no window, nothing overlapped);
        clamped at 0 so measurement jitter never reports negative
        overlap."""
        with self._lock:
            if "dispatch_wait" not in self.stages:
                return None
            return max(0.0, self.stages.get("dispatch", 0.0)
                       - self.stages.get("dispatch_wait", 0.0))

    def overlap_efficiency(self) -> float | None:
        """Fraction of host prepare work hidden under device compute:
        1 - infeed_wait/prepare, clamped to [0, 1]. 1.0 = the consumer
        never waited (prepare fully overlapped); 0.0 = fully serial.
        None when nothing was prepared (empty frame / no prefetch)."""
        prep = self.stages.get("prepare", 0.0)
        if prep <= 0.0:
            return None
        wait = self.stages.get("infeed_wait", 0.0)
        return max(0.0, min(1.0, 1.0 - wait / prep))

    def finish(self, wall_seconds: float | None = None):
        """Close out the run: record wall time and publish totals into
        the process-wide metrics registry (map_batches runs/rows
        counters, per-stage seconds, wall-time histogram). Called by the
        executor; idempotent enough for tests (re-publishing would
        double-count, so the executor calls it exactly once)."""
        if wall_seconds is not None:
            self.wall_seconds = wall_seconds
        self.finished = True
        _metrics.counter("frame.map_batches.runs").inc()
        rows = self.config.get("rows")
        if rows:
            _metrics.counter("frame.map_batches.rows").inc(rows)
        _metrics.histogram("frame.map_batches.wall_seconds").observe(
            self.wall_seconds)
        with self._lock:
            stages = dict(self.stages)
            dispatches = self.calls.get("dispatch", 0)
        if dispatches:
            _metrics.counter("frame.map_batches.batches").inc(dispatches)
        for name, secs in stages.items():
            _metrics.counter(f"frame.stage.{name}.seconds").inc(secs)
        eff = self.overlap_efficiency()
        if eff is not None:
            _metrics.gauge("frame.overlap_efficiency").set(eff)
        # the async dispatch window's run-level truth (ROADMAP 2):
        # mean in-flight depth + the seconds the window actually hid
        overlap = self.dispatch_overlap_s()
        if overlap is not None:
            _metrics.gauge("frame.dispatch.overlap_s").set(overlap)
        with self._lock:
            inflight = self.gauges.get("dispatch_inflight")
        if inflight is not None:
            _metrics.gauge("frame.dispatch.inflight").set(
                inflight.to_dict()["mean"])
        # mesh-path waste accounting (ISSUE 11): rows of SPMD padding
        # this run shipped and computed only to throw away — the
        # mesh_scaling bench and the roofline read these
        if self.config.get("mesh"):
            with self._lock:
                pad = int(self.calls.get("pad_rows", 0))
            _metrics.gauge("frame.mesh.pad_rows").set(pad)
            if rows:
                _metrics.gauge("frame.mesh.pad_overhead_pct").set(
                    100.0 * pad / (int(rows) + pad))
            # 2-D grid truth (ISSUE 16): the model-axis size the run
            # actually executed under — 1 on a data-parallel mesh, >1
            # when tensor-parallel params were resident. obs top and
            # the mesh_2d bench read this to prove the second axis was
            # armed, not silently collapsed to 1-D.
            _metrics.gauge("frame.mesh.model_axis").set(
                int(self.config["mesh"].get("model") or 1))
        # serve-session truth (ISSUE 17): a serve run's report commits
        # the session-mean slot occupancy (the saturation SLO) and the
        # sustained token rate — obs top's serve line and the roofline
        # read these, and the per-step gauge's last value must not
        # stand in for the whole session
        if self.config.get("serve"):
            with self._lock:
                occ = self.gauges.get("slot_occupancy")
                toks = int(self.calls.get("tokens", 0))
            if occ is not None and occ.to_dict()["mean"] is not None:
                _metrics.gauge("serve.batch_occupancy").set(
                    occ.to_dict()["mean"])
            if toks and self.wall_seconds:
                _metrics.gauge("serve.tokens_per_s").set(
                    toks / self.wall_seconds)
        _metrics.get_registry().maybe_flush()

    def report(self) -> dict:
        with self._lock:
            out = {
                "run_id": self.run_id,
                "wall_seconds": round(self.wall_seconds, 4),
                "stage_seconds": {k: round(v, 4)
                                  for k, v in sorted(self.stages.items())},
                "stage_calls": dict(sorted(self.calls.items())),
                # live-progress triple: rows_done climbs per handled
                # batch; age_s is wall-so-far for UNFINISHED runs (the
                # committed wall_seconds stays finish()-only)
                "rows_done": self.rows_done,
                "finished": self.finished,
                "age_s": round(time.perf_counter() - self._t0, 4),
            }
            for name, h in sorted(self.gauges.items()):
                d = h.to_dict()
                out[f"{name}_mean"] = round(d["mean"], 2)
                out[f"{name}_max"] = d["max"]
            out.update(self.config)
        eff = self.overlap_efficiency()
        if eff is not None:
            out["overlap_efficiency"] = round(eff, 3)
        overlap = self.dispatch_overlap_s()
        if overlap is not None:
            out["dispatch_overlap_s"] = round(overlap, 4)
        return out


def _ring_size() -> int:
    try:
        return max(1, int(os.environ.get("TPUDL_PIPELINE_RING", "") or 16))
    except ValueError:
        return 16


_REPORTS: deque = deque(maxlen=_ring_size())
_REPORTS_LOCK = _tsan.named_lock("obs.pipeline.ring")


def set_last_pipeline(report: PipelineReport | None):
    """Filed by ``Frame.map_batches`` at the start of every run, so the
    caller above any transformer stack (bench.py, a notebook) can read
    the executor's stage breakdown without threading a handle through
    the transformer APIs. Reports live in a bounded ring keyed by run
    id — concurrent runs no longer clobber each other (each stays
    retrievable via :func:`get_pipeline_report` /
    :func:`pipeline_reports`)."""
    if report is None:
        return
    with _REPORTS_LOCK:
        if _tsan.ENABLED:
            _tsan.check_guarded("obs.pipeline.ring",
                                "pipeline-report ring",
                                lock=_REPORTS_LOCK)
        _REPORTS.append(report)


def last_pipeline_report() -> dict | None:
    """Stage breakdown of the most recent map_batches run (or None)."""
    with _REPORTS_LOCK:
        newest = _REPORTS[-1] if _REPORTS else None
    return newest.report() if newest is not None else None


def pipeline_reports() -> dict[str, dict]:
    """``{run_id: report_dict}`` for the ring's runs, oldest→newest."""
    with _REPORTS_LOCK:
        reports = list(_REPORTS)
    return {r.run_id: r.report() for r in reports}


def get_pipeline_report(run_id: str) -> dict | None:
    """One ring entry by run id (None once evicted)."""
    # snapshot under the ring lock, render outside it — like the two
    # accessors above (report() takes the report's own lock and does
    # real work; holding the ring across it is needless contention)
    with _REPORTS_LOCK:
        match = next((r for r in _REPORTS if r.run_id == run_id), None)
    return match.report() if match is not None else None
