"""Device traces + the host/device merged timeline.

The device half of the observability subsystem: capture a jax.profiler
trace (:func:`profile`), parse the trace-viewer JSON it writes
(:func:`load_trace_events`), aggregate the XLA Modules/Ops lanes
(:func:`summarize_device_trace`) — and MERGE the host-span tracer's
export (:mod:`tpudl.obs.tracer`) with the device lanes into one Chrome
trace (:func:`merge_trace_events`) plus one summary
(:func:`summarize_merged`): device busy %, host stage totals, and how
much host work was hidden under device compute. ``python -m tpudl.obs
trace <dir>`` drives all of this from the command line.

Time bases: the profiler's trace-viewer events use an opaque device
time base; host spans are epoch µs. The merge normalizes EACH stream to
its own first event, so the combined timeline is stream-relative — the
right call when both streams cover the same window (the
``obs.profile`` + tracer pattern), and stated in the summary either way.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os

__all__ = ["profile", "named_scope", "load_trace_events",
           "summarize_device_trace", "load_host_trace_events",
           "find_trace_files", "merge_trace_events", "summarize_merged"]

HOST_PID = 0  # merged-trace pid for the host lane (device pids re-number up)


@contextlib.contextmanager
def profile(log_dir: str):
    """Capture a jax.profiler trace for the enclosed block; view with
    tensorboard-plugin-profile or xprof against ``log_dir``, or parse
    programmatically with :func:`load_trace_events` +
    :func:`summarize_device_trace`. The capture window is recorded on
    the host-span tracer, so ``export_chrome_trace(path,
    window="profile")`` exports exactly the spans this block covered —
    the merged-timeline pairing."""
    import time

    import jax

    from tpudl.obs import tracer as _tracer_mod

    t0_us = time.time() * 1e6
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        _tracer_mod.get_tracer().last_profile_window = (t0_us,
                                                        time.time() * 1e6)


def named_scope(name: str):
    """Label pipeline stages inside jitted code (jax.named_scope; jax
    imported lazily so host-only Frame pipelines — which report into
    this module every map_batches call — never pay the jax import)."""
    import jax

    return jax.named_scope(name)


def load_trace_events(trace_dir: str) -> list[dict]:
    """Events from the newest trace-viewer JSON under ``trace_dir``
    (written by :func:`profile`; works for tunneled backends too — the
    PJRT plugin populates real device lanes)."""
    paths = glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                      recursive=True)
    if not paths:
        raise FileNotFoundError(f"no trace.json.gz under {trace_dir}")
    with gzip.open(max(paths, key=os.path.getmtime)) as f:
        tr = json.load(f)
    return tr["traceEvents"] if isinstance(tr, dict) else tr


def load_host_trace_events(path: str) -> list[dict]:
    """Events from a host-span tracer export (plain or gzipped JSON)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        tr = json.load(f)
    return tr["traceEvents"] if isinstance(tr, dict) else tr


def find_trace_files(trace_dir: str) -> dict:
    """Locate the newest host export and device trace under a directory:
    ``{"host": path|None, "device": path|None}``. Host exports are the
    tracer's ``*.host.trace.json`` (optionally ``.gz``); device traces
    are the profiler's ``*.trace.json.gz`` (host exports excluded)."""
    host = [p for pat in ("**/*.host.trace.json", "**/*.host.trace.json.gz")
            for p in glob.glob(os.path.join(trace_dir, pat), recursive=True)]
    dev = [p for p in glob.glob(os.path.join(trace_dir, "**/*.trace.json.gz"),
                                recursive=True)
           if not p.endswith(".host.trace.json.gz")]
    newest = lambda ps: max(ps, key=os.path.getmtime) if ps else None  # noqa: E731
    return {"host": newest(host), "device": newest(dev)}


def summarize_device_trace(events: list[dict]) -> dict:
    """Aggregate DEVICE-side time from a trace-viewer event list.

    Returns ``{"module_us": total_us_across_XLA-Module_executions,
    "module_count": n, "ops": {name: {us, count, category, long_name,
    bytes}}}``. The "XLA Modules" lane is the compiled program's
    on-device wall time — the honest chip-side throughput denominator,
    independent of host/tunnel dispatch latency; the "XLA Ops" lane is
    the per-fusion attribution (SURVEY.md §5.1). Empty summary (count 0)
    when the trace has no TPU lanes (CPU backend)."""
    procs, lanes = _trace_metadata(events)
    device_pids = {p for p, n in procs.items() if "TPU" in (n or "")}
    module_us, module_count = 0.0, 0
    ops: dict[str, dict] = {}
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        lane = lanes.get((e["pid"], e["tid"]), "")
        if lane == "XLA Modules":
            module_us += e.get("dur", 0.0)
            module_count += 1
        elif lane == "XLA Ops":
            a = e.get("args", {})
            rec = ops.setdefault(e["name"], {
                "us": 0.0, "count": 0, "category": "", "long_name": "",
                "bytes": 0})
            rec["us"] += e.get("dur", 0.0)
            rec["count"] += 1
            rec["category"] = a.get("hlo_category", rec["category"])
            rec["long_name"] = a.get("long_name", rec["long_name"])
            rec["bytes"] += int(a.get("bytes_accessed", 0) or 0)
    return {"module_us": module_us, "module_count": module_count,
            "ops": ops}


def _trace_metadata(events):
    """(pid → process name, (pid, tid) → lane name) from "M" events."""
    procs, lanes = {}, {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            procs[e["pid"]] = e["args"].get("name", "")
        elif e.get("name") == "thread_name":
            lanes[(e["pid"], e["tid"])] = e["args"].get("name", "")
    return procs, lanes


def _durations(events, keep) -> list[tuple[float, float]]:
    """(start, end) µs intervals of "X" events passing ``keep(e)``."""
    out = []
    for e in events:
        if e.get("ph") == "X" and keep(e):
            ts = float(e.get("ts", 0.0))
            out.append((ts, ts + float(e.get("dur", 0.0))))
    return out


def _merged(intervals) -> list[tuple[float, float]]:
    """Coalesce possibly-overlapping intervals — the ONE sweep behind
    both union and intersection (diverging copies would skew
    device_busy_us vs overlap_us)."""
    out: list = []
    for s, e in sorted(intervals):
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _union_us(intervals) -> float:
    """Total covered time of possibly-overlapping intervals."""
    return sum(e - s for s, e in _merged(intervals))


def _intersection_us(a, b) -> float:
    """Covered time where union(a) and union(b) overlap."""
    am, bm = _merged(a), _merged(b)
    i = j = 0
    total = 0.0
    while i < len(am) and j < len(bm):
        s = max(am[i][0], bm[j][0])
        e = min(am[i][1], bm[j][1])
        if s < e:
            total += e - s
        if am[i][1] < bm[j][1]:
            i += 1
        else:
            j += 1
    return total


def _normalize(events) -> list[dict]:
    """Shift a stream's "X" timestamps so its first event starts at 0
    (metadata events pass through untouched)."""
    xs = [float(e["ts"]) for e in events
          if e.get("ph") == "X" and "ts" in e]
    if not xs:
        return list(events)
    base = min(xs)
    out = []
    for e in events:
        if e.get("ph") == "X" and "ts" in e:
            e = dict(e)
            e["ts"] = float(e["ts"]) - base
        out.append(e)
    return out


def merge_trace_events(host_events: list[dict],
                       device_events: list[dict]) -> list[dict]:
    """One Chrome trace with the host-span lane alongside the device
    lanes. Each stream is normalized to its own start (time bases are
    incompatible: host = epoch µs, device = profiler-internal); host
    events take ``pid=HOST_PID`` and device pids are renumbered from 1
    upward so the lanes can never collide."""
    host = _normalize(host_events)
    dev = _normalize(device_events)
    merged = []
    for e in host:
        e = dict(e)
        e["pid"] = HOST_PID
        merged.append(e)
    pid_map: dict = {}
    for e in device_events:
        if "pid" in e and e["pid"] not in pid_map:
            pid_map[e["pid"]] = len(pid_map) + 1
    for e in dev:
        e = dict(e)
        if "pid" in e:
            e["pid"] = pid_map[e["pid"]]
        merged.append(e)
    return merged


def summarize_merged(host_events: list[dict],
                     device_events: list[dict]) -> dict:
    """The merged-timeline summary behind ``python -m tpudl.obs trace``.

    - ``device``: :func:`summarize_device_trace` of the device stream;
    - ``device_busy_us`` / ``device_busy_frac``: union of XLA-Modules
      intervals over the stream's wall window — the chip's duty cycle;
    - ``host_stage_us``: per-span-name host totals (the run-wide
      generalization of PipelineReport's stage_seconds);
    - ``host_busy_us``: union of all host spans;
    - ``overlap_us`` / ``host_overlap_frac``: host-busy time that
      coincides with device-busy time, on each stream's own normalized
      clock — the run-level overlap-efficiency twin. Both streams must
      cover the same window for this to mean overlap (the
      ``obs.profile`` + tracer capture pattern does).
    """
    procs, lanes = _trace_metadata(device_events)
    device_pids = {p for p, n in procs.items() if "TPU" in (n or "")}
    dev_norm = _normalize(device_events)
    host_norm = _normalize(host_events)
    mod_iv = _durations(
        dev_norm, lambda e: e.get("pid") in device_pids
        and lanes.get((e["pid"], e.get("tid")), "") == "XLA Modules")
    host_iv = _durations(host_norm, lambda e: True)
    host_stage_us: dict[str, float] = {}
    host_stage_calls: dict[str, int] = {}
    for e in host_norm:
        if e.get("ph") == "X":
            host_stage_us[e["name"]] = (host_stage_us.get(e["name"], 0.0)
                                        + float(e.get("dur", 0.0)))
            host_stage_calls[e["name"]] = host_stage_calls.get(e["name"],
                                                               0) + 1
    xs = [x for s, e in mod_iv + host_iv for x in (s, e)]
    wall_us = (max(xs) - min(xs)) if xs else 0.0
    dev_xs = [x for s, e in mod_iv for x in (s, e)]
    dev_wall = (max(dev_xs) - min(dev_xs)) if dev_xs else 0.0
    device_busy = _union_us(mod_iv)
    host_busy = _union_us(host_iv)
    overlap = _intersection_us(host_iv, mod_iv)
    summary = summarize_device_trace(device_events)
    top = sorted(summary["ops"].items(), key=lambda kv: -kv[1]["us"])[:5]
    return {
        "device": summary,
        "device_busy_us": round(device_busy, 1),
        "device_busy_frac": (round(device_busy / dev_wall, 4)
                             if dev_wall > 0 else None),
        "host_stage_us": {k: round(v, 1)
                          for k, v in sorted(host_stage_us.items())},
        "host_stage_calls": dict(sorted(host_stage_calls.items())),
        "host_busy_us": round(host_busy, 1),
        "overlap_us": round(overlap, 1),
        "host_overlap_frac": (round(overlap / host_busy, 4)
                              if host_busy > 0 else None),
        "wall_us": round(wall_us, 1),
        "top_ops": [{"name": k, "us": round(v["us"], 1),
                     "count": v["count"], "category": v["category"]}
                    for k, v in top],
    }
