"""``python -m tpudl.obs`` — the observability CLI.

``trace <dir>`` merges the newest host-span export
(``*.host.trace.json[.gz]``, written by
``obs.get_tracer().export_chrome_trace``) with the newest jax.profiler
device trace (``*.trace.json.gz``) under ``<dir>``, writes the combined
Chrome trace to ``<dir>/merged.trace.json`` (open it in Perfetto /
chrome://tracing) and prints the merged summary: device busy time, host
stage totals, overlap, top ops. Either stream alone still summarizes —
a CPU-only run gets host totals, a host-blind capture gets device lanes.

``metrics <file.jsonl>`` schema-checks and tail-summarizes a
``TPUDL_METRICS_FILE`` emission (delegates the check to
``tools/validate_metrics.py``'s rules).

``doctor <dump-or-dir>`` merges flight-recorder dumps
(``tpudl-dump-*.json.gz``, one per process) and classifies the failure
— infeed stall vs decode-error storm vs dispatch slowdown vs clean
external kill — printing the timeline tail, per-stage throughput at
time of death, and the suspect stage (:mod:`tpudl.obs.doctor`).

``ledger <dump-or-dir>`` re-checks the attribution plane's
reconciliation invariant offline — per-scope sums + the unattributed
bucket against the global counters, recomputed from each artifact's own
``ledger`` + ``metrics`` sections — over every flight dump and status
file under the path, then prints merged per-scope totals
(:mod:`tpudl.obs.attribution`; rc 0 reconciled / 1 mismatch / 2 none).

``top <status-dir>`` renders a refreshing terminal view of every live
``tpudl-status-<pid>.json`` in the directory (written by processes
running with ``TPUDL_STATUS_DIR`` set): active runs with per-stage
times, rows done/total + ETA, heartbeat ages, and the roofline/advisor
verdict. ``--once`` prints one frame and exits (rc 2 when nothing is
running there). :mod:`tpudl.obs.live` owns the file contract.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tpudl.obs import trace as T


def _fmt_us(us: float) -> str:
    return f"{us / 1e3:.2f} ms" if us >= 1e3 else f"{us:.0f} us"


def cmd_trace(trace_dir: str, out_path: str | None = None) -> int:
    found = T.find_trace_files(trace_dir)
    host_events = (T.load_host_trace_events(found["host"])
                   if found["host"] else [])
    # load the exact file find_trace_files selected (a re-glob could
    # pick a newer gzipped HOST export as the device stream);
    # load_host_trace_events is format-wise just "events from one
    # [gzipped] trace JSON", which is what's needed here
    device_events = (T.load_host_trace_events(found["device"])
                     if found["device"] else [])
    if not host_events and not device_events:
        print(f"no host or device traces under {trace_dir}",
              file=sys.stderr)
        return 2
    print(f"host trace:   {found['host'] or '(none)'}")
    print(f"device trace: {found['device'] or '(none)'}")
    merged = T.merge_trace_events(host_events, device_events)
    out_path = out_path or os.path.join(trace_dir, "merged.trace.json")
    with open(out_path, "w") as f:
        json.dump({"traceEvents": merged, "displayTimeUnit": "ms"}, f)
    print(f"merged trace: {out_path} (open in Perfetto / chrome://tracing)")
    s = T.summarize_merged(host_events, device_events)
    print("\n== merged timeline summary ==")
    print(f"wall window:        {_fmt_us(s['wall_us'])}")
    busy = s["device_busy_frac"]
    print(f"device busy:        {_fmt_us(s['device_busy_us'])}"
          + (f" ({busy:.1%} of device window)" if busy is not None else "")
          + f" across {s['device']['module_count']} module executions")
    print(f"host busy:          {_fmt_us(s['host_busy_us'])}")
    ov = s["host_overlap_frac"]
    print(f"host/device overlap: {_fmt_us(s['overlap_us'])}"
          + (f" ({ov:.1%} of host work hidden under device compute)"
             if ov is not None else ""))
    if s["host_stage_us"]:
        print("host stages:")
        for name, us in sorted(s["host_stage_us"].items(),
                               key=lambda kv: -kv[1]):
            print(f"  {name:<28} {_fmt_us(us):>12}"
                  f"  x{s['host_stage_calls'][name]}")
    if s["top_ops"]:
        print("top device ops:")
        for op in s["top_ops"]:
            print(f"  {op['name']:<28} {_fmt_us(op['us']):>12}"
                  f"  x{op['count']}  {op['category']}")
    return 0


def cmd_metrics(path: str) -> int:
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "tools"))
    try:
        from validate_metrics import validate_metrics_file
    except ImportError:
        # installed wheels ship only tpudl.*; the validator lives in the
        # repo's tools/ dir
        print("tools/validate_metrics.py not found (run from a source "
              "checkout, or use tools/validate_metrics.py directly)",
              file=sys.stderr)
        return 2

    errors, n_lines, last = validate_metrics_file(path)
    for err in errors:
        print(f"INVALID: {err}", file=sys.stderr)
    print(f"{path}: {n_lines} lines, "
          f"{'OK' if not errors else f'{len(errors)} errors'}")
    if last:
        print(f"last snapshot ({last.get('event')}, pid {last.get('pid')}):")
        for name, m in sorted(last.get("metrics", {}).items()):
            if m["type"] == "counter":
                print(f"  {name:<40} {m['value']}")
            elif m["type"] == "gauge":
                print(f"  {name:<40} {m['value']} "
                      f"(mean {m.get('mean')}, max {m.get('max')})")
            else:
                print(f"  {name:<40} n={m['count']} mean={m.get('mean')} "
                      f"p95={m.get('p95')}")
    return 0 if not errors else 1


def cmd_doctor(path: str, tail: int = 12) -> int:
    from tpudl.obs import doctor as D

    got = D.diagnose(path)
    if got is None:
        print(f"no flight-recorder dumps (tpudl-dump-*.json[.gz]) "
              f"under {path}", file=sys.stderr)
        return 2
    merged, diagnosis = got
    print(D.format_report(merged, diagnosis, tail=tail))
    # rc contract: 0 = readable + classified, 1 = unclassified (a human
    # must look), 2 = no dumps at all
    return 0 if diagnosis["classification"] != "unclassified" else 1


def cmd_ledger(path: str) -> int:
    """Offline attribution reconciliation: re-check the ledger
    invariant (per-scope sums + unattributed == global counters) in
    every flight dump and status file under ``path`` — recomputed from
    the artifact's OWN ledger + metrics sections, never trusting an
    embedded verdict — and print the merged per-scope totals.

    rc contract (sibling of doctor's): 0 = every artifact reconciles,
    1 = at least one mismatch, 2 = no ledger-bearing artifact found."""
    from tpudl.obs import attribution as A
    from tpudl.obs import doctor as D
    from tpudl.obs import live as L

    artifacts = []  # (label, ledger snapshot, metrics snapshot)
    for d in D.load_dumps(path):
        led = d.get("ledger")
        if isinstance(led, dict):
            artifacts.append((f"dump pid {d.get('pid')} "
                              f"({d.get('_path', '?')})",
                              led, d.get("metrics") or {}))
    if os.path.isdir(path):
        for st in L.read_statuses(path):
            led = st.get("ledger")
            if isinstance(led, dict):
                artifacts.append((f"status pid {st.get('pid')} "
                                  f"({st.get('_path', '?')})",
                                  led, st.get("metrics") or {}))
    if not artifacts:
        print(f"no ledger-bearing dumps or status files under {path}",
              file=sys.stderr)
        return 2
    bad = 0
    merged: dict[str, dict] = {}
    for label, led, metrics in artifacts:
        rec = A.reconcile_snapshot(led, metrics)
        verdict = "RECONCILED" if rec["ok"] else "MISMATCH"
        print(f"{verdict}: {label} — "
              f"{len(led.get('scopes') or {})} scope(s), "
              f"{int(led.get('evicted') or 0)} evicted")
        for c in rec["checks"]:
            if not c["ok"]:
                bad += 1
                print(f"  {c['field']}: ledger {c['ledger']} != "
                      f"{c['metric']} {c['global']}")
        rows = list((led.get("scopes") or {}).items())
        una = led.get("unattributed") or {}
        if any(isinstance(v, (int, float)) and v for v in una.values()):
            rows.append(("(unattributed)", una))
        for key, row in rows:
            at = merged.setdefault(key, {})
            for f in A.LEDGER_FIELDS:
                v = row.get(f)
                if isinstance(v, (int, float)):
                    at[f] = at.get(f, 0.0) + float(v)
    print(f"\n== merged scope totals ({len(artifacts)} artifact(s)) ==")
    for key, row in sorted(merged.items()):
        bits = [f"{f} {row[f]:.0f}" for f in
                ("rows_in", "rows_out", "tokens_in", "tokens_out",
                 "wire_bytes", "hbm_bytes", "serve_completed")
                if row.get(f)]
        print(f"  {key:<28} " + ("  ".join(bits) or "(no charges)"))
    return 1 if bad else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpudl.obs",
        description="merge + summarize tpudl traces, metrics and dumps")
    sub = p.add_subparsers(dest="cmd", required=True)
    pt = sub.add_parser("trace", help="merge host + device traces in a dir")
    pt.add_argument("trace_dir")
    pt.add_argument("--out", default=None,
                    help="merged trace path (default <dir>/merged.trace.json)")
    pm = sub.add_parser("metrics", help="validate + summarize a metrics JSONL")
    pm.add_argument("path")
    pd = sub.add_parser(
        "doctor", help="classify a failure from flight-recorder dump(s)")
    pd.add_argument("path", help="one tpudl-dump-*.json.gz or a dir of them")
    pd.add_argument("--tail", type=int, default=12,
                    help="timeline tail length (default 12 spans)")
    pl = sub.add_parser(
        "ledger",
        help="offline attribution reconciliation over dumps/status "
             "files")
    pl.add_argument("path",
                    help="one dump file or a dir of dumps/status files")
    pp = sub.add_parser(
        "top", help="live view of tpudl-status-*.json files in a dir")
    pp.add_argument("status_dir",
                    help="the TPUDL_STATUS_DIR processes write into")
    pp.add_argument("--once", action="store_true",
                    help="print one frame and exit (rc 2 when empty)")
    pp.add_argument("--interval", type=float, default=2.0,
                    help="refresh period in seconds (default 2)")
    args = p.parse_args(argv)
    if args.cmd == "trace":
        return cmd_trace(args.trace_dir, args.out)
    if args.cmd == "doctor":
        return cmd_doctor(args.path, args.tail)
    if args.cmd == "ledger":
        return cmd_ledger(args.path)
    if args.cmd == "top":
        from tpudl.obs import live as L

        return L.top_main(args.status_dir, once=args.once,
                          interval=args.interval)
    return cmd_metrics(args.path)


if __name__ == "__main__":
    sys.exit(main())
