"""Host-span tracer: thread-aware wall-clock spans in a bounded ring.

The host half of the merged timeline (OBSERVABILITY.md): any layer
wraps work in ``obs.span("stage", **attrs)`` and the span lands in a
process-wide ring buffer with thread id/name, run id and attributes.
``export_chrome_trace`` writes the ring as Chrome trace-event JSON —
the same format the jax.profiler's trace-viewer dump uses — so
``python -m tpudl.obs trace <dir>`` can merge host prepare/dispatch/d2h
spans with the XLA Module/Ops device lanes into one timeline
(:mod:`tpudl.obs.trace`).

Clock model: durations come from ``time.perf_counter()`` (monotonic,
sub-µs); each span's start is stamped in epoch microseconds from a
live ``time.time()`` read at span end, so exports stay aligned with
wall-clock windows (``obs.profile`` records its capture window the
same way) even across suspend/NTP steps. Device traces carry their own
opaque time base; the merge normalizes each stream to its own start
(see ``merge_trace_events``) — alignment is per-stream-relative, which
is exact for the intended use (both streams captured over the same
window by ``obs.profile`` + the tracer).

Hot-loop discipline: recording a span is two perf_counter reads plus a
lock-guarded deque append — the ring (``TPUDL_TRACE_RING`` spans,
default 65536) never grows past its cap, so tracing can stay on in
production.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque

from tpudl.testing import tsan as _tsan

__all__ = ["Span", "Tracer", "get_tracer", "span", "export_chrome_trace"]

_DEFAULT_RING = 65536


class Span:
    """One completed host span (times in epoch microseconds)."""

    __slots__ = ("name", "ts_us", "dur_us", "tid", "thread_name", "attrs")

    def __init__(self, name, ts_us, dur_us, tid, thread_name, attrs):
        self.name = name
        self.ts_us = ts_us
        self.dur_us = dur_us
        self.tid = tid
        self.thread_name = thread_name
        self.attrs = attrs

    def to_event(self, pid: int) -> dict:
        e = {"ph": "X", "name": self.name, "pid": pid, "tid": self.tid,
             "ts": self.ts_us, "dur": self.dur_us}
        if self.attrs:
            e["args"] = dict(self.attrs)
        return e


class Tracer:
    """Bounded, thread-safe span ring.

    ``with tracer.span("decode", batch=3):`` records one span on exit;
    raising inside the block still records it (the failing span is
    usually the interesting one) with ``error`` set in its attrs.
    """

    def __init__(self, ring: int | None = None):
        if ring is None:
            try:
                ring = int(os.environ.get("TPUDL_TRACE_RING", "")
                           or _DEFAULT_RING)
            except ValueError:
                ring = _DEFAULT_RING
        self._spans: deque[Span] = deque(maxlen=max(1, int(ring)))
        self._lock = _tsan.named_lock("obs.tracer.ring")
        self.dropped = 0  # spans pushed out of the ring
        # (start_us, end_us) of the most recent obs.profile capture —
        # set by tpudl.obs.trace.profile so exports can window to it
        self.last_profile_window: tuple[float, float] | None = None

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        t0 = time.perf_counter()
        try:
            yield
        except BaseException as e:
            attrs = dict(attrs)
            attrs["error"] = type(e).__name__
            raise
        finally:
            dur_us = (time.perf_counter() - t0) * 1e6
            # epoch stamp taken LIVE at span end (duration still from
            # the monotonic clock): a frozen import-time anchor would
            # drift from profile()'s time.time() window across suspend
            # or NTP steps, silently emptying window="profile" exports
            ts_us = time.time() * 1e6 - dur_us
            th = threading.current_thread()
            s = Span(name, ts_us, dur_us, th.ident or 0, th.name,
                     attrs or None)
            with self._lock:
                if len(self._spans) == self._spans.maxlen:
                    self.dropped += 1
                self._spans.append(s)

    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self):
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    def to_events(self, window: tuple[float, float] | None = None,
                  ) -> list[dict]:
        """Chrome trace-event list: process/thread metadata + one "X"
        event per span, epoch-µs timestamps. ``window=(start_us,
        end_us)`` keeps only spans overlapping it — the ring outlives
        any one capture, and merging a device trace against
        pre-capture spans would mis-attribute overlap."""
        pid = os.getpid()
        spans = self.spans()
        if window is not None:
            w0, w1 = window
            spans = [s for s in spans
                     if s.ts_us + s.dur_us >= w0 and s.ts_us <= w1]
        events = [{"ph": "M", "pid": pid, "name": "process_name",
                   "args": {"name": "tpudl host"}}]
        seen_tids = {}
        for s in spans:
            if s.tid not in seen_tids:
                seen_tids[s.tid] = s.thread_name
        for tid, tname in sorted(seen_tids.items()):
            events.append({"ph": "M", "pid": pid, "tid": tid,
                           "name": "thread_name", "args": {"name": tname}})
        events.extend(s.to_event(pid) for s in spans)
        return events

    def export_chrome_trace(self, path: str,
                            window: object = None) -> str:
        """Write the ring as ``{"traceEvents": [...]}`` JSON. Name the
        file ``*.host.trace.json`` so the CLI's directory scan finds it
        next to the profiler's ``*.trace.json.gz``.

        ``window="profile"`` keeps only spans overlapping the most
        recent ``obs.profile`` capture (the merged-timeline workflow —
        without it a long-lived process exports its whole ring and the
        merge attributes overlap to pre-capture spans); an explicit
        ``(start_us, end_us)`` tuple windows arbitrarily; None exports
        everything."""
        if window == "profile":
            window = self.last_profile_window
        payload = {"traceEvents": self.to_events(window=window),
                   "displayTimeUnit": "ms",
                   "metadata": {"tpudl": "host-span-tracer",
                                "dropped_spans": self.dropped}}
        with open(path, "w") as f:
            json.dump(payload, f)
        return path


_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def span(name: str, **attrs):
    """``with obs.span("ml.Featurizer.transform", rows=n):`` — record a
    host span on the process-wide tracer."""
    return _TRACER.span(name, **attrs)


def export_chrome_trace(path: str, window: object = None) -> str:
    return _TRACER.export_chrome_trace(path, window=window)
