"""``obs doctor`` — turn flight-recorder dumps into a diagnosis.

The offline half of failure forensics (OBSERVABILITY.md): given one
``tpudl-dump-*.json.gz`` (or a directory of them from a multi-host
gang), merge per-host evidence and CLASSIFY the failure:

- ``preempted_resumable`` — the job runtime (tpudl.jobs) caught the
  SIGTERM, checkpointed, and exited RC_PREEMPTED: the dump carries a
  resume-manifest pointer — relaunch the same JobSpec to resume.
  Checked FIRST: stall/storm history must not bury the one actionable
  fact;
- ``infeed_stall`` — the watchdog flagged a frozen input-side stage
  (prepare/h2d/infeed), or the pipeline report died with the consumer
  parked in ``infeed_wait``: the input pipeline stopped delivering;
- ``decode_error_storm`` — decode/corruption errors are a large
  fraction of reads (``imageio.decode_errors``, ``data.cache.corrupt``
  and the error ring agree): the data went bad, not the code;
- ``recompile_storm`` — the traceck sentinel (``TPUDL_TRACECK=1``,
  tpudl.testing.traceck) flagged a fn identity retracing past its
  threshold (``traceck.storms`` and the error ring agree): the run
  was recompiling instead of computing — ranked beside (and checked
  before) ``dispatch_slowdown``, because a storm IS the usual cause
  of a slow dispatch that nobody can explain;
- ``degraded_run`` — the fault-containment supervisor
  (tpudl.frame.supervisor, FAULTS.md) was walking its degradation
  ladder when the process died: ``frame.degraded.*`` metrics and the
  ``frame.degraded`` error-ring events name the rungs and the faults
  that triggered them. Checked after the storm rules (a storm explains
  WHY the run was degrading) and before the stall rules — a run the
  supervisor was actively retrying is not "stuck", it is recovering,
  and the actionable fact is which rung it died on. Gated on
  degradation being CURRENT at death (the exhaustion dump, the
  supervisor's live heartbeat, or the newest report's ``degraded_to``)
  so one long-recovered fault never reroutes a later unrelated death;
- ``overload_shed`` — the serve plane's admission control was
  rejecting a sustained fraction of offered load when the process
  died (``serve.rejects`` against ``serve.requests``, with the queue
  depth/cap gauges as the at-death evidence): the death is — or rode
  on — an overload the queue answered with TYPED rejects, not a hang.
  Ordered after ``degraded_run`` (the ladder explains WHY capacity
  shrank when both fired) and before the stall rules: a saturated
  serve loop still beating its heartbeat is shedding, not stuck;
- ``slo_burn`` — the serve plane was admitting fine but missing its
  latency objective: the windowed burn gauge (``serve.slo.burn_short``,
  tpudl.obs.slo) was >= 1 at death and the error ring holds tail
  exemplars whose segment breakdowns
  (queue_wait/batching/prefill/decode, tpudl.serve.reqtrace) name
  WHERE the time went. Ordered after ``overload_shed`` — shedding
  outranks slow (typed rejects are the louder, more actionable fact)
  — and before the stall rules: a burning-but-live serve loop still
  beats its heartbeat (slow, not stuck);
- ``dispatch_slowdown`` — a stall (or dominant stage share) in
  ``dispatch``: the device/backend stopped answering or slowed;
- ``clean_external_kill`` — a SIGTERM/SIGQUIT dump with no stall and
  no error storm, and NO resume state: the driver killed a healthy
  run (the rc=124 class);
- ``exception`` — an unhandled exception dump: the error is right
  there;
- ``unclassified`` — evidence exists but matches no rule (everything
  the doctor looked at is printed, so a human can take over).

Importable (:func:`load_dumps` / :func:`merge_dumps` / :func:`classify`
/ :func:`format_report`) and runnable:
``python -m tpudl.obs doctor <dump-or-dir>``.
"""

from __future__ import annotations

import glob
import gzip
import json
import os

__all__ = ["load_dump", "load_dumps", "merge_dumps", "classify",
           "format_report", "INFEED_STAGES"]

# input-side stage names: a stall whose last beat named one of these is
# the input pipeline's fault, not the device's
INFEED_STAGES = ("prepare", "h2d", "infeed", "infeed_wait", "decode",
                 "pack", "cache")
# storm thresholds: at least this many bad events AND this fraction of
# the read attempts (an isolated corrupt file is noise, not a storm)
STORM_MIN_EVENTS = 8
STORM_MIN_FRAC = 0.10

# overload_shed thresholds: same shape as the storm gate — absolute
# floor (a handful of rejects on a tiny run is noise) AND a fraction
# of OFFERED load (admitted + rejected), so a long healthy run with a
# brief historical blip never reroutes an unrelated death
SHED_MIN_EVENTS = 8
SHED_MIN_FRAC = 0.10

# slo_burn gates: the burn gauge must show the budget actually burning
# at death AND enough tail exemplars must exist to make the dominant-
# segment attribution statistics, not an anecdote
SLO_BURN_MIN = 1.0
SLO_MIN_EXEMPLARS = 3
# the reqtrace segment model, in lifecycle order, with the remedy each
# dominant segment points at
SLO_SEGMENTS = ("queue_wait", "batching", "prefill", "decode")
SLO_REMEDIES = {
    "queue_wait": "requests park at admission — raise "
                  "TPUDL_SERVE_SLOTS or add serving capacity",
    "batching": "rung packing is the cost — check the prompt-bucket "
                "ladder (TPUDL_BUCKET_LADDER)",
    "prefill": "first-token work dominates — warm the AOT program "
               "store (TPUDL_COMPILE_AOT) so prefill rungs restore, "
               "not compile",
    "decode": "decode steps dominate — lower max_new, raise "
              "TPUDL_SERVE_SLOTS, or add device capacity",
}


def load_dump(path: str) -> dict:
    """One dump file (gzip or plain JSON) → payload dict."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8") as f:
        return json.load(f)


def find_dump_files(path: str) -> list[str]:
    """Dump files under ``path`` (a file is itself; a directory is
    scanned for the recorder's naming pattern, both gzip and plain)."""
    if os.path.isdir(path):
        hits = sorted(glob.glob(os.path.join(path, "tpudl-dump-*.json.gz"))
                      + glob.glob(os.path.join(path, "tpudl-dump-*.json")))
        return hits
    return [path] if os.path.exists(path) else []


def load_dumps(path: str) -> list[dict]:
    """Every parseable dump under ``path``; unreadable files are
    skipped (a torn dump from a dying host must not block the
    readable ones)."""
    dumps = []
    for p in find_dump_files(path):
        try:
            d = load_dump(p)
        except (OSError, json.JSONDecodeError, EOFError):
            continue
        if isinstance(d, dict) and d.get("schema") == "tpudl-flight-dump":
            d["_path"] = p
            dumps.append(d)
    return dumps


def merge_dumps(dumps: list[dict]) -> dict:
    """Per-process dumps → one merged view. Deduplication is keyed by
    (process_index, pid) — only dumps from the SAME process (an
    explicit obs.dump() plus the death dump) collapse to the newest;
    two processes sharing index 0 (a bench parent and its trial
    subprocess in one dir) both keep their evidence. ``hosts`` keys
    are the process index, suffixed with the pid only when several
    processes share an index. The merged timeline tail interleaves
    every process's spans by wall-clock."""
    by_proc: dict[tuple[int, int], dict] = {}
    for d in dumps:
        key = (int(d.get("process_index", 0) or 0),
               int(d.get("pid", 0) or 0))
        cur = by_proc.get(key)
        if cur is None or d.get("ts", 0) >= cur.get("ts", 0):
            by_proc[key] = d
    idx_counts: dict[int, int] = {}
    for idx, _pid in by_proc:
        idx_counts[idx] = idx_counts.get(idx, 0) + 1
    hosts: dict[str, dict] = {}
    items = []  # (host label for attribution, dump)
    for (idx, pid), d in sorted(by_proc.items()):
        label = str(idx) if idx_counts[idx] == 1 else f"{idx}:{pid}"
        hosts[label] = d
        items.append((label, d))
    spans = []
    for label, d in items:
        for s in d.get("spans", []) or []:
            spans.append(dict(s, host=label))
    spans.sort(key=lambda s: s.get("ts_us") or 0)

    def _by_ts(key):
        # wall-clock order across processes: "the last stall" must be
        # the NEWEST event, not whichever dump iterated last
        entries = [dict(e, host=label) for label, d in items
                   for e in d.get(key, []) or []]
        entries.sort(key=lambda e: e.get("ts") or 0)
        return entries

    return {"hosts": hosts, "n_hosts": len(hosts),
            "spans": spans,
            "stalls": _by_ts("stalls"),
            "errors": _by_ts("errors"),
            "restarts": _by_ts("restarts")}


def _metric_value(dump: dict, name: str) -> float:
    m = (dump.get("metrics") or {}).get(name) or {}
    v = m.get("value")
    return float(v) if isinstance(v, (int, float)) else 0.0


def _last_report(dump: dict) -> dict | None:
    reports = dump.get("pipeline_reports") or {}
    if not reports:
        return None
    # ring order is oldest→newest; run ids are "<pid>-<seq>"
    return list(reports.values())[-1]


def _stage_rates(report: dict | None) -> dict:
    """Per-stage throughput at time of death: seconds, calls and
    seconds-per-call for each executor stage of the newest report."""
    if not report:
        return {}
    secs = report.get("stage_seconds") or {}
    calls = report.get("stage_calls") or {}
    out = {}
    for name, s in secs.items():
        n = calls.get(name) or 0
        out[name] = {"seconds": round(float(s), 4), "calls": int(n),
                     "s_per_call": round(float(s) / n, 5) if n else None}
    return out


def _stall_stage(stall: dict) -> str | None:
    # the frozen stage is the one ENTERED longest ago and never exited
    # (in_flight), not the last one to beat: a wedged dispatch outlives
    # the prepare pool's final beats
    inflight = stall.get("in_flight") or {}
    if inflight:
        return max(inflight.items(),
                   key=lambda kv: kv[1].get("age_s") or 0)[0]
    info = stall.get("info") or {}
    stage = info.get("stage")
    return str(stage) if stage is not None else None


def _ledger_evidence(hosts: dict) -> list[str]:
    """Common evidence from the dumps' attribution ledgers (v3): name
    the scope that dominated the process at death — the WHO axis every
    classification benefits from in a multi-tenant process — and flag a
    broken reconciliation (a ledger/global mismatch is itself a bug
    worth surfacing, whatever killed the run)."""
    merged: dict[str, float] = {}
    hbm: dict[str, float] = {}
    bad_checks: list[str] = []
    for d in hosts.values():
        led = d.get("ledger") or {}
        rows = list((led.get("scopes") or {}).items())
        una = led.get("unattributed") or {}
        if any(isinstance(v, (int, float)) and v for v in una.values()):
            rows.append(("(unattributed)", una))
        for key, row in rows:
            work = sum(float(row.get(f) or 0) for f in
                       ("rows_in", "rows_out", "tokens_in",
                        "tokens_out", "serve_completed"))
            merged[key] = merged.get(key, 0.0) + work
            hbm[key] = hbm.get(key, 0.0) \
                + float(row.get("hbm_bytes") or 0)
        rec = led.get("reconcile") or {}
        if rec and not rec.get("ok", True):
            bad_checks.extend(
                f"{c['field']} ledger {c['ledger']} != global "
                f"{c['global']}" for c in rec.get("checks", [])
                if not c.get("ok"))
    out: list[str] = []
    if merged:
        key, work = max(merged.items(), key=lambda kv: kv[1])
        line = (f"dominant scope at death: {key} "
                f"({work:.0f} rows+tokens attributed")
        if hbm.get(key):
            line += f", {hbm[key] / 2**20:.1f} MB HBM resident"
        out.append(line + f"; {len(merged)} scope(s) in the ledger)")
    if bad_checks:
        out.append("ledger reconciliation BROKEN at death: "
                   + "; ".join(bad_checks[:3]))
    return out


def _is_infeed(stall: dict) -> bool:
    stage = (_stall_stage(stall) or "").lower()
    if any(k in stage for k in INFEED_STAGES):
        return True
    name = str(stall.get("name", "")).lower()
    # a stalled frame heartbeat with no stage info yet: the run froze
    # before its first dispatch — the input side by construction
    return stage == "" and "frame" in name


def classify(merged: dict) -> dict:
    """The diagnosis: ``{classification, suspect_stage, suspect_host,
    evidence: [...], stage_rates}``. Rules are ordered by specificity —
    an error storm explains a stall (workers burning time on garbage),
    so the storm wins when both fire."""
    evidence: list[str] = []
    hosts = merged.get("hosts") or {}
    stalls = merged.get("stalls") or []
    errors = merged.get("errors") or []
    restarts = merged.get("restarts") or []

    # evidence common to every rule
    decode_errs = sum(_metric_value(d, "imageio.decode_errors")
                      for d in hosts.values())
    corrupt = sum(_metric_value(d, "data.cache.corrupt")
                  for d in hosts.values())
    reads = sum(_metric_value(d, "imageio.files_read")
                + _metric_value(d, "data.cache.hits")
                + _metric_value(d, "data.cache.misses")
                for d in hosts.values())
    bad = decode_errs + corrupt
    ring_bad = sum(1 for e in errors
                   if str(e.get("kind", "")).startswith(
                       ("imageio", "decode", "data.cache", "shard")))
    newest = max(hosts.values(), key=lambda d: d.get("ts", 0)) \
        if hosts else {}
    reason = str(newest.get("reason", ""))
    report = _last_report(newest)
    rates = _stage_rates(report)
    suspect_host = None
    if stalls:
        suspect_host = stalls[-1].get("host")
    if restarts:
        evidence.append(
            f"{len(restarts)} gang restart(s); last: "
            f"{restarts[-1].get('error_type')}: "
            f"{str(restarts[-1].get('error'))[:120]} "
            f"(attempt {restarts[-1].get('attempt')}, "
            f"step {restarts[-1].get('step')})")
    evidence.extend(_ledger_evidence(hosts))

    # 1. the job runtime turned the kill into a recovery event: the
    #    dump says so (reason) or carries the job.preempted breadcrumb
    #    with the resume-manifest pointer. FIRST rule: the runtime
    #    literally checkpointed and exited rc 75 — stall/storm evidence
    #    from earlier in the run's history must not bury the one
    #    actionable fact (relaunch the spec); it still rides along in
    #    the evidence list. Checked across ALL hosts — in a gang, any
    #    member that persisted resume state makes the death resumable
    preempt_ev = None
    for d in sorted(hosts.values(), key=lambda d: d.get("ts", 0),
                    reverse=True):
        for ev in reversed(d.get("events") or []):
            if ev.get("kind") == "job.preempted":
                preempt_ev = ev
                break
        if preempt_ev is not None:
            break
    if reason == "preempted_resumable" or preempt_ev is not None:
        manifest = (preempt_ev or {}).get("manifest")
        if stalls:
            last = stalls[-1]
            evidence.append(
                f"history: watchdog flagged {len(stalls)} stall(s); "
                f"last: {last.get('name')} frozen {last.get('age_s')}s "
                f"in stage {_stall_stage(last) or 'unknown'!r}")
        if bad:
            evidence.append(f"history: {decode_errs:.0f} decode errors "
                            f"+ {corrupt:.0f} corrupt shards over "
                            f"{reads:.0f} read attempts")
        evidence.insert(0, (
            "the job runtime checkpointed and exited on the kill "
            "(rc 75, preempted-resumable); resume state: "
            f"{manifest or 'see job-manifest.json in the job workdir'}"
            + (f", cursor {preempt_ev.get('cursor')}"
               if preempt_ev and preempt_ev.get("cursor") else "")))
        evidence.append("relaunch the SAME JobSpec to resume with "
                        "bounded rework (JOBS.md)")
        return {"classification": "preempted_resumable",
                "suspect_stage": None, "suspect_host": None,
                "resume_manifest": manifest,
                "evidence": evidence, "stage_rates": rates}

    # 2. decode-error storm: the strongest failure signal — bad data
    #    starves or stalls everything downstream of it
    if bad >= STORM_MIN_EVENTS and bad >= STORM_MIN_FRAC * max(reads, 1.0):
        evidence.insert(0, (
            f"{decode_errs:.0f} decode errors + {corrupt:.0f} corrupt "
            f"shards over {reads:.0f} read attempts "
            f"({bad / max(reads, 1.0):.0%}); {ring_bad} sample(s) in "
            "the error ring"))
        return {"classification": "decode_error_storm",
                "suspect_stage": "decode",
                "suspect_host": suspect_host,
                "evidence": evidence, "stage_rates": rates}

    # 2b. recompile storm: the traceck sentinel measured a fn identity
    #     retracing past TPUDL_TRACECK_STORM. Checked BEFORE the stall
    #     rules — a retrace pins the host in compilation for ~60 s per
    #     program, which reads as a dispatch stall/slowdown from
    #     outside; the storm is the cause, not the symptom
    storms = sum(_metric_value(d, "traceck.storms")
                 for d in hosts.values())
    storm_ring = [e for e in errors
                  if str(e.get("kind", "")).startswith("traceck")]
    if storms or storm_ring:
        retraces = sum(_metric_value(d, "traceck.retraces")
                       for d in hosts.values())
        evidence.insert(0, (
            f"{storms:.0f} recompile storm(s) flagged by the traceck "
            f"sentinel ({retraces:.0f} retraces total); each retrace "
            f"recompiles (~60 s on the real chip)"))
        for e in storm_ring[-3:]:
            evidence.append(
                f"storm: {e.get('fn', '?')} traced "
                f"{e.get('traces', '?')} times")
        if stalls:
            last = stalls[-1]
            evidence.append(
                f"history: watchdog flagged {len(stalls)} stall(s); "
                f"last: {last.get('name')} frozen {last.get('age_s')}s "
                f"in stage {_stall_stage(last) or 'unknown'!r}")
        evidence.append("fix the churn site (the static "
                        "jit-cache-churn rule names it: python -m "
                        "tools.tpudl_check --rules jit-cache-churn "
                        "<paths>)")
        return {"classification": "recompile_storm",
                "suspect_stage": "dispatch",
                "suspect_host": suspect_host,
                "evidence": evidence, "stage_rates": rates}

    # 2c. degraded run: the fault-containment supervisor was mid-ladder
    #     when the process died — the rung trail is the diagnosis (and
    #     a killed retrying run must not read as a generic stall).
    #     ``exhausted`` dumps carry their own typed error; both shapes
    #     land here so a degraded-then-killed run is one class.
    #     Gated on degradation being CURRENT at death — the exhaustion
    #     dump itself, the supervisor's heartbeat still registered, or
    #     the NEWEST pipeline report carrying degraded_to — never on
    #     the cumulative counters alone: one long-recovered fault early
    #     in a process's life must not reroute every later unrelated
    #     death away from the stall/kill classes
    degr_rungs = sum(_metric_value(d, "frame.degraded.rungs")
                     for d in hosts.values())
    degr_ring = [e for e in errors
                 if str(e.get("kind", "")).startswith("frame.degraded")]
    sup_hb = (newest.get("heartbeats") or {}).get(
        "frame.supervisor") or {}
    degr_current = (
        reason == "degraded_exhausted"
        # a LIVE supervisor heartbeat only counts when ITS run has
        # actually applied rungs (it beats rungs=len(self.rungs)):
        # under process-wide TPUDL_FRAME_DEGRADE=1 every supervised
        # run registers one, and mere presence would let a stale
        # recovered fault reroute a later unrelated death
        or int((sup_hb.get("info") or {}).get("rungs") or 0) > 0
        or bool(report and report.get("degraded_to")))
    if degr_current and (degr_rungs or degr_ring):
        exhausted = sum(_metric_value(d, "frame.degraded.exhausted")
                        for d in hosts.values())
        recovered = sum(
            _metric_value(d, "frame.degraded.recovered_batches")
            for d in hosts.values())
        evidence.insert(0, (
            f"the executor supervisor applied {degr_rungs:.0f} "
            f"degradation rung(s) before death"
            + (f"; ladder EXHAUSTED {exhausted:.0f} time(s) "
               "(typed error + this dump)" if exhausted else "")
            + (f"; {recovered:.0f} batch(es) recovered on degraded "
               "rungs" if recovered else "")))
        suspect = None
        for e in degr_ring[-3:]:
            evidence.append(
                f"rung: {e.get('rung', e.get('kind'))} after "
                f"{e.get('type')} in stage {e.get('stage')!r}")
            suspect = e.get("stage") or suspect
        if stalls:
            last = stalls[-1]
            evidence.append(
                f"history: watchdog flagged {len(stalls)} stall(s); "
                f"last: {last.get('name')} frozen {last.get('age_s')}s "
                f"in stage {_stall_stage(last) or 'unknown'!r}")
        evidence.append("the rung trail + FAULTS.md name the knob that "
                        "was being degraded; fix the underlying fault "
                        "(ring entries carry the original exception)")
        return {"classification": "degraded_run",
                "suspect_stage": suspect,
                "suspect_host": suspect_host,
                "evidence": evidence, "stage_rates": rates}

    # 2d. overload shed: admission control was rejecting a sustained
    #     fraction of offered load at death — the serve plane answered
    #     pressure with typed rejects (the load-shedding contract),
    #     and the actionable fact is capacity, not a bug hunt. Before
    #     the stall rules: a saturated loop still beating its
    #     heartbeat is shedding, not stuck.
    rejects = sum(_metric_value(d, "serve.rejects")
                  for d in hosts.values())
    admitted = sum(_metric_value(d, "serve.requests")
                   for d in hosts.values())
    offered = rejects + admitted
    if rejects >= SHED_MIN_EVENTS \
            and rejects >= SHED_MIN_FRAC * max(offered, 1.0):
        shed_host = None
        for h, d in hosts.items():
            if _metric_value(d, "serve.rejects"):
                shed_host = h
                break
        depth = _metric_value(newest, "serve.queue_depth")
        cap = _metric_value(newest, "serve.queue_cap")
        sheds = sum(_metric_value(d, "serve.deadline_sheds")
                    for d in hosts.values())
        evidence.insert(0, (
            f"admission control rejected {rejects:.0f} of "
            f"{offered:.0f} offered requests "
            f"({rejects / max(offered, 1.0):.0%}) — sustained "
            "overload, shed by typed rejects"))
        if cap:
            evidence.append(
                f"queue at death: depth {depth:.0f} of cap {cap:.0f}")
        if sheds:
            evidence.append(
                f"{sheds:.0f} request(s) shed on expired deadlines")
        if stalls:
            last = stalls[-1]
            evidence.append(
                f"history: watchdog flagged {len(stalls)} stall(s); "
                f"last: {last.get('name')} frozen {last.get('age_s')}s "
                f"in stage {_stall_stage(last) or 'unknown'!r}")
        evidence.append(
            "the queue stayed bounded and clients got typed answers; "
            "raise TPUDL_SERVE_QUEUE_CAP / TPUDL_SERVE_SLOTS or add "
            "serving capacity (SERVE.md)")
        return {"classification": "overload_shed",
                "suspect_stage": "admission",
                "suspect_host": shed_host or suspect_host,
                "evidence": evidence, "stage_rates": rates}

    # 2e. slo burn: admission was fine but the latency objective was
    #     NOT being met at death — the windowed burn gauge says the
    #     budget was burning and the tail exemplars in the error ring
    #     say where the time went. After overload_shed (shedding
    #     outranks slow) and before the stall rules (a slow-but-live
    #     loop still beats its heartbeat).
    exemplars = [e for e in errors
                 if str(e.get("kind", "")).startswith("serve.slo")]
    burn = max((_metric_value(d, "serve.slo.burn_short")
                for d in hosts.values()), default=0.0)
    if len(exemplars) >= SLO_MIN_EXEMPLARS and burn >= SLO_BURN_MIN:
        target = max((_metric_value(d, "serve.slo.target_ms")
                      for d in hosts.values()), default=0.0)
        win_p99 = max((_metric_value(d, "serve.slo.window_p99_ms")
                       for d in hosts.values()), default=0.0)
        seg_tot: dict[str, float] = {}
        for e in exemplars:
            for seg in SLO_SEGMENTS:
                v = e.get(f"{seg}_ms")
                if isinstance(v, (int, float)):
                    seg_tot[seg] = seg_tot.get(seg, 0.0) + float(v)
        total_ms = sum(seg_tot.values())
        dominant = (max(seg_tot.items(), key=lambda kv: kv[1])[0]
                    if seg_tot else None)
        headline = (f"p99 burn: windowed p99 {win_p99:.0f}ms against "
                    f"the {target:.0f}ms objective "
                    f"(burn {burn:.1f}x the error budget)")
        if dominant is not None:
            share = seg_tot[dominant] / max(total_ms, 1e-9)
            headline += (f"; {share:.0%} of tail latency across "
                         f"{len(exemplars)} exemplar(s) is {dominant}")
        evidence.insert(0, headline)
        if seg_tot:
            evidence.append("tail time by segment: " + "  ".join(
                f"{k} {v:.0f}ms" for k, v in sorted(
                    seg_tot.items(), key=lambda kv: -kv[1])))
        if dominant is not None:
            evidence.append(SLO_REMEDIES.get(
                dominant, "add serving capacity (SERVE.md)"))
        if stalls:
            last = stalls[-1]
            evidence.append(
                f"history: watchdog flagged {len(stalls)} stall(s); "
                f"last: {last.get('name')} frozen {last.get('age_s')}s "
                f"in stage {_stall_stage(last) or 'unknown'!r}")
        return {"classification": "slo_burn",
                "suspect_stage": dominant,
                "suspect_host": (exemplars[-1].get("host")
                                 or suspect_host),
                "evidence": evidence, "stage_rates": rates}

    # 3/4. watchdog stalls: which side froze?
    if stalls:
        last = stalls[-1]
        stage = _stall_stage(last)
        evidence.insert(0, (
            f"watchdog flagged {len(stalls)} stall(s); last: "
            f"{last.get('name')} frozen {last.get('age_s')}s in stage "
            f"{stage or 'unknown'!r} on host {last.get('host')}"))
        if _is_infeed(last):
            return {"classification": "infeed_stall",
                    "suspect_stage": stage or "prepare",
                    "suspect_host": last.get("host"),
                    "evidence": evidence, "stage_rates": rates}
        if stage is not None:
            return {"classification": "dispatch_slowdown",
                    "suspect_stage": stage,
                    "suspect_host": last.get("host"),
                    "evidence": evidence, "stage_rates": rates}
        # a supervised non-executor unit (train step, UDF call, HPO
        # trial) froze with no stage attribution: an honest "stall"
        # beats guessing a side — the dump's thread stacks say where
        evidence.append("no stage attribution (non-executor "
                        "heartbeat); see the stall's thread stacks "
                        "in the dump")
        return {"classification": "stall",
                "suspect_stage": None,
                "suspect_host": last.get("host"),
                "evidence": evidence, "stage_rates": rates}

    # 5. no stall, no storm, external signal: a healthy run was killed
    if reason.startswith("signal"):
        evidence.insert(0, (
            f"dump reason {reason!r} with no stalls and no error "
            "storm — the process was killed from outside while making "
            "progress"))
        if rates:
            dominant = max(rates.items(),
                           key=lambda kv: kv[1]["seconds"])
            total = sum(v["seconds"] for v in rates.values()) or 1.0
            evidence.append(
                f"time went to {dominant[0]!r} "
                f"({dominant[1]['seconds'] / total:.0%} of stage "
                "time) — slow, not stuck")
        return {"classification": "clean_external_kill",
                "suspect_stage": None, "suspect_host": None,
                "evidence": evidence, "stage_rates": rates}

    # 6. unhandled exception: the error explains itself
    err = newest.get("error")
    if reason == "exception" and err:
        evidence.insert(0, f"unhandled {err.get('type')}: "
                        f"{str(err.get('message'))[:200]}")
        return {"classification": "exception",
                "suspect_stage": None, "suspect_host": None,
                "evidence": evidence, "stage_rates": rates}

    # 7. a slow-but-alive dispatch dominating the last report
    if rates:
        dominant = max(rates.items(), key=lambda kv: kv[1]["seconds"])
        total = sum(v["seconds"] for v in rates.values()) or 1.0
        share = dominant[1]["seconds"] / total
        if dominant[0] == "dispatch" and share > 0.8:
            evidence.insert(0, (
                f"dispatch holds {share:.0%} of stage time "
                f"({dominant[1]['s_per_call']}s/call) in the last "
                "report — device-bound at death"))
            return {"classification": "dispatch_slowdown",
                    "suspect_stage": "dispatch", "suspect_host": None,
                    "evidence": evidence, "stage_rates": rates}

    evidence.insert(0, f"reason {reason!r}; no rule matched")
    return {"classification": "unclassified", "suspect_stage": None,
            "suspect_host": None, "evidence": evidence,
            "stage_rates": rates}


def format_report(merged: dict, diagnosis: dict,
                  tail: int = 12) -> str:
    """Human-readable doctor output: verdict first, then the evidence,
    per-stage throughput at death, and the merged timeline tail."""
    lines = []
    hosts = merged.get("hosts") or {}
    lines.append(f"== tpudl obs doctor — {len(hosts)} host dump(s) ==")
    for idx in sorted(hosts, key=lambda k: [int(x)
                                            for x in str(k).split(":")]):
        d = hosts[idx]
        lines.append(
            f"  host {idx}: pid {d.get('pid')} reason "
            f"{d.get('reason')!r} ({d.get('_path', '?')})")
    lines.append("")
    lines.append(f"DIAGNOSIS: {diagnosis['classification']}"
                 + (f"  (suspect stage: {diagnosis['suspect_stage']}"
                    + (f", host {diagnosis['suspect_host']}"
                       if diagnosis.get("suspect_host") is not None
                       else "") + ")"
                    if diagnosis.get("suspect_stage") else ""))
    for ev in diagnosis.get("evidence", []):
        lines.append(f"  - {ev}")
    rates = diagnosis.get("stage_rates") or {}
    if rates:
        lines.append("")
        lines.append("per-stage throughput at time of death:")
        for name, r in sorted(rates.items(),
                              key=lambda kv: -kv[1]["seconds"]):
            per = (f"{r['s_per_call'] * 1e3:.2f} ms/call"
                   if r["s_per_call"] is not None else "-")
            lines.append(f"  {name:<14} {r['seconds']:>9.3f}s "
                         f"x{r['calls']:<6} {per}")
    spans = merged.get("spans") or []
    if spans:
        lines.append("")
        lines.append(f"timeline tail (last {min(tail, len(spans))} "
                     "spans):")
        for s in spans[-tail:]:
            dur_ms = (s.get("dur_us") or 0) / 1e3
            lines.append(f"  [host {s.get('host', 0)}] "
                         f"{s.get('name', '?'):<28} {dur_ms:>10.2f} ms"
                         + (f"  {s['attrs']}" if s.get("attrs") else ""))
    errors = merged.get("errors") or []
    if errors:
        lines.append("")
        lines.append(f"error ring tail ({min(5, len(errors))} of "
                     f"{len(errors)}):")
        for e in errors[-5:]:
            etype = f"{e['type']} " if e.get("type") else ""
            lines.append(f"  [host {e.get('host', 0)}] "
                         f"{e.get('kind')}: {etype}"
                         f"{str(e.get('message'))[:100]}")
    return "\n".join(lines)


def diagnose(path: str) -> tuple[dict, dict] | None:
    """Convenience: load + merge + classify ``path``; None when no
    dumps are found."""
    dumps = load_dumps(path)
    if not dumps:
        return None
    merged = merge_dumps(dumps)
    return merged, classify(merged)
