"""Stall watchdog: heartbeat registry + the no-progress daemon.

A hung infeed and a slow run look identical from outside — both just
stop printing. The watchdog makes the difference observable WHILE the
process is still alive (OBSERVABILITY.md "Failure forensics"):

- instrumented layers hold a :class:`Heartbeat` while they own work and
  ``beat()`` on every unit of progress — the frame executor beats per
  stage event (prepare/h2d/dispatch/d2h), ``Trainer.fit`` per step, UDF
  calls and HPO trials per invocation;
- a daemon thread (:class:`Watchdog`) scans the active heartbeats every
  ``interval`` seconds; one that hasn't beaten for
  ``TPUDL_WATCHDOG_STALL_S`` seconds is flagged as STALLED: the event —
  name, last-beat info (which stage froze), age, and a snapshot of
  EVERY Python thread's stack (``sys._current_frames``) — lands in the
  flight recorder's stall ring, ``obs.watchdog.stalls`` is bumped, and
  a warning is logged. One flag per stall episode (re-armed by the next
  beat), so a 10-minute hang is one event, not 600.

The daemon starts lazily on the first ``heartbeat(...)`` when
``TPUDL_WATCHDOG_STALL_S`` is set (> 0), or explicitly via
:func:`start_watchdog`. Beating is a lock + two attribute writes — the
executor overhead guard (tests/test_obs_flight.py) covers it.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
import traceback

from tpudl.testing import tsan as _tsan

__all__ = ["Heartbeat", "HeartbeatRegistry", "Watchdog", "get_registry",
           "heartbeat", "start_watchdog", "stop_watchdog",
           "thread_stacks"]

log = logging.getLogger("tpudl.obs.watchdog")

DEFAULT_STALL_S = 30.0


def _env_stall_s() -> float:
    try:
        return float(os.environ.get("TPUDL_WATCHDOG_STALL_S", "") or 0.0)
    except ValueError:
        return 0.0


def thread_stacks(limit: int = 40) -> dict[str, list[str]]:
    """Every live Python thread's current stack, formatted — the "where
    is everyone frozen" snapshot a stall event carries. Keys are
    ``"<tid>:<thread name>"``."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for tid, frame in sys._current_frames().items():
        stack = traceback.format_stack(frame, limit=limit)
        out[f"{tid}:{names.get(tid, '?')}"] = [ln.rstrip()
                                               for ln in stack]
    return out


class Heartbeat:
    """One unit of supervised work. Use as a context manager::

        with watchdog.heartbeat("train.fit", steps=100) as hb:
            for step in ...:
                hb.beat(step=step)

    While the block is active and not beating, the daemon counts its
    age; leaving the block deregisters it (finished work can't stall).

    Two refinements matter for honest attribution:

    - **parent re-arm** — a heartbeat created while another is active
      on the same thread records it as its parent, and every beat
      re-arms the whole parent chain. An outer coarse heartbeat (a UDF
      call, an HPO trial) with one beat per invocation therefore never
      false-flags while its inner executor/trainer heartbeats are
      making progress — it only stalls when EVERYTHING under it does;
    - **in-flight stages** — ``stage_enter``/``stage_exit`` (used by
      ``PipelineReport.stage``) track which stages are currently
      ENTERED and for how long. A stall's suspect is the oldest
      in-flight stage, not the most recent beat: a wedged dispatch
      stays in flight while the prepare pool's final beats come and
      go, so it cannot be mis-blamed on the input side.
    """

    __slots__ = ("name", "info", "started", "last_beat", "beats",
                 "stalled", "parent", "_registry", "_inflight",
                 "_iflock")

    def __init__(self, name: str, registry: "HeartbeatRegistry",
                 parent: "Heartbeat | None" = None, **info):
        self.name = str(name)
        self.info = dict(info)
        self.started = time.monotonic()
        self.last_beat = self.started
        self.beats = 0
        self.stalled = False
        self.parent = parent
        self._registry = registry
        self._inflight: dict[str, list] = {}  # stage -> [count, t0]
        # one lock per heartbeat covers the beat fields AND the
        # in-flight stage map: the watchdog daemon and the status
        # writer snapshot both while beat()/stage_enter() mutate them
        self._iflock = _tsan.named_lock("obs.watchdog.heartbeat")

    def beat(self, **info):
        """Progress happened. ``info`` overlays the heartbeat's info
        (e.g. ``stage="prepare"``) so a later stall names the exact
        stage that beat LAST; the parent chain is re-armed too.

        Guarded by ``_iflock``: the daemon and the status writer copy
        ``info`` concurrently, and a dict mutated mid-copy raises
        RuntimeError in the READER (tests/test_concurrency.py pins the
        regression). Parent locks are taken one at a time AFTER
        releasing our own — per-heartbeat locks share a rank and must
        never nest (tpudl/analysis/locks.py)."""
        now = time.monotonic()
        with self._iflock:
            self.last_beat = now
            self.beats += 1
            self.stalled = False  # re-arm: one event per stall episode
            if info:
                self.info.update(info)
        p = self.parent
        while p is not None:  # child progress IS parent progress
            with p._iflock:
                p.last_beat = now
                p.stalled = False
            p = p.parent

    def stage_enter(self, stage: str):
        """A named stage began (and beat): it stays IN FLIGHT until
        ``stage_exit``, so a freeze inside it is attributable even
        after other stages beat afterwards."""
        self.beat(stage=stage)
        with self._iflock:
            ent = self._inflight.setdefault(stage, [0, 0.0])
            if ent[0] == 0:
                ent[1] = time.monotonic()
            ent[0] += 1

    def stage_exit(self, stage: str):
        self.beat()
        with self._iflock:
            ent = self._inflight.get(stage)
            if ent is not None:
                ent[0] -= 1
                if ent[0] <= 0:
                    self._inflight.pop(stage, None)

    def inflight(self, now: float | None = None) -> dict:
        """``{stage: {count, age_s}}`` of currently-entered stages —
        the stall event's suspect material."""
        now = now if now is not None else time.monotonic()
        with self._iflock:
            # a stage_enter() can land between the caller's `now` and
            # this snapshot — clamp like describe(): never negative
            return {k: {"count": v[0],
                        "age_s": round(max(0.0, now - v[1]), 3)}
                    for k, v in self._inflight.items()}

    def age(self, now: float | None = None) -> float:
        return (now if now is not None else time.monotonic()) \
            - self.last_beat

    def mark_stalled(self):
        """Daemon-side: flag this heartbeat's stall episode (re-armed
        by the next beat)."""
        with self._iflock:
            self.stalled = True

    def describe(self, now: float | None = None) -> dict:
        now = now if now is not None else time.monotonic()
        with self._iflock:
            # a beat can land between the caller's `now` and this
            # snapshot — clamp: an age is never negative
            snap = {"name": self.name, "info": dict(self.info),
                    "beats": self.beats,
                    "age_s": round(max(0.0, now - self.last_beat), 3),
                    "alive_s": round(now - self.started, 3),
                    "stalled": self.stalled}
        # sequential second acquisition (inflight takes the same
        # non-reentrant lock) — never nested
        snap["in_flight"] = self.inflight(now)
        return snap

    def __enter__(self) -> "Heartbeat":
        return self

    def __exit__(self, *exc):
        self._registry._remove(self)
        return False


class HeartbeatRegistry:
    """Thread-safe set of active heartbeats (the watchdog's scan
    list). A per-thread stack links nested heartbeats (parent re-arm,
    see :class:`Heartbeat`)."""

    def __init__(self):
        self._lock = _tsan.named_lock("obs.watchdog.registry")
        self._active: set[Heartbeat] = set()
        self._tls = threading.local()

    def _stack(self) -> list:
        s = getattr(self._tls, "stack", None)
        if s is None:
            s = self._tls.stack = []
        return s

    def start(self, name: str, **info) -> Heartbeat:
        stack = self._stack()
        parent = stack[-1] if stack else None
        hb = Heartbeat(name, self, parent=parent, **info)
        with self._lock:
            if _tsan.ENABLED:
                _tsan.check_guarded("obs.watchdog.registry",
                                    "heartbeat registry active set",
                                    lock=self._lock)
            self._active.add(hb)
        stack.append(hb)
        return hb

    def _remove(self, hb: Heartbeat):
        with self._lock:
            self._active.discard(hb)
        # normally a LIFO pop on the creating thread; an exit from
        # another thread just leaves a harmless dead parent link
        s = getattr(self._tls, "stack", None)
        if s and hb in s:
            s.remove(hb)

    def active(self) -> list[Heartbeat]:
        with self._lock:
            return list(self._active)

    def describe(self) -> dict:
        """``{name: descriptor}`` of every active heartbeat — what a
        flight dump records so the doctor sees who was mid-work at
        death (duplicate names keep the oldest-beat entry: the stuck
        one is the interesting one)."""
        now = time.monotonic()
        out: dict[str, dict] = {}
        for hb in sorted(self.active(), key=lambda h: h.last_beat):
            out.setdefault(hb.name, hb.describe(now))
        return out

    def clear(self):
        with self._lock:
            self._active.clear()
        s = getattr(self._tls, "stack", None)
        if s:
            del s[:]


class Watchdog:
    """The no-progress daemon. ``stall_s`` is the flag threshold;
    ``interval`` the scan period (default ``stall_s / 4``, floored at
    50 ms so tests with sub-second thresholds stay responsive)."""

    def __init__(self, registry: HeartbeatRegistry,
                 stall_s: float | None = None,
                 interval: float | None = None):
        self.registry = registry
        self.stall_s = float(stall_s if stall_s is not None
                             else (_env_stall_s() or DEFAULT_STALL_S))
        self.interval = float(interval if interval is not None
                              else max(0.05, self.stall_s / 4.0))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpudl-watchdog")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.scan()
            except Exception:  # the observer never kills the observed
                log.debug("watchdog scan failed", exc_info=True)

    def scan(self) -> list[dict]:
        """One pass over the active heartbeats; returns the stall
        events it flagged (tests drive this directly for determinism).
        Also feeds the flight recorder's metric-tick ring, so a dump
        carries the metric trajectory sampled at watchdog cadence."""
        from tpudl.obs import flight as _flight
        from tpudl.obs import metrics as _metrics

        now = time.monotonic()
        flagged = []
        for hb in self.registry.active():
            if hb.stalled or hb.age(now) <= self.stall_s:
                continue
            hb.mark_stalled()  # one event per episode
            # describe() snapshots info/beats/in_flight under the
            # heartbeat's lock — reading the live dicts here raced
            # beat()'s mutations (the Heartbeat.beat regression test)
            desc = hb.describe(now)
            event = {"ts": time.time(), "name": hb.name,
                     "info": desc["info"], "beats": desc["beats"],
                     "age_s": desc["age_s"],
                     "stall_s": self.stall_s,
                     "in_flight": desc["in_flight"],
                     "active": sorted(h.name
                                      for h in self.registry.active()),
                     "stacks": thread_stacks()}
            flagged.append(event)
            _metrics.counter("obs.watchdog.stalls").inc()
            _flight.get_recorder().record_stall(event)
            log.warning(
                "watchdog: %r made no progress for %.1fs (> %.1fs) — "
                "last info %s; thread stacks recorded in the flight "
                "recorder", hb.name, desc["age_s"], self.stall_s,
                desc["info"])
        _flight.get_recorder().record_metrics_tick()
        return flagged


_REGISTRY = HeartbeatRegistry()
_WATCHDOG: Watchdog | None = None
_WATCHDOG_LOCK = _tsan.named_lock("obs.watchdog.daemon")


def get_registry() -> HeartbeatRegistry:
    return _REGISTRY


def heartbeat(name: str, **info) -> Heartbeat:
    """Register supervised work on the process-wide registry (and
    lazily start the daemon when ``TPUDL_WATCHDOG_STALL_S`` is set).
    Use as a context manager; call ``.beat()`` on progress.

    Registering also arms the live status writer
    (:mod:`tpudl.obs.live`, ``TPUDL_STATUS_DIR``): any layer that
    supervises work is by definition work worth watching in
    ``obs top``, so the one registrar covers executor/trainer/UDF/HPO
    without per-layer plumbing."""
    _maybe_autostart()
    try:
        from tpudl.obs import live as _live

        _live.ensure_status_writer()
    # tpudl: ignore[swallowed-except] — the observer never kills the
    # observed: a broken status writer just means no obs top
    except Exception:
        pass
    return _REGISTRY.start(name, **info)


def _maybe_autostart():
    if _WATCHDOG is None and _env_stall_s() > 0:
        start_watchdog()


def start_watchdog(stall_s: float | None = None,
                   interval: float | None = None) -> Watchdog:
    """Start (or return) the process-wide daemon. Explicit args win
    over ``TPUDL_WATCHDOG_STALL_S``."""
    global _WATCHDOG
    with _WATCHDOG_LOCK:
        if _WATCHDOG is None:
            _WATCHDOG = Watchdog(_REGISTRY, stall_s=stall_s,
                                 interval=interval)
            _WATCHDOG.start()
        return _WATCHDOG


def stop_watchdog():
    """Stop and forget the daemon (tests)."""
    global _WATCHDOG
    with _WATCHDOG_LOCK:
        if _WATCHDOG is not None:
            # tpudl: ignore[lock-held-blocking] — may-analysis:
            # name-based resolution maps .stop() onto StatusWriter.stop
            # too; this receiver is a Watchdog, whose stop() joins the
            # daemon with timeout=2.0 and touches no device path
            _WATCHDOG.stop()
            _WATCHDOG = None
