"""Process-wide metrics registry: counters, gauges, bounded histograms.

The run-wide half of the observability subsystem (OBSERVABILITY.md):
every layer (frame executor, imageIO, ml transformers, HPO, UDFs, the
Trainer loop) publishes into ONE thread-safe registry, so a whole run's
numbers are readable from a single ``snapshot()`` instead of scattered
per-call artifacts. Opt-in JSONL sink: set ``TPUDL_METRICS_FILE`` and
snapshots stream to disk (periodic, throttled by
``TPUDL_METRICS_FLUSH_S``) plus one ``final`` line at interpreter exit;
``tools/validate_metrics.py`` schema-checks the emissions.

Naming convention: dotted lowercase ``layer.component.metric``
(``frame.map_batches.runs``, ``imageio.files_read``,
``train.step_seconds``). Hot-loop discipline: one metric update is a
lock + a few scalar ops (the executor overhead guard in
tests/test_obs_metrics.py pins the total at <5% of a real pipeline).
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import time
from collections import deque

from tpudl.testing import tsan as _tsan

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "get_registry", "counter", "gauge", "histogram", "snapshot",
           "flush_metrics", "Meter", "timed", "percentile"]

# per-histogram/gauge retained samples; running aggregates keep
# mean/max exact over ALL samples no matter the cap
DEFAULT_SAMPLE_CAP = 4096


def percentile(sorted_xs, q: float):
    """Nearest-rank percentile of an ASCENDING-sorted sequence
    (``None`` when empty) — THE percentile for every obs/serve
    consumer: histograms, the serve load generator, the SLO window.
    One definition so a bench p99 and an obs p99 can never disagree by
    implementation."""
    if not sorted_xs:
        return None
    return sorted_xs[min(len(sorted_xs) - 1, int(q * len(sorted_xs)))]


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class Counter:
    """Monotonic counter (float increments allowed: seconds/bytes
    accumulate through the same type)."""

    kind = "counter"
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = _tsan.named_lock("obs.metrics.counter")

    def inc(self, amount: float = 1.0):
        a = float(amount)  # numpy scalars would poison the JSON sink
        with self._lock:
            self.value += a

    def to_dict(self) -> dict:
        return {"type": "counter", "value": self.value}


class Gauge:
    """Last-value gauge with running mean/max over every ``set``."""

    kind = "gauge"
    __slots__ = ("value", "count", "total", "max", "_lock")

    def __init__(self):
        self.value = None
        self.count = 0
        self.total = 0.0
        self.max = None
        self._lock = _tsan.named_lock("obs.metrics.gauge")

    def set(self, value: float):
        v = float(value)
        with self._lock:
            self.value = v
            self.count += 1
            self.total += v
            self.max = v if self.max is None else max(self.max, v)

    def to_dict(self) -> dict:
        with self._lock:
            return {"type": "gauge", "value": self.value,
                    "count": self.count, "max": self.max,
                    "mean": (self.total / self.count) if self.count else None}


class Histogram:
    """Bounded-memory sample distribution.

    Keeps the last ``cap`` samples (ring) for percentiles, plus running
    count/sum/min/max so mean and extremes stay exact over ALL samples —
    a long streaming run can observe forever in O(cap) memory.
    """

    kind = "histogram"
    __slots__ = ("samples", "count", "total", "min", "max", "_lock")

    def __init__(self, cap: int = DEFAULT_SAMPLE_CAP):
        self.samples: deque = deque(maxlen=max(1, int(cap)))
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self._lock = _tsan.named_lock("obs.metrics.histogram")

    def observe(self, value: float):
        v = float(value)
        with self._lock:
            self.samples.append(v)
            self.count += 1
            self.total += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)

    @contextlib.contextmanager
    def time(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0)

    def _percentile(self, sorted_ring: list, q: float):
        return percentile(sorted_ring, q)

    def to_dict(self) -> dict:
        with self._lock:
            ring = sorted(self.samples)
            return {
                "type": "histogram", "count": self.count,
                "sum": self.total, "min": self.min, "max": self.max,
                "mean": (self.total / self.count) if self.count else None,
                "p50": percentile(ring, 0.50),
                "p95": percentile(ring, 0.95),
                "p99": percentile(ring, 0.99),
            }


class MetricsRegistry:
    """Thread-safe name → metric map with an opt-in JSONL sink.

    ``counter``/``gauge``/``histogram`` get-or-create by name (a name
    pins its kind — asking for the same name as a different kind
    raises: silent kind aliasing would corrupt the emission schema).
    ``snapshot()`` returns a plain-dict view of everything. The sink
    (``TPUDL_METRICS_FILE``) appends one JSON line per flush; periodic
    flushes piggyback on metric updates, throttled to one per
    ``TPUDL_METRICS_FLUSH_S`` (default 60) seconds, and ``atexit``
    writes a ``final`` line.
    """

    def __init__(self):
        self._metrics: dict[str, object] = {}
        self._lock = _tsan.named_lock("obs.metrics.registry")
        self._next_flush = 0.0  # monotonic deadline; 0 = resolve lazily
        self._atexit_registered = False

    def _get(self, name: str, cls, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                if _tsan.ENABLED:
                    _tsan.check_guarded("obs.metrics.registry",
                                        "metrics registry name map",
                                        lock=self._lock)
                m = self._metrics[name] = cls(**kw)
                self._register_atexit()
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str,
                  cap: int = DEFAULT_SAMPLE_CAP) -> Histogram:
        """Get-or-create by name. ``cap`` is a CREATION-time parameter:
        the first call for a name fixes its sample ring; later calls
        return the existing histogram regardless of ``cap`` (running
        aggregates are exact either way — only percentile window width
        is at stake)."""
        return self._get(name, Histogram, cap=cap)

    def snapshot(self, prefix=None) -> dict:
        """Plain-dict view of the registry. ``prefix`` (a string or a
        tuple of strings, as for ``str.startswith``) filters INSIDE the
        lock so per-tick readers — the 1 Hz status writer, the SLO
        engine — copy and serialize only the names they render instead
        of the whole registry."""
        with self._lock:
            items = [(name, m) for name, m in self._metrics.items()
                     if prefix is None or name.startswith(prefix)]
        return {name: m.to_dict() for name, m in sorted(items)}

    def reset(self):
        """Drop every metric (tests; a process restart equivalent)."""
        with self._lock:
            self._metrics.clear()
            self._next_flush = 0.0

    # -- sink --------------------------------------------------------------
    def _register_atexit(self):
        if not self._atexit_registered:
            self._atexit_registered = True
            atexit.register(self.flush, event="final")

    def sink_path(self) -> str | None:
        # read per flush attempt (flushes are throttled) so tests and
        # late `export TPUDL_METRICS_FILE=...` both take effect
        return os.environ.get("TPUDL_METRICS_FILE") or None

    def maybe_flush(self):
        """Throttled periodic flush — call from update paths that want
        long runs to stream snapshots without owning a timer thread.
        The deadline check-and-set is lock-guarded so two threads
        passing the throttle together cannot both append (duplicate or
        interleaved snapshot lines)."""
        now = time.monotonic()
        if now < self._next_flush:  # cheap unlocked fast path
            return False
        with self._lock:
            if now < self._next_flush:
                return False
            self._next_flush = now + _env_float("TPUDL_METRICS_FLUSH_S",
                                                60.0)
        return self.flush(event="snapshot")

    def flush(self, event: str = "snapshot") -> bool:
        """Append one JSONL line (the validate_metrics.py schema) to the
        sink; no-op without ``TPUDL_METRICS_FILE``. Never raises — a
        full disk must not take down the pipeline being observed."""
        path = self.sink_path()
        if not path:
            return False
        line = {"ts": time.time(), "event": event, "pid": os.getpid(),
                "metrics": self.snapshot()}
        try:
            with open(path, "a") as f:
                f.write(json.dumps(line) + "\n")
            return True
        except (OSError, TypeError, ValueError):
            # full disk or an unserializable stray value: the pipeline
            # being observed must not die for its observer
            return False


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY


def counter(name: str) -> Counter:
    return _REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return _REGISTRY.gauge(name)


def histogram(name: str, cap: int = DEFAULT_SAMPLE_CAP) -> Histogram:
    return _REGISTRY.histogram(name, cap=cap)


def snapshot(prefix=None) -> dict:
    return _REGISTRY.snapshot(prefix=prefix)


def flush_metrics(event: str = "snapshot") -> bool:
    return _REGISTRY.flush(event=event)


@contextlib.contextmanager
def timed(name: str):
    """Histogram-observe the enclosed block's wall seconds (and give the
    periodic sink a chance to flush — instrumented call sites need no
    extra plumbing for long-run streaming)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _REGISTRY.histogram(name).observe(time.perf_counter() - t0)
        _REGISTRY.maybe_flush()


class Meter:
    """Throughput/latency meter for the executor hot loop.

    ``with meter.batch(n):`` around each device call; ``meter.report()``
    yields {examples, seconds, examples_per_sec, examples_per_sec_per_chip}.
    Warmup batches (compile) can be excluded via ``skip`` — report both
    cold and warm numbers, never silently drop the compile cost.

    Edge cases are clamped, not silent (round-6 fix): a negative
    ``skip`` counts everything; ``skip >= len(batches)`` keeps the LAST
    batch (an all-warmup report claiming 0 examples hid real runs), and
    the report surfaces ``skipped`` so the clamp is visible.
    """

    def __init__(self, n_chips: int = 1, skip: int = 0):
        self.n_chips = max(1, int(n_chips))
        self.skip = int(skip)
        self._batches: list[tuple[int, float]] = []

    @contextlib.contextmanager
    def batch(self, n_examples: int):
        t0 = time.perf_counter()
        yield
        self._batches.append((int(n_examples), time.perf_counter() - t0))

    def _effective_skip(self) -> int:
        n = len(self._batches)
        skip = min(max(0, self.skip), n)
        if n and skip == n:
            skip = n - 1  # keep at least one measured batch
        return skip

    def report(self) -> dict:
        skip = self._effective_skip()
        counted = self._batches[skip:]
        ex = sum(n for n, _ in counted)
        secs = sum(t for _, t in counted)
        all_ex = sum(n for n, _ in self._batches)
        all_secs = sum(t for _, t in self._batches)
        eps = ex / secs if secs > 0 else 0.0
        return {
            "examples": ex,
            "seconds": round(secs, 4),
            "examples_per_sec": round(eps, 2),
            "examples_per_sec_per_chip": round(eps / self.n_chips, 2),
            "cold_examples_per_sec": round(all_ex / all_secs, 2)
            if all_secs > 0 else 0.0,
            "batches": len(self._batches),
            "skipped": skip,
        }

    def json_line(self, metric: str, baseline: float | None = None,
                  extra: dict | None = None) -> str:
        r = self.report()
        value = r["examples_per_sec_per_chip"]
        out = {
            "metric": metric,
            "value": value,
            "unit": "images/sec/chip",
            "vs_baseline": round(value / baseline, 3) if baseline else None,
        }
        if extra:
            out.update(extra)
        return json.dumps(out)
