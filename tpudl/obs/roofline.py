"""Roofline bottleneck attribution + the knob advisor.

PROFILE.md measured the structural truth of this backend: the chip runs
InceptionV3 at ~34 ms/step (~7,470 img/s) while end-to-end wall clock
sits orders of magnitude lower, and the residual is split between the
tunnel's blocking dispatch round-trip and the 8–22 MB/s wire. This
module turns that one-off forensic finding into a PER-RUN perf model:
given one :class:`~tpudl.obs.pipeline.PipelineReport` (live or
finished), the wire probe, and optionally the device-side step time, it
decomposes achieved vs achievable throughput across
``prepare / wire(h2d) / dispatch / d2h`` and emits a concrete **knob
verdict** — what to set ``fuse_steps`` / ``prefetch_depth`` /
``prepare_workers`` / ``wire_codec`` to, with the predicted gain, all
from the same model. This is the input surface the ROADMAP-2 async
executor will consume for auto-tuning, and the live monitor
(:mod:`tpudl.obs.live`) republishes the verdict on every status tick.

The stage-time model it reads (PIPELINE.md):

- ``dispatch`` seconds on the mesh=None tunnel path INCLUDE the H2D
  transfer and the device compute (the runtime's arg transfer rides the
  dispatch). The model splits them: device compute from
  ``device_ms_per_dispatch`` (a jax.profiler number, PROFILE.md), wire
  time from ``bytes_prepared / h2d_MBps``, and what remains is the
  blocking dispatch round-trip — the fusable part;
- ``infeed_wait`` is prepare work the pipeline failed to hide;
- ``d2h`` is the measured outfeed drain.

Every ``analyze()`` publishes ``obs.roofline.*`` gauges so long runs
stream their own bottleneck trajectory through the metrics sink.
"""

from __future__ import annotations

import math
import os

from tpudl.obs.metrics import _env_float

__all__ = ["RooflineReport", "analyze", "advise", "autotune_seed",
           "KNOB_CAPS", "AUTOTUNE_KNOBS"]

# advisor ceilings — the executor's own sane bounds (a recommendation
# past these would trade host RAM / compile time / in-flight device
# buffers for nothing)
KNOB_CAPS = {"fuse_steps": 16, "prefetch_depth": 8, "prepare_workers": 8,
             "dispatch_depth": 8}

# the knobs Frame.map_batches seeds from advise() when left unset
# (TPUDL_FRAME_AUTOTUNE, on by default — the ROADMAP-2 closed loop)
AUTOTUNE_KNOBS = ("fuse_steps", "dispatch_depth", "prefetch_depth")

# a component under this share of the gap is not worth a knob verdict
_MINOR_FRAC = 0.10


class RooflineReport:
    """One run's decomposition of achieved vs achievable throughput.

    Seconds (over the whole run):

    - ``device_compute_s``   on-chip execution (None when no device
      step time was available — attribution then stops at the dispatch
      stage without splitting it);
    - ``wire_h2d_s``         modeled host→device transfer
      (``bytes_prepared / h2d_MBps``, clamped into the measured
      dispatch window on the tunnel path);
    - ``dispatch_overhead_s`` the blocking per-dispatch round-trip
      residue — what multi-step fusion amortizes;
    - ``prepare_unhidden_s`` consumer seconds blocked on the infeed
      (``infeed_wait`` — prepare work prefetch failed to hide);
    - ``d2h_s``              measured outfeed drain;
    - ``collective_s``       model-axis communication on a 2-D mesh
      (the tensor-parallel all-reduce/reduce-scatter share of the
      dispatch window — supplied per dispatch by a profile or the
      mesh_2d bench's arm delta; 0 on 1-D grids);
    - ``other_s``            wall minus all of the above (host glue).

    ``gap_attribution`` maps each non-compute component to its fraction
    of the device-vs-e2e gap (``wall - device_compute``); ``bottleneck``
    names the largest. ``advice`` is the knob advisor's ranked
    recommendation list (see :func:`advise`).
    """

    def __init__(self, **kw):
        self.run_id = kw.get("run_id")
        self.rows = kw.get("rows")
        self.wall_s = kw.get("wall_s")
        self.achieved_rows_per_s = kw.get("achieved_rows_per_s")
        self.achievable_rows_per_s = kw.get("achievable_rows_per_s")
        self.device_compute_s = kw.get("device_compute_s")
        self.wire_h2d_s = kw.get("wire_h2d_s")
        self.dispatch_overhead_s = kw.get("dispatch_overhead_s")
        self.prepare_unhidden_s = kw.get("prepare_unhidden_s")
        self.d2h_s = kw.get("d2h_s")
        self.collective_s = kw.get("collective_s")
        self.other_s = kw.get("other_s")
        self.gap_s = kw.get("gap_s")
        self.gap_attribution = kw.get("gap_attribution") or {}
        self.bottleneck = kw.get("bottleneck")
        self.inputs = kw.get("inputs") or {}
        self.advice = kw.get("advice") or []
        self.verdict = kw.get("verdict")

    def dispatch_plus_wire_frac(self) -> float | None:
        """Share of the gap owned by the tunnel (dispatch round-trip +
        wire both ways) — the PROFILE.md diagnosis as one number."""
        if not self.gap_attribution:
            return None
        return sum(self.gap_attribution.get(k, 0.0)
                   for k in ("dispatch", "wire_h2d", "d2h"))

    def to_dict(self) -> dict:
        def r(v, nd=4):
            return None if v is None else round(v, nd)

        return {
            "run_id": self.run_id,
            "rows": self.rows,
            "wall_s": r(self.wall_s),
            "achieved_rows_per_s": r(self.achieved_rows_per_s, 2),
            "achievable_rows_per_s": r(self.achievable_rows_per_s, 2),
            "device_compute_s": r(self.device_compute_s),
            "wire_h2d_s": r(self.wire_h2d_s),
            "dispatch_overhead_s": r(self.dispatch_overhead_s),
            "prepare_unhidden_s": r(self.prepare_unhidden_s),
            "d2h_s": r(self.d2h_s),
            "collective_s": r(self.collective_s),
            "other_s": r(self.other_s),
            "gap_s": r(self.gap_s),
            "gap_attribution": {k: r(v) for k, v
                                in self.gap_attribution.items()},
            "bottleneck": self.bottleneck,
            "inputs": self.inputs,
            "advice": self.advice,
            "verdict": self.verdict,
        }


def _wire_probe_mbps(allow_probe: bool = True) -> float | None:
    """The process's cached bare-device_put H2D probe (one probe ever,
    ``TPUDL_WIRE_MBPS`` overrides) — tpudl.data owns the probe; the
    model only consumes it. None = unknown (never guessed fast).
    ``allow_probe=False`` reads the env/cache WITHOUT ever issuing a
    device op or importing jax — the status-writer thread's contract
    (a host-only process must stay host-only)."""
    env = os.environ.get("TPUDL_WIRE_MBPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        from tpudl.data import codec as _codec

        if not allow_probe:
            return _codec._WIRE_MBPS_CACHE.get("mbps")
        return _codec.probe_wire_mbps()
    except Exception:
        return None


def analyze(report: dict | None = None, *,
            h2d_mbps: float | None = None,
            device_ms_per_dispatch: float | None = None,
            bytes_prepared: float | None = None,
            collective_ms_per_dispatch: float | None = None,
            publish: bool = True,
            allow_probe: bool = True) -> RooflineReport | None:
    """Build a :class:`RooflineReport` from one pipeline-report dict.

    ``report`` defaults to ``obs.last_pipeline_report()``. ``h2d_mbps``
    defaults to ``TPUDL_WIRE_MBPS`` / the process's cached wire probe.
    ``device_ms_per_dispatch`` is the on-device time of ONE dispatch
    (PROFILE.md's "XLA Modules" ms/step × fuse_steps for fused
    programs); when absent (``TPUDL_DEVICE_MS_PER_STEP`` is read as a
    fallback) the dispatch stage is attributed whole, un-split.
    ``bytes_prepared`` overrides the executor's own byte accounting.
    ``collective_ms_per_dispatch`` is the model-axis communication time
    of ONE dispatch (a profile's ICI all-reduce/reduce-scatter total,
    or the mesh_2d bench's measured TP-vs-DP arm delta); it carves a
    ``collective`` component out of the dispatch residue — only
    honored when the report ran on a mesh whose ``model`` axis is >1
    (on a 1-D grid there is no model-axis traffic to attribute).
    Returns None when the report has no dispatches to attribute.
    """
    if report is None:
        from tpudl.obs import pipeline as _pipeline

        report = _pipeline.last_pipeline_report()
    if not report:
        return None
    stages = report.get("stage_seconds") or {}
    calls = report.get("stage_calls") or {}
    n_disp = int(calls.get("dispatch") or 0)
    rows = report.get("rows_done") or report.get("rows") or 0
    wall = report.get("wall_seconds") or report.get("age_s") or 0.0
    dispatch_s = float(stages.get("dispatch", 0.0))
    if "dispatch_wait" in stages:
        # async dispatch window: the ``dispatch`` stage is pool-summed
        # across the window's threads (it may exceed wall time) and the
        # overlapped part is already HIDDEN — attributing it would
        # mis-charge time the executor paid for once. What the consumer
        # actually paid is the window wait: the unhidden residue.
        dispatch_s = float(stages.get("dispatch_wait", 0.0))
    if n_disp <= 0 or wall <= 0 or rows <= 0:
        return None

    if h2d_mbps is None:
        h2d_mbps = _wire_probe_mbps(allow_probe)
    if device_ms_per_dispatch is None:
        env_ms = _env_float("TPUDL_DEVICE_MS_PER_STEP", 0.0)
        if env_ms > 0:
            fuse = int(report.get("fuse_steps") or 1)
            device_ms_per_dispatch = env_ms * max(1, fuse)
    if bytes_prepared is None:
        bytes_prepared = calls.get("bytes_prepared")

    achieved = rows / wall
    explicit_h2d = float(stages.get("h2d", 0.0))  # mesh path only

    device_s = None
    achievable = None
    if device_ms_per_dispatch is not None and device_ms_per_dispatch > 0:
        device_s = n_disp * device_ms_per_dispatch / 1e3
        if device_s > 0:
            achievable = rows / device_s

    prepare_unhidden = float(stages.get("infeed_wait", 0.0))
    d2h = float(stages.get("d2h", 0.0))
    gap = max(0.0, wall - (device_s or 0.0))

    # wire model: bytes over the measured link. On the tunnel path the
    # transfer rides INSIDE dispatch, so the modeled wire time is
    # clamped into the dispatch window that remains after compute — a
    # probe taken during different link weather must not "explain" more
    # of the dispatch stage than the stage measured. Bytes served from
    # the HBM device cache never crossed the link — `bytes_prepared`
    # still counts them (it means "bytes fed to dispatch"), so the wire
    # model subtracts the resident share or a mostly-resident run would
    # report a phantom wire bottleneck (ISSUE 12 satellite).
    bytes_hbm = float(calls.get("bytes_hbm_hit") or 0.0)
    wire_h2d = None
    wire_in_dispatch = 0.0
    wire_bytes = max(0.0, float(bytes_prepared or 0.0) - bytes_hbm)
    if explicit_h2d <= 0 and wire_bytes and h2d_mbps and h2d_mbps > 0:
        modeled = wire_bytes / 2**20 / h2d_mbps
        window = max(0.0, dispatch_s - (device_s or 0.0))
        wire_h2d = wire_in_dispatch = min(modeled, window)

    dispatch_overhead = None
    if device_s is not None:
        dispatch_overhead = max(
            0.0, dispatch_s - device_s - wire_in_dispatch)
    dispatch_comp = (dispatch_overhead if dispatch_overhead is not None
                     else max(0.0, dispatch_s - wire_in_dispatch))

    if explicit_h2d > 0:
        # mesh path: h2d has its OWN measured stage, but it is POOL-
        # SUMMED prepare-worker seconds largely hidden under dispatch
        # (PIPELINE.md: prepare-side stages can exceed wall time) — it
        # may only claim the part of the gap nothing else explains
        wire_h2d = min(explicit_h2d, max(
            0.0, gap - prepare_unhidden - d2h - dispatch_comp))

    # model-axis communication (ISSUE 16): tensor-parallel collectives
    # execute INSIDE the dispatched program, so their time hides in the
    # dispatch residue — a supplied per-dispatch collective time carves
    # it out as its own component (clamped: a profile from different
    # weather may not "explain" more dispatch time than was measured)
    model_axis = int((report.get("mesh") or {}).get("model") or 1)
    collective_s = 0.0
    if (collective_ms_per_dispatch is not None
            and collective_ms_per_dispatch > 0 and model_axis > 1):
        collective_s = min(n_disp * collective_ms_per_dispatch / 1e3,
                           max(0.0, dispatch_comp))
        dispatch_comp = max(0.0, dispatch_comp - collective_s)

    comps = {
        "prepare": prepare_unhidden,
        "wire_h2d": wire_h2d or 0.0,
        "dispatch": dispatch_comp,
        "d2h": d2h,
        "collective": collective_s,
    }
    other = max(0.0, gap - sum(comps.values()))
    attribution = {}
    if gap > 0:
        # normalized so the fractions can never sum past 1 even when
        # measured consumer-wall components overlap in odd ways
        scale = min(1.0, gap / max(gap, sum(comps.values()) + other))
        attribution = {k: min(1.0, v * scale / gap)
                       for k, v in comps.items()}
        attribution["other"] = min(1.0, other * scale / gap)
    bottleneck = (max(comps, key=comps.get)
                  if any(v > 0 for v in comps.values()) else None)

    rr = RooflineReport(
        run_id=report.get("run_id"), rows=rows, wall_s=wall,
        achieved_rows_per_s=achieved, achievable_rows_per_s=achievable,
        device_compute_s=device_s, wire_h2d_s=wire_h2d,
        dispatch_overhead_s=dispatch_overhead,
        prepare_unhidden_s=prepare_unhidden, d2h_s=d2h,
        collective_s=collective_s or None, other_s=other,
        gap_s=gap, gap_attribution=attribution, bottleneck=bottleneck,
        inputs={
            "h2d_mbps": h2d_mbps,
            "device_ms_per_dispatch": device_ms_per_dispatch,
            "bytes_prepared": bytes_prepared,
            "n_dispatches": n_disp,
            "fuse_steps": report.get("fuse_steps"),
            "dispatch_depth": report.get("dispatch_depth"),
            "prefetch_depth": report.get("prefetch_depth"),
            "prepare_workers": report.get("prepare_workers"),
            "wire_codec": report.get("wire_codec"),
            "batch_size": report.get("batch_size"),
            # mesh topology + the measured sharded-transfer stage
            # (ISSUE 11): the advisor's dispatch_depth/fuse_steps recs
            # apply unchanged to sharded reports — a mesh multiplies
            # compute, not the per-dispatch round-trip, so on a
            # wire-bound tunnel overlap matters MORE per chip
            "mesh": report.get("mesh"),
            "model_axis": model_axis,
            "collective_ms_per_dispatch": collective_ms_per_dispatch,
            "h2d_s": explicit_h2d or None,
            "pad_rows": calls.get("pad_rows"),
            # HBM residency (ISSUE 12): whether the run already rode
            # the device cache, and how many dispatch-fed bytes never
            # crossed the wire — the advisor's device_cache rec and
            # the wire subtraction above both key on these
            "device_cache": report.get("device_cache"),
            "bytes_hbm_hit": bytes_hbm or None,
            # cold-start attribution (ISSUE 15): the first dispatch
            # carries trace+compile on a cold process; its excess over
            # the steady-state per-dispatch time is what the AOT
            # program store (COMPILE.md) removes — the `precompile`
            # advisor rec keys on it
            "aot": report.get("aot"),
            "aot_hits": calls.get("aot_hits"),
            "aot_misses": calls.get("aot_misses"),
            "first_dispatch_s": calls.get("first_dispatch_s"),
            "cold_start_s": _cold_start_s(stages, calls, n_disp),
            # serve-session shape (ISSUE 17): mean slot occupancy and
            # the admission caps the session ran under — the advisor's
            # queue_cap rec keys on rejecting load while slots idled
            "serve": report.get("serve"),
            "serve_queue_cap": report.get("queue_cap"),
            "serve_occupancy": report.get("slot_occupancy_mean"),
            "serve_rejects": _serve_rejects(report),
        })
    rr.advice = advise(rr)
    rr.verdict = _verdict(rr)
    if publish:
        _publish(rr)
    return rr


def _next_pow2(x: float) -> int:
    return 1 << max(0, math.ceil(math.log2(max(1.0, x))))


def _cold_start_s(stages: dict, calls: dict, n_disp: int) -> float | None:
    """The first dispatch's excess over the steady-state per-dispatch
    time — trace + XLA compile on a cold process (the measured cost the
    AOT program store removes). None when the run can't attribute it
    (single dispatch, or no first-dispatch sample)."""
    first = float(calls.get("first_dispatch_s") or 0.0)
    if first <= 0 or n_disp <= 1:
        return None
    total = float(stages.get("dispatch", 0.0))
    steady = max(0.0, total - first) / (n_disp - 1)
    cold = first - steady
    return cold if cold > 0 else None


def _serve_rejects(report: dict) -> float | None:
    """Admission rejects for a serve report, from the process registry
    (the queue publishes there, not into the per-run report). None for
    non-serve reports — the key must not imply serve semantics on an
    executor run."""
    if not report.get("serve"):
        return None
    from tpudl.obs import metrics as _m

    v = float(_m.counter("serve.rejects").value)
    return v or None


def advise(rr: RooflineReport) -> list[dict]:
    """Knob recommendations ranked by predicted gain, each
    ``{knob, current, recommended, predicted_gain_pct, saved_s,
    reason}``. The predictions come from the SAME decomposition the
    attribution used — no second model:

    - **dispatch round-trip**, first choice: the D-deep async dispatch
      window overlaps the round-trips themselves — depth d→d' keeps
      d/d' of the overhead visible AND hides the same share of the d2h
      drain (copies start at dispatch), with no recompilation and no
      full-size-batch constraint, which is why it outranks fusion on a
      purely dispatch-bound run;
    - **dispatch round-trip**, second lever: fusion amortizes 1/fuse —
      raising ``fuse_steps`` f→f' keeps f/f' of the overhead (one
      compiled program per f' microbatches; the two compose);
    - **unhidden prepare** halves (conservatively) when the pool
      doubles — prepare is embarrassingly parallel across batches, but
      decode sources may serialize internally;
    - **wire** shrinks with the codec (4× for u8 image pixels, 2× for
      bf16; 'auto' is recommended so a non-u8-able batch still gets the
      safe pick).
    """
    recs = []
    if rr.gap_s is None or rr.gap_s <= 0 or not rr.wall_s:
        return recs
    inp = rr.inputs

    def _rec(knob, current, recommended, saved_s, reason):
        new_wall = max(rr.wall_s - saved_s,
                       rr.device_compute_s or 1e-9)
        gain = rr.wall_s / new_wall - 1.0
        if gain < 0.02:  # sub-2% predictions are model noise
            return
        recs.append({
            "knob": knob, "current": current, "recommended": recommended,
            "saved_s": round(saved_s, 4),
            "predicted_gain_pct": round(100 * gain, 1),
            "reason": reason,
        })

    # 0) cold start → precompile (ISSUE 15): the first dispatch paid
    #    trace + XLA compile while every later one ran warm. The AOT
    #    program store removes it from every FUTURE process (restored
    #    serialized executables before the first batch lands), so the
    #    rec fires only when the store is not already armed — armed
    #    runs warm themselves up on the next start automatically.
    cold = inp.get("cold_start_s")
    if cold and cold > _MINOR_FRAC * rr.gap_s and not inp.get("aot"):
        _rec("precompile", "off", "on", float(cold),
             f"the first dispatch carried {float(cold):.2f}s of "
             f"trace+compile (cold start); arm TPUDL_COMPILE_AOT=1 so "
             f"a fresh process restores precompiled programs from the "
             f"AOT store before the first batch (COMPILE.md)")
    # 1) dispatch round-trip → dispatch_depth (the async window): depth
    #    d hides all but ~1/d of the blocking round-trip residue, and —
    #    because the D2H copies start at dispatch — the same share of
    #    the outfeed drain rides under later dispatches. Recommended
    #    FIRST: it needs no recompile and no full-size-batch run, so on
    #    a purely dispatch-bound shape it is the cheaper, bigger win.
    if (rr.dispatch_overhead_s is not None
            and rr.dispatch_overhead_s > _MINOR_FRAC * rr.gap_s):
        cur_dd = max(1, int(inp.get("dispatch_depth") or 1))
        target_overhead = max(0.1 * (rr.device_compute_s or 0.0), 1e-3)
        want_dd = cur_dd * rr.dispatch_overhead_s / target_overhead
        new_dd = min(KNOB_CAPS["dispatch_depth"],
                     max(2 * cur_dd, _next_pow2(want_dd)))
        if new_dd > cur_dd:
            hidden = 1.0 - cur_dd / new_dd
            saved = (rr.dispatch_overhead_s + (rr.d2h_s or 0.0)) * hidden
            _rec("dispatch_depth", cur_dd, new_dd, saved,
                 f"dispatch round-trip is "
                 f"{rr.dispatch_overhead_s:.2f}s of the run; a "
                 f"{new_dd}-deep in-flight window overlaps the "
                 f"round-trips (and the d2h drain) leaving "
                 f"~{cur_dd}/{new_dd} visible, with no recompile")
    # 2) dispatch round-trip → fuse_steps (composes with the window)
    if (rr.dispatch_overhead_s is not None
            and rr.dispatch_overhead_s > _MINOR_FRAC * rr.gap_s):
        cur = max(1, int(inp.get("fuse_steps") or 1))
        # pick the fuse depth that pushes the overhead under ~10% of
        # device compute (or the cap); power of two keeps the compiled
        # (m, B, ...) signatures few
        target_overhead = max(0.1 * (rr.device_compute_s or 0.0), 1e-3)
        want = cur * rr.dispatch_overhead_s / target_overhead
        new = min(KNOB_CAPS["fuse_steps"], max(2 * cur, _next_pow2(want)))
        if new > cur:
            saved = rr.dispatch_overhead_s * (1.0 - cur / new)
            _rec("fuse_steps", cur, new, saved,
                 f"dispatch round-trip is "
                 f"{rr.dispatch_overhead_s:.2f}s of the run; one fused "
                 f"program per {new} microbatches keeps ~{cur}/{new} "
                 f"of it")
    # 3) unhidden prepare → prepare_workers (+ depth to feed them)
    if (rr.prepare_unhidden_s is not None
            and rr.prepare_unhidden_s > _MINOR_FRAC * rr.gap_s):
        cur_w = max(1, int(inp.get("prepare_workers") or 1))
        cur_d = max(1, int(inp.get("prefetch_depth") or 1))
        new_w = min(KNOB_CAPS["prepare_workers"], 2 * cur_w)
        new_d = min(KNOB_CAPS["prefetch_depth"], max(cur_d, new_w + 1))
        if new_w > cur_w:
            saved = rr.prepare_unhidden_s * 0.5
            n_before = len(recs)
            _rec("prepare_workers", cur_w, new_w, saved,
                 f"{rr.prepare_unhidden_s:.2f}s of prepare went "
                 f"unhidden (infeed_wait); a {new_w}-worker pool with "
                 f"depth {new_d} hides more of it")
            if len(recs) > n_before and new_d > cur_d:
                recs.append({
                    "knob": "prefetch_depth", "current": cur_d,
                    "recommended": new_d, "saved_s": 0.0,
                    "predicted_gain_pct": 0.0,
                    "reason": "companion to prepare_workers — the queue "
                              "must hold the extra in-flight batches",
                })
    # 4) wire → codec
    codec = str(inp.get("wire_codec") or "off")
    if (rr.wire_h2d_s is not None
            and rr.wire_h2d_s > _MINOR_FRAC * rr.gap_s
            and codec in ("off", "identity")):
        # u8 image pixels ship 4×, bf16 floats 2× — predict with the
        # conservative 2× ('auto' picks the safe codec per column)
        saved = rr.wire_h2d_s * 0.5
        _rec("wire_codec", codec, "auto", saved,
             f"H2D transfer is {rr.wire_h2d_s:.2f}s at "
             f"{inp.get('h2d_mbps')} MB/s; a wire codec ships 2–4× "
             f"fewer bytes (DATA.md)")
    # 5) wire → device cache (HBM residency, ISSUE 12): a wire-bound
    #    run whose whole dataset fits the resident budget should pin it
    #    — every epoch/repeat run past the first then ships ZERO bytes.
    #    Advisory only (never autotuned: it allocates device memory);
    #    the budget is read env/cache-only — this path must never
    #    import jax or touch a device (the status-thread contract).
    if (rr.wire_h2d_s is not None
            and rr.wire_h2d_s > _MINOR_FRAC * rr.gap_s
            and not inp.get("device_cache")):
        bp = inp.get("bytes_prepared")
        budget = _hbm_budget_bytes()
        if bp and budget and float(bp) <= budget:
            # warm passes pay no wire at all; the first pass already
            # happened, so the whole modeled wire time is the saving
            # on every repeat
            _rec("device_cache", "off", "on", rr.wire_h2d_s,
                 f"H2D transfer is {rr.wire_h2d_s:.2f}s and the "
                 f"dataset ({bp / 2**20:.0f} MB prepared) fits the "
                 f"{budget / 2**20:.0f} MB HBM budget; device-resident "
                 f"batches make every later epoch ship zero wire "
                 f"bytes (DATA.md 'Cache hierarchy')")
    # 6) model-axis collectives (ISSUE 16): a 2-D run whose dispatch
    #    window is mostly TP communication is over-sharded for its
    #    per-device compute — a narrower model axis (if the params
    #    still fit) trades collective hops back for arithmetic.
    #    Advisory only (never autotuned: resizing the grid re-places
    #    every parameter shard).
    if (rr.collective_s is not None
            and rr.collective_s > _MINOR_FRAC * rr.gap_s):
        cur_tp = max(1, int(inp.get("model_axis") or 1))
        if cur_tp > 1:
            new_tp = cur_tp // 2
            # halving the axis roughly halves the per-layer all-reduce
            # payload each device sends (ring cost ∝ (tp-1)/tp)
            saved = rr.collective_s * 0.5
            _rec("model_axis", cur_tp, new_tp, saved,
                 f"model-axis collectives are {rr.collective_s:.2f}s "
                 f"of the run; if the params fit {new_tp}-way "
                 f"(TPUDL_MESH_MODEL={new_tp}), a narrower grid trades "
                 f"ICI hops back for local compute")
    # 7) serve admission (ISSUE 17): the session REJECTED load while
    #    decode slots sat idle — admission, not capacity, was the
    #    limit. Advisory only (capacity knobs change admission
    #    semantics, never autotuned); conservative saving: perfect
    #    packing serves the same tokens in ~occ of the wall, claim
    #    half of that.
    if inp.get("serve") and (inp.get("serve_rejects") or 0) > 0:
        occ = inp.get("serve_occupancy")
        if occ is not None and float(occ) < 0.5:
            cur_cap = int(inp.get("serve_queue_cap") or 0)
            saved = rr.wall_s * (1.0 - float(occ)) * 0.5
            _rec("queue_cap", cur_cap or "default",
                 (2 * cur_cap) if cur_cap else "raise",
                 saved,
                 f"{inp['serve_rejects']:.0f} request(s) were rejected "
                 f"while mean slot occupancy was {float(occ):.0%} — "
                 f"the queue turned work away from idle slots; raise "
                 f"TPUDL_SERVE_QUEUE_CAP (and/or TPUDL_SERVE_SLOTS) "
                 f"so admission matches decode capacity (SERVE.md)")
    recs.sort(key=lambda r: -r["predicted_gain_pct"])
    return recs


def _hbm_budget_bytes() -> int | None:
    """The device-cache budget WITHOUT device access (env override or
    the process's already-derived figure) — None when unknown, which
    suppresses the device_cache recommendation rather than guessing."""
    try:
        from tpudl.data import device_cache as _dc

        return _dc.budget_bytes(allow_device=False)
    except Exception:
        return None


def _verdict(rr: RooflineReport) -> str:
    """One operator-readable line: what binds the run and what to do."""
    if rr.gap_s is None or rr.wall_s is None:
        return "unknown: not enough measurements"
    if rr.device_compute_s is not None and rr.gap_s < 0.2 * rr.wall_s:
        return (f"device-bound: {rr.achieved_rows_per_s:.0f} rows/s is "
                f"within 20% of the chip's "
                f"{rr.achievable_rows_per_s:.0f} rows/s ceiling")
    name = {"dispatch": "dispatch-bound", "wire_h2d": "wire-bound",
            "prepare": "prepare-bound", "d2h": "outfeed-bound",
            "collective": "collective-bound"}.get(
                rr.bottleneck, "host-bound")
    if rr.advice:
        top = rr.advice[0]
        return (f"{name}: set {top['knob']} "
                f"{top['current']}→{top['recommended']} "
                f"(predicted +{top['predicted_gain_pct']:.0f}%)")
    return f"{name}: no actionable knob (see gap_attribution)"


def _publish(rr: RooflineReport) -> None:
    """``obs.roofline.*`` gauges — the model's trajectory in the same
    registry/sink every other layer publishes to."""
    from tpudl.obs import metrics as _m

    if rr.achieved_rows_per_s is not None:
        _m.gauge("obs.roofline.achieved_rows_per_s").set(
            rr.achieved_rows_per_s)
    if rr.achievable_rows_per_s is not None:
        _m.gauge("obs.roofline.achievable_rows_per_s").set(
            rr.achievable_rows_per_s)
    for comp, frac in (rr.gap_attribution or {}).items():
        _m.gauge(f"obs.roofline.gap_frac.{comp}").set(frac)
    if rr.collective_s:
        # model-axis comm seconds (ISSUE 16) — absolute, beside the
        # normalized gap_frac.collective fraction above
        _m.gauge("obs.roofline.collective_s").set(rr.collective_s)
    if rr.advice:
        _m.gauge("obs.roofline.predicted_gain_pct").set(
            rr.advice[0]["predicted_gain_pct"])


def autotune_seed(report: dict | None = None, *,
                  allow_probe: bool = False,
                  match: dict | None = None) -> dict:
    """The async executor's knob seed: ``{knob: value}`` for the
    :data:`AUTOTUNE_KNOBS` the advisor recommends over the PREVIOUS
    run's report (default: ``obs.last_pipeline_report()``) — how
    ``TPUDL_FRAME_AUTOTUNE`` closes the ROADMAP-2 loop without
    hand-set env knobs. Values are the advisor's own ``recommended``
    numbers, clamped into :data:`KNOB_CAPS`; an empty dict (no prior
    report, nothing attributable, no confident advice, or a
    ``match`` miss) leaves the executor on its defaults.

    ``match`` is the workload guard: ``{report_key: value}`` pairs the
    prior report must carry verbatim, or nothing seeds. The executor
    passes its own ``batch_size`` — the advisor's numbers are
    per-dispatch quantities at THAT batch geometry, and a process that
    alternates workloads (a big featurizer, then a tiny scorer) must
    not tune each run for the other's report.

    ``allow_probe`` defaults to False here — seeding happens on the
    executor's hot setup path and must never issue a device op (the
    cached probe / ``TPUDL_WIRE_MBPS`` is consumed when known)."""
    if report is None:
        from tpudl.obs import pipeline as _pipeline

        report = _pipeline.last_pipeline_report()
    if not report:
        return {}
    for key, want in (match or {}).items():
        if report.get(key) != want:
            return {}
    rr = analyze(report, publish=False, allow_probe=allow_probe)
    if rr is None:
        return {}
    seeds: dict = {}
    for rec in rr.advice:
        knob = rec.get("knob")
        val = rec.get("recommended")
        if knob in AUTOTUNE_KNOBS and knob not in seeds \
                and isinstance(val, (int, float)):
            cap = KNOB_CAPS.get(knob)
            seeds[knob] = max(1, min(int(val), cap) if cap else int(val))
    return seeds
