"""The machine-readable registry of every ``TPUDL_*`` environment knob.

One declaration per knob: name, value kind, default, owning subsystem,
and a one-line meaning. Three consumers share it (ANALYSIS.md):

1. the static checker (:mod:`tpudl.analysis.checker`, rule
   ``undeclared-knob``): every ``"TPUDL_*"`` string literal read in the
   source must be declared here — an env read nobody documented is a
   schema change nobody reviewed;
2. the docs: the knob tables in ANALYSIS.md are rendered from this
   module (:func:`render_knob_table`), so prose can't drift from code;
3. the registry round-trip test (tests/test_analysis.py): every
   declared knob is actually read somewhere, every read knob is
   declared — deleting a knob's last use without deleting its
   declaration fails CI, and vice versa.

Adding a knob = add a :class:`Knob` entry here, then use the literal.
The checker points at this file when it flags an undeclared read.
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = ["Knob", "KNOBS", "KNOB_NAMES", "knobs_by_subsystem",
           "render_knob_table"]


@dataclass(frozen=True)
class Knob:
    name: str        # the full TPUDL_* env var
    kind: str        # int | float | bool | str | enum | path | json
    default: str     # rendered default ("" = unset / derived)
    subsystem: str   # frame | data | obs | jobs | train | zoo |
                     # compile | serve | text | bench
    help: str        # one line, present tense


KNOBS: tuple[Knob, ...] = (
    # -- frame executor (PIPELINE.md) ----------------------------------
    Knob("TPUDL_FRAME_PREFETCH", "bool", "1", "frame",
         "0 force-disables the pipelined executor (serial arm: no "
         "prefetch, no prepare pool, no fusion)"),
    Knob("TPUDL_FRAME_PREFETCH_DEPTH", "int", "2", "frame",
         "bounded infeed queue depth (prepared batches in flight)"),
    Knob("TPUDL_FRAME_PREPARE_WORKERS", "int", "2", "frame",
         "prepare-pool threads packing/decoding batches concurrently"),
    Knob("TPUDL_FRAME_FUSE_STEPS", "int", "1", "frame",
         "microbatches per compiled lax.scan dispatch (1 = off)"),
    Knob("TPUDL_FRAME_DISPATCH_DEPTH", "int", "2", "frame",
         "async dispatch window: in-flight dispatches kept as futures "
         "(1 = blocking dispatch)"),
    Knob("TPUDL_FRAME_DONATE", "bool", "1", "frame",
         "donate input buffers on the fused/codec-wrapped dispatch "
         "paths (0 = off)"),
    Knob("TPUDL_FRAME_AUTOTUNE", "bool", "1", "frame",
         "seed unset fuse_steps/dispatch_depth/prefetch_depth from the "
         "roofline advisor's recommendations (0 = off)"),
    Knob("TPUDL_FRAME_DEGRADE", "bool", "0", "frame",
         "1 arms the fault-containment supervisor (FAULTS.md): "
         "classified executor faults retry the run down the bounded "
         "degradation ladder instead of dying"),
    Knob("TPUDL_FRAME_DEGRADE_MAX_RUNGS", "int", "6", "frame",
         "degradation rungs the supervisor may apply before raising "
         "the typed error with a flight dump"),
    Knob("TPUDL_MESH_FAST_PATH", "bool", "1", "frame",
         "0 reverts the mesh executor to the conservative pre-ISSUE-11 "
         "path (serial blocking dispatch, blocking transfer barrier, "
         "no fusion/donation/autotune under a mesh) — the A/B arm and "
         "escape hatch"),
    Knob("TPUDL_MESH_MODEL", "int", "1", "frame",
         "model-axis size for 2-D (data, model) meshes — build_mesh's "
         "n_model default and the HorovodRunner/estimator grid fold "
         "(>1 arms GSPMD tensor parallelism)"),
    Knob("TPUDL_FRAME_IO_WORKERS", "int", "8", "frame",
         "LazyFileColumn file-read threads"),
    Knob("TPUDL_FRAME_DECODE_WORKERS", "int", "1", "frame",
         "image-decode threads per batch slice"),
    Knob("TPUDL_DECODE_THREADS", "int", "", "frame",
         "native image loader decode threads (default: native layer "
         "picks)"),
    Knob("TPUDL_PIPELINE_RING", "int", "16", "frame",
         "PipelineReports retained in the bounded ring"),
    # -- data: wire codecs + shard cache (DATA.md) ---------------------
    Knob("TPUDL_WIRE_CODEC", "enum", "", "data",
         "wire codec for map_batches inputs: identity|u8|bf16|auto "
         "(unset = off)"),
    Knob("TPUDL_WIRE_MBPS", "float", "", "data",
         "H2D bandwidth override in MB/s (skips the bare-device_put "
         "wire probe; also read by the roofline model)"),
    Knob("TPUDL_DATA_BF16_WIRE_MBPS", "float", "1000", "data",
         "wire speed below which codec 'auto' picks bf16 for float "
         "columns"),
    Knob("TPUDL_DATA_CACHE_DIR", "path", "", "data",
         "prepared-batch shard cache directory (unset = cache off)"),
    Knob("TPUDL_DATA_VERIFY", "enum", "first", "data",
         "shard checksum policy: first|always|never"),
    Knob("TPUDL_DATA_DEVICE_CACHE", "bool", "0", "data",
         "1 arms HBM-tier batch residency: prepared encoded batches "
         "pin in device memory, epochs >= 2 ship zero wire bytes"),
    Knob("TPUDL_DATA_HBM_BUDGET_MB", "float", "", "data",
         "device-cache resident-byte budget in MB (unset = a "
         "conservative fraction of reported device memory)"),
    # -- observability (OBSERVABILITY.md) ------------------------------
    Knob("TPUDL_METRICS_FILE", "path", "", "obs",
         "JSONL metrics sink path (unset = no sink)"),
    Knob("TPUDL_METRICS_FLUSH_S", "float", "60", "obs",
         "min seconds between periodic metrics-sink flushes"),
    Knob("TPUDL_TRACE_RING", "int", "65536", "obs",
         "host-span tracer ring capacity"),
    Knob("TPUDL_STATUS_DIR", "path", "", "obs",
         "arms the live status writer: tpudl-status-<pid>.json lands "
         "here (unset = off)"),
    Knob("TPUDL_STATUS_INTERVAL_S", "float", "1.0", "obs",
         "live status writer period (floor 0.05)"),
    Knob("TPUDL_OBS_SCOPES", "int", "64", "obs",
         "attribution-ledger cardinality bound: live scope rows kept "
         "before LRU eviction folds the oldest into unattributed"),
    Knob("TPUDL_WATCHDOG_STALL_S", "float", "0", "obs",
         "heartbeat age that flags a stall; > 0 lazily starts the "
         "watchdog daemon (0/unset = off)"),
    Knob("TPUDL_FLIGHT_DIR", "path", "", "obs",
         "flight-recorder dump directory (default: cwd)"),
    Knob("TPUDL_FLIGHT_BATCHES", "int", "32", "obs",
         "flight recorder: batch-descriptor ring capacity"),
    Knob("TPUDL_FLIGHT_ERRORS", "int", "64", "obs",
         "flight recorder: error ring capacity"),
    Knob("TPUDL_FLIGHT_STALLS", "int", "16", "obs",
         "flight recorder: stall-event ring capacity"),
    Knob("TPUDL_FLIGHT_TICKS", "int", "32", "obs",
         "flight recorder: metric-tick ring capacity"),
    Knob("TPUDL_FLIGHT_REQUESTS", "int", "64", "obs",
         "flight recorder: completed-serve-request descriptor ring "
         "capacity (trace ids + segment timings, never prompt "
         "content)"),
    Knob("TPUDL_FLIGHT_SPANS", "int", "512", "obs",
         "span-ring tail length embedded in a dump"),
    Knob("TPUDL_FAULTHANDLER", "bool", "0", "obs",
         "1 wires stdlib faulthandler to tpudl-fault-<pid>.log for "
         "native (libtpu/XLA) crashes"),
    Knob("TPUDL_DEVICE_MS_PER_STEP", "float", "0", "obs",
         "measured device ms/step fed to the roofline model (0/unset "
         "= derive from the report)"),
    Knob("TPUDL_TRACECK", "bool", "0", "obs",
         "1 arms the recompile-storm sentinel (tpudl.testing.traceck): "
         "jax.jit gains a trace-counting shim, retraces per fn "
         "identity land in traceck.* metrics + the flight error ring"),
    Knob("TPUDL_TRACECK_STORM", "int", "3", "obs",
         "traces of one fn identity beyond which the sentinel files a "
         "recompile_storm finding"),
    # -- jobs / train / retries (JOBS.md) ------------------------------
    Knob("TPUDL_RETRY_IO_ATTEMPTS", "int", "3", "jobs",
         "io_policy() total attempts per file operation (1 disables)"),
    Knob("TPUDL_RETRY_IO_BACKOFF_S", "float", "0.05", "jobs",
         "io_policy() base backoff seconds (exponential + jitter)"),
    Knob("TPUDL_HPO_TRIAL_ATTEMPTS", "int", "1", "jobs",
         "attempts per HPO trial (unset/1 = no retry)"),
    Knob("TPUDL_TRAIN_RESTART_BACKOFF_S", "float", "0.1", "train",
         "gang-restart base backoff seconds (HorovodRunner)"),
    Knob("TPUDL_FAULT_PLAN", "json", "", "jobs",
         "fault-injection plan JSON (tpudl.testing.faults), honored "
         "across process boundaries"),
    Knob("TPUDL_TSAN", "bool", "0", "jobs",
         "1 arms the runtime lock sanitizer (tpudl.testing.tsan): "
         "named_lock() hands out instrumented locks, findings land in "
         "tsan.* metrics + tpudl-tsan-<pid>.json (CONCURRENCY.md)"),
    Knob("TPUDL_TSAN_DEADLOCK_S", "float", "10", "jobs",
         "armed-acquisition wait slice before the sanitizer walks the "
         "wait-for graph for a deadlock cycle"),
    # -- zoo -----------------------------------------------------------
    Knob("TPUDL_WEIGHTS_DIR", "path", "", "zoo",
         "offline pretrained-weights directory (<model>.npz artifacts)"),
    Knob("TPUDL_IMAGENET_CLASS_INDEX", "path", "", "zoo",
         "imagenet class-index JSON override (else keras cache)"),
    Knob("TPUDL_S2D_STEM", "bool", "0", "zoo",
         "1 enables the space-to-depth conv stem (defaults OFF: slower "
         "on this backend, see zoo/s2d.py)"),
    # -- compile subsystem (COMPILE.md) --------------------------------
    Knob("TPUDL_COMPILE_CACHE_DIR", "path",
         "~/.cache/tpudl/xla_cache", "compile",
         "persistent XLA compilation cache directory (0 disables, "
         "loudly: warn-once + compile.cache_disabled)"),
    Knob("TPUDL_COMPILE_AOT", "str", "", "compile",
         "arms the AOT program store: 1 = on at "
         "<compile cache dir>/programs, a path = on at that "
         "directory, unset/0 = off. Dispatch consults precompiled "
         "executables; misses background-compile + persist for the "
         "next process"),
    Knob("TPUDL_COMPILE_BUCKETS", "str", "", "compile",
         "shape-bucket ladder: pow2 | pow2ish (also 1/auto) | an "
         "explicit comma list of rungs | unset/0 = off. Ragged "
         "dispatch shapes pad to the nearest rung so the workload "
         "runs through O(log n) compiled programs"),
    # -- bench (bench.py header) ---------------------------------------
    Knob("TPUDL_BENCH_BUDGET_S", "float", "2400", "bench",
         "soft wall-clock budget; remaining sub-benches skip past it"),
    Knob("TPUDL_BENCH_DEADLINE_S", "float", "3300", "bench",
         "hard watchdog backstop: dump + emit the partial summary"),
    Knob("TPUDL_BENCH_SUBBENCH_FRAC", "float", "0.5", "bench",
         "max fraction of the remaining budget one sub-bench may spend"),
    Knob("TPUDL_BENCH_QUICK", "bool", "0", "bench",
         "1 runs the headline config only with shrunk trial counts"),
    Knob("TPUDL_BENCH_DTYPE", "str", "bfloat16", "bench",
         "compute dtype for the featurize benches"),
    Knob("TPUDL_BENCH_BATCH", "int", "256", "bench",
         "featurize batch size"),
    Knob("TPUDL_BENCH_N", "int", "1024", "bench",
         "featurize row count"),
    Knob("TPUDL_BENCH_TRIALS", "int", "2", "bench",
         "trials per arm (sync-mode phase)"),
    Knob("TPUDL_BENCH_STREAM_TRIALS", "int", "4", "bench",
         "streaming-phase subprocess trials per arm (0 disables; 1 "
         "when quick)"),
    Knob("TPUDL_BENCH_STREAM_BUDGET_S", "float", "1500", "bench",
         "streaming phase: stop starting trials past this wall-clock"),
    Knob("TPUDL_BENCH_TRIAL_TIMEOUT_S", "float", "450", "bench",
         "per-subprocess trial kill timeout"),
    Knob("TPUDL_BENCH_SKIP_BASELINE", "bool", "0", "bench",
         "1 skips the TF-CPU baseline side"),
    Knob("TPUDL_BENCH_RECORD_NAME", "str", "BENCH_r05_full", "bench",
         "basename for the full record written to bench_records/"),
    Knob("TPUDL_BENCH_COMPUTE_ITERS", "int", "8", "bench",
         "compute-only sub-bench iterations"),
    Knob("TPUDL_BENCH_COMPUTE_BATCH", "int", "256", "bench",
         "compute-only sub-bench batch size"),
    Knob("TPUDL_BENCH_CURVE_STEPS", "int", "120", "bench",
         "training-curve sub-bench step count"),
    Knob("TPUDL_BENCH_CURVE_BATCH", "int", "32", "bench",
         "training-curve sub-bench batch size"),
    Knob("TPUDL_BENCH_TRAIN_BATCH", "int", "64", "bench",
         "horovod-train sub-bench batch size"),
    Knob("TPUDL_BENCH_TRAIN_STEPS", "int", "10", "bench",
         "horovod-train sub-bench step count"),
    Knob("TPUDL_BENCH_MLP_ROWS", "int", "65536", "bench",
         "keras-transformer MLP sub-bench row count"),
    Knob("TPUDL_BENCH_PRED_N", "int", "512", "bench",
         "predictor sub-bench image count"),
    Knob("TPUDL_BENCH_EST_INC_FILES", "int", "96", "bench",
         "incremental-estimator sub-bench file count"),
    Knob("TPUDL_BENCH_EST_INC_BATCH", "int", "16", "bench",
         "incremental-estimator sub-bench batch size"),
    Knob("TPUDL_BENCH_DECODE_N", "int", "256", "bench",
         "decode sub-bench image count"),
    Knob("TPUDL_BENCH_DATA_N", "int", "512", "bench",
         "data-pipeline sub-bench row count"),
    Knob("TPUDL_BENCH_HBM_N", "int", "512", "bench",
         "device-cache sub-bench row count (epoch-1 cold vs epoch-2 "
         "resident)"),
    Knob("TPUDL_BENCH_DATA_FILES", "int", "192", "bench",
         "data-pipeline cache sub-bench file count"),
    Knob("TPUDL_BENCH_FAULT_N", "int", "512", "bench",
         "fault-recovery sub-bench row count (clean vs "
         "injected-fault+recovery arms)"),
    Knob("TPUDL_BENCH_ASYNC_N", "int", "768", "bench",
         "async-dispatch A/B sub-bench row count"),
    Knob("TPUDL_BENCH_ASYNC_DEPTH", "int", "4", "bench",
         "async-dispatch A/B sub-bench depth-D arm window size"),
    Knob("TPUDL_BENCH_MESH_N", "int", "1024", "bench",
         "mesh-scaling sub-bench row count (virtual 8-device child)"),
    Knob("TPUDL_BENCH_MESH2D_N", "int", "1024", "bench",
         "2-D mesh sub-bench row count (8x1 vs 4x2 interleaved child)"),
    Knob("TPUDL_BENCH_FLASH_SEQS", "str", "2048,4096,8192,16384",
         "bench", "flash-attention sub-bench sequence-length ladder"),
    Knob("TPUDL_BENCH_PREEMPT_STEPS", "int", "300", "bench",
         "preemption sub-bench child-job step count"),
    Knob("TPUDL_BENCH_COLD_N", "int", "256", "bench",
         "cold-start sub-bench row count (empty- vs warmed-program-"
         "store first-result subprocess A/B)"),
    # -- serve plane (SERVE.md) ----------------------------------------
    Knob("TPUDL_SERVE_QUEUE_CAP", "int", "64", "serve",
         "request-queue admission cap: past this depth submits get a "
         "typed reject (serve.rejects) instead of unbounded growth"),
    Knob("TPUDL_SERVE_SLOTS", "int", "8", "serve",
         "decode slots per model engine — the fixed leading dim of "
         "the slot KV cache (one compiled step program per geometry)"),
    Knob("TPUDL_SERVE_DEADLINE_S", "float", "", "serve",
         "default per-request deadline (seconds from submit); expired "
         "requests are shed typed before/while decoding (unset = "
         "no deadline)"),
    Knob("TPUDL_SERVE_HBM_MB", "float", "", "serve",
         "admission budget on QUEUED payload bytes (MB): submits past "
         "it get a typed hbm_budget reject (unset = off)"),
    Knob("TPUDL_BENCH_SERVE_N", "int", "48", "bench",
         "serve sub-bench total request count driven by the "
         "closed-loop load generator"),
    Knob("TPUDL_BENCH_SERVE_CLIENTS", "int", "4", "bench",
         "serve sub-bench closed-loop client thread count (offered "
         "concurrency)"),
    Knob("TPUDL_BENCH_SERVE_P99_MS", "float", "2000", "bench",
         "serve sub-bench p99 latency target (ms): sustained QPS is "
         "judged only when the measured p99 meets it"),
    # -- serve telemetry (ISSUE 18: lifecycle traces + SLO engine) -----
    Knob("TPUDL_SERVE_TRACE", "bool", "1", "serve",
         "request lifecycle tracing: 0 disarms ReqTrace entirely "
         "(every stamp site gates on it; the <5% overhead guard "
         "measures this toggle)"),
    Knob("TPUDL_SERVE_TRACE_EVENTS", "int", "64", "serve",
         "per-request trace event cap (bounded stamp list; terminal "
         "stamps always land inside it)"),
    Knob("TPUDL_SERVE_TRACE_CADENCE", "int", "16", "serve",
         "decode cadence: stamp every N-th decoded token into the "
         "request trace"),
    Knob("TPUDL_SERVE_SLO_P99_MS", "float", "500", "serve",
         "the latency objective (ms): windowed availability and burn "
         "rate (serve.slo.*) are computed against it"),
    Knob("TPUDL_SERVE_SLO_WINDOW_S", "float", "30", "serve",
         "short SLO window (seconds); the long burn window is 10x "
         "this (the classic multi-window pairing)"),
    Knob("TPUDL_SERVE_SLO_TAIL_K", "float", "4", "serve",
         "tail-exemplar gate: a completed request slower than k x the "
         "windowed median is captured with its segment breakdown into "
         "the error ring"),
    # -- text plane (TEXT.md: tokenizer codec + LM stages) -------------
    Knob("TPUDL_TEXT_WIRE_DTYPE", "enum", "", "text",
         "TokenCodec wire dtype: u16|i32 (unset = auto: u16 when the "
         "vocab fits 65536 ids, else i32); an explicit codec arg "
         "always wins over the env"),
    Knob("TPUDL_BENCH_LM_ROWS", "int", "192", "bench",
         "lm_train sub-bench corpus row count (rounded down to full "
         "frame batches for stable packed shapes)"),
    Knob("TPUDL_BENCH_LM_SEQ", "int", "64", "bench",
         "lm_train sub-bench packed sequence length (docs are sized "
         "so each batch packs to exactly [batch, seq])"),
    Knob("TPUDL_BENCH_LM_BATCH", "int", "32", "bench",
         "lm_train sub-bench frame batch size (= packed rows per "
         "train step)"),
    Knob("TPUDL_BENCH_LM_PROMPTS", "int", "48", "bench",
         "lm_generate sub-bench ragged prompt count (6 distinct "
         "lengths cycled)"),
    Knob("TPUDL_BENCH_LM_MAX_NEW", "int", "8", "bench",
         "lm_generate sub-bench tokens generated per prompt"),
)

KNOB_NAMES = frozenset(k.name for k in KNOBS)


def knobs_by_subsystem() -> dict[str, list[Knob]]:
    out: dict[str, list[Knob]] = {}
    for k in KNOBS:
        out.setdefault(k.subsystem, []).append(k)
    return out


def render_knob_table(subsystem: str | None = None) -> str:
    """Markdown table of (a subsystem's) knobs — the docs' single
    source (ANALYSIS.md embeds the output verbatim)."""
    rows = [k for k in KNOBS
            if subsystem is None or k.subsystem == subsystem]
    lines = ["| knob | kind | default | meaning |",
             "|---|---|---|---|"]
    for k in rows:
        default = k.default if k.default != "" else "*(unset)*"
        lines.append(f"| `{k.name}` | {k.kind} | `{default}` "
                     f"| {k.help} |")
    return "\n".join(lines)
