"""tpudl.analysis — static enforcement of the codebase's contracts.

Three pieces (ANALYSIS.md):

- :mod:`tpudl.analysis.checker`: the AST invariant checker — eight
  rules distilled from PRs 2–7 (atomic writes, flag-only signal
  handlers, the one RetryPolicy, no hot-path device syncs, no silent
  excepts, declared knobs/metrics, locked globals), with
  ``# tpudl: ignore[rule] — reason`` suppressions;
- :mod:`tpudl.analysis.knobs`: the registry of every ``TPUDL_*`` env
  knob (the docs' knob tables render from it);
- :mod:`tpudl.analysis.metric_names`: the registry of every
  ``tpudl.obs`` metric name (shared with tools/validate_metrics.py);
- :mod:`tpudl.analysis.concurrency`: the INTERPROCEDURAL half
  (CONCURRENCY.md) — the whole-tree lock graph and the four
  concurrency rules (lock-order, lock-held-blocking, signal-lock,
  daemon-shared-write);
- :mod:`tpudl.analysis.locks`: the registry of every product lock
  (name / module / guards / declared rank) — feeds the lock graph,
  the runtime sanitizer (:mod:`tpudl.testing.tsan`), and the
  CONCURRENCY.md inventory table;
- :mod:`tpudl.analysis.traceguard`: the JIT-BOUNDARY half — which
  functions are traced (jit/scan/_fused_wrapper/CodecPlan.wrap/
  device_fn= entries, plus transitively everything they call) and the
  five trace rules (trace-time-effect, host-op-on-traced,
  traced-branch, donation-reuse, jit-cache-churn). Runtime twin:
  :mod:`tpudl.testing.traceck` (``TPUDL_TRACECK=1`` recompile-storm
  sentinel).

CLI: ``python -m tools.tpudl_check tpudl tools bench.py``
(exit 0 clean / 2 findings / 1 error; ``--rules`` / ``--json`` for
selective machine-readable runs). Wired into run-tests.sh and tier-1
via tests/test_analysis.py + tests/test_concurrency.py.
"""

from .checker import (Finding, RULES, Suppression, check_file,
                      check_paths, check_source, collect_usage,
                      iter_python_files)
from .concurrency import (CONCURRENCY_RULES, LockGraph, LockSite,
                          analyze as analyze_concurrency,
                          analyze_sources, build_lock_graph,
                          registry_coverage)
from .traceguard import (TRACE_RULES, TracedFn,
                         analyze as analyze_trace,
                         analyze_sources as analyze_trace_sources,
                         traced_functions)
from .knobs import KNOBS, KNOB_NAMES, Knob, render_knob_table
from .locks import (LOCKS, LOCK_NAMES, LockDecl, lock_order,
                    render_lock_table)
from .metric_names import (METRIC_NAMES, METRIC_PATTERNS, METRICS,
                           Metric, is_declared_metric,
                           render_metric_table, unknown_metric_names)

__all__ = [
    "Finding", "RULES", "Suppression", "check_file", "check_paths",
    "check_source", "collect_usage", "iter_python_files",
    "CONCURRENCY_RULES", "LockGraph", "LockSite",
    "analyze_concurrency", "analyze_sources", "build_lock_graph",
    "registry_coverage",
    "TRACE_RULES", "TracedFn", "analyze_trace",
    "analyze_trace_sources", "traced_functions",
    "Knob", "KNOBS", "KNOB_NAMES", "render_knob_table",
    "LockDecl", "LOCKS", "LOCK_NAMES", "lock_order",
    "render_lock_table",
    "Metric", "METRICS", "METRIC_NAMES", "METRIC_PATTERNS",
    "is_declared_metric", "render_metric_table",
    "unknown_metric_names",
]
