"""tpudl.analysis — static enforcement of the codebase's contracts.

Three pieces (ANALYSIS.md):

- :mod:`tpudl.analysis.checker`: the AST invariant checker — eight
  rules distilled from PRs 2–7 (atomic writes, flag-only signal
  handlers, the one RetryPolicy, no hot-path device syncs, no silent
  excepts, declared knobs/metrics, locked globals), with
  ``# tpudl: ignore[rule] — reason`` suppressions;
- :mod:`tpudl.analysis.knobs`: the registry of every ``TPUDL_*`` env
  knob (the docs' knob tables render from it);
- :mod:`tpudl.analysis.metric_names`: the registry of every
  ``tpudl.obs`` metric name (shared with tools/validate_metrics.py).

CLI: ``python -m tools.tpudl_check tpudl tools bench.py``
(exit 0 clean / 2 findings / 1 error). Wired into run-tests.sh and
tier-1 via tests/test_analysis.py.
"""

from .checker import (Finding, RULES, check_file, check_paths,
                      check_source, collect_usage, iter_python_files)
from .knobs import KNOBS, KNOB_NAMES, Knob, render_knob_table
from .metric_names import (METRIC_NAMES, METRIC_PATTERNS, METRICS,
                           Metric, is_declared_metric,
                           render_metric_table, unknown_metric_names)

__all__ = [
    "Finding", "RULES", "check_file", "check_paths", "check_source",
    "collect_usage", "iter_python_files",
    "Knob", "KNOBS", "KNOB_NAMES", "render_knob_table",
    "Metric", "METRICS", "METRIC_NAMES", "METRIC_PATTERNS",
    "is_declared_metric", "render_metric_table",
    "unknown_metric_names",
]
