"""Interprocedural jit-boundary analyzer: who is traced, and the five
contracts traced code must honor.

Third analyzer half (ANALYSIS.md; per-file rules live in
:mod:`tpudl.analysis.checker`, the lock graph in
:mod:`tpudl.analysis.concurrency`, whose call-graph machinery this
module reuses). The whole pipeline surface now runs through cached
jitted programs — ``_fused_wrapper`` retention, ``CodecPlan.wrap``
variants, mesh-fused ``lax.scan`` — and each of those contracts was,
until this module, enforced only by convention and runtime counters.

Phase 1 finds the **traced set**: a function is traced when it reaches
a trace entry —

- decorated ``@jax.jit`` / ``@jit`` / ``@partial(jax.jit, ...)``;
- passed to ``jax.jit(f)`` / ``jax.pmap(f)`` / ``lax.scan(f, ...)``;
- passed to the house wrappers ``_fused_wrapper(f, ...)`` or
  ``<plan>.wrap(f, ...)``;
- the first argument of a call site carrying a truthy ``device_fn=``
  marker (the executor's explicit "this fn is a device program" flag);

plus, transitively, anything a traced function calls (name-based
may-analysis, the same call resolution as the lock graph —
over-approximation is the design, and the sweep's fix-or-suppress
pass is the accuracy mechanism, exactly PR 8/9's deal).

Phase 2 runs five rules:

- ``trace-time-effect``: obs counters/gauges/histograms, flight
  breadcrumbs (``record_*``), env reads (``os.environ``/
  ``os.getenv``), ``print``/logging inside traced code. These execute
  ONCE at trace time: a counter bumped inside a fused prologue records
  one increment for the whole life of the compiled program and
  silently lies per-step thereafter.
- ``host-op-on-traced``: ``np.*`` calls and ``.item()``/``float()``/
  ``int()``/``bool()`` coercions applied to traced values — a host
  round-trip (or a ConcretizationError) inside the program.
- ``traced-branch``: Python ``if``/``while`` on a traced value.
  Static-under-trace accesses (``x.shape``/``x.ndim``/``x.dtype``/
  ``x.size``, ``len(x)``, ``isinstance``, ``is None``) are exempt —
  shape dispatch is the house idiom, value dispatch is the bug.
- ``donation-reuse``: a variable passed to a donating wrapper
  (``_fused_wrapper(..., donate=)``, ``plan.wrap(..., donate=)``,
  ``jax.jit(..., donate_argnums=)``) and read again afterwards in the
  same scope — the static companion to the runtime
  ``data.hbm.donation_blocked`` fallback (PR 12).
- ``jit-cache-churn``: jit/wrap programs built inside loops or over
  per-call closures (a fresh lambda/local def per invocation defeats
  the ``fn._tpudl_fused[key]`` retention pattern — every call
  retraces, ~60 s per recompile on the real chip, ROADMAP item 3),
  and unhashable (list/dict/set literal) static arguments.

Traced-value tracking is a per-function forward dataflow: parameters
(minus ``self``/``cls`` and any the jit site marks static via
``static_argnums``/``static_argnames``) seed the set; assignments
whose right side references a traced value or calls into
``jnp.*``/``jax.*``/``lax.*`` extend it.

Suppression: the shared ``# tpudl: ignore[rule] — reason`` grammar,
accepted at ANY witness site (the offending line, the traced
function's ``def`` line, or the trace-entry site that made it traced).

Runtime twin: :mod:`tpudl.testing.traceck` (``TPUDL_TRACECK=1``)
counts actual retraces per fn identity and files recompile-storm
findings — the seeded-storm test proves both halves fire from one
source.
"""

from __future__ import annotations

import ast

from .checker import Finding, _HINTS  # noqa: F401  (re-export surface)
from .concurrency import _Emitter, _Func, _dotted, _link, read_sources

__all__ = ["TRACE_RULES", "TracedFn", "analyze", "analyze_sources",
           "traced_functions"]

TRACE_RULES = ("trace-time-effect", "host-op-on-traced", "traced-branch",
               "donation-reuse", "jit-cache-churn")

# dotted tails that construct a compiled program from their fn argument
_JIT_DOTTED = {"jax.jit", "jit", "jax.pmap", "pmap"}
_STATIC_KWARGS = ("static_argnums", "static_argnames")
# attribute accesses that are STATIC under trace (shape dispatch)
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
# calls whose result is static even over traced args
_STATIC_CALLS = {"len", "isinstance", "getattr", "hasattr", "type",
                 "callable", "id", "repr"}
_LOG_TAILS = {"debug", "info", "warning", "error", "exception",
              "critical", "log", "warn"}


class TracedFn:
    """Why one function is traced: the entry kind and witness site."""

    __slots__ = ("key", "kind", "file", "line", "via", "static_params")

    def __init__(self, key, kind, file, line, via=None):
        self.key = key          # "<module>:<qual>"
        self.kind = kind        # jit|scan|fused|wrap|device_fn|call
        self.file = file        # trace-entry witness file
        self.line = line        # trace-entry witness line
        self.via = via          # caller qual for transitive entries
        self.static_params: set = set()


def _call_tail(d: str) -> str:
    return d.rsplit(".", 1)[-1] if d else ""


def _bind_targets(n) -> list:
    """The binding targets of an Assign OR AnnAssign — an annotation
    (`g: Callable = jax.jit(f)`) must not break maker/factory
    recognition."""
    return n.targets if isinstance(n, ast.Assign) else [n.target]


def _truthy_const(node) -> bool:
    return isinstance(node, ast.Constant) and bool(node.value)


def _falsy_const(node) -> bool:
    return isinstance(node, ast.Constant) and not node.value


def _static_params_of(call: ast.Call, fnode) -> set:
    """Parameter names a jit call marks static (the ones that are NOT
    traced even though they are parameters)."""
    out: set = set()
    if fnode is None:
        return out
    params = [a.arg for a in fnode.args.posonlyargs + fnode.args.args]
    for kw in call.keywords:
        if kw.arg not in _STATIC_KWARGS:
            continue
        v = kw.value
        elems = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
        for e in elems:
            if isinstance(e, ast.Constant):
                if isinstance(e.value, int) and not isinstance(
                        e.value, bool) and 0 <= e.value < len(params):
                    out.add(params[e.value])
                elif isinstance(e.value, str):
                    out.add(e.value)
    return out


def _donate_positions(call: ast.Call):
    """Donated arg positions of a donating-maker call, or None when the
    call does not donate. ``all`` = every positional arg donated (the
    house wrappers donate their whole input tree)."""
    d = _dotted(call.func)
    tail = _call_tail(d)
    if tail in ("_fused_wrapper", "wrap"):
        for kw in call.keywords:
            if kw.arg == "donate" and not _falsy_const(kw.value):
                return "all"    # donate=True or donate=<flag var>: may
        return None
    if d in _JIT_DOTTED:
        for kw in call.keywords:
            if kw.arg != "donate_argnums":
                continue
            v = kw.value
            # only None/False mean "no donation" — donate_argnums=0
            # donates ARG 0 (an int zero is an argnum, not a flag)
            if isinstance(v, ast.Constant) and \
                    (v.value is None or v.value is False):
                return None
            if isinstance(v, (ast.Tuple, ast.List)):
                if not v.elts:
                    return None   # explicit donate-NOTHING: ()
                elems = v.elts
            else:
                elems = [v]
            pos = set()
            unknown = False
            for e in elems:
                if isinstance(e, ast.Constant) and \
                        isinstance(e.value, int) and \
                        not isinstance(e.value, bool):
                    pos.add(e.value)
                else:
                    unknown = True
            if pos:
                return pos
            # a non-literal spec (donate_argnums=<var>) MAY donate
            # anything — the may-analysis default
            return "all" if unknown else None
    return None


def _static_argnum_positions(call: ast.Call) -> set:
    """Literal static_argnums positions visible at a jit call."""
    pos: set = set()
    for kw in call.keywords:
        if kw.arg != "static_argnums":
            continue
        v = kw.value
        elems = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
        for e in elems:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                pos.add(e.value)
    return pos


class _FnScope:
    """One function's AST plus the bookkeeping phase 2 needs."""

    __slots__ = ("key", "node", "file", "module", "qual", "func")

    def __init__(self, key, node, file, module, qual, func):
        self.key = key
        self.node = node
        self.file = file
        self.module = module
        self.qual = qual
        self.func = func      # the linker's _Func (call resolution)


def _iter_scopes(scan):
    """Every function in a module scan, with the SAME qual scheme the
    concurrency linker uses (class bodies reset qual to the class
    name; nested defs join with '.') so keys line up."""
    out = []

    def walk(node, qual, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, child.name, child.name)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                fq = f"{qual}.{child.name}" if qual else child.name
                out.append((fq, child, cls))
                walk(child, fq, cls)
            else:
                walk(child, qual, cls)

    walk(scan.tree, "", None)
    return out


class _TraceLinker:
    """Phase 1: the traced set over the whole tree."""

    def __init__(self, linker):
        self.linker = linker
        self.scopes: dict[str, _FnScope] = {}
        self._scan_scopes: dict[int, list] = {}  # id(scan) -> scopes
        for scan in linker.scans:
            scoped = _iter_scopes(scan)
            self._scan_scopes[id(scan)] = scoped
            for fq, node, _cls in scoped:
                f = scan.funcs.get(fq)
                if f is None:
                    continue
                self.scopes[f.key] = _FnScope(
                    f.key, node, scan.rel, scan.module, fq, f)
        self.traced: dict[str, TracedFn] = {}

    # -- trace-entry discovery ----------------------------------------
    def _module_ctx(self, scan) -> _Func:
        return _Func(key=f"{scan.module}:<module>", module=scan.module,
                     qual="", cls=None, file=scan.rel, line=0,
                     name="<module>")

    def resolve(self, desc, f: _Func) -> list[_Func]:
        """The linker's call resolution, minus its bare-method-name
        fallback for EXTERNAL module attributes: ``jnp.log`` /
        ``jax.lax.scan`` must not resolve to some repo function that
        happens to be named ``log``/``scan`` — one such mismatch marks
        a whole host subsystem traced and floods the sweep."""
        _, d = desc
        if "." in d:
            head = d.split(".", 1)[0]
            s = self.linker.by_module.get(f.module)
            if s is not None:
                if head in s.imports and \
                        s.imports[head] not in self.linker.by_module:
                    return []
                if head in s.from_imports:
                    mod, orig = s.from_imports[head]
                    if f"{mod}.{orig}" not in self.linker.by_module \
                            and mod not in self.linker.by_module:
                        return []   # `from jax import lax` → lax.scan
        return self.linker.resolve_call(desc, f)

    def _resolve_fn_arg(self, expr, ctx: _Func) -> list[_Func]:
        if isinstance(expr, ast.Lambda):
            return []           # no body scope to analyze; churn rules
            # judge the lambda at its construction site instead
        d = _dotted(expr)
        if not d:
            return []
        return self.resolve(("call", d), ctx)

    def _mark(self, f: _Func, kind, file, line, via=None):
        if f.key in self.traced:
            return False
        self.traced[f.key] = TracedFn(f.key, kind, file, line, via=via)
        return True

    def discover(self):
        for scan in self.linker.scans:
            mod_ctx = self._module_ctx(scan)
            # decorator roots
            for fq, node, _cls in self._scan_scopes[id(scan)]:
                f = scan.funcs.get(fq)
                if f is None:
                    continue
                for dec in node.decorator_list:
                    call = dec if isinstance(dec, ast.Call) else None
                    d = _dotted(call.func if call else dec)
                    if d in _JIT_DOTTED:
                        self._mark(f, "jit", scan.rel, dec.lineno)
                    elif call is not None and \
                            _call_tail(d) == "partial" and call.args and \
                            _dotted(call.args[0]) in _JIT_DOTTED:
                        if self._mark(f, "jit", scan.rel, dec.lineno):
                            self.traced[f.key].static_params |= \
                                _static_params_of(call, node)
            # call-site roots
            for node in ast.walk(scan.tree):
                if not isinstance(node, ast.Call):
                    continue
                d = _dotted(node.func)
                tail = _call_tail(d)
                kind = None
                fn_expr = None
                if d in _JIT_DOTTED and node.args:
                    kind, fn_expr = "jit", node.args[0]
                elif tail == "scan" and "lax" in d and node.args:
                    kind, fn_expr = "scan", node.args[0]
                elif tail == "_fused_wrapper" and node.args:
                    kind, fn_expr = "fused", node.args[0]
                elif tail == "wrap" and isinstance(node.func,
                                                   ast.Attribute) \
                        and node.args:
                    kind, fn_expr = "wrap", node.args[0]
                elif node.args and any(
                        kw.arg == "device_fn" and _truthy_const(kw.value)
                        for kw in node.keywords):
                    kind, fn_expr = "device_fn", node.args[0]
                if kind is None:
                    continue
                ctx = self._ctx_for(scan, node)
                for g in self._resolve_fn_arg(fn_expr, ctx):
                    fresh = self._mark(g, kind, scan.rel, node.lineno)
                    if fresh and kind == "jit":
                        gnode = self.scopes.get(g.key)
                        self.traced[g.key].static_params |= \
                            _static_params_of(
                                node, gnode.node if gnode else None)
        self._propagate()

    def _ctx_for(self, scan, node) -> _Func:
        """The innermost function enclosing ``node`` (for name
        resolution), else a module-level pseudo context."""
        best = None
        for fq, fnode, _cls in self._scan_scopes[id(scan)]:
            if fnode.lineno <= node.lineno <= (fnode.end_lineno or
                                               fnode.lineno):
                if best is None or fnode.lineno >= best[1].lineno:
                    best = (fq, fnode)
        if best is not None:
            f = scan.funcs.get(best[0])
            if f is not None:
                return f
        return self._module_ctx(scan)

    def _propagate(self):
        """Transitive closure: whatever a traced fn calls is traced."""
        work = list(self.traced)
        while work:
            key = work.pop()
            f = self.linker.funcs.get(key)
            if f is None:
                continue
            for desc, line, _held in f.calls:
                for g in self.resolve(desc, f):
                    if g.key not in self.traced:
                        self.traced[g.key] = TracedFn(
                            g.key, "call", f.file, line, via=f.qual)
                        work.append(g.key)


# -- phase 2: the rules -------------------------------------------------

class _RuleRunner:
    def __init__(self, tl: _TraceLinker, emitter: _Emitter):
        self.tl = tl
        self.emitter = emitter

    def run(self):
        for key, why in sorted(self.tl.traced.items()):
            scope = self.tl.scopes.get(key)
            if scope is None:
                continue
            self._check_traced_fn(scope, why)
        # donation-reuse and jit-cache-churn judge HOST code (the
        # scopes that BUILD and CALL the programs), so every function
        # is checked, traced or not — plus one pseudo-scope per MODULE
        # body: a script-level warmup loop is the canonical churn
        # pattern, and the doctor's remediation pointer must not
        # dead-end on it
        module_scopes = [
            _FnScope(f"{scan.module}:<module>", scan.tree, scan.rel,
                     scan.module, "<module>", None)
            for scan in self.tl.linker.scans]
        for scope in sorted(list(self.tl.scopes.values()) +
                            module_scopes,
                            key=lambda s: (s.file, s.qual)):
            self._check_donation(scope)
            self._check_churn(scope)

    # -- traced-value dataflow ----------------------------------------
    def _traced_names(self, scope: _FnScope, why: TracedFn) -> set:
        node = scope.node
        traced: set = set()
        if why.kind != "call":
            # parameters seed the traced set only for ROOT traced fns
            # — a jit/scan/wrap entry's arguments really are tracers.
            # A transitively-traced helper's params are unknowable
            # (name-based may-analysis would brand every static string
            # /int argument a tracer and flood traced-branch); inside
            # it, values born from jnp./lax. calls still count.
            args = node.args
            traced = {a.arg for a in (args.posonlyargs + args.args +
                                      args.kwonlyargs)}
            for va in (args.vararg, args.kwarg):
                if va is not None:
                    traced.add(va.arg)
            traced -= {"self", "cls"}
            traced -= why.static_params
        # iterate to a FIXPOINT: the walk yields nodes out of source
        # order, so a bounded pass count would silently drop any
        # assignment chain deeper than the pass count — exactly the
        # a0 = jnp.f(x); a1 = a0 + 1; a2 = a1 * 2 shape numeric code
        # is made of
        changed = True
        while changed:
            changed = False
            for stmt in self._own_nodes(node):
                if isinstance(stmt, (ast.Assign, ast.AnnAssign,
                                     ast.AugAssign)):
                    value = stmt.value
                    if value is None:
                        continue
                    if self._dynamic_refs(value, traced) or \
                            self._has_device_call(value):
                        targets = (stmt.targets
                                   if isinstance(stmt, ast.Assign)
                                   else [stmt.target])
                        for t in targets:
                            for n in ast.walk(t):
                                if isinstance(n, ast.Name) and \
                                        n.id not in traced:
                                    traced.add(n.id)
                                    changed = True
        return traced

    @staticmethod
    def _own_nodes(fnode):
        """Walk a function body WITHOUT descending into nested defs —
        a nested def is its own traced scope (reached via the closure)
        and must not double-report under its parent."""
        stack = list(ast.iter_child_nodes(fnode))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            yield n
            stack.extend(ast.iter_child_nodes(n))

    @staticmethod
    def _has_device_call(expr) -> bool:
        for n in ast.walk(expr):
            if isinstance(n, ast.Call):
                d = _dotted(n.func)
                head = d.split(".", 1)[0]
                if head in ("jnp", "lax", "jax"):
                    return True
        return False

    @staticmethod
    def _static_ctx(node) -> bool:
        """Is ``node`` a static-under-trace/donation context (shape
        dispatch, metadata access, identity comparison)? THE shared
        predicate for every exemption walker — one list to extend."""
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return True
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d in _STATIC_CALLS or _call_tail(d) in _STATIC_CALLS:
                return True
        if isinstance(node, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            return True
        return False

    def _dynamic_refs(self, expr, traced) -> list:
        """Traced Name loads used DYNAMICALLY in ``expr`` — references
        through static-under-trace accessors (.shape/.ndim/len()/
        isinstance/is-None) are pruned."""
        out: list = []
        if self._static_ctx(expr):
            return out
        if isinstance(expr, ast.Name) and expr.id in traced and \
                isinstance(expr.ctx, ast.Load):
            return [expr]
        for child in ast.iter_child_nodes(expr):
            out.extend(self._dynamic_refs(child, traced))
        return out

    # -- rules on traced functions ------------------------------------
    def _check_traced_fn(self, scope: _FnScope, why: TracedFn):
        traced = self._traced_names(scope, why)
        where = (f"traced via {why.kind} at {why.file}:{why.line}"
                 + (f" (through {why.via})" if why.via else ""))
        sites_tail = [(scope.file, scope.node.lineno),
                      (why.file, why.line)]
        for n in self._own_nodes(scope.node):
            if isinstance(n, ast.Call):
                self._check_effect_call(n, scope, where, sites_tail)
                self._check_host_op(n, traced, scope, where, sites_tail)
            elif isinstance(n, ast.Subscript) and \
                    _dotted(n.value) == "os.environ" and \
                    isinstance(n.ctx, ast.Load):
                self.emitter.emit(
                    "trace-time-effect",
                    f"os.environ read inside traced "
                    f"{scope.qual!r} ({where}) — the env is read ONCE "
                    f"at trace time, not per step",
                    [(scope.file, n.lineno)] + sites_tail)
            elif isinstance(n, (ast.If, ast.While)):
                refs = self._dynamic_refs(n.test, traced)
                if refs:
                    names = sorted({r.id for r in refs})
                    kind = "while" if isinstance(n, ast.While) else "if"
                    self.emitter.emit(
                        "traced-branch",
                        f"Python {kind} on traced value(s) "
                        f"{names} inside {scope.qual!r} ({where}) — "
                        f"concretizes the tracer",
                        [(scope.file, n.lineno)] + sites_tail)

    def _check_effect_call(self, call, scope, where, sites_tail):
        d = _dotted(call.func)
        tail = _call_tail(d)
        effect = None
        if tail in ("counter", "gauge", "histogram") and call.args:
            effect = f"obs {tail}()"
        elif tail.startswith("record_"):
            effect = f"flight breadcrumb {tail}()"
        elif d == "os.getenv" or d.startswith("os.environ"):
            effect = f"env read {d}()"
        elif d == "print":
            effect = "print()"
        elif tail in _LOG_TAILS and self._logger_receiver(d):
            effect = f"logging call {d}()"
        if effect is None:
            return
        self.emitter.emit(
            "trace-time-effect",
            f"{effect} inside traced {scope.qual!r} ({where}) — "
            f"executes once at trace time, then never again per step",
            [(scope.file, call.lineno)] + sites_tail)

    @staticmethod
    def _logger_receiver(d: str) -> bool:
        """Does the dotted receiver look like a LOGGER (logging.info,
        log.warning, self._logger.error), not any object whose name
        merely contains 'log' (catalog.error, dialog.warning)?"""
        if d.startswith("logging."):
            return True
        head = d.rsplit(".", 1)[0].rsplit(".", 1)[-1].lower()
        return head in ("log", "logger") or head.endswith("_log") or \
            head.endswith("logger")

    def _check_host_op(self, call, traced, scope, where, sites_tail):
        d = _dotted(call.func)
        tail = _call_tail(d)
        bad = None
        if (d.startswith("np.") or d.startswith("numpy.")) and any(
                self._dynamic_refs(a, traced)
                for a in list(call.args)
                + [kw.value for kw in call.keywords]):
            bad = f"{d}(...)"
        elif tail == "item" and not call.args and not call.keywords and \
                isinstance(call.func, ast.Attribute) and \
                self._dynamic_refs(call.func.value, traced):
            bad = ".item()"
        elif isinstance(call.func, ast.Name) and \
                call.func.id in ("float", "int", "bool") and \
                len(call.args) == 1 and \
                self._dynamic_refs(call.args[0], traced):
            bad = f"{call.func.id}(...)"
        if bad is None:
            return
        self.emitter.emit(
            "host-op-on-traced",
            f"{bad} applied to a traced value inside {scope.qual!r} "
            f"({where}) — host coercion under trace",
            [(scope.file, call.lineno)] + sites_tail)

    # -- rules on program-building host code ---------------------------
    def _check_donation(self, scope: _FnScope):
        node = scope.node
        makers: dict[str, object] = {}   # bound name -> positions|'all'
        donated: list = []               # (name, call_line, call_end)
        # pass 1: donating-maker bindings (the walk is not in source
        # order, so makers must be complete before calls are judged)
        for n in self._own_nodes(node):
            if isinstance(n, (ast.Assign, ast.AnnAssign)) and \
                    isinstance(n.value, ast.Call):
                pos = _donate_positions(n.value)
                if pos is not None:
                    for t in _bind_targets(n):
                        if isinstance(t, ast.Name):
                            makers[t.id] = pos
        # pass 2: calls through a maker donate their positional args
        for n in self._own_nodes(node):
            if not isinstance(n, ast.Call):
                continue
            pos = None
            if isinstance(n.func, ast.Name) and n.func.id in makers:
                pos = makers[n.func.id]
            elif isinstance(n.func, ast.Call):
                pos = _donate_positions(n.func)   # maker()(args) form
            if pos is None:
                continue
            for i, a in enumerate(n.args):
                if pos != "all" and i not in pos:
                    continue
                if isinstance(a, ast.Name):
                    donated.append((a.id, n.lineno,
                                    n.end_lineno or n.lineno))
        if not donated:
            return
        names = {name for name, _l, _e in donated}
        # loads through static-under-donation accessors (.shape/.ndim/
        # len()/isinstance) are METADATA reads — legal on a donated
        # array (only data access dies), pruned like the traced-value
        # rules prune them
        loads = self._dyn_load_lines(node, names)
        stores: dict[str, list] = {}
        for n in self._own_nodes(node):
            if isinstance(n, ast.Name) and not isinstance(n.ctx,
                                                          ast.Load):
                stores.setdefault(n.id, []).append(n.lineno)
        for name, call_line, call_end in donated:
            for use in sorted(loads.get(name, [])):
                if use <= call_end:
                    # inside the (possibly multi-line) donating call
                    # itself: that load IS the donation, not a reuse
                    continue
                # the call line counts as a rebind site (the canonical
                # donate-and-rebind idiom `x = g(x)` stores g's RESULT
                # into x) — but a store ON the use line does not: in
                # `x = x + 1` the RHS reads the dead buffer BEFORE the
                # rebind lands
                st = [s for s in stores.get(name, [])
                      if call_line <= s < use]
                if st:
                    break   # rebound before the use: later uses see
                    # the NEW binding, not the donated buffer. (A loop
                    # target's own store sits at the FOR line, before
                    # call_line — it never exempts a same-iteration
                    # read of the dead buffer, which executes before
                    # the next rebind.)
                self.emitter.emit(
                    "donation-reuse",
                    f"{name!r} donated to a jitted program at line "
                    f"{call_line} and read again at line {use} in "
                    f"{scope.qual!r} — the donated buffer is dead "
                    f"after dispatch",
                    [(scope.file, use), (scope.file, call_line),
                     (scope.file, getattr(scope.node, "lineno", 1))])
                break       # one finding per donated name

    def _check_churn(self, scope: _FnScope):
        node = scope.node
        # names whose jit-result flows into a subscript store = the
        # retention pattern (per_fn[key] = fused / self._jits[k] = fn)
        cached_names: set = set()
        for n in self._own_nodes(node):
            if isinstance(n, ast.Assign):
                has_sub = any(isinstance(t, ast.Subscript)
                              for t in n.targets)
                if has_sub:
                    if isinstance(n.value, ast.Name):
                        cached_names.add(n.value.id)
                for t in n.targets:
                    if isinstance(t, ast.Name) and (
                            has_sub or self._is_setdefault(n.value)):
                        cached_names.add(t.id)
        decorated_cached = any(
            _call_tail(_dotted(d.func if isinstance(d, ast.Call) else d))
            in ("lru_cache", "cache")
            for d in getattr(node, "decorator_list", []))
        # a jit result that ESCAPES to the caller (returned directly,
        # or via its bound name) is the factory pattern — the caller
        # owns retention (make_train_step and friends), not churn
        returned_names: set = set()
        returned_calls: set = set()
        for n in self._own_nodes(node):
            if isinstance(n, ast.Return) and n.value is not None:
                if isinstance(n.value, ast.Name):
                    returned_names.add(n.value.id)
                elif isinstance(n.value, ast.Call):
                    returned_calls.add(id(n.value))
        local_defs = {c.name for c in ast.iter_child_nodes(node)
                      if isinstance(c, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))}
        loops = [n for n in self._own_nodes(node)
                 if isinstance(n, (ast.For, ast.While, ast.AsyncFor))]
        jit_bound: dict[str, ast.Call] = {}
        for n in self._own_nodes(node):
            if isinstance(n, (ast.Assign, ast.AnnAssign)) and \
                    isinstance(n.value, ast.Call):
                d = _dotted(n.value.func)
                if d in _JIT_DOTTED or _call_tail(d) in (
                        "_fused_wrapper", "wrap"):
                    for t in _bind_targets(n):
                        if isinstance(t, ast.Name):
                            jit_bound[t.id] = n.value
            if not isinstance(n, ast.Call):
                continue
            d = _dotted(n.func)
            tail = _call_tail(d)
            is_jit = d in _JIT_DOTTED
            is_wrap = tail == "_fused_wrapper" or (
                tail == "wrap" and isinstance(n.func, ast.Attribute))
            if not is_jit and not is_wrap:
                continue
            if decorated_cached:
                continue
            bound = self._bound_name(node, n)
            if bound in cached_names or self._stored_in_subscript(node, n):
                continue
            if id(n) in returned_calls or (bound is not None and
                                           bound in returned_names):
                continue
            fn_arg = n.args[0] if n.args else None
            fresh_identity = isinstance(fn_arg, ast.Lambda) or (
                isinstance(fn_arg, ast.Name) and fn_arg.id in local_defs)
            in_loop = any(lp.lineno <= n.lineno <=
                          (lp.end_lineno or lp.lineno) for lp in loops)
            if scope.func is None and not in_loop:
                # module pseudo-scope: the body runs ONCE per process,
                # so `jfn = jax.jit(module_def)` — the canonical hoist
                # the rule's own hint prescribes — is a stable
                # identity, never a per-call closure; only loops churn
                # at module level
                continue
            if is_wrap:
                # the house wrappers RETAIN on fn identity
                # (fn._tpudl_fused[key] / fn._tpudl_codec_wrap[key]):
                # calling them in a loop over a STABLE fn is the
                # pattern working; only a fresh lambda/local-def per
                # call defeats it
                if fresh_identity:
                    self.emitter.emit(
                        "jit-cache-churn",
                        f"{d}(...) over a per-call fn identity in "
                        f"{scope.qual!r} — the wrapper caches on the "
                        f"fn object, and a fresh lambda/closure per "
                        f"call means a fresh cache (and a retrace) "
                        f"every time",
                        [(scope.file, n.lineno),
                         (scope.file, getattr(scope.node, "lineno", 1))])
                continue
            if in_loop:
                self.emitter.emit(
                    "jit-cache-churn",
                    f"{d or 'jit'}(...) built inside a loop in "
                    f"{scope.qual!r} — a fresh program per iteration, "
                    f"every one a retrace",
                    [(scope.file, n.lineno),
                     (scope.file, getattr(scope.node, "lineno", 1))])
                continue
            if fresh_identity:
                self.emitter.emit(
                    "jit-cache-churn",
                    f"{d}(...) over a per-call closure in "
                    f"{scope.qual!r} — each invocation builds a "
                    f"fresh fn identity, so the jit cache never "
                    f"hits (the _fused_wrapper retention pattern "
                    f"caches the wrapper on the fn)",
                    [(scope.file, n.lineno),
                     (scope.file, getattr(scope.node, "lineno", 1))])
        # unhashable static args: g = jit(f, static_argnums=...) then
        # g(..., [literal], ...) at a static position
        for n in self._own_nodes(node):
            if not isinstance(n, ast.Call) or not isinstance(
                    n.func, ast.Name):
                continue
            maker = jit_bound.get(n.func.id)
            if maker is None:
                continue
            static_pos = _static_argnum_positions(maker)
            for i, a in enumerate(n.args):
                if i in static_pos and isinstance(
                        a, (ast.List, ast.Dict, ast.Set)):
                    self.emitter.emit(
                        "jit-cache-churn",
                        f"unhashable {type(a).__name__.lower()} "
                        f"literal passed at static position {i} of a "
                        f"jitted call in {scope.qual!r} — static args "
                        f"must hash (use a tuple)",
                        [(scope.file, n.lineno),
                         (scope.file, maker.lineno)])

    def _dyn_load_lines(self, root, names: set) -> dict:
        """name -> [lineno] of DYNAMIC loads (data access) of
        ``names`` in this scope: nested defs are their own scope, and
        static-metadata contexts (_STATIC_ATTRS/_STATIC_CALLS/is-None)
        are pruned."""
        out: dict = {}

        def walk(n):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                return
            if self._static_ctx(n):
                return
            if isinstance(n, ast.Name) and \
                    isinstance(n.ctx, ast.Load) and n.id in names:
                out.setdefault(n.id, []).append(n.lineno)
            if isinstance(n, ast.AugAssign) and \
                    isinstance(n.target, ast.Name) and \
                    n.target.id in names:
                # `x += 1` READS the pre-assignment value even though
                # the target's ctx is Store — on a donated buffer
                # that read is the dead-buffer bug
                out.setdefault(n.target.id, []).append(n.lineno)
            for c in ast.iter_child_nodes(n):
                walk(c)

        for c in ast.iter_child_nodes(root):
            walk(c)
        return out

    @staticmethod
    def _is_setdefault(value) -> bool:
        return isinstance(value, ast.Call) and \
            _call_tail(_dotted(value.func)) == "setdefault"

    @staticmethod
    def _bound_name(fnode, call) -> str | None:
        for n in ast.walk(fnode):
            if isinstance(n, (ast.Assign, ast.AnnAssign)) and \
                    n.value is call:
                for t in _bind_targets(n):
                    if isinstance(t, ast.Name):
                        return t.id
        return None

    @staticmethod
    def _stored_in_subscript(fnode, call) -> bool:
        for n in ast.walk(fnode):
            if isinstance(n, ast.Assign) and n.value is call and any(
                    isinstance(t, ast.Subscript) for t in n.targets):
                return True
            if isinstance(n, ast.Call) and call in n.args and \
                    _call_tail(_dotted(n.func)) == "setdefault":
                return True
        return False


# -- public API --------------------------------------------------------

def traced_functions(sources: dict, modules: dict | None = None
                     ) -> dict[str, TracedFn]:
    """The traced set itself (no findings): what the tests assert
    against and ``--json`` consumers can inspect."""
    linker, _supp, _errors = _link(sources, modules)
    tl = _TraceLinker(linker)
    tl.discover()
    return tl.traced


def analyze_sources(sources: dict, rules=None,
                    modules: dict | None = None,
                    supp_sink: dict | None = None,
                    linked=None) -> list[Finding]:
    """Run the trace rules over in-memory sources (``{relpath: src}``)
    — the fixture entry point and the CLI's shared-source path.
    ``linked`` (from :func:`concurrency.link_sources`) reuses one
    parse across the interprocedural halves."""
    linker, suppressions, _errors = (linked if linked is not None
                                     else _link(sources, modules))
    tl = _TraceLinker(linker)
    tl.discover()
    emitter = _Emitter(suppressions,
                       set(rules) if rules is not None else None)
    _RuleRunner(tl, emitter).run()
    if supp_sink is not None:
        supp_sink.update(suppressions)
    emitter.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return emitter.findings


def analyze(paths, root: str = ".", rules=None
            ) -> tuple[list[Finding], list[str]]:
    """Run the trace rules over files/dirs — (findings, errors), the
    ``check_paths`` contract: unreadable AND unparseable files are
    errors (an unparseable file must never read as a clean one)."""
    sources, modules, errors = read_sources(paths, root=root)
    linked = _link(sources, modules)
    errors.extend(e for e in linked[2] if e not in errors)
    findings = analyze_sources(sources, rules=rules, modules=modules,
                               linked=linked)
    return findings, errors
