"""The registry of ``tpudl.obs`` metric names.

Every counter/gauge/histogram name the codebase publishes is declared
here, exactly once, so the name schema is reviewable in one place
(ANALYSIS.md). Consumers:

1. the static checker (rule ``undeclared-metric``): a literal (or
   f-string) name at a ``counter(...)``/``gauge(...)``/
   ``histogram(...)`` call site must match a declaration — dashboards
   and the bench sentinel key on these strings, so an unreviewed
   rename is a silent break;
2. ``tools/validate_metrics.py``: the JSONL-sink validator can
   cross-check emitted names against this registry (opt-in
   ``--check-names`` — sink files may legitimately carry user-defined
   metrics);
3. the round-trip test (tests/test_analysis.py): declared ⊆ used and
   used ⊆ declared over ``tpudl/``, ``tools/``, ``bench.py``.

Families with a runtime-computed segment (``frame.stage.<name>.seconds``)
are declared as patterns with exactly one ``*`` segment; the checker
matches an f-string's constant head/tail against them.
"""

from __future__ import annotations

from dataclasses import dataclass
from fnmatch import fnmatchcase

__all__ = ["Metric", "METRICS", "METRIC_NAMES", "METRIC_PATTERNS",
           "is_declared_metric", "unknown_metric_names",
           "render_metric_table"]


@dataclass(frozen=True)
class Metric:
    name: str     # exact dotted name, or a pattern with one '*'
    kind: str     # counter | gauge | histogram | report-gauge
    help: str


METRICS: tuple[Metric, ...] = (
    # -- frame executor ------------------------------------------------
    Metric("frame.map_batches.runs", "counter",
           "map_batches runs finished"),
    Metric("frame.map_batches.rows", "counter",
           "rows processed across runs"),
    Metric("frame.map_batches.batches", "counter",
           "dispatches issued across runs"),
    Metric("frame.map_batches.wall_seconds", "histogram",
           "wall time per run"),
    Metric("frame.stage.*.seconds", "counter",
           "cumulative seconds per executor stage "
           "(prepare/h2d/dispatch/d2h/infeed_wait)"),
    Metric("frame.overlap_efficiency", "gauge",
           "1 - infeed_wait/prepare for the last run"),
    Metric("frame.dispatch.inflight", "gauge",
           "mean in-flight dispatch-window occupancy of the last "
           "async run"),
    Metric("frame.dispatch.overlap_s", "gauge",
           "dispatch seconds the in-flight window hid from the "
           "consumer (last async run)"),
    Metric("frame.degraded.rungs", "counter",
           "degradation-ladder rungs applied by the fault-containment "
           "supervisor (FAULTS.md)"),
    Metric("frame.degraded.recovered_batches", "counter",
           "batches completed by runs that survived on a degraded "
           "rung"),
    Metric("frame.degraded.exhausted", "counter",
           "supervised runs whose ladder ran out (typed error + flight "
           "dump)"),
    Metric("frame.mesh.pad_rows", "gauge",
           "rows of SPMD batch padding the last mesh run shipped and "
           "discarded"),
    Metric("frame.mesh.pad_overhead_pct", "gauge",
           "pad rows as a percent of the last mesh run's dispatched "
           "rows"),
    Metric("frame.mesh.model_axis", "gauge",
           "model-axis size of the last mesh run's grid (1 = pure "
           "data parallelism, >1 = GSPMD tensor parallelism)"),
    Metric("frame.mesh.idle_devices", "gauge",
           "devices stranded by a grid smaller than the host's device "
           "count (build_mesh warn-once rides along)"),
    Metric("queue_depth", "report-gauge",
           "infeed queue depth sampled per batch (PipelineReport)"),
    Metric("dispatch_inflight", "report-gauge",
           "in-flight dispatches sampled per submit (PipelineReport; "
           "max can never exceed dispatch_depth)"),
    Metric("mesh_pad_rows", "report-gauge",
           "SPMD pad rows sampled per mesh batch (PipelineReport)"),
    Metric("slot_occupancy", "report-gauge",
           "active decode slots over total, sampled per serve tick "
           "(PipelineReport; feeds serve.batch_occupancy at finish)"),
    Metric("wire_batch_bytes", "report-gauge",
           "bytes shipped per batch (PipelineReport)"),
    # -- data: codecs + shard cache ------------------------------------
    Metric("data.wire.bytes_shipped", "counter",
           "encoded bytes put on the H2D wire"),
    Metric("data.wire.bytes_dense", "counter",
           "what the same batches would have shipped un-encoded"),
    Metric("data.wire.bytes_saved", "counter",
           "dense minus shipped"),
    Metric("data.codec.encode_seconds", "counter",
           "host time spent wire-encoding"),
    Metric("data.codec.*.batches", "counter",
           "batches encoded per codec (identity/u8/bf16)"),
    Metric("data.cache.hits", "counter", "shard-cache verified hits"),
    Metric("data.cache.misses", "counter", "shard-cache misses"),
    Metric("data.cache.puts", "counter", "shards written"),
    Metric("data.cache.corrupt", "counter",
           "shards failing checksum (re-prepared, never fatal)"),
    Metric("data.cache.evicted", "counter",
           "shards unlinked by eviction mid-read (treated as a miss)"),
    Metric("data.cache.bytes_read", "counter", "shard bytes read"),
    Metric("data.cache.bytes_written", "counter", "shard bytes written"),
    # -- data: HBM-tier device cache (DATA.md "Cache hierarchy") -------
    Metric("data.hbm.bytes_resident", "gauge",
           "bytes currently pinned in the device batch cache"),
    Metric("data.hbm.budget_bytes", "gauge",
           "device-cache resident-byte budget "
           "(TPUDL_DATA_HBM_BUDGET_MB or derived)"),
    Metric("data.hbm.hits", "counter",
           "batches served device-resident (zero wire bytes)"),
    Metric("data.hbm.misses", "counter",
           "device-cache lookups that fell through to the lower tiers"),
    Metric("data.hbm.puts", "counter", "batches made resident"),
    Metric("data.hbm.evictions", "counter",
           "LRU entries evicted to fit the budget"),
    Metric("data.hbm.bytes_served", "counter",
           "bytes served from HBM instead of the wire (the roofline "
           "subtracts these from its wire attribution)"),
    Metric("data.hbm.put_failed", "counter",
           "batches that failed to become resident mid-placement "
           "(tallies stayed consistent; fell back to the wire)"),
    Metric("data.hbm.donation_blocked", "counter",
           "resident batches routed away from a donating program "
           "(resident buffers are never donated)"),
    # -- image IO ------------------------------------------------------
    Metric("imageio.files_read", "counter", "files read off disk"),
    Metric("imageio.bytes_read", "counter", "bytes read off disk"),
    Metric("imageio.decode_errors", "counter",
           "undecodable images (null row, error ring sample)"),
    Metric("imageio.memo_hits", "counter",
           "LazyFileColumn memo hits (no re-read)"),
    Metric("imageio.uris_loaded", "counter",
           "URIs loaded via load_uri_batch"),
    # -- ml / hpo / tuning ---------------------------------------------
    Metric("estimator.trials", "counter", "estimator tuning trials run"),
    Metric("estimator.train_steps", "counter",
           "estimator train steps across trials"),
    Metric("estimator.trial_final_loss", "gauge",
           "last trial's final loss"),
    Metric("hpo.trials_started", "counter", "HPO trials started"),
    Metric("hpo.trials_completed", "counter", "HPO trials completed"),
    Metric("hpo.trials_failed", "counter",
           "HPO trials failed (after retries)"),
    Metric("hpo.trial_seconds", "histogram", "wall time per HPO trial"),
    Metric("hpo.trial_retries", "counter",
           "HPO trial attempts beyond the first"),
    Metric("ml.*.transforms", "counter",
           "transform() calls per ml transformer class"),
    Metric("ml.*.rows_in", "counter",
           "rows entering transform() per transformer class"),
    Metric("ml.*.rows_out", "counter",
           "rows leaving transform() per transformer class"),
    Metric("ml.*.fits", "counter",
           "fit() calls per estimator class"),
    Metric("udf.*.calls", "counter",
           "invocations per registered UDF"),
    Metric("udf.*.rows", "counter",
           "rows processed per registered UDF"),
    Metric("tuning.cv_folds", "counter", "cross-validation folds run"),
    Metric("tuning.cv_evaluations", "counter",
           "cross-validation model evaluations"),
    Metric("tuning.cv_last_metric", "gauge", "last CV fold metric"),
    Metric("tuning.cv_best_metric", "gauge", "best CV metric so far"),
    # -- train ---------------------------------------------------------
    Metric("train.steps", "counter", "optimizer steps taken"),
    Metric("train.examples", "counter", "examples consumed"),
    Metric("train.step_seconds", "histogram", "wall time per step"),
    Metric("train.last_step", "gauge",
           "last completed step (live progress)"),
    Metric("train.restarts", "counter", "gang restarts"),
    Metric("train.restart_backoff_s", "histogram",
           "backoff slept before each gang restart"),
    Metric("train.checkpoint_save_seconds", "histogram",
           "wall time per checkpoint save"),
    Metric("train.checkpoint_restore_seconds", "histogram",
           "wall time per checkpoint restore"),
    Metric("train.checkpoint.corrupt", "counter",
           "checkpoints failing checksum on restore (fell back)"),
    # -- jobs / retries ------------------------------------------------
    Metric("retry.attempts", "counter",
           "retry attempts across all RetryPolicy call sites"),
    Metric("retry.*", "counter",
           "retry attempts per kind (io.read, hpo.trial, ...)"),
    Metric("retry.backoff_s", "histogram",
           "seconds slept per retry backoff"),
    # -- obs self-metrics ----------------------------------------------
    Metric("obs.watchdog.stalls", "counter",
           "heartbeats flagged stalled (once per episode)"),
    Metric("tsan.lock_order_inversions", "counter",
           "armed sanitizer: observed ABBA inversions (once per edge "
           "pair)"),
    Metric("tsan.deadlocks", "counter",
           "armed sanitizer: wait-for cycles / self-deadlocks detected"),
    Metric("tsan.lockset_violations", "counter",
           "armed sanitizer: registered structure mutated without its "
           "declared guard lock"),
    Metric("traceck.traces", "counter",
           "armed sentinel: jitted-fn traces observed (one per "
           "compile)"),
    Metric("traceck.retraces", "counter",
           "armed sentinel: second-or-later traces of one fn identity "
           "(each one a recompile)"),
    Metric("traceck.storms", "counter",
           "armed sentinel: identities tracing past "
           "TPUDL_TRACECK_STORM (one recompile-storm finding each)"),
    # -- compile subsystem (COMPILE.md) --------------------------------
    Metric("compile.hits", "counter",
           "AOT program-store dispatch hits (precompiled/restored "
           "executable ran — no trace possible)"),
    Metric("compile.misses", "counter",
           "AOT program-store dispatch misses (jitted path ran; "
           "signature recorded + background-compiled)"),
    Metric("compile.aot_s", "counter",
           "seconds spent AOT-compiling, serializing and restoring "
           "programs (off the dispatch hot path)"),
    Metric("compile.bucket_pad_rows", "counter",
           "rows of bucket-ladder padding shipped and stripped "
           "(the price of O(log n) program signatures)"),
    Metric("compile.observed", "counter",
           "novel program signatures recorded into the manifest"),
    Metric("compile.programs_compiled", "counter",
           "programs AOT-compiled (warmup + background misses)"),
    Metric("compile.programs_restored", "counter",
           "serialized executables deserialized into the program "
           "table at process start (the zero-cold-start path)"),
    Metric("compile.serialize_failed", "counter",
           "programs whose executable could not be serialized "
           "(table-only for this process; a restart re-lowers them)"),
    Metric("compile.deserialize_failed", "counter",
           "persisted executables that failed to deserialize "
           "(skipped; the jit path covers them)"),
    Metric("compile.exec_failed", "counter",
           "table hits whose executable refused its args (dropped; "
           "fell back to the jitted path)"),
    Metric("compile.store_corrupt", "counter",
           "corrupt program-store artifacts quarantined (manifest or "
           "executable checksum)"),
    Metric("compile.cache_disabled", "counter",
           "persistent-compilation-cache setup failures (a cold fleet "
           "is diagnosable: warn-once + flight breadcrumb ride along)"),
    Metric("obs.roofline.achieved_rows_per_s", "gauge",
           "measured end-to-end throughput (roofline input)"),
    Metric("obs.roofline.achievable_rows_per_s", "gauge",
           "modeled throughput with the gap closed"),
    Metric("obs.roofline.predicted_gain_pct", "gauge",
           "top advisor recommendation's predicted gain"),
    Metric("obs.roofline.gap_frac.*", "gauge",
           "device-vs-e2e gap share attributed per component "
           "(prepare/wire_h2d/dispatch/d2h/other/collective)"),
    Metric("obs.roofline.collective_s", "gauge",
           "gap seconds attributed to model-axis collectives (2-D "
           "mesh runs with a measured comm share)"),
    # -- serve plane (SERVE.md) ----------------------------------------
    Metric("serve.requests", "counter",
           "requests ADMITTED by the queue (offered load = requests "
           "+ rejects)"),
    Metric("serve.rejects", "counter",
           "typed admission rejects (queue_full / hbm_budget) — the "
           "load-shedding evidence obs doctor's overload_shed reads"),
    Metric("serve.deadline_sheds", "counter",
           "requests shed on an expired deadline (queued or "
           "mid-decode, both typed DeadlineExceeded)"),
    Metric("serve.queue_depth", "gauge",
           "current request-queue depth (bounded by "
           "TPUDL_SERVE_QUEUE_CAP)"),
    Metric("serve.queue_cap", "gauge",
           "the admission cap the queue was built with (at-death "
           "evidence for overload_shed)"),
    Metric("serve.inserts", "counter",
           "prompt prefills inserted into decode slots"),
    Metric("serve.evictions", "counter",
           "slots freed EARLY (deadline shed, cancel, supervised "
           "retry) — natural completions are serve.completed"),
    Metric("serve.steps", "counter",
           "slot decode-step dispatches (one compiled program per "
           "step, every active slot rides it)"),
    Metric("serve.tokens", "counter",
           "tokens emitted across all slots"),
    Metric("serve.tokens_per_s", "gauge",
           "sustained token rate of the last finished serve session"),
    Metric("serve.completed", "counter",
           "requests finished with their full token budget"),
    Metric("serve.batches", "counter",
           "rung-bucketed dynamic batches dispatched for ragged "
           "featurize/UDF payloads (RungBatcher)"),
    Metric("serve.batch_occupancy", "gauge",
           "real rows/slots over rung/slot capacity for the last "
           "dispatch (session mean committed at finish; the "
           "saturation SLO: > 0.5 under load)"),
    Metric("serve.latency_ms", "histogram",
           "end-to-end request latency, submit to completion "
           "(p50/p99 are the serving SLO line)"),
    Metric("serve.ttft_s", "histogram",
           "time-to-first-token, submit to prefill completion (the "
           "warm-start win: deserialization, not a 60s jit)"),
    Metric("serve.models", "gauge",
           "models registered in the serve registry"),
    # -- serve SLO plane (ISSUE 18: tpudl.obs.slo windows) -------------
    Metric("serve.slo.target_ms", "gauge",
           "the configured latency objective "
           "(TPUDL_SERVE_SLO_P99_MS) the windowed gauges judge "
           "against"),
    Metric("serve.slo.window_p50_ms", "gauge",
           "p50 latency over the short SLO window "
           "(TPUDL_SERVE_SLO_WINDOW_S) — recent, not lifetime"),
    Metric("serve.slo.window_p99_ms", "gauge",
           "p99 latency over the short SLO window — the number an "
           "operator pages on"),
    Metric("serve.slo.availability", "gauge",
           "fraction of short-window requests meeting the objective"),
    Metric("serve.slo.burn_short", "gauge",
           "error-budget burn rate over the short window (violating "
           "fraction / the 1% p99 budget; >= 1 = burning)"),
    Metric("serve.slo.burn_long", "gauge",
           "burn rate over the long (10x) window — page when BOTH "
           "burn, investigate when only the short one does"),
    Metric("serve.slo.exemplars", "counter",
           "tail exemplars captured into the error ring (latency > "
           "TPUDL_SERVE_SLO_TAIL_K x the windowed median)"),
    # -- attribution plane (ISSUE 20: tpudl.obs.attribution) -----------
    Metric("attribution.scopes_evicted", "counter",
           "ledger scope rows LRU-evicted at the TPUDL_OBS_SCOPES "
           "cardinality bound (totals fold into the unattributed "
           "bucket — the reconciliation invariant survives)"),
    # -- text plane (TEXT.md: tokenizer codec + LM stages) -------------
    Metric("text.tokenize.calls", "counter",
           "tokenize_pack invocations on the prepare pool (epoch-2 "
           "delta MUST be 0 on a cached tokenized Dataset — the "
           "zero-decode warm-replay evidence)"),
    Metric("text.tokenize.tokens", "counter",
           "token ids produced by tokenization (pre-padding)"),
    Metric("text.tokenize.seconds", "histogram",
           "host tokenize+pack latency per prepare call"),
    Metric("text.pack.rows", "counter",
           "packed batch rows emitted (ragged right-padded or dense "
           "chunked)"),
    Metric("text.pack.pad_tokens", "counter",
           "pad ids written into packed batches (the padding tax "
           "bucketing bounds)"),
    Metric("text.pack.fill_pct", "gauge",
           "real-token fraction of the last packed batch (100 = no "
           "padding; dense packing pins this near 100)"),
    Metric("lm.embed.rows", "counter",
           "strings embedded by LMFeaturizer (masked mean-pooled "
           "hidden states)"),
    Metric("lm.classify.rows", "counter",
           "strings labeled by LMClassifier (argmax over class-token "
           "logits at the last real position)"),
    Metric("lm.generate.requests", "counter",
           "prompts completed by LMGenerator transforms"),
    Metric("lm.generate.tokens", "counter",
           "tokens generated by LMGenerator (post-EOS-trim; the "
           "lm_generate bench rate numerator)"),
)

METRIC_NAMES = frozenset(m.name for m in METRICS if "*" not in m.name)
METRIC_PATTERNS = tuple(m.name for m in METRICS if "*" in m.name)


def is_declared_metric(name: str) -> bool:
    """Exact-name membership, falling back to the one-'*' patterns."""
    if name in METRIC_NAMES:
        return True
    return any(fnmatchcase(name, p) for p in METRIC_PATTERNS)


def matches_pattern_prefix(head: str, tail: str = "") -> bool:
    """True when an f-string name with constant ``head``/``tail`` around
    one dynamic segment fits a declared pattern (the checker's view of
    ``f"frame.stage.{name}.seconds"``: head ``frame.stage.``, tail
    ``.seconds``). Containment, not equality: ``f"retry.io.{op}"``
    (head ``retry.io.``) expands only to names the declared ``retry.*``
    already covers, so a sub-family under a declared pattern needs no
    redundant registry entry."""
    for p in METRIC_PATTERNS:
        ph, _, pt = p.partition("*")
        if head.startswith(ph) and tail.endswith(pt):
            return True
    return False


def unknown_metric_names(names) -> list[str]:
    """The subset of ``names`` not declared here (for the JSONL-sink
    validator's opt-in cross-check)."""
    return sorted(n for n in set(names) if not is_declared_metric(n))


def render_metric_table() -> str:
    """Markdown table of the declared names (ANALYSIS.md embeds it)."""
    lines = ["| metric | kind | meaning |", "|---|---|---|"]
    for m in METRICS:
        lines.append(f"| `{m.name}` | {m.kind} | {m.help} |")
    return "\n".join(lines)
