"""AST invariant checker: the rules PRs 2–7 enforced by reviewer memory.

Eight rules, each derived from a contract this codebase already paid
for (ANALYSIS.md documents the history and the fix idiom per rule):

- ``hot-sync``          blocking device sync on the executor hot path
- ``atomic-write``      durable artifact written without tmp+os.replace
- ``signal-handler``    more than flag-sets/os.write in signal context
- ``adhoc-retry``       sleep-in-except/loop outside jobs/retry.py
- ``swallowed-except``  bare/broad except that swallows silently
- ``undeclared-knob``   TPUDL_* literal missing from knobs registry
- ``undeclared-metric`` obs metric literal missing from name registry
- ``unlocked-global``   global rebound without a lock in a threaded
                        module

Suppression: ``# tpudl: ignore[rule-id] — reason`` on the flagged line
or alone on the line above. The reason is REQUIRED — a reasonless
ignore is itself a finding. ``# tpudl: hot-path`` on (or above) a
``def`` marks that one function hot for ``hot-sync``; the executor's
``with report.stage("dispatch"|"d2h"|"h2d")`` blocks are hot
implicitly.

Pure stdlib + the two sibling registries; importable
(``from tpudl.analysis import check_paths``) and runnable via
``python -m tools.tpudl_check`` (exit 0 clean / 2 findings / 1 error,
the validator convention).
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field

from . import knobs as _knobs
from . import metric_names as _metric_names

__all__ = ["Finding", "RULES", "Suppression", "check_source",
           "check_file", "check_paths", "collect_usage",
           "iter_python_files"]

RULES: dict[str, str] = {
    "hot-sync": "blocking device sync (block_until_ready/.item()/"
                "np.asarray/jax.device_get/bare .result()/.wait() on "
                "an in-flight future) on the executor hot path",
    "atomic-write": "durable artifact opened for write without the "
                    "tmp + os.replace idiom in the same function",
    "signal-handler": "signal handler does more than set flags / "
                      "os.write / chain the previous handler",
    "adhoc-retry": "time.sleep in an except/retry loop outside "
                   "tpudl/jobs/retry.py (use RetryPolicy)",
    "swallowed-except": "bare or over-broad except that swallows the "
                        "exception without re-raise or logging",
    "undeclared-knob": "TPUDL_* env literal not declared in "
                       "tpudl/analysis/knobs.py",
    "undeclared-metric": "obs metric name not declared in "
                         "tpudl/analysis/metric_names.py",
    "unlocked-global": "module global rebound outside a lock in a "
                       "module that spawns threads",
    # the four INTERPROCEDURAL rules (tpudl.analysis.concurrency —
    # they reason over the whole tree at once; listed here so the
    # suppression grammar and --list-rules see one catalog)
    "lock-order": "cycle in the acquired-under lock graph (ABBA "
                  "deadlock risk across any number of call hops)",
    "lock-held-blocking": "lock held across a blocking operation "
                          "(bounded-queue put / join / device sync / "
                          "durable IO / subprocess / sleep), directly "
                          "or through a callee",
    "signal-lock": "lock acquisition interprocedurally reachable from "
                   "a signal.signal-registered handler",
    "daemon-shared-write": "attribute/global written from both a "
                           "thread-reachable function and foreground "
                           "code with no common lock",
    # the five TRACE rules (tpudl.analysis.traceguard — the jit
    # boundary: which functions are traced, and what must never happen
    # inside them)
    "trace-time-effect": "host side effect (obs counter/gauge, flight "
                         "breadcrumb, env read, print/logging) inside "
                         "traced code — it runs ONCE at trace time and "
                         "silently lies per-step thereafter",
    "host-op-on-traced": "np.* call or .item()/float()/int() host "
                         "coercion on a traced value inside traced "
                         "code (breaks tracing or forces a sync)",
    "traced-branch": "Python if/while on a traced value inside traced "
                     "code (ConcretizationError; use lax.cond/"
                     "lax.select/jnp.where)",
    "donation-reuse": "a buffer passed to a donating jitted wrapper "
                      "and read again afterwards in the same scope "
                      "(the donated buffer is dead)",
    "jit-cache-churn": "jit/wrap program built per call or per loop "
                       "iteration (fresh closure defeats the "
                       "_fused_wrapper retention pattern), or called "
                       "with unhashable static args — every call "
                       "retraces (~60 s per recompile, ROADMAP 3)",
    # the gate's self-audit (tools/tpudl_check.py full runs only)
    "stale-suppression": "an '# tpudl: ignore[rule]' comment whose "
                         "line no longer produces a finding under "
                         "that rule (the suppression has rotted as "
                         "code moved)",
}

_HINTS: dict[str, str] = {
    "hot-sync": "keep the hot path async (ROADMAP item 2); if the sync "
                "IS this stage's job, suppress with the reason",
    "atomic-write": "write to <path>.tmp.<pid> then os.replace() it "
                    "into place (the shard-manifest contract)",
    "signal-handler": "set a flag (threading.Event) and do the work at "
                      "the next boundary on a normal thread",
    "adhoc-retry": "route through tpudl.jobs.retry.RetryPolicy (e.g. "
                   "io_policy()) so attempts/backoff are counted",
    "swallowed-except": "narrow the except, re-raise, or record a "
                        "breadcrumb (flight recorder / obs counter / "
                        "log) before continuing",
    "undeclared-knob": "add a Knob(...) entry to "
                       "tpudl/analysis/knobs.py (docs render from it)",
    "undeclared-metric": "add a Metric(...) entry to "
                         "tpudl/analysis/metric_names.py",
    "unlocked-global": "guard the write with the module's lock, or use "
                       "a bounded thread-safe structure",
    "lock-order": "acquire in registry rank order (tpudl/analysis/"
                  "locks.py; CONCURRENCY.md) — release the outer lock "
                  "first, or merge the critical sections",
    "lock-held-blocking": "move the blocking call outside the with "
                          "block (snapshot under the lock, do the slow "
                          "work after release)",
    "signal-lock": "signal handlers set flags only (JOBS.md): do the "
                   "locked work at the next boundary on a normal "
                   "thread",
    "daemon-shared-write": "take the structure's named_lock at BOTH "
                           "write sites, or make one side copy-on-read",
    "trace-time-effect": "move the effect outside the traced fn (count "
                         "at the dispatch site, read env before "
                         "wrapping), or use jax.debug.print/callback "
                         "for genuine per-step effects",
    "host-op-on-traced": "use the jnp./lax. equivalent on device; "
                         "materialize AFTER the program returns (and "
                         "outside hot stages — see hot-sync)",
    "traced-branch": "branch on static shape/dtype info, hoist the "
                     "predicate to a static arg, or rewrite with "
                     "lax.cond/lax.select/jnp.where",
    "donation-reuse": "copy before donating, route through the "
                      "non-donating wrapper variant (PR 12's "
                      "donation_blocked fallback), or stop reading "
                      "the buffer after dispatch",
    "jit-cache-churn": "hoist the jit to module scope or cache the "
                       "wrapper on the fn (_fused_wrapper retention "
                       "pattern: fn._tpudl_fused[key]); keep static "
                       "args hashable (tuples, not lists)",
    "stale-suppression": "delete the ignore comment, or re-anchor it "
                         "to the line that still produces the finding",
}

_KNOB_RE = re.compile(r"TPUDL_[A-Z0-9_]+\Z")
_SUPPRESS_RE = re.compile(
    r"#\s*tpudl:\s*ignore\[([a-z\-, ]+)\]\s*[-–—:]?\s*(.*)")
_HOT_RE = re.compile(r"#\s*tpudl:\s*hot-path\b")
_HOT_STAGES = {"dispatch", "d2h", "h2d"}
_DURABLE_RE = re.compile(
    r"manifest|status|dump|checkpoint|ckpt|summary|"
    r"\.(json|jsonl|npy|npz)\b", re.IGNORECASE)
_METRIC_CALLS = {"counter", "gauge", "histogram"}
_BROAD_EXC = {"Exception", "BaseException"}
# calls that are legitimate from signal context: async-signal-safe
# syscalls, handler re-registration — matched by DOTTED form so a
# buffered logfile.write() or pool.kill() doesn't ride the os.* pass
_HANDLER_DOTTED_ALLOW = {"os.write", "os.kill", "os._exit", "os.getpid",
                         "signal.signal"}


@dataclass
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str
    hint: str = ""

    def render(self) -> str:
        s = f"{self.path}:{self.line}:{self.col}: [{self.rule}] " \
            f"{self.message}"
        if self.hint:
            s += f"\n    hint: {self.hint}"
        return s


@dataclass
class Suppression:
    """One ``# tpudl: ignore[rules] — reason`` comment. A single object
    is registered at every line it covers (its own line and the next
    code line), so a finding absorbed at either marks the SAME record
    used — the stale-suppression audit (tools/tpudl_check.py) reports
    records whose rules never absorbed anything."""
    rules: set            # valid rule ids named in the bracket
    reason: str
    line: int             # the comment's own line (the audit anchor)
    col: int = 0
    used: set = field(default_factory=set)  # rule ids that absorbed


@dataclass
class _Ctx:
    """Lexical context threaded through the walk."""
    func: ast.AST | None = None        # enclosing function node
    hot: bool = False                  # hot-path scope (marker/stage)
    in_except: bool = False
    in_loop_try: bool = False          # inside try within a loop
    in_loop: bool = False
    funcs: dict = field(default_factory=dict)  # visible name -> def


class _FileChecker:
    def __init__(self, src: str, path: str, relpath: str):
        self.src = src
        self.path = path
        self.rel = relpath.replace(os.sep, "/")
        self.lines = src.splitlines()
        self.findings: list[Finding] = []
        # line -> [Suppression] (one record may appear under two lines)
        self.suppressions: dict[int, list[Suppression]] = {}
        self.hot_lines: set[int] = set()
        self.docstring_positions: set[tuple[int, int]] = set()
        self.used_knobs: set[str] = set()
        self.used_metrics: set[str] = set()
        self.used_metric_patterns: set[tuple[str, str]] = set()
        self.spawns_threads = False
        self.global_names: set[str] = set()

    # -- comments: suppressions + hot markers --------------------------
    def _scan_comments(self):
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.src).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                line = tok.start[0]
                standalone = self.lines[line - 1].lstrip().startswith("#")
                target = line
                if standalone:
                    # a standalone suppression covers the next code
                    # line, skipping the rest of its comment block
                    target = line + 1
                    while target <= len(self.lines) and (
                            not self.lines[target - 1].strip() or
                            self.lines[target - 1].lstrip()
                            .startswith("#")):
                        target += 1
                m = _SUPPRESS_RE.search(tok.string)
                if m:
                    rules = {r.strip() for r in m.group(1).split(",")}
                    reason = m.group(2).strip()
                    unknown = rules - set(RULES)
                    if unknown:
                        self._emit(line, tok.start[1], "bad-suppression",
                                   f"unknown rule id in suppression: "
                                   f"{sorted(unknown)}",
                                   suppressible=False)
                    valid = rules & set(RULES)
                    # all-unknown rule ids register NOTHING: a typo'd
                    # ignore must not become a suppress-everything that
                    # hides the line's genuine findings
                    if valid:
                        rec = Suppression(rules=valid, reason=reason,
                                          line=line, col=tok.start[1])
                        self.suppressions.setdefault(target, []).append(
                            rec)
                        if standalone:
                            # also cover the comment's own line so a
                            # same-line OR line-above placement both
                            # work (same record: usage marks once)
                            self.suppressions.setdefault(line, []).append(
                                rec)
                if _HOT_RE.search(tok.string):
                    self.hot_lines.add(target)
                    self.hot_lines.add(line)
        except tokenize.TokenError:
            pass

    # -- finding emission (suppression-aware) --------------------------
    def _emit(self, line: int, col: int, rule: str, message: str,
              suppressible: bool = True, also_lines: tuple = ()):
        if suppressible:
            for ln in (line, *also_lines):
                for sup in self.suppressions.get(ln, []):
                    if rule in sup.rules:
                        # a reasonless match still ABSORBED the finding
                        # (used for the stale audit) — but is its own
                        # finding: the reason is required
                        sup.used.add(rule)
                        if not sup.reason:
                            self.findings.append(Finding(
                                self.rel, ln, col, rule,
                                f"suppression for [{rule}] is missing "
                                f"its required reason",
                                "write the why after the bracket: "
                                "# tpudl: ignore[rule] — <reason>"))
                        return
        self.findings.append(Finding(self.rel, line, col, rule,
                                     message, _HINTS.get(rule, "")))

    # -- entry ---------------------------------------------------------
    def run(self) -> list[Finding]:
        self._scan_comments()
        try:
            tree = ast.parse(self.src, filename=self.path)
        except SyntaxError as e:
            raise _ParseError(f"{self.rel}: {e}") from e
        self._collect_docstrings(tree)
        self.spawns_threads = self._module_spawns_threads(tree)
        self._walk(tree, _Ctx())
        self.findings.sort(key=lambda f: (f.line, f.col, f.rule))
        return self.findings

    def _collect_docstrings(self, tree):
        for node in ast.walk(tree):
            if isinstance(node, (ast.Module, ast.ClassDef,
                                 ast.FunctionDef, ast.AsyncFunctionDef)):
                body = node.body
                if body and isinstance(body[0], ast.Expr) and \
                        isinstance(body[0].value, ast.Constant) and \
                        isinstance(body[0].value.value, str):
                    c = body[0].value
                    self.docstring_positions.add((c.lineno, c.col_offset))

    @staticmethod
    def _module_spawns_threads(tree) -> bool:
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                f = node.func
                if (isinstance(f, ast.Attribute) and f.attr == "Thread") \
                        or (isinstance(f, ast.Name) and f.id == "Thread"):
                    return True
        return False

    # -- helpers -------------------------------------------------------
    @staticmethod
    def _call_name(func) -> str:
        if isinstance(func, ast.Attribute):
            return func.attr
        if isinstance(func, ast.Name):
            return func.id
        return ""

    @staticmethod
    def _dotted(node) -> str:
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
        return ".".join(reversed(parts))

    def _expr_idents(self, node):
        """Every identifier / string fragment in an expression — the
        'does this path look durable' evidence for atomic-write."""
        out = []
        for n in ast.walk(node):
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                out.append(n.value)
            elif isinstance(n, ast.Name):
                out.append(n.id)
            elif isinstance(n, ast.Attribute):
                out.append(n.attr)
        return out

    @staticmethod
    def _scope_calls_os_replace(scope) -> bool:
        for n in ast.walk(scope):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr in ("replace", "rename") and \
                    isinstance(n.func.value, ast.Name) and \
                    n.func.value.id == "os":
                return True
        return False

    def _stage_label(self, withitem) -> str | None:
        """``with report.stage("dispatch")`` → 'dispatch'."""
        call = withitem.context_expr
        if isinstance(call, ast.Call) and \
                isinstance(call.func, ast.Attribute) and \
                call.func.attr == "stage" and call.args and \
                isinstance(call.args[0], ast.Constant) and \
                isinstance(call.args[0].value, str):
            return call.args[0].value
        return None

    # -- the walk ------------------------------------------------------
    def _walk(self, node, ctx: _Ctx):
        for child in ast.iter_child_nodes(node):
            self._visit(child, ctx)

    def _visit(self, node, ctx: _Ctx):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._check_function(node, ctx)
            hot = node.lineno in self.hot_lines or any(
                d.lineno in self.hot_lines for d in node.decorator_list)
            ctx.funcs[node.name] = node
            # nested defs do NOT inherit hot: a prepare-pool closure
            # inside map_batches is its own (prepare-stage) scope
            sub = _Ctx(func=node, hot=hot, funcs=dict(ctx.funcs))
            self._walk(node, sub)
            return
        if isinstance(node, ast.ClassDef):
            sub = _Ctx(func=ctx.func, hot=ctx.hot,
                       funcs=dict(ctx.funcs))
            self._walk(node, sub)
            return
        if isinstance(node, ast.With):
            hot = ctx.hot or any(
                (self._stage_label(i) or "") in _HOT_STAGES
                for i in node.items)
            sub = _Ctx(func=ctx.func, hot=hot, in_except=ctx.in_except,
                       in_loop=ctx.in_loop, in_loop_try=ctx.in_loop_try,
                       funcs=ctx.funcs)
            self._walk(node, sub)
            return
        if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
            sub = _Ctx(func=ctx.func, hot=ctx.hot,
                       in_except=ctx.in_except, in_loop=True,
                       in_loop_try=ctx.in_loop_try, funcs=ctx.funcs)
            self._walk(node, sub)
            return
        if isinstance(node, ast.Try):
            body_ctx = _Ctx(func=ctx.func, hot=ctx.hot,
                            in_except=ctx.in_except, in_loop=ctx.in_loop,
                            in_loop_try=ctx.in_loop or ctx.in_loop_try,
                            funcs=ctx.funcs)
            for child in node.body + node.orelse + node.finalbody:
                self._visit(child, body_ctx)
            for handler in node.handlers:
                self._check_except(handler)
                h_ctx = _Ctx(func=ctx.func, hot=ctx.hot, in_except=True,
                             in_loop=ctx.in_loop,
                             in_loop_try=ctx.in_loop_try, funcs=ctx.funcs)
                for child in handler.body:
                    self._visit(child, h_ctx)
            return
        if isinstance(node, ast.Call):
            self._check_call(node, ctx)
        elif isinstance(node, ast.Constant):
            self._check_knob_literal(node)
        elif isinstance(node, (ast.Assign, ast.AugAssign)) and ctx.func:
            self._check_global_write(node, ctx)
        self._walk(node, ctx)

    # -- rule: swallowed-except ---------------------------------------
    def _check_except(self, handler: ast.ExceptHandler):
        names = []
        t = handler.type
        if t is None:
            names = [None]
        elif isinstance(t, ast.Name):
            names = [t.id]
        elif isinstance(t, ast.Tuple):
            names = [e.id for e in t.elts if isinstance(e, ast.Name)]
        bare = t is None
        broad = any(n in _BROAD_EXC for n in names if n)
        if not bare and not broad:
            return
        if not bare and not self._swallows(handler):
            return
        if bare:
            self._emit(handler.lineno, handler.col_offset,
                       "swallowed-except",
                       "bare except: catches SystemExit/"
                       "KeyboardInterrupt and hides the cause")
            return
        if self._swallows(handler):
            which = next(n for n in names if n in _BROAD_EXC)
            self._emit(handler.lineno, handler.col_offset,
                       "swallowed-except",
                       f"except {which} swallows silently (no raise, "
                       f"no breadcrumb, exception unused)")

    @staticmethod
    def _swallows(handler: ast.ExceptHandler) -> bool:
        """True when the handler neither re-raises, nor calls anything
        (a log/record/metric call is a breadcrumb), nor returns a
        value, nor uses the bound exception."""
        for n in ast.walk(handler):
            if isinstance(n, (ast.Raise, ast.Call)):
                return False
            if isinstance(n, ast.Return) and n.value is not None:
                return False
            if handler.name and isinstance(n, ast.Name) and \
                    n.id == handler.name and isinstance(n.ctx, ast.Load):
                return False
        return True

    # -- rule: undeclared-knob ----------------------------------------
    def _check_knob_literal(self, node: ast.Constant):
        if not isinstance(node.value, str):
            return
        if not _KNOB_RE.fullmatch(node.value):
            return
        if (node.lineno, node.col_offset) in self.docstring_positions:
            return
        if self.rel.endswith("tpudl/analysis/knobs.py"):
            return  # the declarations themselves are not USES: counting
            # them would make every declared knob self-count as read and
            # the 'declared but never read' audit could never fire
        self.used_knobs.add(node.value)
        if node.value not in _knobs.KNOB_NAMES:
            self._emit(node.lineno, node.col_offset, "undeclared-knob",
                       f"env knob {node.value!r} is not in the knob "
                       f"registry")

    # -- rule: undeclared-metric / hot-sync / adhoc-retry /
    #    atomic-write / signal-handler (all call-shaped) ---------------
    def _check_call(self, node: ast.Call, ctx: _Ctx):
        name = self._call_name(node.func)
        dotted = self._dotted(node.func)

        # undeclared-metric
        if name in _METRIC_CALLS and node.args and \
                not self.rel.endswith("tpudl/analysis/metric_names.py"):
            self._check_metric_name(node)

        # hot-sync
        if ctx.hot:
            self._check_hot_sync(node, name, dotted)

        # adhoc-retry
        if dotted == "time.sleep" and \
                not self.rel.endswith("tpudl/jobs/retry.py") and \
                (ctx.in_except or (ctx.in_loop and ctx.in_loop_try)):
            where = ("an except block" if ctx.in_except
                     else "a try inside a loop")
            self._emit(node.lineno, node.col_offset, "adhoc-retry",
                       f"time.sleep in {where} looks like an ad-hoc "
                       f"retry/backoff")

        # atomic-write: open(path, "w"/"wb") on a durable-looking path
        if name == "open" and isinstance(node.func, ast.Name):
            self._check_atomic_open(node, ctx)
        if dotted in ("np.save", "np.savez", "np.savez_compressed",
                      "numpy.save", "numpy.savez"):
            self._check_atomic_npsave(node, ctx)

        # signal-handler registration
        if dotted == "signal.signal" and len(node.args) == 2:
            self._check_signal_registration(node, ctx)

    def _check_metric_name(self, node: ast.Call):
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            n = arg.value
            self.used_metrics.add(n)
            if not _metric_names.is_declared_metric(n):
                self._emit(node.lineno, node.col_offset,
                           "undeclared-metric",
                           f"metric name {n!r} is not in the metric "
                           f"registry")
        elif isinstance(arg, ast.JoinedStr):
            head, tail, seen_dyn = "", "", False
            for v in arg.values:
                if isinstance(v, ast.Constant) and \
                        isinstance(v.value, str):
                    if seen_dyn:
                        tail += v.value
                    else:
                        head += v.value
                else:
                    if seen_dyn:   # two dynamic segments: treat tail
                        tail = ""  # as unknowable, match on head only
                    seen_dyn = True
            if not head and not tail:
                return  # fully dynamic: plumbing, not a declaration site
            self.used_metric_patterns.add((head, tail))
            if not _metric_names.matches_pattern_prefix(head, tail):
                self._emit(node.lineno, node.col_offset,
                           "undeclared-metric",
                           f"dynamic metric family "
                           f"{head + '*' + tail!r} is not a declared "
                           f"pattern in the metric registry")

    def _check_hot_sync(self, node: ast.Call, name: str, dotted: str):
        bad = None
        if name == "block_until_ready":
            bad = "block_until_ready"
        elif name == "item" and not node.args and not node.keywords:
            bad = ".item()"
        elif name in ("result", "wait") and \
                isinstance(node.func, ast.Attribute) and \
                not node.args and not node.keywords:
            # the async-dispatch window's helpers: a bare .result() /
            # .wait() on an in-flight future inside a hot stage blocks
            # the dispatch loop exactly like block_until_ready (the
            # executor's own window waits live in their own
            # ``dispatch_wait`` stage, which is deliberately NOT hot)
            bad = f".{name}() (in-flight future)"
        elif dotted in ("jax.device_get",):
            bad = "jax.device_get"
        elif dotted in ("np.asarray", "np.array", "numpy.asarray",
                        "numpy.array") and len(node.args) == 1 and \
                not node.keywords:
            # single-arg form: a host-side np.asarray(x, dtype) on a
            # scalar is fine; a bare asarray on a device array is a
            # blocking D2H round-trip
            bad = f"{dotted}(...) (device→host materialization)"
        if bad:
            self._emit(node.lineno, node.col_offset, "hot-sync",
                       f"{bad} inside a hot-path scope blocks the "
                       f"dispatch pipeline")

    def _check_atomic_open(self, node: ast.Call, ctx: _Ctx):
        mode = None
        if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
            mode = node.args[1].value
        for kw in node.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = kw.value.value
        if not (isinstance(mode, str) and mode.startswith("w")):
            return
        if not node.args:
            return
        evidence = " ".join(self._expr_idents(node.args[0])).lower()
        if "tmp" in evidence or "temp" in evidence:
            return  # writing the tmp side of the idiom itself
        if not _DURABLE_RE.search(evidence):
            return
        scope = ctx.func if ctx.func is not None else None
        if scope is not None and self._scope_calls_os_replace(scope):
            return
        self._emit(node.lineno, node.col_offset, "atomic-write",
                   "durable-looking path opened for write without "
                   "os.replace in the same function (a crash leaves a "
                   "torn artifact)")

    def _check_atomic_npsave(self, node: ast.Call, ctx: _Ctx):
        if not node.args:
            return
        evidence = " ".join(self._expr_idents(node.args[0])).lower()
        if "tmp" in evidence or "temp" in evidence:
            return
        if not _DURABLE_RE.search(evidence):
            return
        scope = ctx.func if ctx.func is not None else None
        if scope is not None and self._scope_calls_os_replace(scope):
            return
        self._emit(node.lineno, node.col_offset, "atomic-write",
                   "np.save to a durable-looking path without "
                   "os.replace in the same function")

    # -- rule: signal-handler -----------------------------------------
    def _check_signal_registration(self, node: ast.Call, ctx: _Ctx):
        target = node.args[1]
        handler = None
        if isinstance(target, ast.Name):
            handler = ctx.funcs.get(target.id)
        if handler is None:
            return  # SIG_DFL / prev-handler variable / lambda-free
        params = {a.arg for a in handler.args.args}
        for stmt in handler.body:
            self._check_handler_stmt(stmt, params, handler)

    def _check_handler_stmt(self, stmt, params: set, handler):
        if isinstance(stmt, (ast.Pass, ast.Raise, ast.Return,
                             ast.Global, ast.Nonlocal)):
            return
        if isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Constant):
            return  # docstring
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            value = stmt.value
            if value is None or isinstance(
                    value, (ast.Constant, ast.Name, ast.Attribute)):
                return  # flag set
        if isinstance(stmt, ast.If):
            for s in stmt.body + stmt.orelse:
                self._check_handler_stmt(s, params, handler)
            return
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            if self._dotted(call.func) in _HANDLER_DOTTED_ALLOW:
                return
            if isinstance(call.func, ast.Attribute) and \
                    call.func.attr == "set" and not call.args:
                return  # event.set() — the threading.Event flag idiom
            if isinstance(call.func, ast.Name) and call.func.id in params:
                return  # chaining the previous handler
        # a suppression on the handler's def line covers the whole
        # handler: one documented reason beats one comment per line
        self._emit(stmt.lineno, stmt.col_offset, "signal-handler",
                   f"signal handler {handler.name!r} does non-trivial "
                   f"work in signal context (an interrupted frame may "
                   f"hold a lock this needs)",
                   also_lines=(handler.lineno,))

    # -- rule: unlocked-global ----------------------------------------
    def _check_global_write(self, node, ctx: _Ctx):
        if not self.spawns_threads:
            return
        if getattr(ctx.func, "name", "").endswith("_locked"):
            return  # the caller-holds-the-lock naming contract
        declared = self._globals_in(ctx.func)
        if not declared:
            return
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        # flatten tuple/list/starred targets: `_A, _B = a, b` rebinds
        # both globals just as racily as the single-name form
        names = set()
        stack = list(targets)
        while stack:
            t = stack.pop()
            if isinstance(t, ast.Name):
                names.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                stack.extend(t.elts)
            elif isinstance(t, ast.Starred):
                stack.append(t.value)
        hit = names & declared
        if not hit:
            return
        if self._under_lock(node, ctx.func):
            return
        self._emit(node.lineno, node.col_offset, "unlocked-global",
                   f"module global {sorted(hit)[0]!r} rebound without "
                   f"a lock in a thread-spawning module")

    @staticmethod
    def _globals_in(func) -> set:
        out = set()
        if func is None:
            return out
        for n in ast.walk(func):
            if isinstance(n, ast.Global):
                out.update(n.names)
        return out

    def _under_lock(self, node, func) -> bool:
        """Is ``node`` lexically inside a ``with <something lock-y>``
        in ``func``? (Ancestor scan — cheap at this file count.)"""
        for w in ast.walk(func):
            if not isinstance(w, ast.With):
                continue
            span_ok = (w.lineno <= node.lineno and
                       (w.end_lineno or w.lineno) >= node.lineno)
            if not span_ok:
                continue
            for item in w.items:
                for ident in self._expr_idents(item.context_expr):
                    if "lock" in str(ident).lower():
                        return True
        return False

    # -- rule: hot-sync markers on functions (checked in _visit) -------
    def _check_function(self, node, ctx: _Ctx):
        pass  # marker resolution happens in _visit


class _ParseError(Exception):
    pass


# -- public API --------------------------------------------------------

def check_source(src: str, filename: str = "<src>",
                 relpath: str | None = None) -> list[Finding]:
    """Check one source string (the tests' fixture entry point)."""
    return _FileChecker(src, filename, relpath or filename).run()


def check_file(path: str, root: str = ".") -> list[Finding]:
    rel = os.path.relpath(path, root)
    with open(path, encoding="utf-8") as f:
        src = f.read()
    return _FileChecker(src, path, rel).run()


def iter_python_files(paths) -> list[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__",)]
                out.extend(os.path.join(dirpath, fn)
                           for fn in sorted(filenames)
                           if fn.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return sorted(set(out))


def check_paths(paths, root: str = ".",
                sources: dict | None = None,
                supp_sink: dict | None = None) -> tuple[list[Finding],
                                                        list[str]]:
    """(findings, errors) over files/dirs. Errors are unreadable or
    unparseable files — the CLI maps them to exit 1. Pass ``sources``
    (``{relpath: src}``, already read) to skip the file IO — the CLI
    reads the tree once and feeds both checker halves. ``supp_sink``
    (``{relpath: {line: [Suppression]}}``) receives each file's
    suppression records with their usage marks — the stale-suppression
    audit's evidence."""
    findings: list[Finding] = []
    errors: list[str] = []
    if sources is not None:
        for rel, src in sorted(sources.items()):
            fc = _FileChecker(src, rel, rel)
            try:
                findings.extend(fc.run())
            except _ParseError as e:
                errors.append(str(e))
                continue
            if supp_sink is not None:
                supp_sink[rel.replace(os.sep, "/")] = fc.suppressions
        return findings, errors
    for path in iter_python_files(paths):
        rel = os.path.relpath(path, root)
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except (OSError, UnicodeDecodeError) as e:
            # a non-UTF-8 source is an ERROR line + rc 1, not a
            # traceback through the lint gate
            errors.append(f"{path}: {e}")
            continue
        fc = _FileChecker(src, path, rel)
        try:
            findings.extend(fc.run())
        except _ParseError as e:
            errors.append(str(e))
            continue
        if supp_sink is not None:
            supp_sink[rel.replace(os.sep, "/")] = fc.suppressions
    return findings, errors


def collect_usage(paths, root: str = ".") -> dict:
    """Scan without judging: which knobs / metric names / dynamic
    metric families the tree actually uses. Feeds the registry
    round-trip test (declared ⊆ used, used ⊆ declared)."""
    knobs: set[str] = set()
    metrics: set[str] = set()
    patterns: set[tuple[str, str]] = set()
    for path in iter_python_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except (OSError, UnicodeDecodeError):
            continue  # check_paths reports these; usage just skips
        fc = _FileChecker(src, path, os.path.relpath(path, root))
        try:
            fc.run()
        except _ParseError:
            continue
        knobs |= fc.used_knobs
        metrics |= fc.used_metrics
        patterns |= fc.used_metric_patterns
    return {"knobs": knobs, "metrics": metrics,
            "metric_patterns": patterns}
