"""The machine-readable registry of every product lock.

One declaration per lock the codebase constructs (via
:func:`tpudl.testing.tsan.named_lock` — the name literal at the
construction site IS the registry key). Consumers (CONCURRENCY.md):

1. the static concurrency analyzer
   (:mod:`tpudl.analysis.concurrency`): the interprocedural lock graph
   resolves every construction site to a declaration, and the coverage
   round-trip test (tests/test_concurrency.py) fails when a
   ``threading.Lock``/``RLock``/``Condition`` appears in ``tpudl/``
   without one (or a declaration loses its construction site);
2. the runtime sanitizer (:mod:`tpudl.testing.tsan`): armed runs check
   observed acquisition order against the declared ranks and name
   locks in inversion/deadlock/lockset findings;
3. the docs: CONCURRENCY.md's lock inventory table renders from this
   module (:func:`render_lock_table`) — drift fails a test, the
   ANALYSIS.md pattern.

**Declared order**: ``order`` is a rank — a thread holding a lock may
only acquire locks of a STRICTLY HIGHER rank (outer/coarse locks are
low, leaf scalar locks are high). Equal ranks must never nest (the
per-instance locks of one class share a rank for exactly this reason).
The ranks document the intended global order; the static ``lock-order``
rule checks the real call graph for cycles regardless, and the armed
sanitizer reports rank violations it actually observes.
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = ["LockDecl", "LOCKS", "LOCK_NAMES", "lock_order",
           "render_lock_table"]


@dataclass(frozen=True)
class LockDecl:
    name: str       # the named_lock(...) literal, dotted lowercase
    module: str     # owning module (dotted, under tpudl)
    kind: str       # lock | rlock | condition. A condition is built by
                    # WRAPPING a named lock in stdlib Condition —
                    # named_lock itself refuses kind="condition" so a
                    # plain Lock can never stand in for one silently.
    scope: str      # "module" (one per process) | "instance" (per obj)
    order: int      # rank: may only acquire strictly higher while held
    guards: str     # one line: the state this lock protects


LOCKS: tuple[LockDecl, ...] = (
    # -- rank 10: coarse outer locks (held across whole operations) ----
    LockDecl("data.shards.manifest", "tpudl.data.shards", "lock",
             "instance", 10,
             "ShardCache shard map + verified set + manifest file IO"),
    LockDecl("jobs.runtime.manifest", "tpudl.jobs.runtime", "lock",
             "instance", 10,
             "JobRuntime resume-manifest read/modify/write"),
    LockDecl("compile.program_store", "tpudl.compile.store", "lock",
             "instance", 10,
             "ProgramStore entry/table maps, pending set, pool "
             "futures + manifest file IO (the shard-manifest "
             "contract)"),
    # -- rank 12: checkpoint store (acquired under an estimator trial's
    #    save lock when a trial persists its result) ------------------
    LockDecl("train.checkpoint.manifest", "tpudl.train.checkpoint",
             "lock", "instance", 12,
             "CheckpointManager manifest + checkpoint store IO"),
    LockDecl("native.build", "tpudl.native", "lock", "module", 10,
             "one-shot native decoder build (cc subprocess) + dlopen"),
    LockDecl("ml.estimator.save", "tpudl.ml.estimator", "lock",
             "instance", 10,
             "shared keras model write-back across trial threads"),
    # -- rank 15 -------------------------------------------------------
    LockDecl("ml.estimator.step_cache", "tpudl.ml.estimator", "lock",
             "instance", 15,
             "compiled-train-step cache shared across trials"),
    LockDecl("image.lazyfile.transform", "tpudl.image.imageIO", "lock",
             "instance", 15,
             "LazyFileColumn serial-decode contract (non-thread-safe "
             "transforms run one batch at a time)"),
    # -- rank 16: obs singleton start/stop (their start/stop paths may
    #    reach the wire probe (20) and the report rings) --------------
    LockDecl("obs.live.writer", "tpudl.obs.live", "lock", "module", 16,
             "status-writer singleton start/stop"),
    LockDecl("obs.watchdog.daemon", "tpudl.obs.watchdog", "lock",
             "module", 16, "watchdog daemon singleton start/stop"),
    LockDecl("data.device_cache.singleton", "tpudl.data.device_cache",
             "lock", "module", 16,
             "process-wide DeviceBatchCache create/reset (construction "
             "publishes the budget gauges — metrics locks are higher)"),
    LockDecl("compile.store.singleton", "tpudl.compile.store", "lock",
             "module", 16,
             "process-wide ProgramStore create/re-root (a changed "
             "TPUDL_COMPILE_AOT dir swaps the instance)"),
    # -- rank 18 -------------------------------------------------------
    LockDecl("data.codec.plan", "tpudl.data.codec", "lock", "instance",
             18, "CodecPlan per-column codec resolution/adoption"),
    # -- rank 20 -------------------------------------------------------
    LockDecl("data.codec.wire_probe", "tpudl.data.codec", "lock",
             "module", 20,
             "process-wide H2D wire-bandwidth probe cache (one probe, "
             "ever)"),
    LockDecl("testing.faults.arm", "tpudl.testing.faults", "lock",
             "module", 20, "fault-plan arm/disarm singleton"),
    LockDecl("testing.traceck", "tpudl.testing.traceck", "lock",
             "module", 20,
             "traceck per-fn-identity trace counts + storm findings "
             "(metrics/flight reporting happens AFTER release)"),
    LockDecl("ml.hpo.slices", "tpudl.ml.hpo", "lock", "module", 20,
             "free device-slice list under the trial thread pool "
             "(function-local; module scope = one per run_parallel "
             "call)"),
    LockDecl("image.lazyfile.memo", "tpudl.image.imageIO", "lock",
             "instance", 20, "LazyFileColumn small-access decode memo"),
    LockDecl("obs.pipeline.ring", "tpudl.obs.pipeline", "lock",
             "module", 20, "bounded ring of recent PipelineReports"),
    LockDecl("data.device_cache", "tpudl.data.device_cache", "lock",
             "instance", 20,
             "DeviceBatchCache entry map + LRU order + resident-byte "
             "and pin accounting (metrics published outside the lock)"),
    LockDecl("serve.queue", "tpudl.serve.queue", "lock", "instance",
             20,
             "RequestQueue deque + payload-byte ledger (admission "
             "decision; metrics and reject raise happen outside the "
             "lock)"),
    LockDecl("serve.registry", "tpudl.serve.registry", "lock",
             "instance", 20,
             "serve ModelRegistry name→entry map (serve.models gauge "
             "published outside the lock)"),
    LockDecl("serve.loadgen", "tpudl.serve.loadgen", "lock", "module",
             20,
             "closed-loop client tallies: request counter + latency/"
             "TTFT/reject/shed lists (function-local; module scope = "
             "one per run_closed_loop call; never held across a "
             "submit/result wait)"),
    # -- rank 24: the two registries (their armed lockset checks file
    #    breadcrumbs into the flight recorder (25); they never nest
    #    with each other) ---------------------------------------------
    LockDecl("obs.metrics.registry", "tpudl.obs.metrics", "lock",
             "instance", 24,
             "MetricsRegistry name→metric map + flush throttle"),
    LockDecl("obs.watchdog.registry", "tpudl.obs.watchdog", "lock",
             "instance", 24,
             "HeartbeatRegistry active set (the watchdog's scan list)"),
    # -- rank 25 -------------------------------------------------------
    LockDecl("testing.faults.plan", "tpudl.testing.faults", "lock",
             "instance", 25,
             "FaultPlan rule counters + fired list (the hot fire() "
             "hook)"),
    LockDecl("obs.pipeline.report", "tpudl.obs.pipeline", "lock",
             "instance", 25,
             "PipelineReport stages/calls/gauges/progress (prepare "
             "workers + consumer write concurrently)"),
    LockDecl("obs.flight.recorder", "tpudl.obs.flight", "lock",
             "instance", 25,
             "FlightRecorder evidence rings (batches/errors/stalls/"
             "ticks/requests/restarts/events) + dumped-paths list"),
    LockDecl("obs.slo.engine", "tpudl.obs.slo", "lock", "instance", 25,
             "SloEngine windowed stamp ring + cached median + publish "
             "throttle (gauges and exemplar writes happen outside "
             "the lock)"),
    LockDecl("obs.attribution.ledger", "tpudl.obs.attribution", "lock",
             "instance", 26,
             "ScopeLedger scope table + unattributed bucket (LRU "
             "bookkeeping and folds under the lock; the eviction "
             "counter publishes after release — charges nest under "
             "any caller lock but acquire nothing themselves)"),
    # -- rank 30: leaf scalar locks (never acquire anything under) -----
    LockDecl("obs.metrics.counter", "tpudl.obs.metrics", "lock",
             "instance", 30, "one Counter's running value"),
    LockDecl("obs.metrics.gauge", "tpudl.obs.metrics", "lock",
             "instance", 30, "one Gauge's value/count/total/max"),
    LockDecl("obs.metrics.histogram", "tpudl.obs.metrics", "lock",
             "instance", 30,
             "one Histogram's sample ring + running aggregates"),
    LockDecl("obs.watchdog.heartbeat", "tpudl.obs.watchdog", "lock",
             "instance", 30,
             "Heartbeat beat fields (info/last_beat/beats/stalled) + "
             "in-flight stage map"),
    LockDecl("obs.tracer.ring", "tpudl.obs.tracer", "lock", "instance",
             30, "host-span tracer ring + dropped counter"),
    LockDecl("image.lazyfile.reads", "tpudl.image.imageIO", "lock",
             "instance", 30, "LazyFileColumn read counter"),
    LockDecl("data.device_cache.token_memo", "tpudl.data.device_cache",
             "lock", "module", 30,
             "array_token memo map (concurrent estimator trial "
             "threads share it; pure dict ops under the lock)"),
    LockDecl("compile.fingerprint_memo", "tpudl.compile.store", "lock",
             "module", 30,
             "fn_fingerprint weak memo map (dispatch pool + warmup "
             "threads share it; pure dict ops under the lock)"),
)

LOCK_NAMES = frozenset(d.name for d in LOCKS)


def lock_order(name: str) -> int | None:
    for d in LOCKS:
        if d.name == name:
            return d.order
    return None


def render_lock_table() -> str:
    """Markdown lock-inventory table (CONCURRENCY.md embeds the output
    verbatim; the drift test re-renders and compares)."""
    lines = ["| order | lock | module | scope | guards |",
             "|---|---|---|---|---|"]
    for d in sorted(LOCKS, key=lambda d: (d.order, d.name)):
        lines.append(f"| {d.order} | `{d.name}` | `{d.module}` "
                     f"| {d.scope} | {d.guards} |")
    return "\n".join(lines)
