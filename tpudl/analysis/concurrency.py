"""Interprocedural concurrency analyzer: the lock graph + four rules.

Where :mod:`tpudl.analysis.checker` judges one file at a time, this
module parses the WHOLE tree at once and reasons across calls
(CONCURRENCY.md):

1. it finds every lock construction site —
   ``threading.Lock/RLock/Condition`` or the house factory
   ``tpudl.testing.tsan.named_lock("<registry name>")`` — as a module
   global, an instance attribute, or a function local;
2. it builds a call graph (name-based, may-analysis: an attribute call
   resolves to every plausibly-matching method, a plain call through
   imports) and tracks, lexically, which locks are held at every
   acquisition, call, blocking operation, and shared-state write;
3. it propagates acquisitions and blocking operations transitively
   through the call graph, yielding the **acquired-under** edge set:
   ``A → B`` when some path acquires B while A is held.

Four rules read that graph:

- ``lock-order``: a cycle in the acquired-under edges — the classic
  ABBA inversion, across any number of files and call hops;
- ``lock-held-blocking``: a lock held across a blocking operation
  (bounded queue ``put``, argless ``join()``/``result()``/``wait()``,
  ``block_until_ready``, durable-path file IO, ``subprocess``,
  ``time.sleep``) directly or through a callee — the stall/deadlock
  class JOBS.md's flag-only SIGTERM rule exists for;
- ``signal-lock``: a lock acquisition interprocedurally reachable from
  a ``signal.signal``-registered handler (the deep version of the
  intra-procedural ``signal-handler`` rule);
- ``daemon-shared-write``: an attribute/global written both from a
  ``Thread(target=...)``/``submit``-reachable function and from
  foreground code, with no common lock held at the two write sites.

Findings carry the same ``# tpudl: ignore[rule] — reason`` suppression
contract as the per-file rules; an interprocedural finding accepts the
suppression at ANY of its witness sites (the call site, the callee's
``def`` line, the handler's ``def`` line), so one documented reason
covers a deliberate pattern instead of one comment per caller.

This is may-analysis by design: name-based call resolution
over-approximates, and the sweep (fix or reason-suppress every
finding, then gate on clean) is the accuracy mechanism — the same
deal the eight per-file rules made in PR 8.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from . import locks as _locks
from .checker import (Finding, _HINTS, _DURABLE_RE, _FileChecker,
                      iter_python_files)

__all__ = ["CONCURRENCY_RULES", "LockSite", "LockGraph", "analyze",
           "analyze_sources", "build_lock_graph", "read_sources",
           "registry_coverage"]

CONCURRENCY_RULES = ("lock-order", "lock-held-blocking", "signal-lock",
                     "daemon-shared-write")

_LOCK_CTORS = {"threading.Lock": "lock", "threading.RLock": "rlock",
               "threading.Condition": "condition", "Lock": "lock",
               "RLock": "rlock", "Condition": "condition"}

# attribute calls resolved by bare method name are capped at this many
# candidates — a name matching more is too generic to mean anything
_METHOD_CANDIDATE_CAP = 6
# method names too generic for name-based resolution (the blocking
# catalog handles put/join/result/wait separately)
_SKIP_METHODS = frozenset({
    "get", "put", "set", "add", "pop", "append", "appendleft", "update",
    "items", "values", "keys", "join", "close", "read", "write", "open",
    "copy", "split", "strip", "encode", "decode", "format", "lower",
    "upper", "sort", "extend", "clear", "remove", "discard", "wait",
    "result", "done", "cancel", "shutdown", "acquire", "release",
    "tobytes", "reshape", "astype", "flush", "mean", "sum", "info",
    "debug", "warning", "error", "exception", "count", "index", "popleft",
})


def _dotted(node) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    if parts:
        # receiver is a call/subscript: keep the attr tail so
        # get_recorder().record_stall still resolves by method name
        return "().".join(["?"] + list(reversed(parts)))
    return ""


def _expr_idents(node):
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.append(n.value)
        elif isinstance(n, ast.Name):
            out.append(n.id)
        elif isinstance(n, ast.Attribute):
            out.append(n.attr)
    return out


@dataclass
class LockSite:
    lock_id: str          # canonical graph node id
    name: str | None      # named_lock registry literal (None = raw)
    kind: str             # lock | rlock | condition
    file: str             # repo-relative path
    line: int
    module: str
    cls: str | None = None
    attr: str | None = None   # instance-attribute name, if any


@dataclass
class _Func:
    key: str              # "<module>:<qualname>"
    module: str
    qual: str
    cls: str | None
    file: str
    line: int
    name: str
    params: tuple = ()
    # each entry carries the lexically-held descriptor tuple at that
    # point; descriptors are resolved to lock_ids in the link phase
    acquires: list = field(default_factory=list)  # (desc, line, held)
    calls: list = field(default_factory=list)     # (desc, line, held)
    blocking: list = field(default_factory=list)  # (what, line, held)
    writes: list = field(default_factory=list)    # (loc, line, held)


@dataclass
class LockGraph:
    """What `build_lock_graph` hands the coverage test and the CLI."""
    locks: list            # [LockSite]
    edges: dict            # (lock_id_a, lock_id_b) -> witness dict
    functions: dict        # key -> _Func

    def sites_by_name(self) -> dict:
        return {s.name: s for s in self.locks if s.name}

    def anonymous_sites(self) -> list:
        return [s for s in self.locks if s.name is None]


def _flat_targets(targets) -> list:
    """Flatten tuple/list/starred assignment targets — `_A, _B = ...`
    writes both names just as racily as the single-name form (the same
    hardening the per-file unlocked-global rule carries)."""
    out, stack = [], list(targets)
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        elif isinstance(t, ast.Starred):
            stack.append(t.value)
        else:
            out.append(t)
    return out


class _ModuleScan:
    """One file's raw facts (phase 1). Resolution happens globally in
    phase 2 — an instance-attribute lock or a cross-module call can
    only be resolved once every file has been scanned."""

    def __init__(self, src: str, relpath: str, module: str | None = None):
        self.rel = relpath.replace(os.sep, "/")
        if module is None:
            mod = self.rel[:-3] if self.rel.endswith(".py") else self.rel
            if mod.endswith("/__init__"):
                # a package's __init__ IS the package for import
                # resolution (`import tpudl.native` must find its locks)
                mod = mod[: -len("/__init__")]
            module = mod.replace("/", ".")
        self.module = module
        self.tree = ast.parse(src, filename=relpath)
        self.imports: dict[str, str] = {}        # alias -> module
        self.from_imports: dict[str, tuple] = {}  # name -> (module, orig)
        self.locks: list[LockSite] = []
        self.funcs: dict[str, _Func] = {}        # qual -> _Func
        self.classes: dict[str, dict] = {}       # cls -> {meth: qual}
        self.class_attrs: dict[str, set] = {}    # cls -> attrs assigned
        self.signal_handlers: list = []          # (desc, line, qual)
        self.spawns: list = []                   # (desc, line, qual)
        self._scan_imports()
        self._scan(self.tree, qual="", cls=None, func=None, held=())

    # -- phase 1: the walk --------------------------------------------
    def _scan_imports(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.from_imports[a.asname or a.name] = (
                        node.module, a.name)

    def _lock_ctor(self, call: ast.Call):
        """(kind, registry_name) when ``call`` constructs a lock."""
        d = _dotted(call.func)
        tail = d.rsplit(".", 1)[-1]
        if tail == "named_lock":
            name = None
            if call.args and isinstance(call.args[0], ast.Constant) \
                    and isinstance(call.args[0].value, str):
                name = call.args[0].value
            kind = "lock"
            for kw in call.keywords:
                if kw.arg == "kind" and isinstance(kw.value, ast.Constant):
                    kind = str(kw.value.value)
            return kind, name
        if d in _LOCK_CTORS and (d.startswith("threading.")
                                 or tail in self.from_imports):
            return _LOCK_CTORS[d], None
        return None

    def _scan(self, node, qual, cls, func, held):
        for child in ast.iter_child_nodes(node):
            self._visit(child, qual, cls, func, held)

    def _visit(self, node, qual, cls, func, held):
        if isinstance(node, ast.ClassDef):
            self.classes.setdefault(node.name, {})
            self.class_attrs.setdefault(node.name, set())
            self._scan(node, qual=node.name, cls=node.name, func=None,
                       held=())
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fq = f"{qual}.{node.name}" if qual else node.name
            f = _Func(key=f"{self.module}:{fq}", module=self.module,
                      qual=fq, cls=cls, file=self.rel, line=node.lineno,
                      name=node.name,
                      params=tuple(a.arg for a in node.args.args))
            self.funcs[fq] = f
            if cls is not None and qual == cls:
                self.classes[cls][node.name] = fq
            self._scan(node, qual=fq, cls=cls, func=f, held=())
            return
        if isinstance(node, ast.With):
            new_held = held
            for item in node.items:
                desc = self._with_lock_desc(item.context_expr)
                if desc is not None:
                    if func is not None:
                        func.acquires.append((desc, node.lineno, new_held))
                    new_held = new_held + (desc,)
                else:
                    # a non-lock with-item runs with every lock from
                    # the EARLIER items already held: `with self._lock,
                    # open(manifest, "w"):` is durable IO under the
                    # lock, and nested calls in the item's expression
                    # keep their call edges
                    self._visit(item.context_expr, qual, cls, func,
                                new_held)
            for child in node.body:
                self._visit(child, qual, cls, func, new_held)
            return
        if isinstance(node, ast.Call):
            self._visit_call(node, qual, cls, func, held)
            self._scan(node, qual, cls, func, held)
            return
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._visit_assign(node, qual, cls, func, held)
            self._scan(node, qual, cls, func, held)
            return
        self._scan(node, qual, cls, func, held)

    def _with_lock_desc(self, expr):
        """A with-item that acquires a lock → its descriptor."""
        if isinstance(expr, (ast.Name, ast.Attribute)):
            d = _dotted(expr)
            if d:
                return ("lockref", d)
        return None

    def _visit_call(self, call: ast.Call, qual, cls, func, held):
        d = _dotted(call.func)
        tail = d.rsplit(".", 1)[-1] if d else ""

        # explicit .acquire() — an acquisition event (held-set is NOT
        # extended: the matching release is not lexically visible)
        if tail == "acquire" and "." in d and func is not None:
            func.acquires.append((("lockref", d.rsplit(".", 1)[0]),
                                  call.lineno, held))
            return

        # thread spawns
        if tail == "Thread":
            for kw in call.keywords:
                if kw.arg == "target":
                    t = _dotted(kw.value)
                    if t:
                        self.spawns.append((("call", t), call.lineno,
                                            qual))
        elif tail == "submit" and call.args:
            t = _dotted(call.args[0])
            if t:
                self.spawns.append((("call", t), call.lineno, qual))

        # signal handler registration
        if d == "signal.signal" and len(call.args) == 2:
            t = _dotted(call.args[1])
            if t:
                self.signal_handlers.append((("call", t), call.lineno,
                                             qual))

        if func is None:
            return

        # blocking catalog
        blk = self._blocking_kind(call, d, tail)
        if blk is not None:
            func.blocking.append((blk, call.lineno, held))

        # the call edge itself
        if d and tail not in ("Thread", "named_lock") \
                and d not in _LOCK_CTORS:
            func.calls.append((("call", d), call.lineno, held))

    def _blocking_kind(self, call, d, tail) -> str | None:
        if tail == "put" and "queue" in d.lower():
            return "bounded-queue put"
        if tail in ("join", "result", "wait") and not call.args \
                and not call.keywords and "." in d:
            return f"argless .{tail}() (unbounded wait)"
        if tail == "block_until_ready":
            return "block_until_ready (device sync)"
        if d.startswith("subprocess."):
            return f"{d} (child process)"
        if d == "time.sleep":
            return "time.sleep"
        if d in ("np.save", "np.savez", "np.savez_compressed",
                 "numpy.save", "numpy.savez"):
            if call.args and _DURABLE_RE.search(
                    " ".join(_expr_idents(call.args[0])).lower()):
                return f"{d} (durable file IO)"
            return None
        if tail in ("open",) and d in ("open", "gzip.open") and call.args:
            mode = None
            if len(call.args) >= 2 and isinstance(call.args[1],
                                                  ast.Constant):
                mode = call.args[1].value
            for kw in call.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = kw.value.value
            if isinstance(mode, str) and mode[0] in "wa":
                ev = " ".join(_expr_idents(call.args[0])).lower()
                if _DURABLE_RE.search(ev):
                    return "durable file IO (write)"
        return None

    def _visit_assign(self, node, qual, cls, func, held):
        value = node.value
        targets = _flat_targets(
            node.targets if isinstance(node, ast.Assign)
            else [node.target] if node.target is not None else [])
        # lock construction sites
        ctor = (self._lock_ctor(value)
                if isinstance(value, ast.Call) else None)
        if ctor is not None:
            kind, name = ctor
            for t in targets:
                if isinstance(t, ast.Name) and func is None:
                    self.locks.append(LockSite(
                        lock_id=f"{self.module}.{t.id}", name=name,
                        kind=kind, file=self.rel, line=node.lineno,
                        module=self.module))
                elif isinstance(t, ast.Name) and func is not None:
                    self.locks.append(LockSite(
                        lock_id=f"{self.module}.{func.qual}.{t.id}",
                        name=name, kind=kind, file=self.rel,
                        line=node.lineno, module=self.module))
                elif isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self" and cls is not None:
                    self.locks.append(LockSite(
                        lock_id=f"{self.module}.{cls}.{t.attr}",
                        name=name, kind=kind, file=self.rel,
                        line=node.lineno, module=self.module, cls=cls,
                        attr=t.attr))
            return
        # shared-state writes (only inside functions)
        if func is None:
            return
        if value is None:
            return  # annotation-only `self.x: T` — no store happens
        # `x += 1` is a read-modify-write — NEVER a GIL-atomic const
        # store, even though AugAssign.value is the Constant operand
        const_only = isinstance(value, ast.Constant) and \
            not isinstance(node, ast.AugAssign)
        for t in targets:
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name):
                if t.value.id == "self" and cls is not None:
                    if func.name not in ("__init__", "__new__"):
                        func.writes.append(
                            ((("attr", self.module, cls, t.attr),
                              const_only), node.lineno, held))
                    self.class_attrs.setdefault(cls, set()).add(t.attr)
                elif t.value.id != "self":
                    func.writes.append(
                        ((("xattr", t.attr), const_only),
                         node.lineno, held))
        # module-global rebinds: recorded as maybe-global; the linker
        # keeps only names the function actually declares `global`
        names = [t.id for t in targets if isinstance(t, ast.Name)]
        if names:
            func.writes.append(((("maybe-global", tuple(names)),
                                 const_only), node.lineno, held))


class _Linker:
    """Phase 2: resolve descriptors against the full scan set, build
    the transitive lock graph, and run the four rules."""

    def __init__(self, scans: list[_ModuleScan]):
        self.scans = scans
        self.by_module = {s.module: s for s in scans}
        self.funcs: dict[str, _Func] = {}
        self.method_index: dict[str, list[_Func]] = {}
        self.lock_sites: dict[str, LockSite] = {}
        self.lock_attr_index: dict[str, list[LockSite]] = {}
        self.global_decls: dict[str, set] = {}  # func key -> names
        for s in scans:
            for f in s.funcs.values():
                self.funcs[f.key] = f
                self.method_index.setdefault(f.name, []).append(f)
            for site in s.locks:
                self.lock_sites[site.lock_id] = site
                if site.attr:
                    self.lock_attr_index.setdefault(site.attr,
                                                    []).append(site)
        self._collect_global_decls()
        self._acq_memo: dict[str, dict] = {}
        self._blk_memo: dict[str, dict] = {}

    def _collect_global_decls(self):
        for s in self.scans:
            for node in ast.walk(s.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    names = set()
                    for n in ast.walk(node):
                        if isinstance(n, ast.Global):
                            names.update(n.names)
                    if names:
                        for f in s.funcs.values():
                            if f.line == node.lineno and \
                                    f.name == node.name:
                                self.global_decls[f.key] = names

    # -- descriptor resolution ----------------------------------------
    def resolve_lock(self, desc, f: _Func) -> str | None:
        """('lockref', dotted) → lock_id, or a synthetic node for a
        lock-looking name we can't place, or None (not a lock)."""
        _, d = desc
        s = self.by_module[f.module]
        head, _, rest = d.partition(".")
        if head == "self" and rest and f.cls is not None:
            attr = rest.split(".")[0]
            lid = f"{f.module}.{f.cls}.{attr}"
            if lid in self.lock_sites:
                return lid
            # an attr assigned in ANOTHER class of this module (mixin)
            for cls in s.classes:
                lid = f"{f.module}.{cls}.{attr}"
                if lid in self.lock_sites:
                    return lid
        if "." not in d:
            # local lock, then module global, then from-import
            lid = f"{f.module}.{f.qual}.{d}"
            if lid in self.lock_sites:
                return lid
            # enclosing function scopes (nested defs)
            parts = f.qual.split(".")
            for i in range(len(parts) - 1, 0, -1):
                lid = f"{f.module}.{'.'.join(parts[:i])}.{d}"
                if lid in self.lock_sites:
                    return lid
            lid = f"{f.module}.{d}"
            if lid in self.lock_sites:
                return lid
            if d in s.from_imports:
                mod, orig = s.from_imports[d]
                lid = f"{mod}.{orig}"
                if lid in self.lock_sites:
                    return lid
        else:
            if head in s.imports:
                lid = f"{s.imports[head]}.{rest}"
                if lid in self.lock_sites:
                    return lid
            # foreign instance attr (hb._iflock): unique attr wins
            attr = d.rsplit(".", 1)[-1]
            cands = self.lock_attr_index.get(attr, [])
            if len(cands) == 1:
                return cands[0].lock_id
        if "lock" in d.lower():
            # lock-looking but unplaceable: synthesize a per-module
            # node so held-across-blocking still sees it
            return f"?{f.module}.{d}"
        return None

    def resolve_call(self, desc, f: _Func) -> list[_Func]:
        _, d = desc
        s = self.by_module[f.module]
        head, _, rest = d.partition(".")
        tail = d.rsplit(".", 1)[-1]
        if tail == "check_guarded":
            # the sanitizer's assertion probe is NOT a call edge: its
            # breadcrumb path is muted from runtime edge-noting, and
            # its finding path only runs on a MISS — when the checked
            # lock is provably not held. Modeling it would manufacture
            # a by-construction-false order edge out of every probe
            # placed under the very lock it checks.
            return []
        if "." not in d:
            # nested sibling / enclosing scope
            parts = f.qual.split(".")
            for i in range(len(parts), -1, -1):
                q = ".".join(parts[:i] + [d]) if i else d
                g = s.funcs.get(q)
                if g is not None:
                    return [g]
            # classmethod-free constructor: C() runs C.__init__
            if d in s.classes:
                q = s.classes[d].get("__init__")
                if q:
                    return [s.funcs[q]]
            if d in s.from_imports:
                mod, orig = s.from_imports[d]
                ms = self.by_module.get(mod)
                if ms is not None and orig in ms.funcs:
                    return [ms.funcs[orig]]
            return []
        if head == "self" and f.cls is not None:
            meth = rest.split(".")[0]
            q = s.classes.get(f.cls, {}).get(meth)
            if q:
                return [s.funcs[q]]
            for cls, methods in s.classes.items():
                if meth in methods:
                    return [s.funcs[methods[meth]]]
        if head in s.imports:
            ms = self.by_module.get(s.imports[head])
            if ms is not None:
                q = rest.split(".")[0]
                if q in ms.funcs:
                    return [ms.funcs[q]]
        if head in s.from_imports:
            # from x import y; y.attr() — y may be a module or a class
            mod, orig = s.from_imports[head]
            ms = self.by_module.get(f"{mod}.{orig}") or \
                self.by_module.get(mod)
            if ms is not None:
                meth = rest.split(".")[0]
                if meth in ms.funcs:
                    return [ms.funcs[meth]]
        # name-based method resolution (may-analysis)
        if tail in _SKIP_METHODS or tail.startswith("__"):
            return []
        cands = self.method_index.get(tail, [])
        if 1 <= len(cands) <= _METHOD_CANDIDATE_CAP:
            return [g for g in cands if g.key != f.key]
        return []

    def resolve_held(self, held, f: _Func) -> tuple:
        out = []
        for desc in held:
            lid = self.resolve_lock(desc, f)
            if lid is not None:
                out.append(lid)
        return tuple(out)

    # -- transitive closures ------------------------------------------
    def acquires_of(self, f: _Func, _stack=None) -> dict:
        """lock_id -> witness (file, line, qual) acquired in f or any
        callee (cycle-tolerant DFS with memo). Only ROOT results are
        memoized: a closure computed while an ancestor is on the DFS
        stack is truncated by the cycle back-edge, and caching it
        would make findings depend on definition order."""
        if f.key in self._acq_memo:
            return self._acq_memo[f.key]
        is_root = not _stack
        _stack = _stack or set()
        if f.key in _stack:
            return {}
        _stack.add(f.key)
        out: dict = {}
        for desc, line, _held in f.acquires:
            lid = self.resolve_lock(desc, f)
            if lid is not None and lid not in out:
                out[lid] = (f.file, line, f.qual)
        for desc, line, _held in f.calls:
            for g in self.resolve_call(desc, f):
                for lid, w in self.acquires_of(g, _stack).items():
                    out.setdefault(lid, w)
        _stack.discard(f.key)
        if is_root:
            self._acq_memo[f.key] = out
        return out

    def blocking_of(self, f: _Func, _stack=None) -> dict:
        """what -> witness for blocking ops in f or any callee (memo
        on ROOT results only — see acquires_of)."""
        if f.key in self._blk_memo:
            return self._blk_memo[f.key]
        is_root = not _stack
        _stack = _stack or set()
        if f.key in _stack:
            return {}
        _stack.add(f.key)
        out: dict = {}
        for what, line, _held in f.blocking:
            out.setdefault(what, (f.file, line, f.qual))
        for desc, line, _held in f.calls:
            for g in self.resolve_call(desc, f):
                for what, w in self.blocking_of(g, _stack).items():
                    out.setdefault(what, w)
        _stack.discard(f.key)
        if is_root:
            self._blk_memo[f.key] = out
        return out

    # -- the lock graph -----------------------------------------------
    def _note_self_nest(self, lid: str, witness: dict):
        """h == lid nesting: for a non-reentrant lock this is a
        guaranteed self-deadlock (same instance) or an equal-rank
        violation (sibling instances of one per-instance class — equal
        ranks never nest, CONCURRENCY.md). RLocks/conditions are
        reentrant: legit."""
        site = self.lock_sites.get(lid)
        if site is not None and site.kind == "lock":
            self.self_nests.setdefault(lid, []).append(witness)

    def build_edges(self) -> dict:
        """(A, B) -> witness: B acquired (directly or transitively)
        while A held. Same-lock (h == lid) nesting is kept OUT of the
        edge set (a self-loop is not an order cycle) and recorded in
        ``self.self_nests`` instead."""
        edges: dict = {}
        self.self_nests: dict[str, list] = {}
        for f in self.funcs.values():
            for desc, line, held in f.acquires:
                lid = self.resolve_lock(desc, f)
                if lid is None:
                    continue
                for h in self.resolve_held(held, f):
                    if h != lid:
                        edges.setdefault((h, lid),
                                         {"file": f.file, "line": line,
                                          "func": f.qual, "via": None})
                    else:
                        self._note_self_nest(
                            lid, {"file": f.file, "line": line,
                                  "func": f.qual, "via": None})
            for desc, line, held in f.calls:
                hids = self.resolve_held(held, f)
                if not hids:
                    continue
                for g in self.resolve_call(desc, f):
                    for lid, w in self.acquires_of(g).items():
                        for h in hids:
                            if h != lid:
                                edges.setdefault(
                                    (h, lid),
                                    {"file": f.file, "line": line,
                                     "func": f.qual,
                                     "via": f"{g.qual} at {w[0]}:{w[1]}"})
                            else:
                                self._note_self_nest(
                                    lid,
                                    {"file": f.file, "line": line,
                                     "func": f.qual,
                                     "via": f"{g.qual} at {w[0]}:{w[1]}"})
        return edges

    # -- reachability sets --------------------------------------------
    def _closure(self, roots: list[_Func]) -> set:
        seen = set()
        stack = list(roots)
        while stack:
            f = stack.pop()
            if f.key in seen:
                continue
            seen.add(f.key)
            for desc, _line, _held in f.calls:
                stack.extend(self.resolve_call(desc, f))
        return seen


def _scc(edges: dict) -> list[list[str]]:
    """Tarjan over the lock graph; returns SCCs of size >= 2."""
    succ: dict[str, list] = {}
    nodes: set = set()
    for (a, b) in edges:
        succ.setdefault(a, []).append(b)
        nodes.add(a)
        nodes.add(b)
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on: set = set()
    stack: list = []
    out: list[list[str]] = []
    counter = [0]

    def strong(v):
        work = [(v, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on.add(node)
            advanced = False
            for i in range(pi, len(succ.get(node, []))):
                w = succ[node][i]
                if w not in index:
                    work[-1] = (node, i + 1)
                    work.append((w, 0))
                    advanced = True
                    break
                if w in on:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) >= 2:
                    out.append(sorted(comp))
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])

    for n in sorted(nodes):
        if n not in index:
            strong(n)
    return out


class _Emitter:
    """Suppression-aware finding sink over MANY files (an
    interprocedural finding may be silenced at any witness site)."""

    def __init__(self, suppressions: dict, rule_filter):
        # suppressions: file -> {line: [(rules, reason)]}
        self.suppressions = suppressions
        self.rule_filter = rule_filter
        self.findings: list[Finding] = []

    def emit(self, rule: str, message: str, sites: list):
        """``sites`` is [(file, line)], primary first."""
        if self.rule_filter is not None and rule not in self.rule_filter:
            return
        for file, line in sites:
            for sup in self.suppressions.get(file, {}).get(line, []):
                if rule in sup.rules:
                    sup.used.add(rule)
                    if not sup.reason:
                        self.findings.append(Finding(
                            file, line, 0, rule,
                            f"suppression for [{rule}] is missing its "
                            f"required reason",
                            "write the why after the bracket: "
                            "# tpudl: ignore[rule] — <reason>"))
                    return
        file, line = sites[0]
        self.findings.append(Finding(file, line, 0, rule, message,
                                     _HINTS.get(rule, "")))


def _short(lock_id: str) -> str:
    site_name = lock_id.lstrip("?")
    return site_name


def _package_module(path: str) -> str:
    """Dotted module name derived from the FILE, walking up while
    __init__.py exists — correct no matter what cwd or path shape the
    caller used (a cwd-relative fallback would silently break every
    cross-module resolution and report a false clean)."""
    name = os.path.splitext(os.path.basename(path))[0]
    parts = [] if name == "__init__" else [name]
    d = os.path.dirname(os.path.abspath(path))
    while os.path.exists(os.path.join(d, "__init__.py")):
        parts.insert(0, os.path.basename(d))
        d = os.path.dirname(d)
    return ".".join(parts) or name


def read_sources(paths, root: str = ".") -> tuple[dict, dict, list]:
    """Read every python file under ``paths`` ONCE: returns
    ``(sources, modules, errors)`` where sources maps relpath → text,
    and modules carries package-derived dotted names for any path that
    escapes ``root`` (cwd-independence). Shared by both checker halves
    so the gate reads the tree a single time."""
    sources: dict = {}
    modules: dict = {}
    errors: list[str] = []
    for path in iter_python_files(paths):
        rel = os.path.relpath(path, root)
        try:
            with open(path, encoding="utf-8") as f:
                sources[rel] = f.read()
        except (OSError, UnicodeDecodeError) as e:
            errors.append(f"{path}: {e}")
            continue
        # always package-derived: canonical whether the caller scanned
        # from the repo root, a subdir, or with absolute paths
        modules[rel] = _package_module(path)
    return sources, modules, errors


def _link(sources: dict, modules: dict | None = None
          ) -> tuple[_Linker, dict, list]:
    """sources: relpath -> src (modules: optional relpath -> dotted
    module override). Returns (linker, suppressions, parse_errors)."""
    scans = []
    suppressions: dict = {}
    errors: list[str] = []
    modules = modules or {}
    for rel, src in sorted(sources.items()):
        try:
            scans.append(_ModuleScan(src, rel, module=modules.get(rel)))
        except SyntaxError as e:
            errors.append(f"{rel}: {e}")
            continue
        fc = _FileChecker(src, rel, rel)
        fc._scan_comments()
        suppressions[rel.replace(os.sep, "/")] = fc.suppressions
    return _Linker(scans), suppressions, errors


def _run_rules(linker: _Linker, emitter: _Emitter):
    edges = linker.build_edges()

    # -- lock-order ----------------------------------------------------
    # same-lock nesting first: a non-reentrant lock acquired while
    # itself held — self-deadlock (same instance) or equal-rank
    # nesting (sibling instances), either way a contract violation
    for lid, ws in sorted(linker.self_nests.items()):
        ws = sorted(ws, key=lambda w: (w["file"], w["line"]))
        w = ws[0]
        via = f" via {w['via']}" if w.get("via") else ""
        emitter.emit(
            "lock-order",
            f"same-lock nested acquisition: non-reentrant "
            f"{_short(lid)} acquired while already held in "
            f"{w['func']}{via} — same instance self-deadlocks, "
            f"sibling instances are rank-equal (equal ranks never "
            f"nest)",
            [(x["file"], x["line"]) for x in ws])

    for comp in _scc(edges):
        comp_set = set(comp)
        witnesses = sorted(
            ((a, b, w) for (a, b), w in edges.items()
             if a in comp_set and b in comp_set),
            key=lambda t: (t[2]["file"], t[2]["line"]))
        cycle = " -> ".join(_short(c) for c in comp) \
            + f" -> {_short(comp[0])}"
        ws = "; ".join(f"{_short(a)}->{_short(b)} at "
                       f"{w['file']}:{w['line']}"
                       for a, b, w in witnesses[:4])
        emitter.emit(
            "lock-order",
            f"lock-order cycle (ABBA deadlock risk): {cycle} "
            f"[witnesses: {ws}]",
            [(w["file"], w["line"]) for _a, _b, w in witnesses])

    # -- lock-held-blocking -------------------------------------------
    for f in linker.funcs.values():
        for what, line, held in f.blocking:
            hids = linker.resolve_held(held, f)
            if hids:
                emitter.emit(
                    "lock-held-blocking",
                    f"{_short(hids[0])} held across {what} in "
                    f"{f.qual}",
                    [(f.file, line), (f.file, f.line)])
        for desc, line, held in f.calls:
            hids = linker.resolve_held(held, f)
            if not hids:
                continue
            for g in linker.resolve_call(desc, f):
                blocks = linker.blocking_of(g)
                if not blocks:
                    continue
                what, w = next(iter(sorted(blocks.items())))
                emitter.emit(
                    "lock-held-blocking",
                    f"{_short(hids[0])} held across call to "
                    f"{g.qual}, which reaches {what} at "
                    f"{w[0]}:{w[1]}",
                    [(f.file, line), (g.file, g.line),
                     (w[0], w[1])])
                break  # one finding per call site

    # -- signal-lock ---------------------------------------------------
    for s in linker.scans:
        for desc, reg_line, qual in s.signal_handlers:
            # resolve the handler in the registering function's scope
            ctx = s.funcs.get(qual) or _Func(
                key=f"{s.module}:<module>", module=s.module,
                qual="<module>", cls=None, file=s.rel, line=reg_line,
                name="<module>")
            if ctx.module not in linker.by_module:
                continue
            handlers = linker.resolve_call(desc, ctx)
            for h in handlers:
                acq = linker.acquires_of(h)
                for lid, w in sorted(acq.items()):
                    emitter.emit(
                        "signal-lock",
                        f"signal handler {h.qual!r} can reach a lock "
                        f"acquisition of {_short(lid)} at "
                        f"{w[0]}:{w[1]} — an interrupted frame may "
                        f"already hold it",
                        [(h.file, h.line), (s.rel, reg_line)])

    # -- daemon-shared-write ------------------------------------------
    entries: list[_Func] = []
    for s in linker.scans:
        for desc, _line, qual in s.spawns:
            ctx = s.funcs.get(qual) or _Func(
                key=f"{s.module}:<module>", module=s.module,
                qual="<module>", cls=None, file=s.rel, line=_line,
                name="<module>")
            entries.extend(linker.resolve_call(desc, ctx))
    bg = linker._closure(entries)
    writes: dict = {}  # loc -> {"bg": [...], "fg": [...]}
    for f in linker.funcs.values():
        if f.name.endswith("_locked"):
            continue  # the caller-holds-the-lock naming contract
        side = "bg" if f.key in bg else "fg"
        for (loc, const_only), line, held in f.writes:
            if const_only:
                continue  # GIL-atomic flag stores are the house idiom
            if loc[0] == "maybe-global":
                decls = linker.global_decls.get(f.key, set())
                names = [n for n in loc[1] if n in decls]
                # one record PER name: `_A, _B = ...` writes both just
                # as racily as the single-name form
                for n in names:
                    writes.setdefault(("global", f.module, n),
                                      {"bg": [], "fg": []})[side].append(
                        (f, line, linker.resolve_held(held, f)))
                continue
            elif loc[0] == "xattr":
                cands = [
                    (s.module, cls)
                    for s in linker.scans
                    for cls, attrs in s.class_attrs.items()
                    if loc[1] in attrs]
                if len(cands) != 1:
                    continue
                key = ("attr", cands[0][0], cands[0][1], loc[1])
            else:
                key = loc
            writes.setdefault(key, {"bg": [], "fg": []})[side].append(
                (f, line, linker.resolve_held(held, f)))
    for key, sides in sorted(writes.items(), key=lambda kv: str(kv[0])):
        if not sides["bg"] or not sides["fg"]:
            continue
        all_sites = sides["bg"] + sides["fg"]
        common = set(all_sites[0][2])
        for _f, _line, held in all_sites[1:]:
            common &= set(held)
        if common:
            continue
        loc_name = ".".join(str(p) for p in key[1:])
        bg_f, bg_line, _ = sides["bg"][0]
        fg_f, fg_line, _ = sides["fg"][0]
        emitter.emit(
            "daemon-shared-write",
            f"{loc_name} is written from thread-reachable "
            f"{bg_f.qual} ({bg_f.file}:{bg_line}) and foreground "
            f"{fg_f.qual} ({fg_f.file}:{fg_line}) with no common "
            f"lock",
            [(f.file, line) for f, line, _h in all_sites])


# -- public API --------------------------------------------------------

def link_sources(sources: dict, modules: dict | None = None
                 ) -> tuple["_Linker", dict, list]:
    """Parse + comment-scan the tree ONCE: ``(linker, suppressions,
    parse_errors)``. The CLI builds this once and hands it to both
    interprocedural halves (concurrency + traceguard) so the gate
    never re-parses per half; shared Suppression objects also merge
    usage marks for free."""
    return _link(sources, modules)


def analyze_sources(sources: dict, rules=None,
                    modules: dict | None = None,
                    supp_sink: dict | None = None,
                    linked=None) -> list[Finding]:
    """Run the concurrency rules over in-memory sources
    (``{relpath: src}``) — the fixture entry point (and, via
    ``modules``, the shared-source path the CLI uses). ``supp_sink``
    receives this pass's suppression records with usage marks (the
    stale-suppression audit merges them across halves); ``linked``
    (from :func:`link_sources`) skips the re-parse."""
    linker, suppressions, errors = (linked if linked is not None
                                    else _link(sources, modules))
    emitter = _Emitter(suppressions,
                       set(rules) if rules is not None else None)
    _run_rules(linker, emitter)
    if supp_sink is not None:
        supp_sink.update(suppressions)
    emitter.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return emitter.findings


def analyze(paths, root: str = ".", rules=None
            ) -> tuple[list[Finding], list[str]]:
    """Run the concurrency rules over files/dirs. Returns
    (findings, errors); unreadable/unparseable files are errors, same
    contract as ``check_paths``."""
    sources, modules, errors = read_sources(paths, root=root)
    linker, suppressions, parse_errors = _link(sources, modules)
    errors.extend(parse_errors)
    emitter = _Emitter(suppressions,
                       set(rules) if rules is not None else None)
    _run_rules(linker, emitter)
    emitter.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return emitter.findings, errors


def build_lock_graph(paths=None, root: str = ".",
                     sources: dict | None = None) -> LockGraph:
    """The lock graph itself (no findings): every construction site,
    the acquired-under edges, and the function table — what the
    coverage round-trip test audits against the registry
    (:mod:`tpudl.analysis.locks`)."""
    modules = None
    if sources is None:
        sources, modules, _errors = read_sources(paths or [], root=root)
    linker, _supp, _errors = _link(sources, modules)
    return LockGraph(locks=list(linker.lock_sites.values()),
                     edges=linker.build_edges(),
                     functions=linker.funcs)


def registry_coverage(paths, root: str = ".") -> dict:
    """Declared-vs-constructed delta for the lock registry (the
    CONCURRENCY.md round-trip; mirrors the knob/metric audits):
    ``named`` = names seen at named_lock sites, ``anonymous`` = raw
    threading.* construction sites (allowed only in the sanitizer's
    own internals), plus the two drift directions."""
    graph = build_lock_graph(paths, root=root)
    named = {s.name for s in graph.locks if s.name}
    decls = set(_locks.LOCK_NAMES)
    return {
        "named": named,
        "anonymous": [f"{s.file}:{s.line}" for s in graph.locks
                      if s.name is None],
        "undeclared": sorted(named - decls),
        "unconstructed": sorted(decls - named),
    }
