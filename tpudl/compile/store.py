"""The AOT program store: serialized XLA executables keyed by program
signature, so a fresh process dispatches its first batch through an
ALREADY-COMPILED program — zero trace, zero compile, zero cold start.

Three layers (COMPILE.md):

1. an **in-process program table** ``key → jax.stages.Compiled`` — the
   executor consults it at dispatch (``compile.hits`` / ``.misses``);
2. a persisted, checksummed **program manifest**
   (``programs-manifest.json``, atomic tmp+``os.replace`` like every
   durable manifest in this codebase): one entry per observed program
   signature — fn fingerprint + arg shapes/dtypes/shardings + donate +
   mesh topology + backend — each entry carrying a self-crc and, when
   the program is *portable*, the name+crc of a serialized-executable
   file beside it;
3. **serialized executables** (``prog-<key>.bin``:
   ``jax.experimental.serialize_executable`` payload + arg/out
   treedefs, pickled, crc-checked): a fresh process
   :meth:`ProgramStore.ensure_restored`-s them straight into the table
   with NO live function at all — the true zero-cold-start path.

Identity & staleness: the fn fingerprint hashes the function's CODE
(bytecode + consts, recursively through wrapper chains) and its closure
CONTENTS — numpy closures (weights, codec scales) by bounded-sample
crc, so changed weights re-key. A closure holding a live ``jax.Array``
cannot be content-hashed without a device→host fetch (which the warm
path must never issue), so such programs are **non-portable**: their
signatures are still recorded (a relaunch re-lowers them from the live
fn — the trace cost — while the XLA compile rides the persistent
compilation cache), but no executable is serialized, so a stale-weights
program can never be restored. An explicit ``fn.aot_token`` (set it to
a content identity you own, e.g. a weights-artifact checksum) makes
any fn portable.

Misses compile in the background on a small pool (2 threads): the run
that OBSERVES a novel signature pays nothing extra on its hot path; the
NEXT process restores the result. Everything is fail-safe: a corrupt
manifest quarantines and starts empty, a corrupt or backend-mismatched
executable is skipped, a Compiled that refuses its args falls back to
the jitted path — the store can degrade to exactly today's behavior but
never take a run down.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
import sys
import time
import weakref
import zlib
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from tpudl.testing import faults as _faults
from tpudl.testing import tsan as _tsan

__all__ = ["ProgramStore", "get_program_store", "reset_program_store",
           "aot_enabled", "store_dir", "warm_start", "fn_fingerprint",
           "backend_token", "MANIFEST_NAME", "MANIFEST_SCHEMA",
           "MANIFEST_VERSION", "EXE_PREFIX"]

MANIFEST_NAME = "programs-manifest.json"
MANIFEST_SCHEMA = "tpudl-programs"
MANIFEST_VERSION = 1
EXE_PREFIX = "prog-"

_TRUTHY = ("1", "on", "true", "yes")


def aot_enabled(value=None) -> bool:
    """Is the AOT program store armed? An explicit kwarg wins; else
    ``TPUDL_COMPILE_AOT`` — unset/``0``/``off`` = off, ``1`` (or a
    store-directory path) = on."""
    if value is not None:
        return bool(value)
    env = os.environ.get("TPUDL_COMPILE_AOT", "").strip()
    return env != "" and env.lower() not in ("0", "off", "false", "none")


def store_dir() -> str:
    """The program store directory: a path-valued ``TPUDL_COMPILE_AOT``
    names it directly; otherwise ``<compilation cache dir>/programs``
    (the two caches travel together — one operator knob to relocate
    both)."""
    env = os.environ.get("TPUDL_COMPILE_AOT", "").strip()
    if env and env.lower() not in _TRUTHY \
            and env.lower() not in ("0", "off", "false", "none"):
        return os.path.expanduser(env)
    from tpudl.compile.cache import DEFAULT_CACHE_DIR

    base = os.environ.get("TPUDL_COMPILE_CACHE_DIR")
    if not base or base == "0":
        base = DEFAULT_CACHE_DIR
    return os.path.join(os.path.expanduser(base), "programs")


def backend_token() -> dict:
    """The backend identity a serialized executable is valid for —
    platform + device kind + device count + jax version (a deserialized
    binary is an exact artifact of all four)."""
    import jax

    devs = jax.devices()
    return {"platform": devs[0].platform,
            "device_kind": devs[0].device_kind,
            "n_devices": len(devs),
            "jax": jax.__version__}


# -- fn fingerprinting -------------------------------------------------------

_FP_LOCK = _tsan.named_lock("compile.fingerprint_memo")
_FP_MEMO: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()

_SAMPLE = 1 << 16  # closure-array crc sample bytes (head + tail)


def _hash_array(h, arr: np.ndarray) -> None:
    h.update(f"&nd{arr.dtype}{arr.shape}".encode())
    flat = arr.reshape(-1) if arr.flags["C_CONTIGUOUS"] \
        else np.ascontiguousarray(arr).reshape(-1)
    head = flat[: _SAMPLE // max(1, arr.itemsize)]
    tail = flat[-(_SAMPLE // max(1, arr.itemsize)):]
    h.update(zlib.crc32(head.tobytes()).to_bytes(4, "little"))
    h.update(zlib.crc32(tail.tobytes()).to_bytes(4, "little"))


def _hash_obj(h, obj, depth: int, seen: set, state: dict) -> None:
    """One closure/const value into the running fingerprint. Bounded
    depth + identity-set so cyclic wrapper graphs terminate. A live
    ``jax.Array`` marks the fingerprint NON-portable (its content
    cannot be hashed without a device fetch)."""
    if depth > 5 or id(obj) in seen:
        h.update(b"&deep")
        return
    seen.add(id(obj))
    tok = getattr(obj, "aot_token", None)
    if tok is not None and not callable(tok):
        h.update(f"&tok{tok}".encode())
        return
    if obj is None or isinstance(obj, (bool, int, float, complex, str,
                                       bytes)):
        h.update(f"&c{obj!r}".encode())
        return
    import types

    if isinstance(obj, types.ModuleType):
        # function-local imports land in closures: a module's identity
        # is its name — walking its namespace would hash half of jax
        # (and per-process object addresses with it)
        h.update(f"&mod{obj.__name__}".encode())
        return
    if isinstance(obj, type):
        h.update(f"&cls{obj.__module__}.{obj.__qualname__}".encode())
        return
    jax = sys.modules.get("jax")
    if jax is not None and isinstance(obj, jax.Array):
        # shape/dtype only — content-blind, so entries over this fn are
        # never serialized (stale weights could otherwise restore)
        h.update(f"&jax{obj.dtype}{obj.shape}".encode())
        state["portable"] = False
        return
    if isinstance(obj, np.ndarray):
        _hash_array(h, obj)
        return
    if jax is not None and isinstance(obj, jax.sharding.Mesh):
        # a Mesh in a closure (the tp generate path closes over it) is
        # topology, not content: hash axis names + grid shape. The
        # generic walk below would reach the `devices` object ndarray
        # and hash per-process POINTERS — a fingerprint that never
        # matches across runs
        h.update(f"&mesh{dict(obj.shape)!r}".encode())
        return
    code = getattr(obj, "__code__", None)
    if code is not None:
        h.update(f"&fn{getattr(obj, '__qualname__', '?')}".encode())
        h.update(hashlib.sha1(code.co_code).digest())
        for const in code.co_consts:
            if hasattr(const, "co_code"):
                h.update(hashlib.sha1(const.co_code).digest())
            else:
                h.update(f"&k{const!r}".encode())
        for cell in (obj.__closure__ or ()):
            _hash_obj(h, cell.cell_contents, depth + 1, seen, state)
        for d in (obj.__defaults__ or ()):
            _hash_obj(h, d, depth + 1, seen, state)
        # a BOUND METHOD's state lives on __self__, not in cells: two
        # models of one class with different weights baked into self
        # must re-key (module GLOBALS remain out of scope — set
        # fn.aot_token for global-state programs, COMPILE.md)
        owner = getattr(obj, "__self__", None)
        if owner is not None:
            _hash_obj(h, owner, depth + 1, seen, state)
        return
    if isinstance(obj, (tuple, list)):
        h.update(f"&seq{len(obj)}".encode())
        for v in obj[:32]:
            _hash_obj(h, v, depth + 1, seen, state)
        return
    if isinstance(obj, dict):
        h.update(f"&map{len(obj)}".encode())
        for k in sorted(obj, key=repr)[:32]:
            h.update(f"&k{k!r}".encode())
            _hash_obj(h, obj[k], depth + 1, seen, state)
        return
    inner = getattr(obj, "__wrapped__", None) or getattr(obj, "func",
                                                         None)
    if inner is not None and inner is not obj:
        # a jit/partial/shim wrapper: identity lives in what it wraps.
        # args/keywords only when they are REAL bound values (a class
        # or slotted object answers getattr with a descriptor)
        _hash_obj(h, inner, depth + 1, seen, state)
        args = getattr(obj, "args", None)
        if isinstance(args, (tuple, list)):
            for a in args:
                _hash_obj(h, a, depth + 1, seen, state)
        kw = getattr(obj, "keywords", None)
        if isinstance(kw, dict):
            for k, v in sorted(kw.items()):
                h.update(f"&k{k}".encode())
                _hash_obj(h, v, depth + 1, seen, state)
        return
    t = type(obj)
    h.update(f"&o{t.__module__}.{t.__qualname__}".encode())
    attrs = getattr(obj, "__dict__", None)
    if isinstance(attrs, dict) and attrs:
        # content-walk instance state (bounded): covers weights held as
        # attributes (a bound method's model), and avoids the default
        # repr's per-process memory address, which would make the key
        # never match across runs
        for k in sorted(attrs)[:32]:
            h.update(f"&k{k}".encode())
            _hash_obj(h, attrs[k], depth + 1, seen, state)
    else:
        # leaf object: repr, with memory addresses stripped (a lock or
        # opaque handle must degrade to type identity, not a value that
        # re-keys every process)
        h.update(re.sub(r"0x[0-9a-fA-F]+", "0x",
                        repr(obj)[:256]).encode())


def fn_fingerprint(fn) -> tuple[str | None, bool]:
    """``(sha1-hex, portable)`` identity of a program's function —
    stable ACROSS processes for the same source + same closure
    contents. ``None`` when no identity is derivable (the store then
    stands aside for this fn). Memoized per live fn object (the warm
    dispatch path calls this per batch)."""
    try:
        with _FP_LOCK:
            cached = _FP_MEMO.get(fn)
    except TypeError:
        cached = None
    if cached is not None:
        return cached
    tok = getattr(fn, "aot_token", None)
    if tok is not None and not callable(tok):
        out: tuple[str | None, bool] = (
            hashlib.sha1(f"token:{tok}".encode()).hexdigest(), True)
    else:
        h = hashlib.sha1()
        state = {"portable": True}
        _hash_obj(h, fn, 0, set(), state)
        digest = h.hexdigest()
        # a fingerprint that saw no code object anywhere is just a
        # type repr — too weak to key a compiled binary on
        found_code = hasattr(fn, "__code__") or \
            getattr(fn, "__wrapped__", None) is not None or \
            getattr(fn, "func", None) is not None
        out = (digest if found_code else None, state["portable"])
    try:
        with _FP_LOCK:
            _FP_MEMO[fn] = out
    except TypeError:
        pass
    return out


# -- program signatures ------------------------------------------------------

def _sharding_token(x) -> str:
    """Sharding identity of one leaf — shared by live arrays AND
    ``ShapeDtypeStruct`` avals so a warmup-declared signature keys
    identically to the dispatch-time one."""
    sh = getattr(x, "sharding", None)
    if sh is not None and hasattr(sh, "spec"):
        mesh = getattr(sh, "mesh", None)
        axes = dict(getattr(mesh, "shape", {}) or {})
        return f"P{tuple(sh.spec)}|{sorted(axes.items())}"
    # single-device jax arrays and host numpy share one token: a
    # host-lowered executable accepts either (the runtime places host
    # args), so a warmup-declared aval must key like the live array
    return "host"


def _mesh_axes_of_token(tok) -> dict | None:
    """Structured ``{axis: size}`` topology parsed back out of a leaf
    sharding token (``"P(...)|[('data', 4), ('model', 2)]"``) — what
    manifest audits (tools/validate_programs.py) compare, so 1-D and
    2-D entries can be told apart without re-parsing token strings."""
    if not tok or "|" not in tok:
        return None
    import ast

    try:
        pairs = ast.literal_eval(tok.split("|", 1)[1])
        return {str(k): int(v) for k, v in pairs}
    except (ValueError, SyntaxError, TypeError):
        return None


def signature_of(args) -> dict:
    """JSON-shippable signature of one positional-arg tuple (live
    arrays or avals): pytree structure + per-leaf (shape, dtype,
    sharding token)."""
    import jax

    leaves, treedef = jax.tree.flatten(tuple(args))
    return {"tree": str(treedef),
            "leaves": [[list(np.shape(x)),
                        str(getattr(x, "dtype", None)
                            if getattr(x, "dtype", None) is not None
                            else np.asarray(x).dtype),
                        _sharding_token(x)] for x in leaves]}


def _avals_of(args):
    """ShapeDtypeStructs (sharding-carrying for sharded leaves) for
    ``fn.lower(*avals)`` — built EAGERLY from live args so the
    background compile retains no batch data."""
    import jax

    def aval(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x  # warmup-declared aval (sharding preserved)
        if isinstance(x, jax.Array) and getattr(x, "sharding", None) \
                is not None and hasattr(x.sharding, "spec"):
            return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                        sharding=x.sharding)
        a = x if hasattr(x, "shape") and hasattr(x, "dtype") \
            else np.asarray(x)
        return jax.ShapeDtypeStruct(a.shape, a.dtype)

    return jax.tree.map(aval, tuple(args))


def _entry_crc(entry: dict) -> int:
    """Self-checksum over the entry's canonical JSON (sans the crc
    field itself) — the validator's torn-manifest tripwire."""
    body = {k: v for k, v in entry.items() if k != "crc"}
    return zlib.crc32(json.dumps(body, sort_keys=True,
                                 default=str).encode()) & 0xFFFFFFFF


def _crc32_file(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                break
            crc = zlib.crc32(buf, crc)
    return crc & 0xFFFFFFFF


def _metrics():
    """The obs metrics surface, or None in a minimal subprocess —
    every publication site is best-effort: a broken registry must not
    kill a compile that already succeeded."""
    try:
        from tpudl.obs import metrics as _m

        return _m
    except Exception:  # minimal subprocess without obs: None-checked
        return None


class ProgramStore:
    """One store directory: manifest + serialized executables + the
    live program table. Thread-safe (dispatch pool, prepare pool and
    the background compiler all touch it)."""

    def __init__(self, root: str):
        self.root = str(root)
        self._lock = _tsan.named_lock("compile.program_store")
        self._table: dict = {}          # key -> jax.stages.Compiled
        self._entries: dict = {}        # key -> manifest entry
        self._ladder_meta: dict | None = None
        self._pending: set = set()      # keys queued/compiling
        self._restore_state: str | None = None  # None|"pending"|"done"
        self._pool: ThreadPoolExecutor | None = None
        self._futures: list = []
        os.makedirs(self.root, exist_ok=True)
        self._load_manifest()
        self._sweep_stale_files()

    # -- manifest ----------------------------------------------------------
    def _manifest_path(self) -> str:
        return os.path.join(self.root, MANIFEST_NAME)

    def _load_manifest(self) -> None:
        path = self._manifest_path()
        try:
            with open(path) as f:
                m = json.load(f)
        except FileNotFoundError:
            return
        except (OSError, json.JSONDecodeError):
            m = None
        if not isinstance(m, dict) or m.get("schema") != MANIFEST_SCHEMA:
            # corrupt/foreign: quarantine beside (forensics) and start
            # empty — the store must never take a process down
            mm = _metrics()
            if mm is not None:
                mm.counter("compile.store_corrupt").inc()
            try:
                os.replace(path, path + ".corrupt")
            except OSError:
                pass
            return
        entries = m.get("entries")
        if isinstance(entries, dict):
            self._entries = {k: v for k, v in entries.items()
                             if isinstance(v, dict)}
        lad = m.get("ladder")
        if isinstance(lad, dict):
            self._ladder_meta = lad

    def _sweep_stale_files(self) -> None:
        """Unlink executables and tmp leftovers no manifest entry
        references — the artifact of a crash between a bin's publish
        and its manifest seal (the entry then still reads
        ``exe: null``). Age-guarded: a file younger than a minute may
        be another process's in-flight persist on a shared store."""
        try:
            now = time.time()
            referenced = {e.get("exe") for e in self._entries.values()
                          if e.get("exe")}
            for name in os.listdir(self.root):
                if not name.startswith(EXE_PREFIX) or name in referenced:
                    continue
                if not (name.endswith(".bin") or ".tmp." in name):
                    continue
                path = os.path.join(self.root, name)
                try:
                    if now - os.stat(path).st_mtime < 60:
                        continue
                    os.unlink(path)
                except OSError:
                    pass
        except OSError:  # unreadable dir: the store still works
            pass

    def _write_manifest_locked(self) -> None:
        m = {"schema": MANIFEST_SCHEMA, "version": MANIFEST_VERSION,
             "backend": self._backend_or_none(),
             "ladder": self._ladder_meta,
             "updated_ts": time.time(),
             "entries": self._entries}
        tmp = self._manifest_path() + f".tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(m, f)
            os.replace(tmp, self._manifest_path())
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    @staticmethod
    def _backend_or_none():
        try:
            return backend_token()
        except Exception:  # jax not initialized yet: manifest-only use
            return None

    def note_ladder(self, ladder) -> None:
        """Record the bucket ladder this store's signatures were
        observed under (validator: shapes↔ladder consistency)."""
        meta = ladder.to_meta() if ladder is not None else None
        with self._lock:
            if meta != self._ladder_meta:
                self._ladder_meta = meta
                self._write_manifest_locked()

    def entries(self) -> dict:
        with self._lock:
            return dict(self._entries)

    def programs(self) -> int:
        with self._lock:
            return len(self._table)

    # -- keys --------------------------------------------------------------
    def _key_for(self, fp: str, sig: dict, donate: bool) -> str:
        h = hashlib.sha1()
        h.update(fp.encode())
        h.update(json.dumps(sig, sort_keys=True).encode())
        h.update(b"donate" if donate else b"plain")
        try:
            h.update(json.dumps(backend_token(),
                                sort_keys=True).encode())
        except Exception:  # pre-backend probes: still a stable key
            h.update(b"nobackend")
        return h.hexdigest()

    # -- the dispatch-path hook -------------------------------------------
    def call(self, fn, args, *, donate: bool = False,
             bucketed: bool = False, report=None):
        """Run one dispatch THROUGH the store: a table hit executes the
        precompiled program (no trace possible). The FIRST miss of a
        signature AOT-compiles it inline — the same trace+compile the
        jitted path was about to pay, so the miss costs one compile,
        not two — inserts it into the table, and serializes+persists in
        the background; concurrent misses of the same key (and any
        store trouble) fall back to the jitted ``fn``, behavior-
        identical by construction."""
        fp, portable = fn_fingerprint(fn)
        if fp is None or not hasattr(fn, "lower"):
            return fn(*args)
        sig = signature_of(args)
        key = self._key_for(fp, sig, donate)
        with self._lock:
            exe = self._table.get(key)
        if exe is not None:
            try:
                out = exe(*args)
                mm = _metrics()
                if mm is not None:
                    mm.counter("compile.hits").inc()
                if report is not None:
                    report.count("aot_hits")
                return out
            except Exception:
                # arg/backend drift the key failed to capture: drop the
                # program, run the honest path, count the evidence
                mm = _metrics()
                if mm is not None:
                    mm.counter("compile.exec_failed").inc()
                with self._lock:
                    self._table.pop(key, None)
                if donate:
                    # a DONATING executable may have consumed its input
                    # buffers before failing — re-running fn on deleted
                    # args would bury the real fault under a
                    # buffer-deleted error; the original propagates to
                    # the supervisor's classifier instead
                    raise
        mm = _metrics()
        if mm is not None:
            mm.counter("compile.misses").inc()
        if report is not None:
            report.count("aot_misses")
        with self._lock:
            claimed = key not in self._pending
            if claimed:
                self._pending.add(key)
                if key not in self._entries:
                    self._entries[key] = self._new_entry(
                        sig, fn_fp=fp, donate=donate,
                        portable=portable, bucketed=bucketed)
                    self._seal_entry_locked(key)
                    self._write_manifest_locked()
                    observed = True
                else:
                    observed = False
        if not claimed:
            # another thread owns this key's compile: the plain jitted
            # path is the honest concurrent fallback
            return fn(*args)
        if observed:
            mm = _metrics()
            if mm is not None:
                mm.counter("compile.observed").inc()
        try:
            compiled = self._build(fn, key, _avals_of(args))
        except BaseException:
            with self._lock:
                self._pending.discard(key)
            mm = _metrics()
            if mm is not None:
                mm.counter("compile.store_corrupt").inc()
            return fn(*args)  # an exotic fn .lower refuses: jit path
        # persistence (serialize + write + manifest) rides the pool —
        # pending is released by the task; the dispatch returns as soon
        # as the program ran
        self._submit(self._persist_task, key, compiled, portable)
        return compiled(*args)

    def _new_entry(self, sig: dict, *, fn_fp: str, donate: bool,
                   portable: bool, bucketed: bool) -> dict:
        mesh_tok = None
        for leaf in sig["leaves"]:
            if leaf[2] not in ("host", "device"):
                mesh_tok = leaf[2]
                break
        return {"fn": fn_fp, "tree": sig["tree"],
                "leaves": sig["leaves"], "donate": bool(donate),
                "portable": bool(portable), "bucketed": bool(bucketed),
                "mesh": mesh_tok,
                "mesh_axes": _mesh_axes_of_token(mesh_tok),
                "backend": self._backend_or_none(),
                "created_ts": time.time(), "compile_s": None,
                "exe": None, "exe_crc32": None, "exe_nbytes": None}

    def _seal_entry_locked(self, key: str) -> None:
        entry = self._entries[key]
        entry["crc"] = _entry_crc(entry)

    def _submit(self, task, *a) -> None:
        with self._lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=2, thread_name_prefix="tpudl-aot")
            fut = self._pool.submit(task, *a)
            self._futures.append(fut)
            del self._futures[:-64]  # bounded: drained futures only

    def _build(self, fn, key, avals):
        """Lower+compile one signature from the live fn and insert it
        into the program table. The deterministic ``compile.precompile``
        fault point fires per program — a kill here must leave a valid
        manifest behind (the entry was already written atomically; its
        ``exe`` stays null until the persist completes)."""
        _faults.fire("compile.precompile", key=key[:12])
        t0 = time.perf_counter()
        compiled = fn.lower(*avals).compile()
        dt = time.perf_counter() - t0
        with self._lock:
            self._table[key] = compiled
            entry = self._entries.get(key)
            if entry is not None:
                entry["compile_s"] = round(dt, 4)
                self._seal_entry_locked(key)
        mm = _metrics()
        if mm is not None:
            mm.counter("compile.programs_compiled").inc()
            mm.counter("compile.aot_s").inc(dt)
            # attribution pairing with compile.aot_s (same guard: a
            # minimal subprocess without obs charges neither side): a
            # dispatch-path miss charges the dispatching scope (carried
            # onto the window thread); a background warm/restore build
            # runs scope-free and lands in unattributed — both
            # reconcile
            from tpudl.obs import attribution as _attr

            _attr.charge("compile_s", dt)
        return compiled

    def _persist_task(self, key, compiled, portable) -> None:
        try:
            self._persist_exe(key, compiled, portable)
        except Exception:
            # the background pool's backstop: the program already runs
            # from the table; only its durability was lost
            mm = _metrics()
            if mm is not None:
                mm.counter("compile.store_corrupt").inc()
        finally:
            with self._lock:
                self._pending.discard(key)

    def _persist_exe(self, key, compiled, portable) -> None:
        """Serialize one compiled program beside the manifest and seal
        its entry. Bin first, manifest second, both atomic: a crash
        between the two leaves a bin whose entry still reads
        ``exe: null`` — the validator recognizes that in-flight shape
        and the next store open sweeps it (never an integrity error,
        never a partial file)."""
        exe_name = exe_crc = exe_nbytes = None
        if portable:
            try:
                from jax.experimental import serialize_executable as se

                blob = pickle.dumps(se.serialize(compiled))
                import threading

                exe_name = f"{EXE_PREFIX}{key}.bin"
                tmp = os.path.join(
                    self.root, f"{exe_name}.tmp.{os.getpid()}."
                               f"{threading.get_ident()}")
                with open(tmp, "wb") as f:
                    f.write(blob)
                os.replace(tmp, os.path.join(self.root, exe_name))
                exe_crc = zlib.crc32(blob) & 0xFFFFFFFF
                exe_nbytes = len(blob)
            except Exception:  # unserializable backend: table-only
                exe_name = exe_crc = exe_nbytes = None
                mm = _metrics()
                if mm is not None:
                    mm.counter("compile.serialize_failed").inc()
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                entry["exe"] = exe_name
                entry["exe_crc32"] = exe_crc
                entry["exe_nbytes"] = exe_nbytes
                entry["backend"] = self._backend_or_none()
                self._seal_entry_locked(key)
                self._write_manifest_locked()

    # -- explicit (warmup-path) compilation --------------------------------
    def compile_signature(self, fn, args_or_avals, *,
                          donate: bool = False, bucketed: bool = False,
                          block: bool = True) -> bool:
        """AOT-compile ``fn`` at one declared signature — the warmup
        entry point (``ImageBatchWarmup``, ``TinyCausalLM``): no
        synthetic batch, no real-data trace, no device execution.
        Returns True when the program is (or already was) in the
        table."""
        fp, portable = fn_fingerprint(fn)
        if fp is None or not hasattr(fn, "lower"):
            return False
        sig = signature_of(args_or_avals)
        key = self._key_for(fp, sig, donate)
        for _attempt in range(2):
            with self._lock:
                if key in self._table:
                    return True
                claimed = key not in self._pending
                if claimed:
                    self._pending.add(key)
                    if key not in self._entries:
                        self._entries[key] = self._new_entry(
                            sig, fn_fp=fp, donate=donate,
                            portable=portable, bucketed=bucketed)
                        self._seal_entry_locked(key)
                        self._write_manifest_locked()
            if claimed:
                avals = _avals_of(args_or_avals)
                if block:
                    try:
                        compiled = self._build(fn, key, avals)
                        self._persist_exe(key, compiled, portable)
                    finally:
                        with self._lock:
                            self._pending.discard(key)
                    return True
                self._submit(self._warm_task, fn, key, avals, portable)
                return True
            # another thread (a dispatch miss's persist) owns this key:
            # never race it onto the same tmp file or strip its pending
            # marker — wait it out, then re-check (one more claim
            # attempt covers a failed background task)
            if not block:
                return True
            self.drain(180)
        with self._lock:
            return key in self._table

    def _warm_task(self, fn, key, avals, portable) -> None:
        try:
            compiled = self._build(fn, key, avals)
            self._persist_exe(key, compiled, portable)
        except Exception:
            mm = _metrics()
            if mm is not None:
                mm.counter("compile.store_corrupt").inc()
        finally:
            with self._lock:
                self._pending.discard(key)

    # -- restore -----------------------------------------------------------
    def ensure_restored(self, block: bool = False) -> int:
        """Deserialize every persisted executable valid for THIS
        backend into the program table — the fresh-process warm start.
        Idempotent once COMPLETE; an attempt that could not reach the
        backend resets so a later call retries instead of latching the
        process cold forever. ``block=False`` runs on the background
        pool (the executor's setup path must not stall on a big
        store); a later ``block=True`` call waits for an in-flight
        background restore rather than skipping it. Returns the number
        restored by THIS call (0 when deferred/waited)."""
        with self._lock:
            if self._restore_state == "done":
                return 0
            waiting = self._restore_state == "pending"
            if not waiting:
                self._restore_state = "pending"
                todo = [(k, dict(e)) for k, e in self._entries.items()
                        if e.get("exe")]
        if waiting:
            if block:
                self.drain(180)  # the background restore finishes first
            return 0
        if not todo:
            with self._lock:
                self._restore_state = "done"
            return 0
        if block:
            n, completed = self._restore_entries(todo)
            with self._lock:
                self._restore_state = "done" if completed else None
            return n
        self._submit(self._restore_task, todo)
        return 0

    def _restore_task(self, todo) -> None:
        n, completed = self._restore_entries(todo)
        with self._lock:
            self._restore_state = "done" if completed else None

    def _restore_entries(self, todo) -> tuple[int, bool]:
        """(restored count, completed): ``completed=False`` means the
        backend was unreachable and the whole pass should retry later;
        per-entry failures (corrupt/foreign binaries) are final."""
        try:
            backend = backend_token()
        except Exception:
            return 0, False  # backend not up yet: retryable
        try:
            from jax.experimental import serialize_executable as se
        except Exception:
            return 0, True  # this jax cannot deserialize, ever
        n = 0
        t0 = time.perf_counter()
        for key, entry in todo:
            if entry.get("backend") != backend:
                continue  # another topology's binary: not stale, not ours
            path = os.path.join(self.root, str(entry["exe"]))
            try:
                if _crc32_file(path) != entry.get("exe_crc32"):
                    mm = _metrics()
                    if mm is not None:
                        mm.counter("compile.store_corrupt").inc()
                    continue
                with open(path, "rb") as f:
                    payload, in_tree, out_tree = pickle.loads(f.read())
                exe = se.deserialize_and_load(payload, in_tree,
                                              out_tree)
            except Exception:
                # a stale/foreign binary: skipped, the jit path covers
                # it (the counter is the staleness evidence)
                mm = _metrics()
                if mm is not None:
                    mm.counter("compile.deserialize_failed").inc()
                continue
            with self._lock:
                self._table.setdefault(key, exe)
            n += 1
        if n:
            mm = _metrics()
            if mm is not None:
                mm.counter("compile.programs_restored").inc(n)
                mm.counter("compile.aot_s").inc(
                    time.perf_counter() - t0)
        return n, True

    def drain(self, timeout: float | None = None) -> None:
        """Wait for every queued background compile/restore (tests,
        and the bench child that must persist before exiting)."""
        with self._lock:
            futs = list(self._futures)
        for f in futs:
            try:
                f.result(timeout)
            # tpudl: ignore[swallowed-except] — drain reports nothing:
            # each task already counted its own failure
            except Exception:
                pass


# -- the process-wide store --------------------------------------------------

_STORE: ProgramStore | None = None
_STORE_LOCK = _tsan.named_lock("compile.store.singleton")


def get_program_store() -> ProgramStore:
    """The process-wide store at the CURRENT ``store_dir()`` (a changed
    env — tests, bench children — transparently re-roots)."""
    global _STORE
    root = store_dir()
    with _STORE_LOCK:
        if _STORE is None or _STORE.root != root:
            _STORE = ProgramStore(root)
        return _STORE


def reset_program_store() -> None:
    global _STORE
    with _STORE_LOCK:
        _STORE = None


def warm_start(block: bool = True) -> int:
    """Restore the persisted program store (no-op unarmed) — call it
    first thing in a serving process so the first batch dispatches
    through restored executables. ``jobs`` calls it on resume; the
    executor calls the non-blocking form at run setup."""
    if not aot_enabled():
        return 0
    return get_program_store().ensure_restored(block=block)
