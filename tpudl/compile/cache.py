"""Persistent XLA compilation cache wiring (the bottom tier of the
compile subsystem — COMPILE.md has the operator guide).

The reference pays Spark task-dispatch overhead per stage; our analogous
fixed cost is XLA compilation — ~60-200 s for InceptionV3 through a
tunneled dev chip, paid again every process start. JAX's persistent
compilation cache (serialized executables keyed by HLO+flags+topology)
removes the *compile* for repeat runs; the AOT program store
(:mod:`tpudl.compile.store`) sits above it and removes the *trace* too.
This module turns the JAX cache on with sane defaults; it is enabled
automatically by ``bench.py`` and opt-in elsewhere via
``TPUDL_COMPILE_CACHE_DIR`` (set to a directory, or ``0`` to disable).

Cache safety: entries are keyed by backend+topology, so a cache shared
between the CPU-mesh test runs and the TPU chip never cross-serves.

Failure is LOUD: a read-only filesystem or an old jax without the
config surface used to be swallowed silently — a whole fleet could cold
start on every process with nothing in any log. Now the first failure
warns once per process, counts ``compile.cache_disabled``, and files a
flight-recorder breadcrumb, so ``python -m tpudl.obs doctor`` and the
metrics sink both show WHY the fleet is cold.
"""

from __future__ import annotations

import os
import warnings

__all__ = ["enable_compilation_cache", "DEFAULT_CACHE_DIR"]

DEFAULT_CACHE_DIR = os.path.join(os.path.expanduser("~"), ".cache",
                                 "tpudl", "xla_cache")

_warned_disabled = False


def _note_disabled(path: str, exc: BaseException) -> None:
    """The diagnosable-cold-fleet breadcrumb: warn once per process,
    count every occurrence, leave flight evidence (all best-effort —
    cache setup must never take the run down)."""
    global _warned_disabled
    try:
        from tpudl.obs import metrics as _m

        _m.counter("compile.cache_disabled").inc()
        from tpudl.obs import flight as _flight

        _flight.record_error(
            "compile.cache_disabled",
            f"persistent compilation cache disabled at {path!r}: "
            f"{exc!r} — every process start pays full XLA compile",
            path=path)
    # tpudl: ignore[swallowed-except] — the breadcrumb channel itself
    # is best-effort: obs may be unimportable in a minimal subprocess,
    # and the warning below still fires
    except Exception:
        pass
    if not _warned_disabled:
        _warned_disabled = True
        warnings.warn(
            f"tpudl: persistent XLA compilation cache DISABLED "
            f"({path!r}: {exc!r}) — cold starts will pay full compile "
            f"time; fix the directory or set TPUDL_COMPILE_CACHE_DIR",
            RuntimeWarning, stacklevel=3)


def enable_compilation_cache(path: str | None = None) -> str | None:
    """Enable JAX's persistent compilation cache at ``path`` (default:
    ``$TPUDL_COMPILE_CACHE_DIR`` or ``~/.cache/tpudl/xla_cache``).
    Returns the cache dir, or None when disabled/unsupported.
    Precedence: ``TPUDL_COMPILE_CACHE_DIR=0`` kills the cache outright
    (even against an explicit ``path`` — the operator's emergency
    switch), else an explicit ``path`` beats the env beats the
    default."""
    env = os.environ.get("TPUDL_COMPILE_CACHE_DIR")
    if env == "0":
        return None
    path = path or env or DEFAULT_CACHE_DIR
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache everything that took meaningful compile time; tiny
        # programs aren't worth the disk round-trip
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        return path
    except Exception as e:  # old jax or read-only fs: loud, never fatal
        _note_disabled(str(path), e)
        return None


def _reset_warned_for_tests() -> None:
    global _warned_disabled
    _warned_disabled = False
