"""Shape-bucket ladders: snap ragged batch/sequence lengths to O(log n)
compiled program signatures.

Every novel leading-dim shape a jitted program sees costs one retrace +
one XLA compile — ~60–200 s per program on the tunneled chip (ROADMAP
item 3, PROFILE.md). The traceck sentinel *detects* that storm (PR 13);
a :class:`BucketLadder` *prevents* it: a batch of ``n`` rows pads up to
the smallest ladder rung ≥ ``n`` (repeating row 0, the bitwise-honest
``mesh.pad_batch`` discipline — pad rows are stripped from the outputs
before the caller sees them), so a workload of arbitrary ragged sizes
runs through a handful of precompiled programs instead of one compile
per novel shape.

Ladders (``TPUDL_COMPILE_BUCKETS``, or the ``buckets=`` kwarg on
``Frame.map_batches``):

- ``pow2ish`` (the ``1``/``auto`` default): powers of two plus the
  3·2^k midpoints — 1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64, … —
  bounded pad waste ≤ 1/3 of the batch, ~2·log2(n) rungs;
- ``pow2``: pure powers of two (pad waste ≤ ~1/2, log2(n) rungs —
  the tightest program count, the zero-retrace sweep's pick);
- an explicit comma list (``"8,16,32,64"``): serving deployments that
  declared their shapes; sizes past the top rung stay EXACT (honest:
  an undeclared giant batch gets its own program, never silent
  truncation);
- ``0`` / ``off`` / unset: bucketing disabled (every shape exact —
  today's behavior).

Numpy-only at import: the ladder runs on the executor's prepare path
and in the offline validator, neither of which may drag jax in.
"""

from __future__ import annotations

import math
import os

import numpy as np

__all__ = ["BucketLadder", "resolve_ladder", "pad_to", "count_pad_rows",
           "DEFAULT_SPEC"]

DEFAULT_SPEC = "pow2ish"

_OFF = ("", "0", "off", "none", "false")


class BucketLadder:
    """One bucket ladder: ``pick(n)`` → the dispatch size for an
    ``n``-row batch. Generated specs (``pow2ish``/``pow2``) are
    closed-form and unbounded; explicit rung lists return ``n`` itself
    past their top rung (exact dispatch, never a lie)."""

    def __init__(self, spec: str = DEFAULT_SPEC,
                 rungs=None):
        if rungs is not None:
            rungs = sorted({int(r) for r in rungs})
            if not rungs or rungs[0] < 1:
                raise ValueError(f"bucket rungs must be >= 1: {rungs}")
            self.spec = ",".join(str(r) for r in rungs)
            self.rungs: tuple[int, ...] | None = tuple(rungs)
            return
        if spec not in ("pow2", "pow2ish"):
            raise ValueError(
                f"unknown bucket-ladder spec {spec!r} (want 'pow2', "
                f"'pow2ish', or an explicit comma list)")
        self.spec = spec
        self.rungs = None

    def pick(self, n: int) -> int:
        """Smallest rung ≥ ``n`` (``n`` itself past an explicit
        ladder's top rung; ``n <= 0`` is returned unchanged)."""
        n = int(n)
        if n <= 0:
            return n
        if self.rungs is not None:
            for r in self.rungs:
                if r >= n:
                    return r
            return n  # past the declared top: exact, honest
        p = 1 << max(0, math.ceil(math.log2(n)))
        if self.spec == "pow2ish" and p >= 4 and n <= (3 * p) // 4:
            return (3 * p) // 4
        return p

    def is_rung(self, n: int) -> bool:
        return int(n) > 0 and self.pick(int(n)) == int(n)

    def rungs_up_to(self, n: int) -> list[int]:
        """Every distinct rung the ladder can emit for sizes 1..n —
        the declared-signature set precompilation walks."""
        out, seen = [], set()
        for i in range(1, int(n) + 1):
            r = self.pick(i)
            if r not in seen:
                seen.add(r)
                out.append(r)
        return out

    def to_meta(self) -> dict:
        """JSON-shippable identity (the program manifest persists it so
        the validator can audit shapes↔ladder consistency)."""
        return {"spec": self.spec,
                "rungs": list(self.rungs) if self.rungs else None}

    def __repr__(self):
        return f"BucketLadder({self.spec!r})"


def resolve_ladder(value=None) -> BucketLadder | None:
    """The one resolution rule: explicit value beats the
    ``TPUDL_COMPILE_BUCKETS`` env, and ``None`` means *consult the
    env* (unset env = bucketing OFF — opt-in, like the AOT store).
    Accepts a :class:`BucketLadder`, a spec string, ``True`` (the
    default ladder) or ``False``/``"off"``."""
    if isinstance(value, BucketLadder):
        return value
    if value is None:
        value = os.environ.get("TPUDL_COMPILE_BUCKETS", "")
    if value is True:
        return BucketLadder(DEFAULT_SPEC)
    if value is False:
        return None
    spec = str(value).strip().lower()
    if spec in _OFF:
        return None
    if spec in ("1", "auto", "default", "pow2ish"):
        return BucketLadder("pow2ish")
    if spec == "pow2":
        return BucketLadder("pow2")
    try:
        rungs = [int(s) for s in spec.split(",") if s.strip()]
    except ValueError:
        raise ValueError(
            f"TPUDL_COMPILE_BUCKETS={value!r} is neither a known ladder "
            f"spec (pow2, pow2ish, 1, off) nor a comma list of rungs")
    return BucketLadder(rungs=rungs)


def pad_to(arr: np.ndarray, target: int) -> np.ndarray:
    """Pad the leading dim up to ``target`` rows by repeating row 0 —
    the exact ``mesh.pad_batch`` discipline (realistic dtype/scale for
    compiled kernels, bitwise-honest: real rows are untouched and pad
    rows are stripped downstream via the executor's ``n_pad``
    plumbing)."""
    n = int(arr.shape[0])
    if n >= int(target):
        return arr
    pad = np.repeat(
        arr[:1] if n else np.zeros_like(arr, shape=(1, *arr.shape[1:])),
        int(target) - n, axis=0)
    return np.concatenate([arr, pad], axis=0)


def count_pad_rows(n: int) -> None:
    """Publish bucket padding into the process registry
    (``compile.bucket_pad_rows``) — the operator's measure of what the
    O(log n) program count costs in shipped rows."""
    from tpudl.obs import metrics as _m

    _m.counter("compile.bucket_pad_rows").inc(int(n))
