"""tpudl.compile — the compile-cost subsystem (COMPILE.md).

XLA compilation is this backend's analogue of the reference's per-stage
Spark dispatch overhead: ~60–200 s per program on the tunneled chip,
paid again on every process start and again for every novel batch
shape. Three tiers remove it:

1. the **persistent XLA compilation cache**
   (:func:`enable_compilation_cache`, ``TPUDL_COMPILE_CACHE_DIR``) —
   JAX's own disk cache of compiled binaries keyed by HLO;
2. the **AOT program store** (:class:`ProgramStore`,
   ``TPUDL_COMPILE_AOT``) — whole serialized executables keyed by
   fn-fingerprint + shapes + donate + mesh + backend, restored into a
   fresh process with no trace at all, background-compiled on miss;
3. **shape bucketing** (:class:`BucketLadder`,
   ``TPUDL_COMPILE_BUCKETS``) — ragged batch sizes snap to an
   O(log n) rung ladder so the store above has a bounded signature set
   to be warm FOR.

``Frame.map_batches`` consults all three (PIPELINE.md "Bucket pick &
AOT dispatch"); ``ImageBatchWarmup`` and
``TinyCausalLM.precompile_generate`` declare signatures ahead of
traffic; ``tpudl.jobs`` warm-starts the store on resume.
"""

from tpudl.compile.buckets import (BucketLadder, count_pad_rows, pad_to,
                                   resolve_ladder)
from tpudl.compile.cache import DEFAULT_CACHE_DIR, enable_compilation_cache
from tpudl.compile.store import (MANIFEST_NAME, MANIFEST_SCHEMA,
                                 MANIFEST_VERSION, ProgramStore,
                                 aot_enabled, backend_token,
                                 fn_fingerprint, get_program_store,
                                 reset_program_store, store_dir,
                                 warm_start)

__all__ = [
    "enable_compilation_cache", "DEFAULT_CACHE_DIR",
    "BucketLadder", "resolve_ladder", "pad_to", "count_pad_rows",
    "ProgramStore", "get_program_store", "reset_program_store",
    "aot_enabled", "store_dir", "warm_start", "fn_fingerprint",
    "backend_token", "MANIFEST_NAME", "MANIFEST_SCHEMA",
    "MANIFEST_VERSION",
]
