"""Pallas TPU kernels for the hot attention op.

Flash attention in Pallas: tiled ``softmax(QKᵀ/√d)·V`` that never
materializes the full score matrix — Q/K/V tiles stream HBM→VMEM per
grid step, scores hit the MXU via ``jnp.dot(..,
preferred_element_type=f32)``, and the online-softmax state (running
max, normalizer, weighted accumulator) lives in VMEM scratch that
persists across the innermost (K-tile) grid dimension. Peak VMEM is
O(block_q·block_k + block·d) instead of O(S²).

The kernel also returns the per-row **log-sum-exp**, which makes it
ring-composable: :func:`tpudl.attention.ring_attention` with
``use_pallas=True`` runs this kernel on each rotating K/V block and
combines the per-block (out, lse) pairs exactly — the standard
ring/flash-decoding partial-softmax merge.

``q_offset``/``k_offset`` are the blocks' global sequence positions, so
causal masking stays correct when the caller holds only a shard of the
sequence (the ring case).

CPU/tests run the same kernel with ``interpret=True`` (pure jax
semantics, no tiling constraints); on TPU use block sizes that are
multiples of the (8, 128) f32 tile — the defaults are.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention"]

_NEG_INF = -1e30  # finite -inf stand-in: exp(x - _NEG_INF) never NaNs


def _tile_live(causal, qoff_ref, koff_ref, iq, ik, block_q, block_k):
    """Whether this (Q, K) tile has ANY visible pair under causal
    masking — the shared tile-skip predicate for all three kernels."""
    if not causal:
        return jnp.bool_(True)
    return (koff_ref[0] + ik * block_k
            <= qoff_ref[0] + (iq + 1) * block_q - 1)


def _masked_scores(q_ref, k_ref, qoff_ref, koff_ref, iq, ik, *, causal,
                   scale, block_q, block_k, precision):
    """QKᵀ·scale with the global-position causal mask applied — the ONE
    definition of the score tile shared by forward, dq and dkv kernels."""
    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32,
                precision=precision) * scale
    if causal:
        q_pos = (qoff_ref[0] + iq * block_q
                 + jax.lax.broadcasted_iota(jnp.int32,
                                            (block_q, block_k), 0))
        k_pos = (koff_ref[0] + ik * block_k
                 + jax.lax.broadcasted_iota(jnp.int32,
                                            (block_q, block_k), 1))
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    return q, k, s


def _bwd_p(s, lse):
    """Reconstruct softmax weights from the saved log-sum-exp, zeroing
    rows that saw no key (f32 multiplicand: a bool minor-dim insertion
    is unsupported in Mosaic for non-32-bit types)."""
    alive = (lse > _NEG_INF * 0.5).astype(jnp.float32)[:, None]
    return jnp.exp(s - lse[:, None]) * alive


def _flash_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                  m_scr, l_scr, acc_scr, *, causal: bool, scale: float,
                  block_q: int, block_k: int, precision):
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    iq = pl.program_id(1)
    # a K tile strictly in the future of every row of this Q tile
    # contributes nothing; skip BOTH MXU passes (≈2x for long causal)
    live = _tile_live(causal, qoff_ref, koff_ref, iq, ik, block_q, block_k)

    @pl.when(live)
    def _compute():
        _q, _k, s = _masked_scores(
            q_ref, k_ref, qoff_ref, koff_ref, iq, ik, causal=causal,
            scale=scale, block_q=block_q, block_k=block_k,
            precision=precision)

        m_prev = m_scr[:, 0]                          # [TQ]
        m_new = jnp.maximum(m_prev, s.max(axis=1))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])               # [TQ, TK]
        # a row with NO visible key yet has m_new == _NEG_INF and
        # exp(0)==1 for every masked entry; zero it so l stays 0 and
        # finalize reports the row as fully masked, not mean(V)
        p = jnp.where((m_new <= _NEG_INF * 0.5)[:, None], 0.0, p)
        l_new = l_scr[:, 0] * corr + p.sum(axis=1)
        acc_scr[:] = (acc_scr[:] * corr[:, None]
                      + jnp.dot(p, v_ref[0].astype(jnp.float32),
                                preferred_element_type=jnp.float32,
                                precision=precision))
        m_scr[:] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_scr[:, 0]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / safe_l[:, None]).astype(o_ref.dtype)
        # lse = m + log(l); fully-masked rows (l==0) get -inf-equivalent.
        # The row vector is broadcast over an 8-sublane dim purely to
        # satisfy the TPU (8, 128) output-tile rule; callers read row 0.
        lse = jnp.where(l == 0.0, _NEG_INF, m_scr[:, 0] + jnp.log(safe_l))
        lse_ref[0] = jnp.broadcast_to(lse[None, :], lse_ref.shape[1:])


def _bwd_dq_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, do_ref,
                   lse_ref, dlt_ref, dq_ref, dq_scr, *, causal: bool,
                   scale: float, block_q: int, block_k: int, precision):
    """dq = Σ_k  p ⊙ (dOVᵀ − δ + dlse) · scale @ K, accumulated over the
    innermost K-tile grid dim — same tiling discipline as the forward,
    no S² materialization. δ = rowsum(dO ⊙ O), and ``p = exp(s − lse)``
    reconstructs the softmax weights from the saved log-sum-exp."""
    iq, ik, nk = pl.program_id(1), pl.program_id(2), pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    live = _tile_live(causal, qoff_ref, koff_ref, iq, ik, block_q, block_k)

    @pl.when(live)
    def _compute():
        q, k, s = _masked_scores(
            q_ref, k_ref, qoff_ref, koff_ref, iq, ik, causal=causal,
            scale=scale, block_q=block_q, block_k=block_k,
            precision=precision)
        p = _bwd_p(s, lse_ref[0, 0])
        do = do_ref[0].astype(jnp.float32)
        dp = jnp.dot(do, v_ref[0].astype(jnp.float32).T,
                     preferred_element_type=jnp.float32,
                     precision=precision)
        ds = p * (dp - dlt_ref[0, 0][:, None]) * scale
        dq_scr[:] += jnp.dot(ds, k, preferred_element_type=jnp.float32,
                             precision=precision)

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, do_ref,
                    lse_ref, dlt_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                    causal: bool, scale: float, block_q: int,
                    block_k: int, precision):
    """dk = Σ_q (p ⊙ (dOVᵀ − δ + dlse) · scale)ᵀ @ Q ; dv = Σ_q pᵀ @ dO —
    grid over K tiles with the Q-tile dim innermost."""
    ik, iq, nq = pl.program_id(1), pl.program_id(2), pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    live = _tile_live(causal, qoff_ref, koff_ref, iq, ik, block_q, block_k)

    @pl.when(live)
    def _compute():
        q, k, s = _masked_scores(
            q_ref, k_ref, qoff_ref, koff_ref, iq, ik, causal=causal,
            scale=scale, block_q=block_q, block_k=block_k,
            precision=precision)
        p = _bwd_p(s, lse_ref[0, 0])
        do = do_ref[0].astype(jnp.float32)
        dv_scr[:] += jnp.dot(p.T, do, preferred_element_type=jnp.float32,
                             precision=precision)
        dp = jnp.dot(do, v_ref[0].astype(jnp.float32).T,
                     preferred_element_type=jnp.float32,
                     precision=precision)
        ds = p * (dp - dlt_ref[0, 0][:, None]) * scale
        dk_scr[:] += jnp.dot(ds.T, q, preferred_element_type=jnp.float32,
                             precision=precision)

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _pallas_flash_bwd(qh, kh, vh, out, lse, qoff, koff, do, dlse, *,
                      causal, block_q, block_k, interpret, precision):
    """Tiled flash backward: (dq, dk, dv) without any S² tensor.

    The lse cotangent folds in analytically: ∂lse_i/∂s_ij = p_ij, so the
    shared score gradient is ds = p ⊙ (dOVᵀ − δ + dlse) with
    δ = rowsum(dO ⊙ O) − the δ and dlse terms combine into one per-row
    constant fed to both kernels."""
    bh_n, s_q, d = qh.shape
    s_k = kh.shape[1]
    scale = 1.0 / (d ** 0.5)
    do32 = do.astype(jnp.float32)
    # per-row constant: −δ + dlse, folded so the kernels need ONE vector
    dlt = (jnp.sum(do32 * out.astype(jnp.float32), axis=-1)
           - dlse.astype(jnp.float32))
    # broadcast row vectors over an 8-sublane dim (TPU input tiling)
    lse8 = jnp.broadcast_to(lse[:, None, :], (bh_n, 8, s_q))
    dlt8 = jnp.broadcast_to(dlt[:, None, :], (bh_n, 8, s_q))
    kernel_kw = dict(causal=causal, scale=scale, block_q=block_q,
                     block_k=block_k, precision=precision)

    # dq: grid (BH, Sq/TQ, Sk/TK) — q tile fixed per row, K innermost
    def qi_q(bh, iq, ik):
        return (bh, iq, 0)

    def qi_k(bh, iq, ik):
        return (bh, ik, 0)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **kernel_kw),
        grid=(bh_n, s_q // block_q, s_k // block_k),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, d), qi_q),
            pl.BlockSpec((1, block_k, d), qi_k),
            pl.BlockSpec((1, block_k, d), qi_k),
            pl.BlockSpec((1, block_q, d), qi_q),
            pl.BlockSpec((1, 8, block_q), lambda bh, iq, ik: (bh, 0, iq)),
            pl.BlockSpec((1, 8, block_q), lambda bh, iq, ik: (bh, 0, iq)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), qi_q),
        out_shape=jax.ShapeDtypeStruct((bh_n, s_q, d), qh.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qoff, koff, qh, kh, vh, do, lse8, dlt8)

    # dk/dv: grid (BH, Sk/TK, Sq/TQ) — k tile fixed per row, Q innermost
    def ki_k(bh, ik, iq):
        return (bh, ik, 0)

    def ki_q(bh, ik, iq):
        return (bh, iq, 0)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **kernel_kw),
        grid=(bh_n, s_k // block_k, s_q // block_q),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, d), ki_q),
            pl.BlockSpec((1, block_k, d), ki_k),
            pl.BlockSpec((1, block_k, d), ki_k),
            pl.BlockSpec((1, block_q, d), ki_q),
            pl.BlockSpec((1, 8, block_q), lambda bh, ik, iq: (bh, 0, iq)),
            pl.BlockSpec((1, 8, block_q), lambda bh, ik, iq: (bh, 0, iq)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), ki_k),
            pl.BlockSpec((1, block_k, d), ki_k),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh_n, s_k, d), kh.dtype),
            jax.ShapeDtypeStruct((bh_n, s_k, d), vh.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qoff, koff, qh, kh, vh, do, lse8, dlt8)
    return dq, dk, dv


@functools.lru_cache(maxsize=32)
def _flash_fn(causal: bool, block_q: int, block_k: int, interpret: bool,
              precision):
    """One custom-VJP'd head-major flash fn per static config: forward
    AND backward are Pallas kernels (pallas_call has no generic
    autodiff), so neither direction materializes an S² tensor."""

    def fwd_impl(qh, kh, vh, qoff, koff):
        return _pallas_flash_bh(qh, kh, vh, qoff, koff, causal=causal,
                                block_q=block_q, block_k=block_k,
                                interpret=interpret, precision=precision)

    f = jax.custom_vjp(fwd_impl)

    def fwd(qh, kh, vh, qoff, koff):
        out, lse = fwd_impl(qh, kh, vh, qoff, koff)
        return (out, lse), (qh, kh, vh, out, lse, qoff, koff)

    def bwd(res, cots):
        qh, kh, vh, out, lse, qoff, koff = res
        do, dlse = cots
        dq, dk, dv = _pallas_flash_bwd(
            qh, kh, vh, out, lse, qoff, koff, do, dlse, causal=causal,
            block_q=block_q, block_k=block_k, interpret=interpret,
            precision=precision)
        return dq, dk, dv, None, None

    f.defvjp(fwd, bwd)
    return f


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret",
                              "return_lse", "precision"))
def flash_attention(q, k, v, *, causal: bool = False, q_offset=0,
                    k_offset=0, block_q: int = 128, block_k: int = 128,
                    interpret: bool = False, return_lse: bool = False,
                    precision=None):
    """Tiled flash attention. q: [B, Sq, H, D], k/v: [B, Sk, H, D] →
    out [B, Sq, H, D] (and, with ``return_lse``, lse [B, Sq, H] —
    ``logsumexp(scores)`` per query row, for ring partial merges).

    ``q_offset``/``k_offset`` are the blocks' GLOBAL sequence positions
    for causal masking; they may be traced values (each ring device
    passes its rotating source position). Block sizes are advisory:
    non-dividing or Mosaic-unaligned requests shrink to the largest
    legal divisor (full-dim at worst), so any sequence length works."""
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    # non-dividing block requests shrink to the largest divisor (e.g.
    # S=192, block=128 → 64) instead of erroring — same gcd discipline
    # as the ring path, so standalone callers get it too
    block_q = math.gcd(min(block_q, s_q), s_q)
    block_k = math.gcd(min(block_k, s_k), s_k)
    if not interpret:
        # Mosaic tiling: a block's trailing dims must be (8, 128)-aligned
        # OR equal the full array dim. block_q is the lse lane dim and the
        # q sublane dim; block_k is the k sublane dim. An unaligned
        # result falls back to the always-legal full-dim block.
        if block_q % 128 and block_q != s_q:
            block_q = s_q
        if block_k % 8 and block_k != s_k:
            block_k = s_k
    assert s_q % block_q == 0 and s_k % block_k == 0

    # head-major [B*H, S, D]: each grid row owns one (batch, head) pair
    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    qh, kh, vh = to_bh(q), to_bh(k), to_bh(v)
    qoff = jnp.asarray(q_offset, jnp.int32).reshape(1)
    koff = jnp.asarray(k_offset, jnp.int32).reshape(1)
    out, lse = _flash_fn(causal, block_q, block_k, interpret, precision)(
        qh, kh, vh, qoff, koff)
    out = out.reshape(b, h, s_q, d).transpose(0, 2, 1, 3)
    if not return_lse:
        return out
    lse = lse.reshape(b, h, s_q).transpose(0, 2, 1)
    return out, lse


def _pallas_flash_bh(qh, kh, vh, qoff, koff, *, causal, block_q, block_k,
                     interpret, precision=None):
    """The raw kernel launch, head-major [BH, S, D] → (out, lse[BH, S])."""
    bh_n, s_q, d = qh.shape
    s_k = kh.shape[1]
    grid = (bh_n, s_q // block_q, s_k // block_k)
    out, lse8 = _launch(qh, kh, vh, qoff, koff, grid=grid, causal=causal,
                        block_q=block_q, block_k=block_k,
                        interpret=interpret, precision=precision)
    return out, lse8[:, 0, :]


def _launch(qh, kh, vh, qoff, koff, *, grid, causal, block_q, block_k,
            interpret, precision=None):
    bh_n, s_q, d = qh.shape
    kernel = functools.partial(
        _flash_kernel, causal=causal, scale=1.0 / (d ** 0.5),
        block_q=block_q, block_k=block_k, precision=precision)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # q global offset
            pl.BlockSpec(memory_space=pltpu.SMEM),  # k global offset
            pl.BlockSpec((1, block_q, d), lambda bh, iq, ik: (bh, iq, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, iq, ik: (bh, ik, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, iq, ik: (bh, ik, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, iq, ik: (bh, iq, 0)),
            pl.BlockSpec((1, 8, block_q), lambda bh, iq, ik: (bh, 0, iq)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh_n, s_q, d), qh.dtype),
            # lse rides an 8-sublane broadcast dim for TPU output tiling
            jax.ShapeDtypeStruct((bh_n, 8, s_q), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max m
            pltpu.VMEM((block_q, 128), jnp.float32),  # running norm l
            pltpu.VMEM((block_q, d), jnp.float32),    # weighted acc
        ],
        interpret=interpret,
    )(qoff, koff, qh, kh, vh)
