"""GraphDef → JAX interpreter: TF graphs become jittable XLA programs.

This is the execution half of the rebuild of sparkdl's model-ingestion
layer (ref: python/sparkdl/graph/input.py — TFInputGraph ~L40 and its
factory matrix ~L80-350). The reference ships frozen GraphDefs to a TF
C++ session on each executor; here the graph is *translated once* into a
pure jax function — closed over constants, parameterized over variables —
which then jits into a single fused XLA:TPU program. TF is used strictly
as a proto/loader library (SURVEY.md §7.0), never at runtime.

Two modes:
- frozen:    every variable already constant-folded → ``fn(*feeds)``.
- trainable: resource placeholders map to a params pytree →
  ``fn(params, *feeds)`` — differentiable with ``jax.grad`` through the
  whole ingested model, a capability the reference's frozen-protobuf
  design structurally ruled out.

Op coverage targets what TF2/Keras tracing and TF1 freezing actually emit
for MLPs/CNNs (the reference's model space, SURVEY.md §5.7). Unsupported
ops raise ``UnsupportedOpError`` naming the op, at *translation* time.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["UnsupportedOpError", "build_jax_fn", "tensor_name", "op_name",
           "validated_input", "validated_output"]


class UnsupportedOpError(NotImplementedError):
    def __init__(self, op: str, node: str):
        super().__init__(
            f"GraphDef op {op!r} (node {node!r}) has no JAX translation; "
            "supported ops are the TF2/Keras inference set — see "
            "tpudl/ingest/graphdef.py:_OPS"
        )
        self.op = op


# -- tensor-name algebra (ref: sparkdl graph/utils.py as_op_name/as_tensor_name)
def tensor_name(name: str) -> str:
    """Canonicalize ``"x"`` → ``"x:0"`` (graph-output tensor form)."""
    name = name.lstrip("^")
    return name if ":" in name else name + ":0"


def op_name(name: str) -> str:
    """Canonicalize ``"x:0"`` → ``"x"`` (op/node form)."""
    name = name.lstrip("^")
    return name.split(":")[0]


def node_op_map(graph_def) -> dict:
    """{node name → op type} for validator reuse — build ONCE per graph;
    frozen imagenet-scale protos hold thousands of nodes."""
    return {n.name: n.op for n in graph_def.node}


def validated_input(graph_def, name: str, nodes: dict | None = None) -> str:
    """Canonical tensor name for a FEED, verified to be a genuine graph
    input (a Placeholder node) — the rebuild of ref graph/utils.py
    validated_input: feeding an interior tensor is a silent-wrong-result
    bug in the translated program, so it is rejected here."""
    nodes = nodes if nodes is not None else node_op_map(graph_def)
    op = op_name(name)
    if op not in nodes:
        raise ValueError(
            f"input {name!r} not found in graph ({len(nodes)} nodes)")
    if nodes[op] not in ("Placeholder", "PlaceholderWithDefault"):
        raise ValueError(
            f"input {name!r} is a {nodes[op]!r} node, not a graph "
            "input (Placeholder); feeds must be genuine inputs")
    return tensor_name(name)


def validated_output(graph_def, name: str, nodes: dict | None = None) -> str:
    """Canonical tensor name for a FETCH, verified to exist in the graph
    (ref graph/utils.py validated_output)."""
    nodes = nodes if nodes is not None else node_op_map(graph_def)
    if op_name(name) not in nodes:
        raise ValueError(
            f"output {name!r} not found in graph ({len(nodes)} nodes)")
    return tensor_name(name)


def _np_dtype(tf_enum: int):
    import tensorflow as tf

    return np.dtype(tf.dtypes.DType(tf_enum).as_numpy_dtype)


def _const_value(node):
    import tensorflow as tf

    return tf.make_ndarray(node.attr["value"].tensor)


def _attr_list(attr):
    return list(attr.list.i) or list(attr.list.f) or list(attr.list.s)


def _static_or_np(x):
    """Concrete numpy value of ``x`` if available (Const-fed inputs under
    tracing), else None. Shape-like operands must be static for XLA."""
    if isinstance(x, (np.ndarray, np.generic, int, float, list, tuple)):
        return np.asarray(x)
    if isinstance(x, jax.core.Tracer):
        return None
    if isinstance(x, jax.Array):
        return np.asarray(x)
    return None


def _require_static(x, node, what):
    v = _static_or_np(x)
    if v is None:
        raise UnsupportedOpError(
            f"dynamic {what}", f"{node.name} (shape-like operands must be "
            "constants for XLA static shapes)")
    return v


# ---------------------------------------------------------------------------
# op handlers: (node, inputs: list[jnp], ctx) -> value or tuple of values
# ---------------------------------------------------------------------------
_OPS = {}


def op(*names):
    def deco(fn):
        for n in names:
            _OPS[n] = fn
        return fn
    return deco


def _unary(fn):
    return lambda node, xs, ctx: fn(xs[0])


def _binary(fn):
    return lambda node, xs, ctx: fn(xs[0], xs[1])


for _name, _fn in {
    "Relu": jax.nn.relu, "Relu6": lambda x: jnp.clip(x, 0, 6),
    "Elu": jax.nn.elu, "Selu": jax.nn.selu, "Softplus": jax.nn.softplus,
    "Softsign": jax.nn.soft_sign, "Sigmoid": jax.nn.sigmoid,
    "Tanh": jnp.tanh, "Exp": jnp.exp, "Log": jnp.log, "Log1p": jnp.log1p,
    "Sqrt": jnp.sqrt, "Rsqrt": lax.rsqrt, "Square": jnp.square,
    "Neg": jnp.negative, "Abs": jnp.abs, "Sign": jnp.sign,
    "Floor": jnp.floor, "Ceil": jnp.ceil, "Round": jnp.round,
    "Erf": lax.erf, "Sin": jnp.sin, "Cos": jnp.cos,
    "Reciprocal": jnp.reciprocal, "LogicalNot": jnp.logical_not,
    "Identity": lambda x: x, "StopGradient": lax.stop_gradient,
    "ZerosLike": jnp.zeros_like, "OnesLike": jnp.ones_like,
    "Snapshot": lambda x: x,
}.items():
    _OPS[_name] = _unary(_fn)

for _name, _fn in {
    "Add": jnp.add, "AddV2": jnp.add, "Sub": jnp.subtract,
    "Mul": jnp.multiply, "RealDiv": jnp.divide, "Div": jnp.divide,
    "DivNoNan": lambda x, y: jnp.where(y == 0, 0, x / jnp.where(y == 0, 1, y)),
    "FloorDiv": jnp.floor_divide, "FloorMod": jnp.mod, "Mod": jnp.mod,
    "Pow": jnp.power, "Maximum": jnp.maximum, "Minimum": jnp.minimum,
    "SquaredDifference": lambda x, y: jnp.square(x - y),
    "Greater": jnp.greater, "GreaterEqual": jnp.greater_equal,
    "Less": jnp.less, "LessEqual": jnp.less_equal,
    "Equal": jnp.equal, "NotEqual": jnp.not_equal,
    "LogicalAnd": jnp.logical_and, "LogicalOr": jnp.logical_or,
    "BitwiseAnd": jnp.bitwise_and, "BitwiseOr": jnp.bitwise_or,
    "LeftShift": jnp.left_shift, "RightShift": jnp.right_shift,
}.items():
    _OPS[_name] = _binary(_fn)


@op("Const")
def _const(node, xs, ctx):
    return _const_value(node)


@op("NoOp", "Assert", "PreventGradient", "CheckNumerics")
def _noop(node, xs, ctx):
    return xs[0] if xs else None


@op("ReadVariableOp")
def _read_var(node, xs, ctx):
    return xs[0]  # resource input already resolved to the variable's value


@op("Cast")
def _cast(node, xs, ctx):
    return xs[0].astype(_np_dtype(node.attr["DstT"].type)) if hasattr(
        xs[0], "astype") else np.asarray(xs[0], _np_dtype(node.attr["DstT"].type))


@op("AddN")
def _addn(node, xs, ctx):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


@op("MatMul")
def _matmul(node, xs, ctx):
    a, b = xs
    if node.attr["transpose_a"].b:
        a = a.T
    if node.attr["transpose_b"].b:
        b = b.T
    return a @ b


@op("BatchMatMul", "BatchMatMulV2", "BatchMatMulV3")
def _batch_matmul(node, xs, ctx):
    a, b = xs
    if node.attr["adj_x"].b:
        a = jnp.swapaxes(a, -1, -2)
    if node.attr["adj_y"].b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


@op("Einsum")
def _einsum(node, xs, ctx):
    eq = node.attr["equation"].s.decode()
    return jnp.einsum(eq, *xs)


@op("BiasAdd")
def _bias_add(node, xs, ctx):
    x, b = xs
    if node.attr["data_format"].s == b"NCHW":
        return x + b.reshape((1, -1) + (1,) * (x.ndim - 2))
    return x + b


def _nhwc(node, x):
    """Return (x_nhwc, was_nchw) normalizing data_format."""
    fmt = node.attr["data_format"].s or b"NHWC"
    if fmt == b"NCHW":
        return jnp.transpose(x, (0, 2, 3, 1)), True
    return x, False


def _from_nhwc(y, was_nchw):
    return jnp.transpose(y, (0, 3, 1, 2)) if was_nchw else y


def _conv_padding(node):
    pad = node.attr["padding"].s.decode()
    if pad == "EXPLICIT":
        # explicit_paddings pairs are in data-format order; extract spatial
        ep = list(node.attr["explicit_paddings"].list.i)
        if node.attr["data_format"].s == b"NCHW":
            return [(ep[4], ep[5]), (ep[6], ep[7])]
        return [(ep[2], ep[3]), (ep[4], ep[5])]
    return pad


@op("Conv2D")
def _conv2d(node, xs, ctx):
    x, k = xs
    x, nchw = _nhwc(node, x)
    strides = list(node.attr["strides"].list.i)
    dil = list(node.attr["dilations"].list.i) or [1, 1, 1, 1]
    s = (strides[2], strides[3]) if node.attr["data_format"].s == b"NCHW" else (strides[1], strides[2])
    d = (dil[2], dil[3]) if node.attr["data_format"].s == b"NCHW" else (dil[1], dil[2])
    y = lax.conv_general_dilated(
        x, k, window_strides=s, padding=_conv_padding(node),
        rhs_dilation=d, dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return _from_nhwc(y, nchw)


@op("DepthwiseConv2dNative")
def _depthwise(node, xs, ctx):
    x, k = xs
    x, nchw = _nhwc(node, x)
    strides = list(node.attr["strides"].list.i)
    s = (strides[1], strides[2])
    # TF out-channel k is c*mult + m (c-major), which is exactly what a
    # plain reshape of (kh,kw,cin,mult) gives for grouped-conv HWIO.
    kh, kw, cin, mult = k.shape
    k = jnp.reshape(k, (kh, kw, 1, cin * mult))
    y = lax.conv_general_dilated(
        x, k, window_strides=s, padding=_conv_padding(node),
        feature_group_count=cin, dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return _from_nhwc(y, nchw)


@op("Conv2DBackpropInput")
def _conv2d_transpose(node, xs, ctx):
    out_shape, k, x = xs
    out_shape = _require_static(out_shape, node, "output shape")
    strides = list(node.attr["strides"].list.i)
    pad = node.attr["padding"].s.decode()
    y = lax.conv_transpose(
        x, k, strides=(strides[1], strides[2]), padding=pad,
        dimension_numbers=("NHWC", "HWIO", "NHWC"), transpose_kernel=True)
    if tuple(y.shape) != tuple(out_shape):
        raise UnsupportedOpError("Conv2DBackpropInput shape mismatch", node.name)
    return y


def _pool(node, xs, reducer, init):
    x = xs[0]
    x, nchw = _nhwc(node, x)
    ks = list(node.attr["ksize"].list.i)
    st = list(node.attr["strides"].list.i)
    if (node.attr["data_format"].s or b"NHWC") == b"NCHW":
        ks = [ks[0], ks[2], ks[3], ks[1]]
        st = [st[0], st[2], st[3], st[1]]
    pad = node.attr["padding"].s.decode()
    y = lax.reduce_window(x, init, reducer, tuple(ks), tuple(st), pad)
    return y, x, ks, st, pad, nchw


@op("MaxPool")
def _max_pool(node, xs, ctx):
    y, _x, _k, _s, _p, nchw = _pool(node, xs, lax.max, -jnp.inf)
    return _from_nhwc(y, nchw)


@op("AvgPool")
def _avg_pool(node, xs, ctx):
    # TF AvgPool divides by the count of *in-bounds* elements under SAME
    y, x, ks, st, pad, nchw = _pool(node, xs, lax.add, 0.0)
    ones = jnp.ones(x.shape[1:3], x.dtype)[None, :, :, None]
    counts = lax.reduce_window(ones, 0.0, lax.add, tuple(ks), tuple(st), pad)
    return _from_nhwc(y / counts, nchw)


@op("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3")
def _fused_bn(node, xs, ctx):
    x, scale, offset, mean, var = xs[:5]
    if node.attr["is_training"].b:
        raise UnsupportedOpError("FusedBatchNorm(is_training=True)", node.name)
    eps = node.attr["epsilon"].f or 1e-3
    x, nchw = _nhwc(node, x)
    y = (x - mean) * lax.rsqrt(var + eps) * scale + offset
    y = _from_nhwc(y, nchw)
    return (y, mean, var, mean, var, var)  # aux outputs per TF signature


@op("Softmax")
def _softmax(node, xs, ctx):
    return jax.nn.softmax(xs[0], axis=-1)


@op("LogSoftmax")
def _log_softmax(node, xs, ctx):
    return jax.nn.log_softmax(xs[0], axis=-1)


@op("LeakyRelu")
def _leaky_relu(node, xs, ctx):
    alpha = node.attr["alpha"].f if "alpha" in node.attr else 0.2
    return jax.nn.leaky_relu(xs[0], alpha)


@op("Reshape")
def _reshape(node, xs, ctx):
    shape = _require_static(xs[1], node, "reshape target").astype(np.int64)
    return jnp.reshape(xs[0], tuple(int(d) for d in shape))


@op("Squeeze")
def _squeeze(node, xs, ctx):
    dims = list(node.attr["squeeze_dims"].list.i)
    return jnp.squeeze(xs[0], axis=tuple(dims) if dims else None)


@op("ExpandDims")
def _expand_dims(node, xs, ctx):
    axis = int(_require_static(xs[1], node, "axis"))
    return jnp.expand_dims(xs[0], axis)


@op("Transpose")
def _transpose(node, xs, ctx):
    perm = _require_static(xs[1], node, "perm")
    return jnp.transpose(xs[0], tuple(int(p) for p in perm))


@op("ConcatV2")
def _concat(node, xs, ctx):
    axis = int(_require_static(xs[-1], node, "axis"))
    return jnp.concatenate(xs[:-1], axis=axis)


@op("Concat")
def _concat_v1(node, xs, ctx):
    axis = int(_require_static(xs[0], node, "axis"))
    return jnp.concatenate(xs[1:], axis=axis)


@op("Pack")
def _pack(node, xs, ctx):
    return jnp.stack(xs, axis=node.attr["axis"].i)


@op("Unpack")
def _unpack(node, xs, ctx):
    axis = node.attr["axis"].i
    n = node.attr["num"].i
    parts = jnp.split(xs[0], n, axis=axis)
    return tuple(jnp.squeeze(p, axis=axis) for p in parts)


@op("Split")
def _split(node, xs, ctx):
    axis = int(_require_static(xs[0], node, "axis"))
    return tuple(jnp.split(xs[1], node.attr["num_split"].i, axis=axis))


@op("SplitV")
def _splitv(node, xs, ctx):
    sizes = _require_static(xs[1], node, "split sizes")
    axis = int(_require_static(xs[2], node, "axis"))
    idx = np.cumsum(sizes)[:-1]
    return tuple(jnp.split(xs[0], [int(i) for i in idx], axis=axis))


@op("Slice")
def _slice(node, xs, ctx):
    begin = _require_static(xs[1], node, "begin")
    size = _require_static(xs[2], node, "size")
    x = xs[0]
    lims = [b + (s if s != -1 else x.shape[i] - b)
            for i, (b, s) in enumerate(zip(begin, size))]
    return lax.slice(x, [int(b) for b in begin], [int(l) for l in lims])


@op("StridedSlice")
def _strided_slice(node, xs, ctx):
    x, begin, end, strides = xs
    begin = _require_static(begin, node, "begin")
    end = _require_static(end, node, "end")
    strides = _require_static(strides, node, "strides")
    bm = node.attr["begin_mask"].i
    em = node.attr["end_mask"].i
    ell = node.attr["ellipsis_mask"].i
    na = node.attr["new_axis_mask"].i
    sa = node.attr["shrink_axis_mask"].i
    idx = []
    for i in range(len(begin)):
        if ell & (1 << i):
            idx.append(Ellipsis)
        elif na & (1 << i):
            idx.append(None)
        elif sa & (1 << i):
            idx.append(int(begin[i]))
        else:
            b = None if bm & (1 << i) else int(begin[i])
            e = None if em & (1 << i) else int(end[i])
            idx.append(slice(b, e, int(strides[i])))
    return x[tuple(idx)]


@op("Shape")
def _shape(node, xs, ctx):
    # Under jit, shapes are static → emit a constant, keeping XLA happy.
    dt = _np_dtype(node.attr["out_type"].type) if node.attr["out_type"].type else np.int32
    return np.asarray(xs[0].shape, dtype=dt)


@op("Size")
def _size(node, xs, ctx):
    return np.asarray(int(np.prod(xs[0].shape)), dtype=np.int32)


@op("Rank")
def _rank(node, xs, ctx):
    return np.asarray(xs[0].ndim, dtype=np.int32)


@op("Fill")
def _fill(node, xs, ctx):
    dims = _require_static(xs[0], node, "fill dims")
    return jnp.full(tuple(int(d) for d in dims), xs[1])


@op("Range")
def _range(node, xs, ctx):
    s, l, d = (_require_static(v, node, "range operand") for v in xs)
    return jnp.arange(s.item(), l.item(), d.item())


@op("Tile")
def _tile(node, xs, ctx):
    reps = _require_static(xs[1], node, "multiples")
    return jnp.tile(xs[0], tuple(int(r) for r in reps))


@op("Pad", "PadV2", "MirrorPad")
def _pad(node, xs, ctx):
    pads = _require_static(xs[1], node, "paddings")
    cfg = [(int(a), int(b)) for a, b in pads]
    if node.op == "MirrorPad":
        mode = node.attr["mode"].s.decode().lower()
        mode = {"symmetric": "symmetric", "reflect": "reflect"}[mode]
        return jnp.pad(xs[0], cfg, mode=mode)
    cval = xs[2] if len(xs) > 2 else 0
    return jnp.pad(xs[0], cfg, constant_values=cval)


def _reduction(fn):
    def handler(node, xs, ctx):
        axes = _static_or_np(xs[1])
        keep = node.attr["keep_dims"].b
        ax = tuple(int(a) for a in np.atleast_1d(axes)) if axes is not None else None
        if ax is None:
            raise UnsupportedOpError("dynamic reduction axes", node.name)
        return fn(xs[0], axis=ax, keepdims=keep)
    return handler


_OPS["Mean"] = _reduction(jnp.mean)
_OPS["Sum"] = _reduction(jnp.sum)
_OPS["Max"] = _reduction(jnp.max)
_OPS["Min"] = _reduction(jnp.min)
_OPS["Prod"] = _reduction(jnp.prod)
_OPS["All"] = _reduction(jnp.all)
_OPS["Any"] = _reduction(jnp.any)


@op("ArgMax")
def _argmax(node, xs, ctx):
    axis = int(_require_static(xs[1], node, "axis"))
    dt = _np_dtype(node.attr["output_type"].type) if node.attr["output_type"].type else np.int64
    return jnp.argmax(xs[0], axis=axis).astype(dt)


@op("ArgMin")
def _argmin(node, xs, ctx):
    axis = int(_require_static(xs[1], node, "axis"))
    return jnp.argmin(xs[0], axis=axis)


@op("Select", "SelectV2")
def _select(node, xs, ctx):
    return jnp.where(xs[0], xs[1], xs[2])


@op("GatherV2")
def _gather(node, xs, ctx):
    axis = int(_require_static(xs[2], node, "axis"))
    return jnp.take(xs[0], xs[1], axis=axis)


@op("Gather")
def _gather_v1(node, xs, ctx):
    return jnp.take(xs[0], xs[1], axis=0)


@op("TopKV2")
def _topk(node, xs, ctx):
    k = int(_require_static(xs[1], node, "k"))
    vals, idxs = lax.top_k(xs[0], k)
    return vals, idxs.astype(np.int32)


@op("ResizeBilinear")
def _resize_bilinear(node, xs, ctx):
    size = _require_static(xs[1], node, "size")
    x = xs[0]
    out = (x.shape[0], int(size[0]), int(size[1]), x.shape[3])
    if node.attr["half_pixel_centers"].b:
        method = "bilinear"  # jax.image 'bilinear' uses half-pixel centers
        return jax.image.resize(x, out, method=method).astype(x.dtype)
    raise UnsupportedOpError("ResizeBilinear(align_corners legacy)", node.name)


@op("ResizeNearestNeighbor")
def _resize_nearest(node, xs, ctx):
    size = _require_static(xs[1], node, "size")
    x = xs[0]
    out = (x.shape[0], int(size[0]), int(size[1]), x.shape[3])
    return jax.image.resize(x, out, method="nearest")


@op("L2Loss")
def _l2loss(node, xs, ctx):
    return jnp.sum(jnp.square(xs[0])) / 2


@op("Cumsum")
def _cumsum(node, xs, ctx):
    axis = int(_require_static(xs[1], node, "axis"))
    return jnp.cumsum(xs[0], axis=axis)


@op("DecodeRaw")
def _decode_raw(node, xs, ctx):
    # image-struct bytes → tensor (ref: graph/pieces.py buildSpImageConverter
    # uses tf.decode_raw). Host-side only: bytes must be concrete.
    raw = _require_static(xs[0], node, "raw bytes")
    dt = _np_dtype(node.attr["out_type"].type)
    # DT_STRING consts arrive as object arrays holding bytes; .tobytes()
    # on those would serialize PyObject pointers, so take the element.
    payload = raw.item() if raw.dtype == object or raw.shape == () else raw.tobytes()
    if not isinstance(payload, bytes):
        payload = bytes(payload)
    return np.frombuffer(payload, dtype=dt)


# ---------------------------------------------------------------------------
# numpy fast paths for shape math
#
# Under jit, every jnp op stages into the trace (omnistaging) — even on
# constant operands. Shape-computation subgraphs (Flatten's
# Shape→StridedSlice→Pack→Reshape chain, etc.) must stay *concrete* so the
# downstream Reshape sees a static target. These handlers evaluate in
# numpy whenever every input is already concrete.
# ---------------------------------------------------------------------------
def _np_cast(node, xs):
    return np.asarray(xs[0]).astype(_np_dtype(node.attr["DstT"].type))


_NP_FAST = {
    "Pack": lambda node, xs: np.stack(xs, axis=node.attr["axis"].i),
    "Unpack": lambda node, xs: tuple(
        np.squeeze(p, axis=node.attr["axis"].i)
        for p in np.split(xs[0], node.attr["num"].i, axis=node.attr["axis"].i)),
    "ConcatV2": lambda node, xs: np.concatenate(xs[:-1], axis=int(xs[-1])),
    "Cast": _np_cast,
    "Add": lambda node, xs: np.add(*xs), "AddV2": lambda node, xs: np.add(*xs),
    "Sub": lambda node, xs: np.subtract(*xs),
    "Mul": lambda node, xs: np.multiply(*xs),
    "RealDiv": lambda node, xs: np.divide(*xs),
    "FloorDiv": lambda node, xs: np.floor_divide(*xs),
    "FloorMod": lambda node, xs: np.mod(*xs),
    "Maximum": lambda node, xs: np.maximum(*xs),
    "Minimum": lambda node, xs: np.minimum(*xs),
    "Neg": lambda node, xs: np.negative(xs[0]),
    "Equal": lambda node, xs: np.equal(*xs),
    "Greater": lambda node, xs: np.greater(*xs),
    "Less": lambda node, xs: np.less(*xs),
    "Squeeze": lambda node, xs: np.squeeze(
        xs[0], axis=tuple(node.attr["squeeze_dims"].list.i) or None),
    "ExpandDims": lambda node, xs: np.expand_dims(xs[0], int(xs[1])),
    "Reshape": lambda node, xs: np.reshape(
        xs[0], tuple(int(d) for d in np.asarray(xs[1]))),
    "Transpose": lambda node, xs: np.transpose(
        xs[0], tuple(int(p) for p in xs[1])),
    "GatherV2": lambda node, xs: np.take(xs[0], xs[1], axis=int(xs[2])),
    "Range": lambda node, xs: np.arange(xs[0].item(), xs[1].item(), xs[2].item()),
    "Fill": lambda node, xs: np.full(tuple(int(d) for d in xs[0]), xs[1]),
    "Prod": lambda node, xs: np.prod(
        xs[0], axis=tuple(int(a) for a in np.atleast_1d(xs[1])),
        keepdims=node.attr["keep_dims"].b),
    "Sum": lambda node, xs: np.sum(
        xs[0], axis=tuple(int(a) for a in np.atleast_1d(xs[1])),
        keepdims=node.attr["keep_dims"].b),
    "Tile": lambda node, xs: np.tile(xs[0], tuple(int(r) for r in xs[1])),
    "Select": lambda node, xs: np.where(*xs),
    "SelectV2": lambda node, xs: np.where(*xs),
}


def _all_static(xs):
    return all(isinstance(x, (np.ndarray, np.generic, int, float, bytes))
               for x in xs)


# ---------------------------------------------------------------------------
# graph evaluation
# ---------------------------------------------------------------------------
class _GraphEval:
    """One GraphDef (plus its function library) evaluated lazily into an
    env of tensor values. Iterative DFS — no Python recursion limit on
    1000+-node chains (InceptionV3-scale)."""

    def __init__(self, nodes, library):
        self.nodes = {n.name: n for n in nodes}
        self.library = library  # name -> FunctionDef

    def run(self, env: dict, fetches: list[str]):
        for f in fetches:
            self._eval(env, f)
        return [env[tensor_name(f)] for f in fetches]

    def _eval(self, env, fetch):
        stack = [op_name(fetch)]
        while stack:
            name = stack[-1]
            if tensor_name(name) in env or (name + ":0") in env:
                stack.pop()
                continue
            node = self.nodes.get(name)
            if node is None:
                raise KeyError(f"GraphDef has no node {name!r}")
            deps = [i for i in node.input if not i.startswith("^")]
            missing = [d for d in deps if tensor_name(d) not in env]
            if missing:
                stack.extend(op_name(d) for d in missing)
                continue
            stack.pop()
            self._apply(env, node, [env[tensor_name(d)] for d in deps])

    def _apply(self, env, node, xs):
        if node.op in ("PartitionedCall", "StatefulPartitionedCall"):
            out = self._call_function(node.attr["f"].func.name, xs)
        elif node.op == "IdentityN":
            out = tuple(xs)
        elif node.op == "Placeholder" or node.op == "PlaceholderWithDefault":
            if node.op == "PlaceholderWithDefault" and tensor_name(node.name) not in env:
                out = xs[0]
            else:
                raise KeyError(
                    f"placeholder {node.name!r} was not fed (feeds are bound "
                    "before evaluation; is it missing from the input map?)")
        elif node.op in _NP_FAST and xs and _all_static(xs):
            out = _NP_FAST[node.op](node, xs)
        else:
            handler = _OPS.get(node.op)
            if handler is None:
                raise UnsupportedOpError(node.op, node.name)
            out = handler(node, xs, self)
        if isinstance(out, tuple):
            for i, v in enumerate(out):
                env[f"{node.name}:{i}"] = v
        else:
            env[tensor_name(node.name)] = out

    def _call_function(self, fname, xs):
        fdef = self.library[fname]
        sub = _GraphEval(fdef.node_def, self.library)
        env = {}
        for arg, val in zip(fdef.signature.input_arg, xs):
            env[f"{arg.name}:0"] = val
        outs = []
        for out_arg in fdef.signature.output_arg:
            ret = fdef.ret[out_arg.name]  # e.g. "Identity:output:0"
            parts = ret.split(":")
            src = f"{parts[0]}:{parts[-1]}" if len(parts) == 3 else tensor_name(ret)
            sub._eval(env, parts[0])
            outs.append(env[src])
        return tuple(outs) if len(outs) != 1 else outs[0]


def build_jax_fn(graph_def, feeds, fetches, *, capture_map=None):
    """Translate ``graph_def`` into a pure jax-traceable callable.

    feeds/fetches: tensor names ("x" or "x:0"). Returns
    ``fn(*feed_values) -> tuple`` — or, when ``capture_map``
    ({placeholder node name → params-pytree key}) is given,
    ``fn(params, *feed_values) -> tuple`` with every mapped placeholder
    bound from ``params`` (the trainable route; jax.grad flows through).

    The translation is lazy per call, so jit tracing visits exactly the
    subgraph reachable from ``fetches`` — the moral equivalent of the
    reference's ``strip_and_freeze_until`` pruning
    (ref: sparkdl graph/utils.py ~L200), done by tracing instead of proto
    surgery.
    """
    feeds = [tensor_name(f) for f in feeds]
    fetches = [tensor_name(f) for f in fetches]
    ev = _GraphEval(graph_def.node, {f.signature.name: f
                                     for f in graph_def.library.function})

    if capture_map is None:
        def fn(*args):
            if len(args) != len(feeds):
                raise TypeError(f"expected {len(feeds)} inputs {feeds}, got {len(args)}")
            env = dict(zip(feeds, (jnp.asarray(a) for a in args)))
            out = ev.run(env, fetches)
            return tuple(out) if len(out) != 1 else out[0]
    else:
        def fn(params, *args):
            if len(args) != len(feeds):
                raise TypeError(f"expected {len(feeds)} inputs {feeds}, got {len(args)}")
            env = dict(zip(feeds, (jnp.asarray(a) for a in args)))
            for ph, key in capture_map.items():
                env[tensor_name(ph)] = params[key]
            out = ev.run(env, fetches)
            return tuple(out) if len(out) != 1 else out[0]

    fn.input_names = feeds
    fn.output_names = fetches
    return fn
