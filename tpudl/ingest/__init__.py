"""Model ingestion: TF/Keras artifacts → jittable XLA programs.

Rebuild of the reference's graph toolkit + ingester (ref: sparkdl
graph/input.py, graph/builder.py, graph/utils.py) — see
:mod:`tpudl.ingest.input` for the factory matrix and
:mod:`tpudl.ingest.graphdef` for the GraphDef→JAX translator.
"""

from tpudl.ingest.builder import GraphFunction, IsolatedSession
from tpudl.ingest.graphdef import UnsupportedOpError, build_jax_fn
from tpudl.ingest.input import TFInputGraph

__all__ = [
    "TFInputGraph",
    "GraphFunction",
    "IsolatedSession",
    "build_jax_fn",
    "UnsupportedOpError",
]
