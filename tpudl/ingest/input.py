"""TFInputGraph — the universal model-ingestion factory matrix.

Name-for-name rebuild of the reference's ingester
(ref: python/sparkdl/graph/input.py — class TFInputGraph ~L40; factories
fromGraph/fromGraphDef/fromSavedModel/fromSavedModelWithSignature/
fromCheckpoint/fromCheckpointWithSignature ~L80-350). Same six
construction routes, same "freeze variables then hand off" semantics —
but the handoff target is the GraphDef→JAX translator
(:mod:`tpudl.ingest.graphdef`) producing one jittable XLA program,
instead of a GraphDef shipped to executor TF sessions.

TF (2.x compat APIs) is used strictly as the *loader* for TF1-era
artifacts — graphs, SavedModels, Saver checkpoints — per SURVEY.md §7.0.
Two TPU-native additions beyond the reference's matrix:

- ``fromKeras`` — Keras model/file → frozen jax fn (the reference routed
  this through graph/builder.py Keras freezing instead).
- ``fromKerasTrainable`` — Keras model → (fn(params, x), params pytree),
  differentiable end-to-end; the frozen-protobuf reference could only
  ever run inference on ingested models.
"""

from __future__ import annotations

import os

import numpy as np

from tpudl.ingest.graphdef import (build_jax_fn, node_op_map, op_name,
                                   tensor_name, validated_input,
                                   validated_output)

__all__ = ["TFInputGraph"]


def _tf():
    os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
    import tensorflow as tf

    return tf


class TFInputGraph:
    """An ingested, frozen model graph plus its input/output tensor names.

    Attributes mirror the reference (graph/input.py ~L40):

    - ``graph_def``: frozen ``tf.GraphDef`` proto (variables → consts).
    - ``input_tensor_name_from_signature`` / ``output_tensor_name_from_signature``:
      {logical signature name → tensor name} when built from a signature,
      else None.
    - ``input_names`` / ``output_names``: the concrete feed/fetch tensor
      names of the ingested slice.

    ``make_fn()`` yields the jax-traceable callable; everything downstream
    (TFTransformer, TFImageTransformer, UDFs) runs that under ``jit``.
    """

    def __init__(self, graph_def, input_names, output_names,
                 input_sig=None, output_sig=None, params=None,
                 capture_map=None):
        self.graph_def = graph_def
        # feed/fetch validation at ingest time (ref: graph/utils.py
        # validated_input/validated_output): a feed that is not a real
        # graph input, or a fetch that does not exist, fails HERE with a
        # name-level error instead of deep inside the translator.
        nodes = node_op_map(graph_def)
        self.input_names = [validated_input(graph_def, n, nodes)
                            for n in input_names]
        self.output_names = [validated_output(graph_def, n, nodes)
                             for n in output_names]
        self.input_tensor_name_from_signature = input_sig
        self.output_tensor_name_from_signature = output_sig
        self.params = params  # non-None only for the trainable route
        self._capture_map = capture_map

    # -- execution handoff -------------------------------------------------
    def make_fn(self, feeds=None, fetches=None):
        """Build ``fn(*feeds) -> fetches`` (or ``fn(params, *feeds)`` for
        trainable graphs); pure, jax-traceable, jit at the call site."""
        return build_jax_fn(
            self.graph_def,
            feeds or self.input_names,
            fetches or self.output_names,
            capture_map=self._capture_map,
        )

    @property
    def trainable(self) -> bool:
        return self.params is not None

    def __repr__(self):
        return (f"TFInputGraph(inputs={self.input_names}, "
                f"outputs={self.output_names}, trainable={self.trainable})")

    # -- factory matrix (ref routes, same names) ---------------------------
    @classmethod
    def fromGraph(cls, graph, sess, feed_names, fetch_names):
        """TF1-style live graph + session (ref: ~L80)."""
        tf = _tf()
        gdef = _freeze_v1(tf, sess, graph.as_graph_def(add_shapes=True),
                          fetch_names)
        return cls(gdef, feed_names, fetch_names)

    @classmethod
    def fromGraphDef(cls, graph_def, feed_names, fetch_names):
        """Already-frozen GraphDef proto (ref: ~L110)."""
        return cls(graph_def, feed_names, fetch_names)

    @classmethod
    def fromSavedModel(cls, saved_model_dir, tag_set, feed_names, fetch_names):
        """SavedModel with explicit feeds/fetches (ref: ~L150). TF1-style
        exports freeze through the v1 session; TF2 object-graph exports
        (resource variables the v1 freeze cannot read) go through the v2
        concrete-function route with the user's names validated against
        the frozen graph."""
        try:
            gdef, _meta = _load_saved_model_frozen(saved_model_dir, tag_set,
                                                   fetch_names)
        except Exception as v1_err:
            _log_v1_fallback(saved_model_dir, v1_err)
            v2 = _load_saved_model_v2(saved_model_dir, None)
            if v2 is None:
                raise
            gdef, _in_sig, _out_sig = v2
        return cls(gdef, feed_names, fetch_names)

    @classmethod
    def fromSavedModelWithSignature(cls, saved_model_dir, tag_set,
                                    signature_def_key):
        """SavedModel; feeds/fetches resolved from its SignatureDef
        (ref: ~L180). Handles both TF1 exports (v1 loader + freeze) and
        TF2 exports (signature concrete function + v2 freeze)."""
        tf = _tf()
        try:
            with tf.Graph().as_default() as g, \
                    tf.compat.v1.Session(graph=g) as sess:
                meta = tf.compat.v1.saved_model.loader.load(
                    sess, _tags(tag_set), saved_model_dir)
                in_sig, out_sig = _signature_maps(meta, signature_def_key)
                fetch_names = list(out_sig.values())
                gdef = _freeze_v1(tf, sess, g.as_graph_def(add_shapes=True),
                                  fetch_names)
        except Exception as v1_err:
            _log_v1_fallback(saved_model_dir, v1_err)
            v2 = _load_saved_model_v2(saved_model_dir, signature_def_key)
            if v2 is None:
                raise
            gdef, in_sig, out_sig = v2
            fetch_names = list(out_sig.values())
        return cls(gdef, list(in_sig.values()), fetch_names,
                   input_sig=in_sig, output_sig=out_sig)

    @classmethod
    def fromCheckpoint(cls, checkpoint_dir, feed_names, fetch_names):
        """TF1 Saver checkpoint directory (ref: ~L250)."""
        gdef, _meta = _load_checkpoint_frozen(checkpoint_dir, fetch_names)
        return cls(gdef, feed_names, fetch_names)

    @classmethod
    def fromCheckpointWithSignature(cls, checkpoint_dir, signature_def_key):
        """Checkpoint; feeds/fetches from the MetaGraph's SignatureDef
        (ref: ~L300)."""
        tf = _tf()
        ckpt = tf.train.latest_checkpoint(checkpoint_dir)
        if ckpt is None:
            raise ValueError(f"no checkpoint found under {checkpoint_dir!r}")
        from google.protobuf import message

        meta = tf.compat.v1.MetaGraphDef()
        with open(ckpt + ".meta", "rb") as f:
            try:
                meta.ParseFromString(f.read())
            except message.DecodeError as e:
                raise ValueError(f"corrupt meta graph {ckpt}.meta") from e
        with tf.Graph().as_default() as g, tf.compat.v1.Session(graph=g) as sess:
            saver = tf.compat.v1.train.import_meta_graph(meta)
            saver.restore(sess, ckpt)
            in_sig, out_sig = _signature_maps(meta, signature_def_key)
            fetch_names = list(out_sig.values())
            gdef = _freeze_v1(tf, sess, g.as_graph_def(add_shapes=True),
                              fetch_names)
        return cls(gdef, list(in_sig.values()), fetch_names,
                   input_sig=in_sig, output_sig=out_sig)

    # -- TPU-native additions ----------------------------------------------
    @classmethod
    def fromKeras(cls, model_or_path):
        """Keras model instance or .keras/.h5 path → frozen inference graph
        (replaces ref graph/builder.py GraphFunction-from-Keras route)."""
        tf = _tf()
        model = _load_keras(model_or_path)
        cf = _concrete_fn(tf, model)
        from tensorflow.python.framework.convert_to_constants import (
            convert_variables_to_constants_v2)

        frozen = convert_variables_to_constants_v2(cf)
        gdef = frozen.graph.as_graph_def(add_shapes=True)
        return cls(gdef, [t.name for t in frozen.inputs],
                   [t.name for t in frozen.outputs])

    @classmethod
    def fromKerasTrainable(cls, model_or_path):
        """Keras model → trainable ingestion: variables stay symbolic,
        surfaced as a params pytree keyed by variable name; the built fn is
        ``fn(params, x)`` and differentiates with jax.grad."""
        tf = _tf()
        model = _load_keras(model_or_path)
        cf = _concrete_fn(tf, model)
        gdef = cf.graph.as_graph_def(add_shapes=True)
        capture_map, params = {}, {}
        for ext, internal in cf.graph.captures:
            vs = [v for v in cf.variables if v.handle is ext]
            if not vs:
                raise ValueError(
                    f"capture {internal.name!r} is not a model variable; "
                    "non-variable captures are not ingestable as params")
            key = vs[0].name.split(":")[0]
            capture_map[op_name(internal.name)] = key
            params[key] = np.asarray(vs[0])
        n_caps = len(capture_map)
        inputs = [t.name for t in cf.inputs[: len(cf.inputs) - n_caps]]
        outputs = [t.name for t in cf.outputs]
        return cls(gdef, inputs, outputs, params=params,
                   capture_map=capture_map)


# -- loader plumbing -------------------------------------------------------
def _log_v1_fallback(saved_model_dir, err):
    """A genuine v1 failure (wrong tag set, corrupt proto, OOM) must stay
    discoverable even when the v2 loader then succeeds with different
    signatures — otherwise a misrouted TF1 artifact surfaces only a
    confusing v2-side error. INFO, not WARNING: every healthy TF2
    object-graph load also routes through this fallback, so a WARNING
    here would just train users to ignore it. When the v2 loader fails
    too, Python's exception chaining surfaces this v1 error in full."""
    import logging

    logging.getLogger("tpudl.ingest").info(
        "TF1 SavedModel load of %r failed (%s: %s); retrying with the v2 "
        "object-graph loader", saved_model_dir, type(err).__name__, err)


def _tags(tag_set):
    if isinstance(tag_set, str):
        return tag_set.split(",")
    return list(tag_set)


def _freeze_v1(tf, sess, graph_def, fetch_names):
    """variables → consts, pruned to fetches (ref: graph/utils.py
    strip_and_freeze_until ~L200)."""
    out_ops = sorted({op_name(f) for f in fetch_names})
    with _suppress_deprecation():
        return tf.compat.v1.graph_util.convert_variables_to_constants(
            sess, graph_def, out_ops)


def _suppress_deprecation():
    import contextlib

    @contextlib.contextmanager
    def ctx():
        import tensorflow as tf

        prev = tf.compat.v1.logging.get_verbosity()
        tf.compat.v1.logging.set_verbosity(tf.compat.v1.logging.ERROR)
        try:
            yield
        finally:
            tf.compat.v1.logging.set_verbosity(prev)

    return ctx()


def _signature_maps(meta_graph, signature_def_key):
    sig = meta_graph.signature_def.get(signature_def_key)
    if sig is None:
        raise KeyError(
            f"SignatureDef {signature_def_key!r} not found; available: "
            f"{sorted(meta_graph.signature_def)}")
    in_sig = {k: v.name for k, v in sig.inputs.items()}
    out_sig = {k: v.name for k, v in sig.outputs.items()}
    return in_sig, out_sig


def _load_saved_model_frozen(saved_model_dir, tag_set, fetch_names):
    tf = _tf()
    with tf.Graph().as_default() as g, tf.compat.v1.Session(graph=g) as sess:
        meta = tf.compat.v1.saved_model.loader.load(
            sess, _tags(tag_set), saved_model_dir)
        gdef = _freeze_v1(tf, sess, g.as_graph_def(add_shapes=True),
                          fetch_names)
    return gdef, meta


def _load_saved_model_v2(saved_model_dir, signature_def_key):
    """TF2 object-graph SavedModel → (frozen gdef, in_sig, out_sig) via
    the signature's concrete function, or None when the artifact has no
    usable v2 signatures. TF's nest flattens dict structures in sorted
    key order, which is how logical names line up with the frozen
    graph's input/output tensors."""
    tf = _tf()
    try:
        loaded = tf.saved_model.load(saved_model_dir)
        signatures = dict(getattr(loaded, "signatures", {}))
    except Exception:
        return None
    if not signatures:
        return None
    key = signature_def_key or "serving_default"
    if key not in signatures:
        raise KeyError(
            f"SignatureDef {key!r} not found; available: "
            f"{sorted(signatures)}")
    cf = signatures[key]
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)

    frozen = convert_variables_to_constants_v2(cf)
    gdef = frozen.graph.as_graph_def(add_shapes=True)
    args, kwargs = cf.structured_input_signature
    if args:
        raise ValueError(
            f"signature {key!r} takes {len(args)} positional inputs; only "
            "keyword-argument signatures bind logical names unambiguously")
    if len(kwargs) != len(frozen.inputs):
        raise ValueError(
            f"signature {key!r}: {len(kwargs)} named inputs but the frozen "
            f"graph exposes {len(frozen.inputs)} placeholders — cannot bind "
            "logical names to tensors safely")
    # TF nest flattens dicts in sorted-key order; cross-check each
    # placeholder's op name against its signature spec so a flatten-order
    # change fails loudly instead of silently misbinding multi-input feeds.
    in_sig = {}
    for name, t in zip(sorted(kwargs), frozen.inputs):
        spec_name = getattr(kwargs[name], "name", None)
        placeholder = op_name(t.name)
        if spec_name and spec_name != placeholder and name != placeholder:
            raise ValueError(
                f"signature {key!r}: logical input {name!r} (spec name "
                f"{spec_name!r}) would bind to placeholder {placeholder!r}; "
                "refusing ambiguous binding")
        in_sig[name] = t.name
    outs = cf.structured_outputs
    out_keys = sorted(outs) if isinstance(outs, dict) else [
        f"output_{i}" for i in range(len(frozen.outputs))]
    out_sig = {name: t.name for name, t in zip(out_keys, frozen.outputs)}
    return gdef, in_sig, out_sig


def _load_checkpoint_frozen(checkpoint_dir, fetch_names):
    tf = _tf()
    ckpt = tf.train.latest_checkpoint(checkpoint_dir)
    if ckpt is None:
        raise ValueError(f"no checkpoint found under {checkpoint_dir!r}")
    with tf.Graph().as_default() as g, tf.compat.v1.Session(graph=g) as sess:
        saver = tf.compat.v1.train.import_meta_graph(ckpt + ".meta")
        saver.restore(sess, ckpt)
        gdef = _freeze_v1(tf, sess, g.as_graph_def(add_shapes=True),
                          fetch_names)
    return gdef, None


def _load_keras(model_or_path):
    from tpudl.zoo.convert import load_keras_model

    return load_keras_model(model_or_path)


def _concrete_fn(tf, model):
    specs = [tf.TensorSpec([None, *i.shape[1:]], i.dtype) for i in model.inputs]
    if len(specs) != 1:
        raise ValueError(
            f"only single-input Keras models are ingestable (got "
            f"{len(specs)} inputs)")

    @tf.function(autograph=False)
    def f(x):
        return model(x)

    return f.get_concrete_function(specs[0])
