"""Graph-function composition kernel — GraphFunction / IsolatedSession.

Rebuild of ref: python/sparkdl/graph/builder.py (IsolatedSession ~L40,
GraphFunction ~L160, GraphFunction.fromList ~L200). The reference
splices frozen GraphDef protobufs so executors make one native call per
block; in jax the same role is *function composition* — a
:class:`GraphFunction` is a pure fn + named I/O, ``fromList`` pipes a
sequence into one fn, and ``jit`` fuses the whole pipe into a single
XLA program (the splice IS the compile).

``IsolatedSession`` survives only as a compatibility shim: its entire
reason to exist was TF1's global-graph mutation races (SURVEY.md §5.2);
jax functions are pure values, so there is no session state to isolate.
The shim provides the reference's ``asGraphFunction`` /
``importGraphFunction`` verbs over plain callables so ported user code
keeps reading naturally.
"""

from __future__ import annotations

from typing import Callable, Sequence

from tpudl.ingest.graphdef import tensor_name

__all__ = ["GraphFunction", "IsolatedSession"]


class GraphFunction:
    """A pure, jax-traceable fn with named inputs/outputs (the value
    object the reference serializes as (graph_def, inputs, outputs))."""

    def __init__(self, fn: Callable, input_names: Sequence[str] = ("input",),
                 output_names: Sequence[str] = ("output",)):
        if not callable(fn):
            raise TypeError(f"fn must be callable, got {type(fn).__name__}")
        self.fn = fn
        self.input_names = [tensor_name(n) for n in input_names]
        self.output_names = [tensor_name(n) for n in output_names]

    def __call__(self, *args):
        return self.fn(*args)

    def __repr__(self):
        return (f"GraphFunction({self.input_names} -> {self.output_names})")

    # -- constructors (mirror the reference's sources) ---------------------
    @classmethod
    def fromKeras(cls, model_or_file) -> "GraphFunction":
        """Keras model/file → frozen GraphFunction (ref: builder.py
        fromKeras; execution via the GraphDef→JAX translator)."""
        from tpudl.ingest.input import TFInputGraph

        gin = TFInputGraph.fromKeras(model_or_file)
        return cls.fromTFInputGraph(gin)

    @classmethod
    def fromTFInputGraph(cls, gin) -> "GraphFunction":
        fn = gin.make_fn()
        if gin.trainable:
            params = gin.params
            base = fn
            fn = lambda *xs: base(params, *xs)  # noqa: E731
        return cls(fn, gin.input_names, gin.output_names)

    @classmethod
    def fromList(cls, functions: Sequence[tuple[str, "GraphFunction"]]
                 ) -> "GraphFunction":
        """Splice [(scope, gfn), ...] into ONE GraphFunction piping each
        stage's outputs into the next stage's inputs (ref: fromList ~L200
        — protobuf surgery there, plain composition here; jit fuses it).
        Arities must chain: stage k's output count == stage k+1's input
        count.
        """
        functions = list(functions)
        if not functions:
            raise ValueError("fromList of zero functions")
        for (sa, a), (sb, b) in zip(functions, functions[1:]):
            if len(a.output_names) != len(b.input_names):
                raise ValueError(
                    f"cannot pipe {sa!r} ({len(a.output_names)} outputs) "
                    f"into {sb!r} ({len(b.input_names)} inputs)")

        def piped(*args):
            out = args
            for _scope, g in functions:
                res = g(*out)
                out = res if isinstance(res, tuple) else (res,)
            return out if len(out) != 1 else out[0]

        first_scope, first = functions[0]
        last_scope, last = functions[-1]
        return cls(
            piped,
            [f"{first_scope}/{n}" if first_scope else n
             for n in first.input_names],
            [f"{last_scope}/{n}" if last_scope else n
             for n in last.output_names])


class IsolatedSession:
    """Compatibility shim (ref: builder.py IsolatedSession ~L40).

    jax has no mutable global graph, so 'isolation' is the default;
    this context manager simply offers the reference's verbs:

        with IsolatedSession() as issn:
            gfn = issn.importGraphFunction(other_gfn)
            out_gfn = issn.asGraphFunction(my_callable)
    """

    def __init__(self, using_keras: bool = False):
        self.using_keras = using_keras  # accepted for parity; no-op

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def asGraphFunction(self, fn, input_names=("input",),
                        output_names=("output",)) -> GraphFunction:
        return GraphFunction(fn, input_names, output_names)

    def importGraphFunction(self, gfn: GraphFunction, prefix: str = ""
                            ) -> GraphFunction:
        if prefix:
            return GraphFunction(
                gfn.fn,
                [f"{prefix}/{n}" for n in gfn.input_names],
                [f"{prefix}/{n}" for n in gfn.output_names])
        return gfn
