"""Multi-host control plane + per-host input sharding.

The TPU-native replacement for the reference's cluster plumbing
(SURVEY.md §5.8): py4j + Spark netty RPC become
``jax.distributed.initialize`` (one Python runtime per host, coordinator
over DCN); Spark partition shipping becomes per-host file sharding +
``jax.make_array_from_process_local_data`` (each host feeds its local
slice of the global batch; XLA's collectives ride ICI/DCN).

Single-host (the dev box, CI) is the degenerate case: every helper works
unchanged with process_count == 1, so the same user code runs from
laptop mesh-simulation to a multi-host pod.
"""

from __future__ import annotations

from collections.abc import Sequence

import jax
import numpy as np

__all__ = [
    "initialize",
    "process_count",
    "process_index",
    "is_primary",
    "host_shard",
    "global_batch",
]


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None, **kwargs) -> None:
    """Join the multi-host gang. With no arguments this is a documented
    NO-OP: TPU pod slices autodetect through the runtime and a bare
    single host needs no distributed init at all. Pass explicit args for
    DCN/GPU-style bring-up."""
    if coordinator_address is None and num_processes is None:
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id, **kwargs)
    # black-box breadcrumb: after the join, this process's flight dumps
    # are keyed by its process_index (tpudl-dump-host<idx>-<pid>), and
    # the doctor merges every host's file from one shared dir
    from tpudl.obs import flight as _flight

    _flight.get_recorder().record_event(
        "distributed.initialize",
        coordinator=str(coordinator_address),
        process_index=jax.process_index(),
        process_count=jax.process_count())


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def is_primary() -> bool:
    """True on the logical coordinator host (checkpoint writes, logging —
    the reference's rank-0 convention)."""
    return jax.process_index() == 0


def host_shard(items: Sequence, *, index: int | None = None,
               count: int | None = None) -> list:
    """This host's contiguous slice of a global work list (files, URIs).

    Replaces Spark's partition assignment: each host reads only its
    shard, so input I/O scales with hosts. Pads by wrapping so every
    host gets the same count (SPMD steps must agree on batch shape).
    """
    items = list(items)
    count = count if count is not None else jax.process_count()
    index = index if index is not None else jax.process_index()
    if count <= 1:
        return items
    per = -(-len(items) // count)  # ceil
    start = index * per
    shard = items[start:start + per]
    while len(shard) < per and items:
        shard.append(items[(start + len(shard)) % len(items)])
    return shard


def global_batch(host_local: np.ndarray, mesh, axis: str = "data"):
    """Assemble per-host arrays into ONE globally-sharded device array
    (the infeed edge for multi-host training): each process contributes
    its local rows; the result behaves as the full global batch under
    ``jit`` with the mesh's data-axis sharding."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(
        mesh, P(axis, *([None] * (host_local.ndim - 1))))
    if jax.process_count() == 1:
        return jax.device_put(host_local, sharding)
    global_shape = (host_local.shape[0] * jax.process_count(),
                    *host_local.shape[1:])
    return jax.make_array_from_process_local_data(
        sharding, host_local, global_shape)
