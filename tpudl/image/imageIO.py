"""Image struct ⇄ ndarray codec, decode and resize.

TPU-native rebuild of the reference's image I/O layer
(ref: python/sparkdl/image/imageIO.py — imageArrayToStruct ~L120,
imageStructToArray ~L100, imageTypeByOrdinal/Name ~L40-80,
createResizeImageUDF/resizeImage ~L180, readImagesWithCustomFn ~L220-280,
filesToDF ~L200; JVM twin src/main/scala/com/databricks/sparkdl/ImageUtils.scala).

Parity-sensitive layer (SURVEY.md §7.1 item 2): the struct layout is the
Spark image schema — ``origin, height, width, nChannels, mode, data`` with
OpenCV type ordinals and **BGR** channel order for 3/4-channel images, data
row-major. Host-side decode uses PIL (same as the reference's Python path);
device-side conversion to model-ready float tensors lives in
:mod:`tpudl.image.ops` so it fuses into the jitted model program instead of
being a per-row UDF.
"""

from __future__ import annotations

import dataclasses
import os
from io import BytesIO
from typing import Callable, Iterable

import numpy as np

from tpudl.frame.frame import LazyColumn
from tpudl.testing import tsan as _tsan
from tpudl.obs import metrics as _obs_metrics

try:  # PIL is the decode substrate, mirroring the reference's Python path
    from PIL import Image
except ImportError:  # pragma: no cover
    Image = None

__all__ = [
    "ImageType",
    "supportedImageTypes",
    "imageTypeByOrdinal",
    "imageTypeByName",
    "imageArrayToStruct",
    "imageStructToArray",
    "imageStructToPIL",
    "PIL_decode",
    "PIL_decode_and_resize",
    "default_probe",
    "resizeImage",
    "filesToFrame",
    "readImagesWithCustomFn",
    "LazyFileColumn",
    "SPARK_MODE",
]


@dataclasses.dataclass(frozen=True)
class ImageType:
    """One OpenCV storage mode of the Spark image schema.

    ref: imageIO.py's _OcvType table (~L40-80): CV_8UC{1,3,4} and
    CV_32FC{1,3,4} are the modes sparkdl round-trips.
    """

    name: str
    ord: int
    nChannels: int
    dtype: str


_SUPPORTED = [
    ImageType("CV_8UC1", 0, 1, "uint8"),
    ImageType("CV_32FC1", 5, 1, "float32"),
    ImageType("CV_8UC3", 16, 3, "uint8"),
    ImageType("CV_32FC3", 21, 3, "float32"),
    ImageType("CV_8UC4", 24, 4, "uint8"),
    ImageType("CV_32FC4", 29, 4, "float32"),
]
_BY_ORD = {t.ord: t for t in _SUPPORTED}
_BY_NAME = {t.name: t for t in _SUPPORTED}


class SPARK_MODE:
    """Symbolic channel orders (ref: tf_image.py channelOrder param, v1.x)."""

    BGR = "BGR"
    RGB = "RGB"
    GRAY = "L"


def supportedImageTypes() -> list[ImageType]:
    return list(_SUPPORTED)


def imageTypeByOrdinal(ord: int) -> ImageType:
    if ord not in _BY_ORD:
        raise KeyError(
            f"unsupported image mode ordinal {ord}; supported: {sorted(_BY_ORD)}"
        )
    return _BY_ORD[ord]


def imageTypeByName(name: str) -> ImageType:
    if name not in _BY_NAME:
        raise KeyError(
            f"unsupported image mode {name!r}; supported: {sorted(_BY_NAME)}"
        )
    return _BY_NAME[name]


def imageArrayToStruct(imgArray: np.ndarray, origin: str = "") -> dict:
    """ndarray (H, W, C) or (H, W) → Spark image struct dict.

    The array is assumed to already be in storage channel order (BGR for
    color, matching Spark/OpenCV); no flip happens here — flips are explicit
    at decode (`PIL_decode`) or on-device (`tpudl.image.ops`).
    """
    arr = np.asarray(imgArray)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if arr.ndim != 3:
        raise ValueError(f"image array must be 2-D or 3-D, got shape {arr.shape}")
    h, w, c = arr.shape
    if arr.dtype == np.uint8:
        dtype = "uint8"
    elif arr.dtype in (np.float32, np.float64):
        dtype = "float32"
        arr = arr.astype(np.float32)
    else:
        raise ValueError(f"unsupported image array dtype {arr.dtype}")
    matches = [t for t in _SUPPORTED if t.nChannels == c and t.dtype == dtype]
    if not matches:
        raise ValueError(f"no OpenCV mode for nChannels={c} dtype={dtype}")
    t = matches[0]
    return {
        "origin": origin,
        "height": int(h),
        "width": int(w),
        "nChannels": int(c),
        "mode": t.ord,
        "data": np.ascontiguousarray(arr).tobytes(),
    }


def imageStructToArray(imageRow: dict, copy: bool = True) -> np.ndarray:
    """Spark image struct dict → ndarray (H, W, C) in storage order.

    ``copy=False`` returns a read-only view over the struct's bytes for
    hot-path packing (the subsequent ``np.stack`` copies anyway).
    """
    t = imageTypeByOrdinal(imageRow["mode"])
    shape = (imageRow["height"], imageRow["width"], imageRow["nChannels"])
    arr = np.frombuffer(imageRow["data"], dtype=t.dtype).reshape(shape)
    return arr.copy() if copy else arr


def imageStructToPIL(imageRow: dict):
    """struct → PIL image (RGB/L), for resize oracles and visual debugging."""
    arr = imageStructToArray(imageRow)
    t = imageTypeByOrdinal(imageRow["mode"])
    if t.dtype == "float32":
        arr = np.clip(arr, 0, 255).astype(np.uint8)
    c = arr.shape[2]
    if c == 1:
        return Image.fromarray(arr[:, :, 0], mode="L")
    if c == 3:
        return Image.fromarray(arr[:, :, ::-1], mode="RGB")  # BGR → RGB
    if c == 4:
        rgba = arr[:, :, [2, 1, 0, 3]]  # BGRA → RGBA
        return Image.fromarray(rgba, mode="RGBA")
    raise ValueError(f"unsupported channel count {c}")


def PIL_decode(raw_bytes: bytes, origin: str = "") -> dict | None:
    """Decode encoded image bytes (JPEG/PNG/...) → image struct, or None.

    ref: imageIO._decodeImage (~L240): undecodable inputs yield null rows
    rather than failing the job; grayscale widens to 3-channel BGR the way
    the reference normalizes everything to CV_8UC3.
    """
    if Image is None:  # pragma: no cover
        raise ImportError("PIL is required for image decoding")
    try:
        img = Image.open(BytesIO(raw_bytes))
        img = img.convert("RGB")
    except Exception:
        return None
    rgb = np.asarray(img, dtype=np.uint8)
    return imageArrayToStruct(rgb[:, :, ::-1], origin=origin)  # store BGR


def PIL_decode_and_resize(
    raw_bytes: bytes, size: tuple[int, int], origin: str = ""
) -> dict | None:
    """Decode + resize in one host step (the hot input-pipeline path)."""
    if Image is None:  # pragma: no cover
        raise ImportError("PIL is required for image decoding")
    try:
        img = Image.open(BytesIO(raw_bytes)).convert("RGB")
        img = img.resize((size[1], size[0]), Image.BILINEAR)  # PIL takes (W, H)
    except Exception:
        return None
    rgb = np.asarray(img, dtype=np.uint8)
    return imageArrayToStruct(rgb[:, :, ::-1], origin=origin)


def _jpeg_dims(raw: bytes) -> tuple[int, int] | None:
    """(height, width) from a JPEG header via a pure-python SOF-marker
    scan (no decode), or None when the bytes aren't a JPEG. Lets the
    full-size native decode route reuse the fixed-geometry batch API."""
    if len(raw) < 4 or raw[0:2] != b"\xff\xd8":
        return None
    i, n = 2, len(raw)
    while i + 9 < n:
        if raw[i] != 0xFF:
            i += 1
            continue
        marker = raw[i + 1]
        if marker == 0xFF:  # 0xFF fill/padding byte before a marker
            i += 1
            continue
        if marker in (0xD8, 0x01) or 0xD0 <= marker <= 0xD7:
            i += 2  # parameterless markers
            continue
        if marker == 0xDA:  # start-of-scan reached without a SOF
            return None
        seg_len = int.from_bytes(raw[i + 2:i + 4], "big")
        if 0xC0 <= marker <= 0xCF and marker not in (0xC4, 0xC8, 0xCC):
            h = int.from_bytes(raw[i + 5:i + 7], "big")
            w = int.from_bytes(raw[i + 7:i + 9], "big")
            return (h, w) if h > 0 and w > 0 else None
        i += 2 + seg_len
    return None


def default_decode(raw_bytes: bytes, origin: str = "") -> dict | None:
    """Decode bytes → image struct: threaded-C libjpeg for JPEGs when
    ``tpudl.native`` is available (bit-exact with PIL at full size — both
    are libjpeg), PIL for every other format or as fallback. This is the
    reference's executor decode stage with the first-party native decoder
    on the hot path (SURVEY.md §2.3 native contract, §7.3)."""
    from tpudl import native

    if native.available():
        dims = _jpeg_dims(raw_bytes)
        # Decompression-bomb guard (PIL's MAX_IMAGE_PIXELS discipline):
        # headers claiming huge geometry go to PIL, whose bomb check
        # yields the null row instead of a multi-GB allocation.
        if dims is not None and dims[0] * dims[1] <= 64_000_000:
            batch, ok = native.decode_resize_batch(
                [raw_bytes], dims[0], dims[1], n_threads=1)
            if ok[0]:  # already BGR storage order
                return imageArrayToStruct(batch[0], origin=origin)
            # corrupt/unusual JPEG: let PIL take its own shot below
    return PIL_decode(raw_bytes, origin=origin)


# stateless decode over thread-safe substrates (PIL and the native
# threaded decoder both release the GIL): LazyFileColumn may run it for
# several batches concurrently under the executor's prepare pool
default_decode.thread_safe = True


def default_probe(raw_bytes: bytes) -> bool:
    """Cheap validity twin of :func:`default_decode`/:func:`PIL_decode`:
    header parse + stream verify (PIL ``Image.verify`` — no IDCT, no
    color conversion, typically ~10x cheaper than a decode). Lets
    ``dropna``/``IS NULL`` on a lazy image column classify rows without
    pixel-decoding them, so the filter+featurize path decodes each
    surviving row exactly once (round-3 verdict weak #4). Approximation
    note: verify catches unreadable/garbage/truncated files — the
    nullness sources of this layer — but a pathological file could pass
    verify and still decode to None; such a row surfaces as None
    downstream exactly as it would in an unfiltered frame."""
    if Image is None:  # pragma: no cover
        raise ImportError("PIL is required for image probing")
    try:
        img = Image.open(BytesIO(raw_bytes))
        img.verify()
        return True
    except Exception:
        return False


def createNativeImageLoader(height: int, width: int, scale: float = 1.0,
                            n_threads: int | None = None,
                            output_dtype: str = "float32"):
    """Build a URI→ndarray ``imageLoader`` whose ``batch_decode``
    attribute routes a WHOLE URI batch through one threaded native
    decode+resize call — the pack-stage fast path ``load_uri_batch``
    uses for KerasImageFileTransformer/Estimator. Per-URI calls and
    non-JPEG files fall back to PIL; a file failing both raises (the
    estimator path's strictness).

    ``output_dtype`` picks the WIRE representation (DATA.md):

    - ``"float32"`` (default, unchanged numerics): eager
      ``float32 * scale`` RGB in [0, 255]·scale — the identity-codec
      fallback path;
    - ``"uint8"``: raw uint8 RGB pixels with the ``* scale`` normalize
      DEFERRED to the device — the loader declares
      ``wire_scale``/``wire_offset`` and the ``u8`` wire codec's fused
      prologue applies them (``f32(u8) * f32(scale)``: bit-identical
      to the eager float32 path for uint8-sourced images, at 4× fewer
      host→device bytes). KerasImageFileTransformer/Estimator install
      that codec automatically when the loader declares uint8.

    ``n_threads`` (env ``TPUDL_DECODE_THREADS``; default: native layer
    picks min(batch, cpu_count)) caps the native decoder's thread count
    per batch — set it low when several prepare-pool workers decode
    concurrently so the pools don't oversubscribe the host. The file
    reads feeding ``batch_decode`` are fanned over a small thread pool
    too (reads release the GIL); everything here is thread-safe, so
    concurrent ``batch_decode`` calls from the executor's prepare
    workers are fine."""
    if output_dtype not in ("float32", "uint8"):
        raise ValueError(
            f"output_dtype must be 'float32' or 'uint8', got "
            f"{output_dtype!r}")
    raw_u8 = output_dtype == "uint8"
    if n_threads is None:
        env = os.environ.get("TPUDL_DECODE_THREADS")
        try:
            n_threads = max(1, int(env)) if env else None
        except ValueError:
            n_threads = None  # malformed env: let the native layer pick

    def _pil_one(uri: str) -> np.ndarray:
        img = Image.open(uri).convert("RGB").resize(
            (width, height), Image.BILINEAR)
        if raw_u8:
            return np.asarray(img, np.uint8)
        return np.asarray(img, np.float32) * scale

    def _read_all(uris: list) -> list:
        from tpudl.jobs.retry import io_policy
        from tpudl.testing import faults as _faults

        def _read(u):
            def _once():
                _faults.fire("io.read", path=str(u))
                with open(u, "rb") as f:
                    return f.read()

            # same transient-IO retry as LazyFileColumn._read_raw
            return io_policy().call(_once, kind="imageio.read")

        raws = _parallel_map(
            _read, uris,
            _env_workers("TPUDL_FRAME_IO_WORKERS",
                         LazyFileColumn._IO_WORKERS))
        if raws:  # same per-batch accounting as LazyFileColumn reads
            _obs_metrics.counter("imageio.files_read").inc(len(raws))
            _obs_metrics.counter("imageio.bytes_read").inc(
                sum(len(r) for r in raws))
        return raws

    def loader(uri: str) -> np.ndarray:
        from tpudl import native

        if native.available():
            with open(uri, "rb") as f:
                raw = f.read()
            batch, ok = native.decode_resize_batch(
                [raw], height, width, n_threads=1)
            if ok[0]:
                rgb = batch[0][:, :, ::-1]
                if raw_u8:
                    return np.ascontiguousarray(rgb)
                return rgb.astype(np.float32) * scale
        return _pil_one(uri)

    def batch_decode(uris) -> np.ndarray:
        from tpudl import native

        uris = list(uris)
        if not uris:
            return np.zeros((0, height, width, 3),
                            np.uint8 if raw_u8 else np.float32)
        if not native.available():
            return np.stack([_pil_one(u) for u in uris])
        raws = _read_all(uris)
        batch, ok = native.decode_resize_batch(raws, height, width,
                                               n_threads=n_threads)
        rgb = batch[:, :, :, ::-1]
        out = (np.ascontiguousarray(rgb) if raw_u8
               else rgb.astype(np.float32) * scale)
        for i, good in enumerate(ok):
            if not good:
                out[i] = _pil_one(uris[i])
        return out

    loader.batch_decode = batch_decode
    # wire declaration the data layer reads: with raw uint8 output the
    # deferred normalize (scale, offset) becomes the u8 codec's fused
    # device prologue (tpudl.data.codec.U8Codec)
    loader.output_dtype = output_dtype
    loader.wire_scale = float(scale)
    loader.wire_offset = 0.0
    loader.cache_token = (f"native:{height}x{width}:s{scale!r}"
                          f":{output_dtype}")
    # stateless over thread-safe substrates (fresh buffers per call;
    # libjpeg contexts are per-thread in decode.cpp): the executor's
    # prepare pool may run batch_decode for several batches at once
    loader.thread_safe = True
    return loader


def resizeImage(imageRow: dict, height: int, width: int) -> dict:
    """Bilinear host resize of an image struct, PIL-backed.

    ref: imageIO.createResizeImageUDF (~L180) and ImageUtils.resizeImage —
    both references resize with bilinear-style filtering before the model.
    """
    if (imageRow["height"], imageRow["width"]) == (height, width):
        return imageRow
    t = imageTypeByOrdinal(imageRow["mode"])
    if t.dtype == "float32":
        # PIL has no multi-channel float mode; resize each channel as 'F'
        # so CV_32FC* structs keep dtype and values instead of clipping.
        src = imageStructToArray(imageRow, copy=False)
        chans = [
            np.asarray(
                Image.fromarray(src[:, :, c], mode="F").resize(
                    (width, height), Image.BILINEAR
                ),
                dtype=np.float32,
            )
            for c in range(src.shape[2])
        ]
        arr = np.stack(chans, axis=-1)
        return imageArrayToStruct(arr, origin=imageRow.get("origin", ""))
    pil = imageStructToPIL(imageRow)
    resized = pil.resize((width, height), Image.BILINEAR)
    arr = np.asarray(resized, dtype=np.uint8)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    elif arr.shape[2] == 3:
        arr = arr[:, :, ::-1]  # back to BGR storage
    elif arr.shape[2] == 4:
        arr = arr[:, :, [2, 1, 0, 3]]
    return imageArrayToStruct(arr, origin=imageRow.get("origin", ""))


def createResizeImageUDF(size: tuple[int, int]) -> Callable[[dict], dict]:
    """Row-function form, name-parity with the reference (~L180)."""
    height, width = int(size[0]), int(size[1])

    def _resize(row: dict) -> dict:
        return resizeImage(row, height, width)

    return _resize


class LazyFileColumn(LazyColumn):
    """File-backed :class:`tpudl.frame.frame.LazyColumn`: stores only the
    paths; bytes are read (and optionally transformed) per accessed batch,
    so host RAM is O(batch) at any dataset size — the streaming rebuild of
    the reference's lazy/partitioned ``sc.binaryFiles`` RDD (ref: sparkdl
    imageIO.py filesToDF ~L200). ``reads`` counts file reads, so tests can
    assert laziness directly.

    Worker knobs (the ``map_batches`` prepare pool calls ``_get`` for
    DIFFERENT batches concurrently, so everything here is thread-safe):

    - ``io_workers`` (env ``TPUDL_FRAME_IO_WORKERS``, default 8):
      parallel file reads per batch — reads release the GIL;
    - ``decode_workers`` (env ``TPUDL_FRAME_DECODE_WORKERS``, default
      1): parallel per-row ``transform`` calls within one batch. The
      default keeps the documented serial execution for user decoders
      that never promised thread-safety — including ACROSS batches: the
      executor's prepare pool calls ``_get`` for different batches
      concurrently, so an unmarked transform runs under a column-wide
      lock. A transform carrying ``thread_safe = True``
      (``default_decode`` is marked — PIL and the native decoder both
      release the GIL) or an explicit ``decode_workers > 1`` opts into
      concurrency."""

    def __init__(self, paths, transform: Callable | None = None,
                 probe: Callable | None = None,
                 io_workers: int | None = None,
                 decode_workers: int | None = None):

        self._paths = np.asarray(list(paths), dtype=object)
        self._transform = transform
        self._probe = probe  # (path, raw) -> bool; see validity_mask
        self._validity: np.ndarray | None = None
        self._memo: tuple[bytes, np.ndarray] | None = None
        self.reads = 0
        self._reads_lock = _tsan.named_lock("image.lazyfile.reads")
        self._memo_lock = _tsan.named_lock("image.lazyfile.memo")
        self._transform_lock = _tsan.named_lock("image.lazyfile.transform")
        self.io_workers = int(io_workers if io_workers is not None
                              else _env_workers("TPUDL_FRAME_IO_WORKERS",
                                                self._IO_WORKERS))
        self.decode_workers = int(
            decode_workers if decode_workers is not None
            else _env_workers("TPUDL_FRAME_DECODE_WORKERS", 1))

    _IO_WORKERS = 8  # parallel reads per batch; file IO releases the GIL

    def __len__(self) -> int:
        return len(self._paths)

    def _read_raw(self, i: int) -> bytes:
        from tpudl.jobs.retry import io_policy
        from tpudl.testing import faults as _faults

        def _read():
            # fault point: the robustness suite injects transient IO
            # errors (recovery-after-K) exactly here
            _faults.fire("io.read", path=str(self._paths[i]))
            with open(self._paths[i], "rb") as f:
                return f.read()

        # flaky-storage reads retry under the shared IO policy (bounded
        # backoff; every attempt lands in retry.* counters + the flight
        # recorder) instead of poisoning the row on the first EIO
        raw = io_policy().call(_read, kind="imageio.read")
        with self._reads_lock:
            self.reads += 1
        return raw

    def _read_batch(self, indices: np.ndarray) -> list[bytes]:
        raws = _parallel_map(self._read_raw, indices, self.io_workers)
        # counted per BATCH, not per file: the parallel readers must
        # not contend on the process-wide registry lock per read
        if raws:
            _obs_metrics.counter("imageio.files_read").inc(len(raws))
            _obs_metrics.counter("imageio.bytes_read").inc(
                sum(len(r) for r in raws))
        return raws

    # memo only SMALL accesses (head()/limit()/collect-after-head reuse);
    # executor-sized map batches skip it, so no batch of decoded images
    # stays pinned in host RAM after a pipeline finishes
    _MEMO_MAX_ROWS = 32

    def _decode_batch(self, indices: np.ndarray, raws: list) -> np.ndarray:
        """Batched decode: per-row ``transform`` over the read bytes,
        in row order. A transform that opted into concurrency (marked
        ``thread_safe`` or explicit ``decode_workers > 1``) fans rows
        over a thread pool (order preserved via ``ex.map``) and may run
        for several batches at once under the executor's prepare pool;
        otherwise the column-wide lock keeps the documented serial
        execution even across concurrently-prepared batches."""
        out = np.empty(len(indices), dtype=object)
        if self._transform is None:
            out[:] = raws
            return out
        row = lambda ir: self._transform(self._paths[ir[0]], ir[1])  # noqa: E731
        if (getattr(self._transform, "thread_safe", False)
                or self.decode_workers > 1):
            out[:] = _parallel_map(row, zip(indices, raws),
                                   self.decode_workers)
            return out
        with self._transform_lock:
            out[:] = [row(ir) for ir in zip(indices, raws)]
        return out

    def _get(self, indices: np.ndarray) -> np.ndarray:
        # Small-access memo: re-requesting the SAME index set returns the
        # decoded payloads without touching disk.
        key = indices.tobytes()
        with self._memo_lock:
            memo = self._memo
        if memo is not None and memo[0] == key:
            _obs_metrics.counter("imageio.memo_hits").inc()
            return _copy_rows(memo[1])
        raws = self._read_batch(indices)
        out = self._decode_batch(indices, raws)
        if len(indices) <= self._MEMO_MAX_ROWS:
            with self._memo_lock:
                self._memo = (key, out)
            return _copy_rows(out)
        return out

    def validity_mask(self) -> np.ndarray | None:
        """Per-row validity WITHOUT running the transform. A raw-bytes
        column (no transform) is never null. A transform column answers
        only when it has a ``probe`` — a cheap (path, raw) -> bool
        predicate (e.g. an image header/stream verify, no pixel decode)
        that matches ``transform(...) is None`` nullness; the scan reads
        each file once, probes it, and discards the bytes, so
        ``dropna()`` costs reads but ZERO decodes. Cached: repeated
        dropna/IS NULL scans are free. None = no probe (caller falls
        back to the decode scan)."""
        if self._transform is None:
            return np.ones(len(self), dtype=bool)
        if self._probe is None:
            return None
        if self._validity is None:
            flags = np.empty(len(self), dtype=bool)
            for start in range(0, len(self), 256):
                idx = np.arange(start, min(start + 256, len(self)))
                raws = self._read_batch(idx)
                flags[idx] = [bool(self._probe(self._paths[i], raw))
                              for i, raw in zip(idx, raws)]
            self._validity = flags
        return self._validity

    def fingerprint(self) -> str:
        """Content identity WITHOUT reads or decodes (the Frame
        ``fingerprint`` contract, consumed by the tpudl.data shard
        cache): sha1 over each path + its size + mtime, plus the
        transform's cache token — so a rewritten file, a reordered
        listing, or a different decoder re-keys the cache instead of
        replaying stale shards."""
        import hashlib

        from tpudl.data.dataset import _callable_token, _uri_fingerprint

        h = hashlib.sha1()
        if self._transform is not None:
            h.update(
                f"transform:{_callable_token(self._transform)}\n".encode())
        h.update(_uri_fingerprint(self._paths).encode())
        return h.hexdigest()

    def with_transform(self, transform: Callable,
                       probe: Callable | None = None) -> "LazyFileColumn":
        """Same paths, different per-file transform — how readImages
        derives its lazy decoded column from filesToFrame's byte column
        without re-listing or re-sharding. ``probe`` (optional) is the
        transform's cheap validity twin used by :meth:`validity_mask`."""
        return LazyFileColumn(self._paths, transform, probe=probe,
                              io_workers=self.io_workers,
                              decode_workers=self.decode_workers)


def _env_workers(name: str, default: int) -> int:
    from tpudl.frame.frame import _env_int  # the one env-int parser

    return max(1, _env_int(name, default))


def _parallel_map(fn, items, workers: int) -> list:
    """Order-preserving map, fanned over a thread pool when both the
    item count (≥4) and ``workers`` (>1) justify one — the ONE
    implementation behind batch file reads and batched decodes."""
    items = list(items)
    if len(items) >= 4 and workers > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(min(workers, len(items))) as ex:
            return list(ex.map(fn, items))
    return [fn(i) for i in items]


def _copy_rows(arr: np.ndarray) -> np.ndarray:
    """Fresh object array with dict rows shallow-copied, so a caller
    mutating a returned image struct cannot poison the memo (bytes and
    other immutables pass through)."""
    out = np.empty(len(arr), dtype=object)
    for j, v in enumerate(arr):
        out[j] = dict(v) if isinstance(v, dict) else v
    return out


def _listFiles(path: str | Iterable[str]) -> list[str]:
    if isinstance(path, (list, tuple)):
        return [str(p) for p in path]
    if os.path.isdir(path):
        out = []
        for root, _dirs, files in os.walk(path):
            out.extend(os.path.join(root, f) for f in sorted(files))
        return sorted(out)
    return [path]


def filesToFrame(path, numPartitions: int | None = None,
                 host_sharded: bool = False, lazy: bool = True):
    """A Frame with columns (filePath, fileData) over raw file bytes.

    ref: imageIO.filesToDF (~L200) — sc.binaryFiles → DataFrame[filePath,
    fileData]. Like ``binaryFiles``, the default is LAZY: ``fileData`` is
    a :class:`LazyFileColumn` that stores only paths and reads bytes per
    accessed batch, so host RAM is O(batch) at ImageNet scale
    (``lazy=False`` reads everything up front for small interactive
    frames). ``numPartitions`` is the Frame's partition hint: it sets
    ``map_batches``'s default dispatch granularity
    (``batch_size ≈ rows/numPartitions``). ``host_sharded=True`` keeps
    only THIS host's shard of the file list (tpudl.distributed.host_shard
    — the multi-host input plane replacing Spark partition assignment).
    """
    from tpudl.frame import Frame

    paths = _listFiles(path)
    if host_sharded:
        from tpudl import distributed as D

        paths = D.host_shard(paths)
    if lazy:
        data = LazyFileColumn(paths)
    else:
        datas = []
        for p in paths:
            with open(p, "rb") as f:
                datas.append(f.read())
        data = np.array(datas, dtype=object)
    return Frame(
        {"filePath": np.array(paths, dtype=object), "fileData": data},
        num_partitions=numPartitions,
    )


def _decode_row(decode_f, origin, raw):
    """decode_f semantics shared by the eager and lazy read paths
    (ref: readImagesWithCustomFn ~L220): exceptions/None → None row;
    ndarray results are wrapped into structs with the file origin.

    Deliberately NOT retried: ``raw`` is already in memory, so a decode
    failure is deterministic — bad bytes are bad forever, and PIL
    raises OSError-shaped errors for truncated images, which a retry
    policy would misread as transient and re-decode with backoff,
    burning the prepare pool. The transient-IO retry lives on the READ
    side (``_read_raw`` / ``_read_all``), where flakiness is real."""
    try:
        out = decode_f(raw)
    except Exception as e:
        _obs_metrics.counter("imageio.decode_errors").inc()
        # a SAMPLE lands in the flight recorder's error ring (bounded),
        # so a post-mortem shows WHICH files went bad, not just how many
        # (the doctor's decode-error-storm rule, obs/doctor.py)
        from tpudl.obs import flight as _flight

        _flight.record_error("imageio.decode_error", e, origin=origin)
        return None
    if out is None:
        _obs_metrics.counter("imageio.decode_errors").inc()
        from tpudl.obs import flight as _flight

        _flight.record_error("imageio.decode_error",
                             "decode_f returned None", origin=origin)
        return None
    if isinstance(out, dict):
        out = dict(out)
        if not out.get("origin"):
            out["origin"] = origin
        return out
    return imageArrayToStruct(np.asarray(out), origin=origin)


def readImagesWithCustomFn(path, decode_f, numPartition: int | None = None,
                           host_sharded: bool = False, lazy: bool = True,
                           probe_f: Callable | None = None):
    """Read a directory of images with a custom decode function → Frame["image"].

    ref: imageIO.readImagesWithCustomFn (~L220): binaryFiles → decode_f per
    file → image-struct column; undecodable files become None rows.
    ``decode_f`` takes raw bytes and returns an ndarray (H, W, C) **in BGR
    storage order** or an image struct dict or None. Default is LAZY:
    decode happens per accessed batch (inside ``map_batches``'s prefetch
    thread on the executor path), so neither raw bytes nor decoded structs
    for the whole dataset ever sit in host RAM together. Listing and
    host-sharding are delegated to :func:`filesToFrame` so the byte and
    image paths can never diverge.

    ``probe_f`` (optional, lazy path): a cheap ``raw -> bool`` validity
    twin of ``decode_f`` (True iff decode would succeed). When given,
    ``dropna``/``IS NULL`` classify rows via the probe instead of
    decoding them — :func:`readImages` passes :func:`default_probe`.
    """
    from tpudl.frame import Frame

    files = filesToFrame(path, numPartitions=numPartition,
                         host_sharded=host_sharded, lazy=lazy)
    if lazy:
        tr = lambda p, raw: _decode_row(decode_f, p, raw)  # noqa: E731
        # the serial-decode contract follows decode_f's own declaration
        # (default_decode is marked; custom decoders stay serialized)
        tr.thread_safe = bool(getattr(decode_f, "thread_safe", False))
        # cache identity for the shard cache's frame fingerprint: a
        # different decode_f must re-key cached prepared batches
        from tpudl.data.dataset import _callable_token

        tr.cache_token = "decode:" + _callable_token(decode_f)
        col = files["fileData"].with_transform(
            tr, probe=(lambda p, raw: probe_f(raw)) if probe_f else None)
        return Frame({"image": col}, num_partitions=numPartition)
    structs = [_decode_row(decode_f, origin, raw)
               for origin, raw in zip(files["filePath"], files["fileData"])]
    return Frame({"image": np.array(structs, dtype=object)},
                 num_partitions=numPartition)


def readImages(path, numPartition: int | None = None):
    """Default-decode variant matching pre-2.3 sparkdl readImages —
    native libjpeg for JPEGs when available, PIL otherwise
    (:func:`default_decode`); null scans use the header-verify probe so
    ``readImages(...).dropna()`` never decodes a dropped row."""
    return readImagesWithCustomFn(path, default_decode,
                                  numPartition=numPartition,
                                  probe_f=default_probe)
