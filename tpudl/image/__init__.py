from tpudl.image import imageIO, ops  # noqa: F401
from tpudl.image.imageIO import (  # noqa: F401
    ImageType,
    imageArrayToStruct,
    imageStructToArray,
    imageTypeByName,
    imageTypeByOrdinal,
    readImages,
    readImagesWithCustomFn,
    resizeImage,
)
