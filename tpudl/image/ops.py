"""Device-side image fragments that fuse into jitted model programs.

TPU-native rebuild of the reference's in-graph pieces
(ref: python/sparkdl/graph/pieces.py — buildSpImageConverter ~L30,
buildFlattener ~L90). The reference splices protobuf subgraphs so the
executor makes ONE native call per block (SURVEY.md §3.2 key insight); here
the same fusion falls out of composing these functions inside one
``jax.jit`` — XLA fuses the cast/flip/resize/normalize into the conv
prologue, so the batch crosses host→device exactly once as packed uint8.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

__all__ = [
    "sp_image_converter",
    "flattener",
    "resize_bilinear",
    "to_model_input",
]


def sp_image_converter(batch: jax.Array, channel_order_in: str = "BGR",
                       channel_order_out: str = "RGB") -> jax.Array:
    """Packed image batch (B, H, W, C) → float32, in the model's channel order.

    ref: buildSpImageConverter — decode_raw/reshape/cast/BGR-flip as a graph
    fragment. Decode+reshape happen host-side at pack time (tpudl.frame);
    the cast and channel flip live here so they fuse on device.
    """
    x = batch.astype(jnp.float32)
    if channel_order_in != channel_order_out:
        if {channel_order_in, channel_order_out} == {"BGR", "RGB"}:
            if x.shape[-1] == 4:  # BGRA ⇄ RGBA: alpha stays in place
                x = x[..., jnp.array([2, 1, 0, 3])]
            else:
                x = x[..., ::-1]
        elif channel_order_out == "L" or channel_order_in == "L":
            raise ValueError("grayscale conversion must happen at decode time")
        else:
            raise ValueError(
                f"unsupported channel order {channel_order_in}->{channel_order_out}"
            )
    return x


def flattener(batch: jax.Array) -> jax.Array:
    """(B, ...) → (B, prod) float32 — ref: buildFlattener (~L90), the
    'vector' outputMode of TFImageTransformer."""
    return batch.reshape(batch.shape[0], -1).astype(jnp.float32)


@partial(jax.jit, static_argnums=(1, 2))
def resize_bilinear(batch: jax.Array, height: int, width: int) -> jax.Array:
    """Device-side bilinear resize (B, H, W, C) → (B, height, width, C).

    The JVM reference resizes per-row on CPU (ImageUtils.scala, the historic
    bottleneck per SURVEY.md §3.1); doing it on-device keeps the host loop
    out of the hot path entirely.
    """
    b, _, _, c = batch.shape
    return jax.image.resize(
        batch.astype(jnp.float32), (b, height, width, c), method="bilinear"
    )


def to_model_input(batch: jax.Array, height: int, width: int,
                   channel_order_in: str = "BGR",
                   channel_order_out: str = "RGB") -> jax.Array:
    """Fused convert+resize: the standard prologue for every image model."""
    x = sp_image_converter(batch, channel_order_in, channel_order_out)
    if batch.shape[1] != height or batch.shape[2] != width:
        x = jax.image.resize(
            x, (batch.shape[0], height, width, batch.shape[3]), method="bilinear"
        )
    return x
