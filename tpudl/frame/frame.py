"""Minimal columnar batch abstraction — the Spark DataFrame stand-in.

SURVEY.md §7.1 item 3: "intentionally small — transport, not a query
engine". A Frame is an ordered dict of equal-length named columns. Numeric
columns are numpy arrays; ragged/struct/string columns are object arrays.
``map_batches`` is the executor: it packs host batches, pads and shards
them over the mesh's data axis, runs ONE jitted function per batch (the
reference's one-native-call-per-block invariant, SURVEY.md §3.2), and
appends the outputs as new columns.

The reference equivalent is the Spark DataFrame + TensorFrames MapBlocks
path (ref: sparkdl graph/tensorframes_udf.py, tf_image.py:_transform).
"""

from __future__ import annotations

import os
import sys
import time
import warnings
from collections import deque
from collections.abc import Callable, Iterator, Mapping, Sequence
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from tpudl.testing import faults as _faults

__all__ = ["Frame", "LazyColumn", "concat"]


class LazyColumn:
    """A deferred column: elements materialize per access, so host RAM in
    ``map_batches`` is O(batch) no matter the row count — the lazy input
    plane replacing the reference's ``sc.binaryFiles`` partitioned RDD
    (ref: sparkdl imageIO.py filesToDF ~L200; SURVEY.md §5.8). Concrete
    sources implement ``__len__`` and ``_get(indices) -> object ndarray``
    (see tpudl.image.imageIO.LazyFileColumn)."""

    dtype = np.dtype(object)

    def __len__(self) -> int:  # pragma: no cover - abstract
        raise NotImplementedError

    def _get(self, indices: np.ndarray) -> np.ndarray:  # pragma: no cover
        raise NotImplementedError

    def __getitem__(self, idx):
        n = len(self)
        if isinstance(idx, slice):
            return self._get(np.arange(*idx.indices(n)))
        arr = np.asarray(idx)
        if arr.ndim == 0:
            return self._get(np.array([int(arr)]))[0]
        if arr.dtype == bool:
            arr = np.nonzero(arr)[0]
        return self._get(arr.astype(np.intp))

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def subset(self, indices) -> "LazyColumn":
        """A LAZY row-subset view (used by Frame.filter_rows/dropna):
        keeps only the index mapping, so filtering a million-file column
        costs O(rows) indices, not O(dataset) decoded payloads."""
        return _SubsetLazyColumn(self, np.asarray(indices, dtype=np.intp))

    def validity_mask(self):
        """Optional cheap per-row validity (True = row is not null)
        WITHOUT materializing values — lets ``null_mask`` skip the
        decode scan entirely, so ``dropna().map_batches(...)`` decodes
        each surviving row exactly once (round-3 verdict weak #4).
        Returns None when unknown (caller falls back to a value scan);
        sources that can probe override (LazyFileColumn)."""
        return None

    def fingerprint(self) -> str | None:
        """Optional cheap content identity WITHOUT materializing values
        — the prepared-batch cache (``map_batches(cache_dir=...)``)
        keys on it so a changed source re-prepares instead of replaying
        stale shards. None = unknown (the caller must supply an
        explicit ``cache_key``); file-backed sources override
        (LazyFileColumn hashes paths + sizes + mtimes)."""
        return None


class _SubsetLazyColumn(LazyColumn):
    def __init__(self, base: LazyColumn, indices: np.ndarray):
        self._base = base
        self._indices = indices

    def __len__(self) -> int:
        return len(self._indices)

    def _get(self, indices: np.ndarray) -> np.ndarray:
        return self._base._get(self._indices[indices])

    def validity_mask(self):
        base = self._base.validity_mask()
        return None if base is None else base[self._indices]

    def fingerprint(self):
        base = self._base.fingerprint()
        if base is None:
            return None
        import hashlib

        return hashlib.sha1(
            base.encode() + self._indices.tobytes()).hexdigest()


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class _PipelineInfeed:
    """K-deep bounded infeed fed by an N-worker prepare pool: up to
    ``depth`` batches are packed/decoded (and, on the mesh path,
    host→device-transferred) in flight, by up to ``workers`` concurrent
    threads, while the consumer dispatches compute (the tf.data
    parallel-prepare + prefetch design, Murray et al. 2021; replaces the
    round-5 one-deep single-worker double buffer whose serialized PIL
    decode gated the whole executor). Futures are consumed in submission
    order, so batch order — and therefore output row order — is
    preserved no matter which worker finishes first.

    Backpressure: at most ``depth`` prepared batches exist at once, so
    host RAM stays O(depth · batch) at any dataset size."""

    def __init__(self, prepare: Callable, spans: Sequence[tuple[int, int]],
                 depth: int = 2, workers: int = 2, report=None):
        self._prepare = prepare
        self._spans = spans
        self._depth = max(1, int(depth))
        self._ex = ThreadPoolExecutor(
            max_workers=max(1, min(int(workers), self._depth)),
            thread_name_prefix="tpudl-infeed")
        self._futs: deque = deque()
        self._next = 0
        self._report = report
        while self._next < min(self._depth, len(spans)):
            self._submit()

    def _submit(self):
        from tpudl.obs import attribution as _attr

        # the submitter's attribution scope rides onto the worker: a
        # contextvar does not cross the pool boundary by itself, and
        # the prepare path publishes wire/row charges that must land
        # in the SUBMITTING run's ledger row (OBSERVABILITY.md)
        self._futs.append(self._ex.submit(
            _attr.carry(self._prepare), *self._spans[self._next]))
        self._next += 1

    def get(self, i: int):
        fut = self._futs.popleft()
        if self._report is not None:
            # ready-batch count at the moment the consumer takes one:
            # a depth pinned at 0 means the pool can't keep up (host-
            # bound); pinned at depth-1 means the device is the gate
            self._report.gauge("queue_depth",
                               int(fut.done())
                               + sum(f.done() for f in self._futs))
        t0 = time.perf_counter()
        try:
            out = fut.result()
        except BaseException:
            self.close()
            raise  # the worker's original exception, not a pool wrapper
        if self._report is not None:
            self._report.add("infeed_wait", time.perf_counter() - t0)
        if self._next < len(self._spans):
            self._submit()
        elif not self._futs:
            self._ex.shutdown(wait=False)
        return out

    def close(self):
        """Release the pool even when the consumer loop unwinds early
        (fn raised mid-batch) — queued prepares are cancelled and the
        non-daemon workers exit as soon as any in-flight prepare
        finishes, so nothing lingers reading/transferring."""
        for f in self._futs:
            f.cancel()
        self._futs.clear()
        self._ex.shutdown(wait=False, cancel_futures=True)


class _DispatchWindow:
    """D-deep in-flight dispatch window — the futures-not-syncs executor
    core (ROADMAP item 2). The consumer SUBMITS dispatch calls onto a
    small pool and only blocks once ``depth`` results are already in
    flight, so the tunnel's blocking per-dispatch round-trip for batch N
    rides under the dispatches of N+1..N+D instead of serializing the
    loop. Results are consumed strictly in submission order (the output
    row order is untouched, and bit-identity with depth 1 is structural:
    the same per-batch programs run, only their round-trips overlap).

    The consumer's blocked time lands in the ``dispatch_wait`` stage —
    the UNHIDDEN dispatch residue, the analogue of ``infeed_wait`` on
    the prepare side — while the pool threads' ``dispatch`` stage
    seconds become pool-summed (like ``prepare``, they may exceed wall
    time; tpudl.obs.roofline reads ``dispatch_wait`` when present so
    overlapped time is not attributed twice). ``dispatch_inflight`` is
    gauged at every submit; its max can never exceed ``depth``.

    The first dispatch runs alone (the window stays at 1 until the
    first result is consumed): one thread traces/compiles the program,
    and the outfeed mode is picked before the window floods."""

    def __init__(self, depth: int, report):
        self._depth = max(1, int(depth))
        self._ex = ThreadPoolExecutor(max_workers=self._depth,
                                      thread_name_prefix="tpudl-dispatch")
        self._futs: deque = deque()
        self._report = report
        self._primed = False

    def __len__(self) -> int:
        return len(self._futs)

    def full(self) -> bool:
        if not self._primed:
            return bool(self._futs)  # warmup: one dispatch at a time
        return len(self._futs) >= self._depth

    def submit(self, call):
        from tpudl.obs import attribution as _attr

        # carry the consumer's attribution scope onto the dispatch
        # thread (dispatch_s and compile_s charges happen there)
        self._futs.append(self._ex.submit(_attr.carry(call)))
        self._report.gauge("dispatch_inflight", len(self._futs))

    def pop(self):
        """Oldest in-flight dispatch's (result, n_pad), in submission
        order. Blocks only when that dispatch is still in its round
        trip — the wait IS the unhidden residue, accounted as its own
        ``dispatch_wait`` stage (deliberately NOT ``dispatch``: the
        pool already timed the call there)."""
        fut = self._futs.popleft()
        self._primed = True
        with self._report.stage("dispatch_wait"):
            try:
                out = fut.result()
            except BaseException:
                self.close()
                raise  # the dispatch thread's original exception
        return out

    def close(self):
        """Release the pool on every exit path (mirrors
        _PipelineInfeed.close): queued dispatches are cancelled and the
        workers exit as soon as any in-flight call returns."""
        for f in self._futs:
            f.cancel()
        self._futs.clear()
        self._ex.shutdown(wait=False, cancel_futures=True)


def _start_host_copies(result) -> None:
    """Start the device→host copy of every output of one dispatch, ON
    the thread that issued it — D2H of batch N then overlaps the
    dispatch of N+1..N+D (and, at depth 1, the next batch's prepare),
    for BOTH outfeed modes: the windowed drain's ``np.asarray`` and the
    accumulated fetch both find their copies already in flight. Host
    arrays (host fns) have no async copy and need none."""
    for r in result:
        if hasattr(r, "copy_to_host_async"):
            r.copy_to_host_async()


def _is_device_fn(fn) -> bool:
    """Jitted/device-fn detection: any ``jax.stages.Wrapped`` (jit,
    pjit, AOT wrappers) counts, plus the legacy ``lower`` probe for
    compiled executables. A plain-python wrapper AROUND a jitted call
    is still undetectable — ``map_batches(device_fn=True)`` is the
    explicit override (and the executor warns once when outputs come
    back as device arrays anyway)."""
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            if isinstance(fn, jax.stages.Wrapped):
                return True
        # tpudl: ignore[swallowed-except] — jax API drift guard: an
        # exotic jax falls through to the hasattr heuristic below
        except Exception:  # pragma: no cover - jax API drift
            pass
    return hasattr(fn, "lower")


_warned_device_outputs = False


def _warn_device_outputs_once():
    global _warned_device_outputs
    if _warned_device_outputs:
        return
    _warned_device_outputs = True
    warnings.warn(
        "map_batches classified fn as a HOST function (prefetch and "
        "fused dispatch disabled) but its outputs are device arrays — "
        "fn likely wraps a jitted call the heuristic cannot see. Pass "
        "device_fn=True (or prefetch=True) to enable the pipelined "
        "executor.", RuntimeWarning, stacklevel=3)


def _fused_wrapper(fn: Callable, m: int, *, n_args: int | None = None,
                   donate: bool = False) -> Callable:
    """ONE compiled program that runs ``m`` microbatches per dispatch:
    inputs are stacked (m, B, ...), a ``lax.scan`` applies ``fn`` to
    each microbatch on-device, outputs come back flattened (m·B, ...).
    The tunnel pays one dispatch round-trip per m batches instead of
    per batch — the 485 vs 7,472 img/s gap in PROFILE.md is almost
    entirely that per-step round-trip (GPipe-style multi-step fusion,
    Huang et al. 2019).

    ``donate=True`` marks every stacked input as donated
    (``jax.jit(..., donate_argnums=...)``): XLA may reuse the staged
    input buffers for outputs/temps, so steady-state fused dispatch
    allocates nothing extra device-side. Safe by construction here —
    the stacked arrays are freshly ``np.stack``-built host batches the
    executor never reads again (donation changes no values; the
    depth-1/donation-off bit-identity tests pin this).

    The wrapper is cached ON fn itself (``fn._tpudl_fused[key]``): the
    fused program — whose closure pins fn and, transitively, its model
    weights — then lives exactly as long as fn does; the fn↔wrapper
    reference cycle is an ordinary gc-collectible cycle, so a discarded
    transformer frees both (a module-level cache keyed by fn would keep
    the pair alive forever: the wrapper's closure references its own
    key)."""
    donate = bool(donate and n_args)
    key = (int(m), donate)
    per_fn = getattr(fn, "_tpudl_fused", None)
    if per_fn is not None and key in per_fn:
        return per_fn[key]
    import jax

    def fused(*stacked):
        def body(carry, xs):
            r = fn(*xs)
            if not isinstance(r, (tuple, list)):
                r = (r,)
            return carry, tuple(r)

        _, ys = jax.lax.scan(body, None, tuple(stacked))
        return tuple(
            y.reshape((y.shape[0] * y.shape[1],) + y.shape[2:]) for y in ys)

    if donate:
        from tpudl.data import codec as _codec

        _codec.filter_unusable_donation_warning()
        fused = jax.jit(fused, donate_argnums=tuple(range(int(n_args))))
    else:
        fused = jax.jit(fused)

    try:
        if per_fn is None:
            per_fn = fn._tpudl_fused = {}
        per_fn[key] = fused
    except (AttributeError, TypeError):  # fn rejects attributes: uncached
        pass
    return fused


def mesh_fuse_ok(batch_size: int, mesh) -> bool:
    """Can the fused multi-step program run under ``mesh`` at this
    batch geometry? THE one rule — shared by the executor's fuse gate
    and ``ImageBatchWarmup`` (which must warm exactly the program
    variant the timed transform will run): the fast path must be armed
    and full batches must shard evenly over the data axis — a fused
    group stacks M padded microbatches into ``(M, B_pad, ...)``, and
    per-microbatch padding would leave pad rows INTERLEAVED in the
    flattened output. Pick ``batch_size % data-axis == 0`` to enable
    mesh fusion; the ragged TAIL batch always pads + dispatches
    per-batch either way. ``mesh=None`` imposes no constraint.

    On a 2-D ``(data, model)`` grid only the DATA-axis size gates:
    batches shard over ``data`` while the model axis holds parameter
    shards (which never ride the transfer edge — transfer_batch passes
    model-resident leaves through untouched), so a 4×2 mesh fuses at
    any ``batch_size % 4 == 0``, not ``% 8``."""
    if mesh is None:
        return True
    if os.environ.get("TPUDL_MESH_FAST_PATH", "1") == "0":
        return False
    from tpudl import mesh as M

    return int(batch_size) % mesh.shape[M.DATA_AXIS] == 0


def _as_column(values) -> np.ndarray:
    if isinstance(values, LazyColumn):
        return values  # deferred source; materializes per access
    if isinstance(values, np.ndarray):
        return values
    values = list(values)
    if values and isinstance(values[0], (dict, bytes, str, type(None))):
        col = np.empty(len(values), dtype=object)
        col[:] = values
        return col
    try:
        return np.asarray(values)
    except Exception:
        col = np.empty(len(values), dtype=object)
        col[:] = values
        return col


class Frame:
    """Ordered named columns of equal length."""

    def __init__(self, columns: Mapping[str, object], num_partitions: int | None = None):
        self._cols: dict[str, np.ndarray] = {}
        n = None
        for name, values in columns.items():
            col = _as_column(values)
            if n is None:
                n = len(col)
            elif len(col) != n:
                raise ValueError(
                    f"column {name!r} has length {len(col)}, expected {n}"
                )
            self._cols[str(name)] = col
        self._n = n or 0
        self.num_partitions = num_partitions

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_files(cls, path, num_partitions: int | None = None,
                   host_sharded: bool = False) -> "Frame":
        """Streaming file source: columns (filePath, fileData) where the
        bytes column is LAZY — paths only in RAM, reads deferred to the
        accessed batch (the ``sc.binaryFiles`` contract; delegates to
        :func:`tpudl.image.imageIO.filesToFrame`)."""
        from tpudl.image.imageIO import filesToFrame

        return filesToFrame(path, numPartitions=num_partitions,
                            host_sharded=host_sharded, lazy=True)

    # -- schema/access ----------------------------------------------------
    @property
    def columns(self) -> list[str]:
        return list(self._cols)

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, name: str) -> np.ndarray:
        return self._cols[name]

    def __contains__(self, name: str) -> bool:
        return name in self._cols

    def __repr__(self) -> str:
        cols = ", ".join(f"{k}:{v.dtype}" for k, v in self._cols.items())
        return f"Frame[{self._n} rows]({cols})"

    # -- relational-lite --------------------------------------------------
    def select(self, *names: str) -> "Frame":
        missing = [n for n in names if n not in self._cols]
        if missing:
            raise KeyError(f"unknown columns {missing}; have {self.columns}")
        return Frame({n: self._cols[n] for n in names}, self.num_partitions)

    def with_column(self, name: str, values) -> "Frame":
        col = _as_column(values)
        if len(col) != self._n:
            raise ValueError(f"column length {len(col)} != frame length {self._n}")
        out = dict(self._cols)
        out[name] = col
        return Frame(out, self.num_partitions)

    def with_column_renamed(self, old: str, new: str) -> "Frame":
        if new != old and new in self._cols:
            raise ValueError(f"cannot rename {old!r} to existing column {new!r}")
        return Frame(
            {new if k == old else k: v for k, v in self._cols.items()},
            self.num_partitions,
        )

    def drop(self, *names: str) -> "Frame":
        return Frame(
            {k: v for k, v in self._cols.items() if k not in names},
            self.num_partitions,
        )

    def filter_rows(self, mask) -> "Frame":
        mask = np.asarray(mask, dtype=bool)
        idx = np.nonzero(mask)[0]
        return Frame(
            {k: (v.subset(idx) if isinstance(v, LazyColumn) else v[mask])
             for k, v in self._cols.items()},
            self.num_partitions)

    def take(self, indices) -> "Frame":
        """Rows by integer index, in the GIVEN order (duplicates
        allowed) — the ORDER BY backbone; filter_rows is the boolean
        sibling."""
        idx = np.asarray(indices, dtype=int)
        return Frame(
            {k: (v.subset(idx) if isinstance(v, LazyColumn) else v[idx])
             for k, v in self._cols.items()},
            self.num_partitions)

    def dropna(self, subset: Sequence[str] | None = None) -> "Frame":
        """Drop rows with None/NaN in ``subset`` (default: all columns).
        On a LazyColumn nullness comes from the column's cheap
        ``validity_mask`` probe when it has one (NO decode at all — see
        ``null_mask``); otherwise the scan streams in chunks (O(chunk)
        held payloads, decoded once for the scan). Either way the result
        keeps a lazy subset VIEW — filtering a huge readImages() frame
        stays O(batch) in host RAM."""
        names = list(subset) if subset else self.columns
        mask = np.ones(self._n, dtype=bool)
        for n in names:
            mask &= ~null_mask(self._cols[n])
        return self.filter_rows(mask)

    def head(self, n: int = 5) -> "Frame":
        # LazyColumns keep a lazy subset VIEW (like filter_rows) so
        # 'SELECT path FROM t LIMIT n' never reads bytes the projection
        # doesn't use; np.arange(len)[:n] preserves python slice
        # semantics (incl. negative n) so lazy/eager columns agree
        return Frame(
            {k: (v.subset(np.arange(len(v))[:n])
                 if isinstance(v, LazyColumn) else v[:n])
             for k, v in self._cols.items()}, self.num_partitions)

    def limit(self, n: int) -> "Frame":
        return self.head(n)

    def to_dict(self) -> dict[str, np.ndarray]:
        return dict(self._cols)

    def fingerprint(self, cols: Sequence[str] | None = None) -> str:
        """Content identity of the named columns (sha1 hex) — the
        prepared-batch cache's key material (``map_batches(cache_dir=
        ...)``), so a changed input re-prepares instead of replaying
        stale shards. Lazy columns answer via their cheap
        ``fingerprint`` probe (LazyFileColumn: paths + sizes + mtimes —
        NO reads, NO decodes); eager columns hash their bytes (only
        paid when caching is on). A lazy column without a fingerprint
        raises — pass ``cache_key`` explicitly for such sources."""
        import hashlib

        h = hashlib.sha1()
        for name in (list(cols) if cols is not None else self.columns):
            col = self._cols[name]
            h.update(f"col:{name}\n".encode())
            if isinstance(col, LazyColumn):
                fp = col.fingerprint()
                if fp is None:
                    raise ValueError(
                        f"lazy column {name!r} has no content "
                        "fingerprint; pass an explicit cache_key= to "
                        "map_batches/Dataset to enable caching")
                h.update(str(fp).encode())
            elif col.dtype == object:
                for v in col:
                    _hash_value(h, v)
            else:
                h.update(f"{col.dtype}{col.shape}".encode())
                h.update(np.ascontiguousarray(col).tobytes())
        return h.hexdigest()

    def rows(self) -> Iterator[dict]:
        for i in range(self._n):
            yield {k: v[i] for k, v in self._cols.items()}

    def collect(self) -> list[dict]:
        return list(self.rows())

    # -- executor ---------------------------------------------------------
    def iter_batches(self, batch_size: int) -> Iterator[tuple[int, int]]:
        for start in range(0, self._n, batch_size):
            yield start, min(start + batch_size, self._n)

    def map_batches(self, fn: Callable, input_cols: Sequence[str],
                    output_cols: Sequence[str], *,
                    supervise: bool | None = None,
                    **kwargs) -> "Frame":
        """Run ``fn`` over the frame in device-sized batches; append outputs.

        ``fn`` maps packed input arrays → one array or a tuple matching
        ``output_cols``. ``pack`` converts a column slice (object arrays
        included) to a stacked numpy batch; defaults to ``np.stack``-like
        coercion. When ``mesh`` is given, batches are padded to the
        data-axis size and transferred as ONE batched async
        ``device_put`` under ``NamedSharding(P('data'))``
        (``tpudl.mesh.transfer_batch`` — the infeed edge); outputs are
        fetched and unpadded, and the SAME fast path below (fusion,
        async window, donation, codec, autotune) stays armed — no
        parallel-only code path (``TPUDL_MESH_FAST_PATH=0`` is the
        conservative pre-ISSUE-11 escape hatch). This is the rebuild of
        the reference's per-partition TensorFrames MapBlocks execution,
        minus the JVM.

        A 2-D ``(data, model)`` mesh works identically (ISSUE 16):
        batches still ride the one transfer edge sharded over ``data``,
        while ``fn``'s model-sharded closures/params stay device-
        resident under their ``P(None, 'model')``-family shardings
        (transfer_batch passes them through without gathering) — every
        gate keys on the DATA-axis size, so the full fast path stays
        armed at ``n_model > 1``.

        ``batch_size`` defaults to the frame's ``num_partitions`` hint
        (``ceil(rows / num_partitions)`` — the Spark-side meaning of a
        partition as the unit of executor dispatch), else 256.

        The executor is a staged pipeline (PIPELINE.md has the stage-time
        model; every stage reports into ``tpudl.obs.last_pipeline_report``):

        1. ``prepare`` pool — up to ``prepare_workers`` threads
           (``TPUDL_FRAME_PREPARE_WORKERS``, default 2) pack/decode
           batches concurrently, so a 256-image PIL decode no longer
           serializes with compute;
        2. a ``prefetch_depth``-deep bounded infeed queue
           (``TPUDL_FRAME_PREFETCH_DEPTH``, default 2) — host RAM stays
           O(depth · batch);
        3. multi-step fused dispatch — when ``fn`` is a jitted device fn
           and batches are full-size, ``fuse_steps``
           (``TPUDL_FRAME_FUSE_STEPS``, default 1 = off) microbatches are
           stacked and executed by ONE compiled ``lax.scan`` program, so
           a tunneled backend pays one dispatch round-trip per M batches
           (the per-step dispatch latency is ~93% of wall time on the
           judged config, PROFILE.md). Under a ``mesh`` the stacked
           group transfers once with ``NamedSharding(P(None, 'data'))``
           and each scanned microbatch runs data-sharded (fusion needs
           ``batch_size % data-axis == 0`` there — see PIPELINE.md
           "Mesh-native execution");
        4. a ``dispatch_depth``-deep ASYNC dispatch window
           (``TPUDL_FRAME_DISPATCH_DEPTH``, default 2; device fns —
           sharded mesh outputs are async futures too) — up to D
           dispatches stay in flight as futures, so the blocking
           per-dispatch round-trip of batch N rides under the
           dispatches of N+1..N+D; the hot loop never calls
           ``block_until_ready``/``np.asarray`` on a device result.
           With ``donate`` (``TPUDL_FRAME_DONATE``, default on), fused
           and codec-wrapped programs donate their input buffers
           (``jax.jit(..., donate_argnums=...)``) so steady-state
           dispatch allocates nothing extra device-side; shard-cache
           hits are handed to donating programs as writable COPIES,
           never the cache's read-only mmap;
        5. the windowed/accumulated async outfeed — the device→host
           copy of every output starts AT dispatch
           (``copy_to_host_async``), in both outfeed modes, so D2H of
           batch N overlaps later dispatches.

        ``autotune`` (``TPUDL_FRAME_AUTOTUNE``, default on): any of
        ``fuse_steps``/``dispatch_depth``/``prefetch_depth`` left unset
        (no kwarg, no env) is SEEDED from the knob advisor's ranked
        recommendations over the previous run's PipelineReport
        (``obs.analyze_roofline()`` — wire probe + device ms/step +
        report gauges; PIPELINE.md "Async dispatch"). The chosen values
        land on the report's config (``autotuned`` names the seeded
        knobs); explicit kwargs/env always win.

        ``prefetch`` defaults to on for device fns, off for host fns
        (whose inputs must stay numpy). ``device_fn`` overrides the
        detection — the heuristic recognizes ``jax.stages.Wrapped``
        (jit/pjit) and ``.lower()``-bearing executables, but NOT a
        plain-python wrapper around a jitted call; the executor warns
        once when a "host" fn returns device arrays.
        ``TPUDL_FRAME_PREFETCH=0`` force-disables the whole pipelined
        executor — prefetch AND fusion — for the bench A/B arm.

        The ``tpudl.data`` knobs (DATA.md has the operator guide):

        - ``wire_codec`` (env ``TPUDL_WIRE_CODEC``): a codec name
          ('u8', 'bf16', 'identity', 'auto') or a
          :class:`tpudl.data.WireCodec` — prepared batches are
          wire-ENCODED host-side and a restoring prologue is fused in
          front of ``fn`` on device, so an image batch ships as uint8
          + scale instead of float32 (4× fewer H2D bytes). Device fns
          only; a host fn gets a warn-once and the identity path.
        - ``cache_dir`` (env ``TPUDL_DATA_CACHE_DIR``): prepared
          (packed + encoded) batches persist to a checksummed sharded
          cache keyed by the frame's content ``fingerprint`` — repeat
          runs and epochs ≥ 2 over the same inputs skip decode/pack
          entirely. ``cache_key`` overrides the fingerprint for frames
          whose columns cannot self-identify (raises otherwise).
        - ``device_cache`` (env ``TPUDL_DATA_DEVICE_CACHE``): pin the
          prepared, wire-ENCODED batches in device memory (HBM) under
          the ``TPUDL_DATA_HBM_BUDGET_MB`` budget — the top tier of the
          cache hierarchy (DATA.md "Cache hierarchy"). A hit bypasses
          prepare, codec encode and the H2D transfer entirely and feeds
          the dispatch window a resident buffer; epochs ≥ 2 of a
          fitting run ship ZERO wire bytes. Entries are keyed by the
          same fingerprint identity as the shard cache plus the mesh
          topology (a shard resident under one ``NamedSharding`` is a
          key miss on any other mesh). Resident buffers are never
          donated (hits route through the non-donating program —
          ``data.hbm.donation_blocked`` counts the fallback), and
          residency forces ``fuse_steps`` to 1: fusion amortizes the
          per-dispatch round-trip by re-stacking HOST batches, which
          would defeat the residency it rides with. Device fns only.
        The ``tpudl.compile`` knobs (COMPILE.md):

        - ``buckets`` (env ``TPUDL_COMPILE_BUCKETS``, default off): a
          bucket-ladder spec (``"pow2"``, ``"pow2ish"``/``"1"``, an
          explicit ``"8,16,32"`` list, or a
          :class:`tpudl.compile.BucketLadder`). Ragged dispatch shapes
          pad up to the smallest ladder rung (repeating row 0, pad
          rows stripped from the outputs — the mesh-pad discipline),
          so an arbitrary mix of batch sizes runs through O(log n)
          compiled programs instead of one retrace per novel shape.
          If the primary ``batch_size`` itself is not a rung, fusion
          drops to per-batch dispatch (a fused stack would interleave
          the pad rows).
        - ``aot`` (env ``TPUDL_COMPILE_AOT``, default off): consult
          the AOT program store at dispatch — a hit executes a
          precompiled executable (restored from disk on process start:
          zero trace, zero compile); a miss runs the jitted path
          unchanged and background-compiles the signature so the NEXT
          process starts warm. ``compile.{hits,misses}`` count both.
        ``supervise`` (env ``TPUDL_FRAME_DEGRADE``, default OFF): arm
        the fault-containment supervisor (FAULTS.md,
        :mod:`tpudl.frame.supervisor`). Classified executor faults
        retry the run down a bounded degradation ladder — device OOM
        evicts unpinned HBM-cache entries and retries, transient
        transfer/IO faults ride the ONE shared RetryPolicy, repeated
        stage faults halve ``dispatch_depth``, then drop ``fuse_steps``
        to 1, then disable donation, then fall back to the conservative
        serial arm — every rung bitwise-identical to a healthy run of
        that config, recorded as a ``frame.degraded`` flight event and
        on the report (``degraded_to``, ``recovered_batches``).
        Exhaustion (``TPUDL_FRAME_DEGRADE_MAX_RUNGS``) writes a flight
        dump and raises a TYPED taxonomy error (``DeviceOOM`` /
        ``TransferError`` / ``RecompileStorm`` / ``StageFault``)
        chained to the original — never a raw pool-unwind error.
        """
        from tpudl.frame import supervisor as _sup

        if not _sup.enabled(supervise):
            # unarmed: ONE env read, straight into the executor (the
            # overhead guard in tests/test_supervisor.py pins this)
            return self._map_batches_impl(fn, input_cols, output_cols,
                                          **kwargs)
        sup = _sup.Supervisor()

        def attempt(overrides):
            kw = dict(kwargs)
            kw.update(overrides)  # rung knobs beat the caller's
            return self._map_batches_impl(fn, input_cols, output_cols,
                                          _supervisor=sup, **kw)

        return sup.supervise(attempt)

    def _map_batches_impl(
        self,
        fn: Callable,
        input_cols: Sequence[str],
        output_cols: Sequence[str],
        *,
        batch_size: int | None = None,
        mesh=None,
        pack: Callable | None = None,
        check_finite: bool = False,
        prefetch: bool | None = None,
        prefetch_depth: int | None = None,
        prepare_workers: int | None = None,
        fuse_steps: int | None = None,
        dispatch_depth: int | None = None,
        donate: bool | None = None,
        autotune: bool | None = None,
        device_fn: bool | None = None,
        wire_codec=None,
        cache_dir: str | None = None,
        cache_key: str | None = None,
        device_cache: bool | None = None,
        buckets=None,
        aot: bool | None = None,
        _supervisor=None,
    ) -> "Frame":
        """One executor attempt: the full staged pipeline (the
        public :meth:`map_batches` carries the user-facing contract
        and, when supervision is armed, retries this body down the
        degradation ladder — ``_supervisor`` is its ladder-state
        handle)."""
        if batch_size is None:
            if self.num_partitions:
                batch_size = max(1, -(-self._n // int(self.num_partitions)))
            else:
                batch_size = 256
        heuristic = device_fn is None
        device_flag = ((mesh is not None or _is_device_fn(fn))
                       if heuristic else bool(device_fn))
        # the fast-path gates (fusion / window / donation / autotune)
        # need fn to REALLY be a device fn: under a mesh device_flag is
        # forced True (sharded inputs make prefetch/codec routing right
        # even for host fns), but jitting a numpy fn into a fused scan
        # would crash at trace time, and a host fn must never run
        # concurrently on the window's pool threads (mesh=None already
        # enforces this via device_flag — same rule, same heuristic)
        device_fn_real = (_is_device_fn(fn) if heuristic
                          else bool(device_fn))
        if prefetch is None:
            prefetch = device_flag
        killed = os.environ.get("TPUDL_FRAME_PREFETCH", "1") == "0"
        if killed:
            prefetch = False
        # -- mesh fast path (ISSUE 11) ------------------------------------
        # the mesh executor runs the SAME fast path as single-chip:
        # fused multi-step dispatch, the async dispatch window, buffer
        # donation, codec fusion and autotune all stay armed under a
        # mesh. TPUDL_MESH_FAST_PATH=0 reverts to the pre-ISSUE-11
        # conservative mesh executor (serial blocking dispatch,
        # per-batch transfer) — the A/B arm and the escape hatch.
        mesh_fast = (mesh is not None
                     and os.environ.get("TPUDL_MESH_FAST_PATH", "1")
                     != "0")
        mesh_slow = mesh is not None and not mesh_fast
        # -- autotune: seed unset executor knobs from the advisor ---------
        # (ROADMAP 2's closed loop: fuse_steps / dispatch_depth /
        # prefetch_depth come from obs.analyze_roofline()'s ranked recs
        # over the PREVIOUS run's report + the wire probe + device
        # ms/step, instead of hand-set env knobs. Explicit kwargs and
        # env settings always win; the serial kill switch and host fns
        # never autotune.)
        autotune_on = (
            (bool(autotune) if autotune is not None
             else os.environ.get("TPUDL_FRAME_AUTOTUNE", "1") != "0")
            and not killed and device_fn_real and not mesh_slow)
        seeds: dict = {}
        seeded: list[str] = []

        def _resolve(kwarg, env_name, seed_key, default):
            if kwarg is not None:
                return int(kwarg)
            if os.environ.get(env_name, "") != "":
                return _env_int(env_name, default)
            if seed_key in seeds:
                seeded.append(seed_key)
                return int(seeds[seed_key])
            return default

        if autotune_on and any(
                k is None and os.environ.get(e, "") == ""
                for k, e in ((fuse_steps, "TPUDL_FRAME_FUSE_STEPS"),
                             (dispatch_depth, "TPUDL_FRAME_DISPATCH_DEPTH"),
                             (prefetch_depth, "TPUDL_FRAME_PREFETCH_DEPTH"))):
            # read the PREVIOUS run's report before this run files its
            # own into the ring below; never probe the wire from here
            # (the cached probe / TPUDL_WIRE_MBPS is consumed if known).
            # batch_size + mesh shape are the workload guard: the
            # advisor's numbers are per-dispatch quantities at that
            # batch geometry AND topology — a process alternating a
            # sharded featurizer and a single-chip scorer must not
            # cross-tune them
            from tpudl.obs import roofline as _roofline

            seeds = _roofline.autotune_seed(
                allow_probe=False,
                match={"batch_size": int(batch_size),
                       "mesh": (dict(mesh.shape) if mesh is not None
                                else None)})
        depth = _resolve(prefetch_depth, "TPUDL_FRAME_PREFETCH_DEPTH",
                         "prefetch_depth", 2)
        workers = (int(prepare_workers) if prepare_workers is not None
                   else _env_int("TPUDL_FRAME_PREPARE_WORKERS", 2))
        d_depth = max(1, _resolve(dispatch_depth,
                                  "TPUDL_FRAME_DISPATCH_DEPTH",
                                  "dispatch_depth", 2))
        if killed or mesh_slow or not device_fn_real:
            # the async window needs a REAL device fn returning futures
            # (sharded jax arrays are futures too — ISSUE 11); host fns
            # stay serial (their in-place mutations would race on the
            # pool), and the kill switches must yield the serial
            # executor (bench A/B arms)
            d_depth = 1
        donate_flag = (bool(donate) if donate is not None
                       else os.environ.get("TPUDL_FRAME_DONATE", "1")
                       != "0")
        if killed or mesh_slow or not device_fn_real:
            donate_flag = False
        if d_depth > 1 and prefetch and prefetch_depth is None and \
                os.environ.get("TPUDL_FRAME_PREFETCH_DEPTH", "") == "" \
                and "prefetch_depth" not in seeds:
            # a D-deep dispatch window drains prepared batches D at a
            # time: the DEFAULT infeed must be able to feed it (explicit
            # kwarg/env/seeded depths are respected as set)
            depth = max(depth, d_depth)
        if (prepare_workers is None
                and "TPUDL_FRAME_PREPARE_WORKERS" not in os.environ
                and pack is not None
                and not getattr(pack, "thread_safe", False)):
            # a user-supplied pack never promised thread-safety (same
            # contract as LazyFileColumn's decode_workers=1 default):
            # run it single-worker unless the caller opted in — via the
            # kwarg/env, or by marking the callable ``pack.thread_safe
            # = True`` (the first-party packs are marked)
            workers = 1
        fuse = max(1, _resolve(fuse_steps, "TPUDL_FRAME_FUSE_STEPS",
                               "fuse_steps", 1))
        if killed or mesh_slow or not device_fn_real:
            # fusion traces fn into one jitted scan program: it needs a
            # REAL device fn (a numpy fn would crash at trace time),
            # and the A/B kill switches must yield the serial executor
            fuse = 1
        if mesh is not None:
            from tpudl import mesh as M  # jax import only on the mesh path

            multiple = mesh.shape[M.DATA_AXIS]
            if fuse > 1 and not mesh_fuse_ok(batch_size, mesh):
                fuse = 1
                if "fuse_steps" in seeded:
                    # an autotune seed this geometry can never engage
                    # must not be REPORTED as applied (the `autotuned`
                    # contract: listed knobs carry the advisor's values)
                    seeded.remove("fuse_steps")
        missing = [c for c in input_cols if c not in self._cols]
        if missing:
            raise KeyError(f"unknown input columns {missing}")

        from tpudl import obs  # deferred: host-only frames stay light
        from tpudl.obs import attribution as _attr
        from tpudl.obs import flight as _flight

        report = obs.PipelineReport()

        # -- tpudl.data: wire codec + sharded prepared-batch cache -------
        if wire_codec is None:
            wire_codec = os.environ.get("TPUDL_WIRE_CODEC") or None
        if cache_dir is None:
            cache_dir = os.environ.get("TPUDL_DATA_CACHE_DIR") or None
        dc_flag = (bool(device_cache) if device_cache is not None
                   else os.environ.get("TPUDL_DATA_DEVICE_CACHE", "0")
                   == "1")
        # the HBM tier needs a REAL device fn (resident jax arrays
        # would break a host fn's numpy contract) and the fast path
        # armed; the serial kill switch and the conservative mesh arm
        # stay residency-free (their A/B role is the un-cached wire)
        dc_flag = (dc_flag and device_fn_real and not killed
                   and not mesh_slow)
        plan = cache = None
        dcache = dkey = None
        if wire_codec is not None or cache_dir is not None or dc_flag:
            from tpudl.data import codec as _codec

            if wire_codec is not None and not device_flag:
                # a host fn's inputs must stay restored numpy — the
                # device prologue can never run, so shipping encoded
                # bytes would hand fn the wrong values
                _codec.warn_host_fn_codec_once()
                wire_codec = None
            if wire_codec is not None:
                plan = _codec.CodecPlan(wire_codec, len(input_cols),
                                        report=report)
            material = None
            pack_token = None
            if cache_dir is not None or dc_flag:
                from tpudl.data import shards as _shards

                material = cache_key
                if material is None:
                    try:
                        material = self.fingerprint(input_cols)
                    except ValueError:
                        # a lazy column with no content fingerprint:
                        # EXPLICITLY-requested caching (cache_dir, or
                        # device_cache=True as a kwarg) keeps the
                        # clear pass-cache_key error — but the
                        # process-wide TPUDL_DATA_DEVICE_CACHE=1
                        # accelerator must never turn a working
                        # uncached run into a crash; residency just
                        # disarms (plain wire transfer, the device
                        # cache's degrade-never-error contract)
                        if cache_dir is not None or device_cache:
                            raise
                        dc_flag = False
            if cache_dir is not None or dc_flag:
                # the pack is part of the prepared bytes' identity: a
                # different pack (e.g. a loader with another geometry)
                # must re-key, not replay. A pack without an explicit
                # ``cache_token`` keys by repr — object address, so the
                # cache is only reused by the SAME pack object (never
                # stale: two lambdas at one code location, or an edited
                # function body, share a qualname but not an address).
                # First-party packs carry tokens; set one on a custom
                # pack to opt into cross-run reuse (DATA.md).
                pack_token = ("default" if pack is None else
                              getattr(pack, "cache_token", None)
                              or repr(pack))
                key_str = _shards.cache_key(
                    material,
                    cols=",".join(input_cols),
                    batch=int(batch_size),
                    codec=_codec.spec_token(wire_codec),
                    pack=pack_token,
                    # the sanitizer runs on the MISS
                    # path only; a run asking for it
                    # must not warm-skip the check
                    finite=bool(check_finite),
                    layout="map_batches_v1")
            if cache_dir is not None:
                cache = _shards.ShardCache(cache_dir, key_str)
                if plan is not None and cache.meta.get("codecs"):
                    # warm replay MUST restore with the codecs the
                    # shards were encoded with, not a fresh auto pick
                    plan.adopt(cache.meta["codecs"])
            if dc_flag:
                from tpudl.data import device_cache as _dc

                # SAME key material as the shard cache + the mesh
                # topology: a resident shard sharded for one mesh is a
                # key MISS on any other (never resharded in place)
                dkey = _dc.run_key(key_str, mesh)
                dcache = _dc.get_device_cache()
                if fuse > 1:
                    # residency replaces fusion: the fused program
                    # re-stacks HOST microbatches (np.stack), which
                    # would force resident buffers back through the
                    # wire — and under a mesh, fuse==1 is what routes
                    # the sharded transfer through prepare where the
                    # populated buffers are born. Round-trips stay
                    # hidden by the dispatch window.
                    fuse = 1
                    if "fuse_steps" in seeded:
                        # an autotune seed residency disarms must not
                        # be REPORTED as applied (the `autotuned`
                        # contract — same rule as the mesh gate)
                        seeded.remove("fuse_steps")

        # -- tpudl.compile: shape buckets + AOT program store -------------
        # (COMPILE.md; PIPELINE.md "Bucket pick & AOT dispatch".) The
        # ladder snaps ragged dispatch shapes onto O(log n) rungs; the
        # program store serves precompiled executables at dispatch and
        # records misses for the next process. Both are opt-in
        # (TPUDL_COMPILE_BUCKETS / TPUDL_COMPILE_AOT or the kwargs),
        # device fns only, and the serial kill switch disarms them like
        # every other fast-path stage.
        ladder = None
        store = None
        if device_fn_real and not killed:
            from tpudl.compile import buckets as _bk

            ladder = _bk.resolve_ladder(buckets)
            from tpudl.compile import store as _aot_store

            if _aot_store.aot_enabled(aot):
                store = _aot_store.get_program_store()
                # fresh-process warm start: deserialize persisted
                # executables on the background pool so the first
                # batches can already hit (idempotent per process)
                store.ensure_restored()
                store.note_ladder(ladder)
        bucket_full = False
        if ladder is not None:
            # does the PRIMARY batch size itself snap to a rung? If it
            # pads, every full batch carries pad rows — and a fused
            # (m, B, ...) stack would interleave them in the flattened
            # output (the same rule that gates mesh fusion), so fusion
            # drops to per-batch dispatch
            target_full = ladder.pick(int(batch_size))
            if mesh is not None:
                target_full = -(-target_full // multiple) * multiple
            bucket_full = target_full != int(batch_size)
            if bucket_full and fuse > 1:
                fuse = 1
                if "fuse_steps" in seeded:
                    seeded.remove("fuse_steps")

        report.config = {
            "executor": ("pipelined" if (prefetch or fuse > 1
                                         or d_depth > 1)
                         else "serial"),
            "prefetch": bool(prefetch),
            "prefetch_depth": int(depth) if prefetch else 0,
            "prepare_workers": (max(1, min(workers, depth))
                                if prefetch else 0),
            "fuse_steps": fuse,
            "dispatch_depth": int(d_depth),
            "donate": bool(donate_flag),
            "autotune": bool(autotune_on),
            "autotuned": sorted(seeded),
            "batch_size": int(batch_size),
            "rows": self._n,
            # mesh topology on the report: the live monitor, the
            # roofline model and the autotune workload guard all read
            # it; None = single-chip
            "mesh": dict(mesh.shape) if mesh is not None else None,
            "wire_codec": (plan.names()[0] if plan is not None
                           else "off"),
            "batch_cache": bool(cache is not None),
            "device_cache": bool(dcache is not None),
            # tpudl.compile (COMPILE.md): the bucket ladder in force
            # ("off" = exact shapes) and whether dispatch consults the
            # AOT program store — the roofline's cold-start attribution
            # and the live monitor's compile line both read these
            "buckets": ladder.spec if ladder is not None else "off",
            "aot": bool(store is not None),
        }
        obs.set_last_pipeline(report)
        if _supervisor is not None:
            # fault containment (frame.supervisor): the ladder reads
            # the RESOLVED config off this report (what to halve) and
            # recovery stamps degraded_to/recovered_batches onto it
            _supervisor.note_report(report)

        # mesh transfer placement, captured ONCE: fuse==1 runs transfer
        # on the prepare pool (copies start as early as possible and
        # ride under earlier dispatches); fused runs keep host arrays
        # and transfer the stacked (M, B, ...) group on the dispatch
        # thread (handle()'s window-mode fallback only ever LOWERS fuse
        # mid-run when it started > 1, so this flag never flips)
        transfer_in_prepare = mesh is not None and fuse == 1

        def prepare(start, stop):
            """Pack (and, on the prefetch path, transfer) one batch.
            Runs on a prepare-pool thread when prefetching: jax dispatch
            is thread-safe and transfers release the GIL, so this
            overlaps the main thread's compute dispatch. The pool runs
            ``pack`` for DIFFERENT batches concurrently only when the
            pack opted in (see the workers resolution above).

            With a shard cache, a verified hit replaces the whole
            pack/decode/encode path by a memory-mapped read; a miss (or
            a corrupt shard) prepares as usual and persists the result.
            Wire encoding happens AFTER pack and the finite check (the
            check must see restored float values, not wire bytes).

            With a DEVICE cache (DATA.md "Cache hierarchy"), an HBM hit
            short-circuits everything above — no pack, no decode, no
            encode, no transfer: the resident (already encoded, already
            sharded) buffers feed the dispatch window directly, pinned
            until their dispatch returns. Returns
            ``(arrays, n_pad, pin-or-None)`` — a non-None pin marks the
            batch RESIDENT (the consumer routes it through the
            non-donating program and releases the pin after
            dispatch)."""
            with report.stage("prepare"):
                bidx = start // batch_size
                # executor-stage fault points (tpudl.testing.faults):
                # the robustness suite raises/kills inside an exact
                # stage at an exact batch; unarmed this is a None-check
                _faults.fire("frame.prepare", index=bidx)
                # attribution: rows entering the pipeline, charged in
                # the submitting run's scope (carried onto this pool
                # thread by _PipelineInfeed._submit)
                _attr.charge("rows_in", stop - start)
                if dcache is not None:
                    pin = dcache.get((dkey, bidx))
                    # an all-hits replay still needs resolved codecs
                    # for the device prologue (same guard as the shard
                    # cache below) — entries persist their codec keys
                    if pin is not None and (
                            plan is None or plan.resolved()
                            or pin.codecs):
                        if plan is not None and not plan.resolved():
                            plan.adopt(pin.codecs)
                        pins.add(pin)
                        # `bytes_prepared` keeps meaning "bytes fed to
                        # dispatch"; `bytes_hbm_hit` is the resident
                        # share the roofline subtracts from its wire
                        # model (these bytes never crossed the link,
                        # and data.wire.bytes_shipped stays untouched)
                        report.count("bytes_prepared", pin.nbytes)
                        report.count("bytes_hbm_hit", pin.nbytes)
                        report.count("hbm_hits")
                        _flight.record_batch(
                            "prepare", bidx, pin.arrays,
                            rows=stop - start, cache_hit=True,
                            hbm_hit=True, run=report.run_id)
                        return list(pin.arrays), pin.n_pad, pin
                    if pin is not None:
                        pin.release()  # unusable hit: codecs unknown
                packed = None
                cache_hit = False
                if cache is not None:
                    hit = cache.get(bidx)
                    # an all-hits replay still needs resolved codecs for
                    # the device prologue; a cache written by a run that
                    # died before persisting its codec meta re-prepares
                    if hit is not None and (plan is None
                                            or plan.resolved()):
                        report.count("cache_hits")
                        # device fns only read their numpy inputs, so
                        # they keep the zero-copy read-only mmap; a
                        # host fn may mutate in place (legal on the
                        # cold path's fresh arrays), so warm batches
                        # must be writable copies or cold/warm diverge.
                        # DONATING programs also get writable copies: a
                        # donated buffer hands XLA write access, and on
                        # a backend that zero-copies host numpy that
                        # would be the shard file itself (DATA.md). The
                        # only donating program that can SEE these hit
                        # buffers is the codec wrapper's per-batch path
                        # (plan is not None); the fused path re-stacks
                        # into fresh arrays, and without a plan no
                        # wrapper exists to carry donate_argnums — the
                        # default (donate on, no codec) keeps zero-copy
                        # mmap replay. Under a mesh the transfer edge
                        # (mesh.transfer_batch) always COPIES host
                        # buffers into device shards, so a donating
                        # program can never see the mmap there either.
                        donate_sees_hit = (donate_flag
                                           and plan is not None
                                           and mesh is None)
                        packed = (list(hit)
                                  if device_flag and not donate_sees_hit
                                  else [np.array(a) for a in hit])
                        cache_hit = True
                if packed is None:
                    if cache is not None:
                        report.count("cache_misses")
                    packed = []
                    for ci, c in enumerate(input_cols):
                        sl = self._cols[c][start:stop]
                        arr = pack(sl) if pack is not None else _default_pack(sl)
                        if check_finite and np.issubdtype(arr.dtype, np.floating):
                            # input-pipeline sanitizer (SURVEY.md §5.2):
                            # catch bad rows host-side before they enter
                            # a fused program
                            bad = ~np.isfinite(arr).reshape(arr.shape[0], -1).all(1)
                            if bad.any():
                                rows = (np.nonzero(bad)[0][:8] + start).tolist()
                                raise ValueError(
                                    f"non-finite values in column {c!r}, rows "
                                    f"{rows} (batch {start}:{stop})")
                        if plan is not None:
                            arr = plan.encode(ci, arr)
                        packed.append(arr)
                    if cache is not None:
                        cache.put(bidx, packed)
                        if (plan is not None and plan.resolved()
                                and not cache.meta.get("codecs")):
                            cache.set_meta({"codecs": plan.keys()})
                if plan is not None:
                    plan.record_shipped(packed)
                # wire-byte accounting for the roofline model
                # (tpudl.obs.roofline): what this batch will put on the
                # H2D link — nbytes reads a header field, no data touch
                report.count("bytes_prepared",
                             int(sum(int(getattr(a, "nbytes", 0))
                                     for a in packed)))
                # black-box descriptor: shapes/dtypes/fingerprint only
                # (never data) — a dump shows what the last batches
                # looked like (tpudl.obs.flight)
                _flight.record_batch("prepare", bidx, packed,
                                     rows=stop - start,
                                     cache_hit=cache_hit,
                                     run=report.run_id)
                n_pad = 0
                if ladder is not None and packed:
                    # bucket pick (COMPILE.md): snap this batch's
                    # dispatch shape onto the ladder — pad rows repeat
                    # row 0 (the mesh.pad_batch discipline) and are
                    # stripped from the outputs via the same n_pad
                    # plumbing the mesh path uses, so values for real
                    # rows are bitwise-identical to exact dispatch.
                    # Under a mesh the rung rounds up to the data-axis
                    # multiple so SPMD padding never pads twice.
                    rows_b = int(packed[0].shape[0])
                    target = ladder.pick(rows_b)
                    if mesh is not None:
                        target = -(-target // multiple) * multiple
                    if target > rows_b:
                        packed = [_bk.pad_to(a, target) for a in packed]
                        n_pad = target - rows_b
                        report.count("bucket_pad_rows", n_pad)
                        _bk.count_pad_rows(n_pad)
                if mesh is not None:
                    # every column slices the same rows, so one pad count
                    # serves
                    with report.stage("h2d"):
                        _faults.fire("frame.h2d", index=bidx)
                        padded = [M.pad_batch(arr, multiple) for arr in packed]
                        mesh_pad = padded[0][1] if padded else 0
                        packed = [p for p, _ in padded]
                        if mesh_pad:
                            report.count("pad_rows", mesh_pad)
                        report.gauge("mesh_pad_rows", mesh_pad)
                        n_pad += mesh_pad
                        if transfer_in_prepare:
                            # ONE batched ASYNC device_put for every
                            # column (mesh.transfer_batch) — no barrier:
                            # the sharded arrays are futures, and the
                            # copies land while the consumer keeps
                            # dispatching (the old per-batch
                            # block_until_ready serialized the pool on
                            # the wire; the dispatch window now hides
                            # any residual wait as dispatch_wait).
                            # Fused runs skip this: the consumer stacks
                            # M HOST microbatches and transfers the
                            # (M, B, ...) group at dispatch.
                            packed = M.transfer_batch(packed, mesh)
                            if mesh_slow and prefetch:
                                import jax

                                # tpudl: ignore[hot-sync] — the
                                # TPUDL_MESH_FAST_PATH=0 escape hatch
                                # keeps the pre-ISSUE-11 barrier: the
                                # copy lands ON this prepare-pool
                                # thread, so the A/B arm isolates the
                                # new async transfer edge instead of
                                # silently exercising it too
                                jax.block_until_ready(packed)
                if dcache is not None:
                    # populate the HBM tier: the batch becomes resident
                    # NOW and the resident buffers themselves feed this
                    # dispatch — the bytes cross the wire exactly once.
                    # Mesh path (fuse==1 → transfer_in_prepare): packed
                    # is already the sharded device tree; single-chip:
                    # one batched async device_put, budget-gated so an
                    # over-budget batch never ships a doomed copy.
                    codecs = (plan.keys()
                              if plan is not None and plan.resolved()
                              else None)
                    pin = None
                    if mesh is not None:
                        pin = dcache.put((dkey, bidx), packed,
                                         n_pad=n_pad, codecs=codecs)
                    elif dcache.would_fit(
                            sum(int(getattr(a, "nbytes", 0))
                                for a in packed), run=dkey):
                        import jax

                        try:
                            packed = jax.device_put(list(packed))
                        except BaseException:
                            # a placement that dies mid-way (device OOM
                            # is likeliest right here) never touched
                            # the cache tallies — count it and let the
                            # error propagate to the supervisor, whose
                            # OOM rung evicts and retries
                            _dc.count_put_failed()
                            raise
                        pin = dcache.put((dkey, bidx), packed,
                                         n_pad=n_pad, codecs=codecs)
                    if pin is not None:
                        pins.add(pin)
                        return list(pin.arrays), n_pad, pin
                # mesh=None: host arrays go straight into the jitted fn even
                # when prefetching — the runtime's own arg transfer pipelines
                # far better than an explicit device_put on tunneled/remote
                # backends (measured: prefetch-with-device_put was SLOWER
                # than the serial fn-arg route through the tunnel). The
                # prefetch win here is the pack/decode work riding under
                # compute; the transfer stays on the dispatch path (so
                # ``h2d`` shows up inside ``dispatch`` on this path).
                return packed, n_pad, None

        # device-cache pin tokens currently OUTSTANDING (hits +
        # populates awaiting their dispatch): the dispatch path
        # releases AND discards each token, so the set — and, through
        # Pin._entry, the device buffers of entries another run may
        # have evicted meanwhile — stays bounded by the in-flight
        # window, not the whole run. The outer-finally sweep catches
        # only tokens an unwind stranded (cancelled window futures);
        # release is idempotent per token, so the double call is safe.
        # set add/discard are single GIL-atomic ops (prepare-pool and
        # dispatch threads touch it concurrently); the sweep iterates
        # a snapshot.
        pins: set = set()

        outputs: list[list[np.ndarray]] = [[] for _ in output_cols]
        acc: list[list] = [[] for _ in output_cols]  # device-resident results
        segs: list[tuple[int, int]] = []  # (padded_len, n_pad) per dispatch
        pending: list[tuple[tuple, int]] = []
        mode = None  # "acc" (fetch once at end) or "window" (bounded drain)

        def handle(result, n_pad):
            """Route one dispatch's result into the outfeed (acc/window)."""
            nonlocal mode, fuse
            if not isinstance(result, (tuple, list)):
                result = (result,)
            if len(result) != len(output_cols):
                raise ValueError(
                    f"fn returned {len(result)} outputs, expected "
                    f"{len(output_cols)}")
            if mode is None:
                # keyed on device_fn_real, not device_flag: under a
                # mesh device_flag is forced True, but a misclassified
                # jitted WRAPPER still loses the fast path — the hint
                # to pass device_fn=True matters there most
                if (heuristic and not device_fn_real and all(
                        hasattr(r, "copy_to_host_async") for r in result)):
                    _warn_device_outputs_once()
                mode = _pick_fetch_mode(result, max(1, self._n))
                if mode == "window" and fuse > 1:
                    # window mode exists to bound device memory at
                    # O(window · batch); a fused entry holds fuse× that,
                    # so big-output runs fall back to per-batch dispatch
                    fuse = 1
            # rows finished dispatching: the live monitor's progress/ETA
            # source (rows_done/rows_total on the status file)
            done_rows = (int(result[0].shape[0]) if result[0].ndim else 1)
            report.progress(max(0, done_rows - n_pad))
            _attr.charge("rows_out", max(0, done_rows - n_pad))
            if mode == "acc":
                # Keep results device-resident and fetch ONCE per column
                # at the end: device→host fetch has a large fixed cost
                # per round-trip on tunneled/remote PJRT backends, so
                # per-batch fetching serializes the pipeline (round-1
                # bottleneck).
                for i, r in enumerate(result):
                    acc[i].append(r)
                segs.append((int(result[0].shape[0]), n_pad))
            else:
                # Large outputs (e.g. outputMode='image'): bounded
                # window so device memory stays O(window · batch). The
                # host copy already started AT dispatch
                # (_start_host_copies on the dispatching thread), so the
                # drain below blocks only on the oldest entry's
                # in-flight copy.
                pending.append((tuple(result), n_pad))
                if len(pending) > _PIPELINE_WINDOW:
                    with report.stage("d2h"):
                        _faults.fire("frame.d2h")
                        _drain(pending.pop(0), outputs)

        spans = list(self.iter_batches(batch_size))
        # only the leading run of full-size batches is fusable (the
        # ragged tail would change the compiled (m, B, ...) signature)
        n_full = sum(1 for s, e in spans if e - s == batch_size)
        # watchdog supervision: ONE heartbeat for the whole run, beaten
        # at every stage entry (PipelineReport.stage) — a freeze inside
        # prepare/h2d/dispatch/d2h surfaces as a stall NAMING that
        # stage. Registered before the infeed so the prepare pool's
        # first batches are already supervised; deregistered on every
        # exit path below (finished work cannot false-flag).
        hb_run = obs.heartbeat("frame.map_batches", run=report.run_id,
                               rows=self._n)
        report.heartbeat = hb_run
        infeed = (_PipelineInfeed(prepare, spans, depth, workers, report)
                  if prefetch else None)
        consumed = 0

        def next_prepared():
            nonlocal consumed
            out = (infeed.get(consumed) if infeed
                   else prepare(*spans[consumed]))
            consumed += 1
            return out

        run_fn = fn if plan is None else None
        run_fn_direct = fn if plan is None else None

        def _run_fn():
            """``fn`` with the codec prologues fused in front (ONE jit
            program, see CodecPlan.wrap) — bindable only after the
            first batch prepared ('auto' codecs pick from it), hence
            the lazy bind; identity plans return ``fn`` itself. This is
            the NON-donating variant the fused wrapper traces inline
            (donation belongs to the outermost jit only)."""
            nonlocal run_fn
            if run_fn is None:
                run_fn = plan.wrap(fn)
            return run_fn

        def _run_fn_direct():
            """The per-batch dispatch program: donates its inputs when
            donation is armed and the codec wrapper exists to carry the
            ``donate_argnums`` (a bare user fn is never re-jitted just
            to donate — donation rides the wrappers the executor
            already owns)."""
            nonlocal run_fn_direct
            if run_fn_direct is None:
                run_fn_direct = plan.wrap(fn, donate=donate_flag)
            return run_fn_direct

        window = (_DispatchWindow(d_depth, report) if d_depth > 1
                  else None)

        # the roofline's cold-start evidence: the first dispatch's wall
        # time (trace + compile ride inside it on a cold process); the
        # flag list keeps the record single-shot (the first dispatch
        # runs alone — window warmup — so no second writer races it)
        first_dispatched: list = []

        def dispatch(call_fn, args, idx, n_pad, fused=False, pin=None,
                     donate_key=False):
            """Issue one dispatch: directly on the consumer (serial /
            depth 1) or onto the in-flight window. The dispatch stage
            itself — fault point, fn call, and starting the outputs'
            device→host copies — runs on whichever thread executes it;
            results are handled strictly in issue order. ``pin`` is the
            batch's device-cache pin token, released once the dispatch
            has consumed the resident buffers (eviction accounting must
            not drop bytes still feeding an in-flight program)."""
            def run():
                try:
                    call_args = args
                    if mesh is not None and call_args \
                            and isinstance(call_args[0], np.ndarray):
                        # mesh batches still host-side (fused groups,
                        # the ragged tail of a fused run, shape-drift
                        # fallbacks): ONE batched async transfer under
                        # the group's NamedSharding — P(None, data,
                        # ...) for a stacked (M, B, ...) group,
                        # P(data, ...) per batch — on the dispatching
                        # thread, so the copy rides inside the window
                        # like every other round-trip
                        with report.stage("h2d"):
                            call_args = M.transfer_batch(
                                list(call_args), mesh,
                                batch_dim=1 if fused else 0)
                    t_disp = time.perf_counter()
                    with report.stage("dispatch"):
                        _faults.fire("frame.dispatch", index=idx)
                        if store is not None:
                            # AOT program store (COMPILE.md): a hit
                            # executes a precompiled (possibly
                            # restored-from-disk) program — no trace
                            # possible; a miss runs the jitted path
                            # unchanged and background-compiles the
                            # signature for the next process. Only
                            # pure-rung per-batch shapes are marked
                            # bucketed (the validator's shapes↔ladder
                            # audit): a fused stack leads with M, and
                            # a mesh target rounds the rung up to the
                            # data-axis multiple.
                            result = store.call(
                                call_fn, call_args, donate=donate_key,
                                bucketed=(ladder is not None
                                          and not fused
                                          and mesh is None),
                                report=report)
                        else:
                            result = call_fn(*call_args)
                    # attribution: device seconds this scope consumed
                    # (the quota broker's currency, ROADMAP item 5)
                    _attr.charge("dispatch_s",
                                 time.perf_counter() - t_disp)
                    if not first_dispatched:
                        first_dispatched.append(True)
                        report.count("first_dispatch_s",
                                     time.perf_counter() - t_disp)
                    if not isinstance(result, (tuple, list)):
                        result = (result,)
                    # D2H starts NOW, at dispatch, for both outfeed
                    # modes — batch idx's copy overlaps the next
                    # dispatches
                    _start_host_copies(result)
                    return result, n_pad
                finally:
                    if pin is not None:
                        pin.release()
                        pins.discard(pin)

            if fused:
                report.count("fused_dispatches")
            if window is None:
                handle(*run())
                return
            while window.full():
                handle(*window.pop())
            window.submit(run)

        t_wall = time.perf_counter()
        try:
            try:
                while consumed < len(spans):
                    if fuse > 1 and window is not None and mode is None \
                            and len(window):
                        # resolve the outfeed mode BEFORE stacking the
                        # next fused group: if the first result picks
                        # window mode, handle() drops fuse to 1 and the
                        # O(window · batch) device-memory bound must
                        # not be multiplied by an already-stacked group
                        handle(*window.pop())
                        continue
                    if fuse > 1 and consumed + fuse <= n_full:
                        group = [next_prepared() for _ in range(fuse)]
                        try:
                            stacked = [np.stack([g[0][j] for g in group])
                                       for j in range(len(input_cols))]
                        except ValueError:
                            # shapes drifted between microbatches
                            # (variable-geometry pack): dispatch this
                            # group per-batch
                            for packed, n_pad, pin in group:
                                dispatch(_run_fn_direct(), packed,
                                         consumed, n_pad, pin=pin,
                                         donate_key=(donate_flag
                                                     and plan
                                                     is not None))
                            continue
                        fused_fn = _fused_wrapper(
                            _run_fn(), fuse, n_args=len(input_cols),
                            donate=donate_flag)
                        dispatch(fused_fn, stacked, consumed, 0,
                                 fused=True,
                                 donate_key=bool(donate_flag
                                                 and input_cols))
                    else:
                        packed, n_pad, pin = next_prepared()
                        if pin is not None:
                            # RESIDENT batch: never hand a donating
                            # program the cached buffers (XLA would
                            # reuse them, corrupting every later
                            # replay) — the non-donating wrapper
                            # variant runs instead. Only a codec
                            # wrapper can carry donate_argnums on the
                            # per-batch path, so only that combination
                            # counts as a blocked donation.
                            if donate_flag and plan is not None:
                                _dc.count_donation_blocked()
                            dispatch(_run_fn(), packed, consumed,
                                     n_pad, pin=pin)
                        else:
                            dispatch(_run_fn_direct(), packed,
                                     consumed, n_pad,
                                     donate_key=(donate_flag
                                                 and plan is not None))
                while window is not None and len(window):
                    handle(*window.pop())
            finally:
                if window is not None:
                    window.close()
                if infeed is not None:
                    infeed.close()
                if cache is not None:
                    cache.flush()  # persist throttled manifest entries
            while pending:
                with report.stage("d2h"):
                    _faults.fire("frame.d2h")
                    _drain(pending.pop(0), outputs)
            if mode == "acc":
                with report.stage("d2h"):
                    _faults.fire("frame.d2h")
                    _fetch_accumulated(acc, segs, outputs)
        finally:
            # the final d2h drain runs supervised too (a wedged fetch
            # IS the interesting stall); only now does the run's
            # heartbeat leave the watchdog's scan list
            hb_run.__exit__(None, None, None)
            # sweep device-cache pins an unwind stranded (a cancelled
            # window future whose run() never started still holds its
            # batch's pin) — release is idempotent per token; snapshot
            # first, dispatch threads may still be discarding
            for p in list(pins):
                p.release()
            pins.clear()
        # close out the run: wall time + publish totals into the
        # process-wide metrics registry (obs.snapshot() / JSONL sink)
        if plan is not None and plan.resolved():
            # deferred specs ('auto'/'u8') now know their pick — the
            # report shows what actually ran, not what was asked for
            report.config["wire_codec"] = plan.names()[0]
        report.finish(time.perf_counter() - t_wall)
        out = self
        for name, chunks in zip(output_cols, outputs):
            col = np.concatenate(chunks, axis=0) if chunks else np.empty((0,))
            if col.ndim > 1:
                obj = np.empty(len(col), dtype=object)
                obj[:] = list(col)
                col = obj
            out = out.with_column(name, col)
        return out


_PIPELINE_WINDOW = 2  # in-flight device batches retained before fetch
_ACC_FETCH_CAP = 512 * 1024 * 1024  # max bytes held on device in "acc" mode


def _pick_fetch_mode(result, est_total_rows: int) -> str:
    """Device-resident accumulation for small outputs (features, scores),
    windowed drain for big ones (image-sized tensors) or host results.
    Sized per ROW (not per dispatch) so fused multi-step dispatches —
    whose results are fuse_steps× bigger — estimate the same total."""
    if not all(hasattr(r, "copy_to_host_async") for r in result):
        return "window"  # fn returned host arrays; drain is free
    rows = max(1, int(result[0].shape[0]) if result[0].ndim else 1)
    per_row = sum(r.nbytes for r in result) / rows
    return "acc" if per_row * est_total_rows <= _ACC_FETCH_CAP else "window"


def _fetch_accumulated(acc, segs, outputs):  # tpudl: hot-path
    """Fetch the accumulated device results: start (or re-arm)
    ``copy_to_host_async`` on EVERY pending array first, so all the
    copies cross the tunnel concurrently, THEN convert each chunk —
    each ``np.asarray`` blocks only on its own already-in-flight copy
    instead of issuing one serialized round-trip at a time (the
    round-10 d2h fix; dispatch normally armed these copies already —
    re-arming a finished copy is a no-op). Concatenation happens
    host-side; per-batch mesh padding is stripped per segment."""
    for chunks in acc:
        for r in chunks:
            if hasattr(r, "copy_to_host_async"):
                r.copy_to_host_async()
    for i, chunks in enumerate(acc):
        if not chunks:
            continue
        # tpudl: ignore[hot-sync] — this fetch IS the d2h stage: every
        # chunk's copy is already in flight (armed above + at dispatch),
        # so each conversion awaits its own copy, nothing else
        parts = [np.asarray(r) for r in chunks]
        host = np.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]
        if any(n_pad for _, n_pad in segs):
            out, pos = [], 0
            for padded_len, n_pad in segs:
                out.append(host[pos: pos + padded_len - n_pad])
                pos += padded_len
            outputs[i].extend(out)
        else:
            outputs[i].append(host)


def _drain(entry, outputs):  # tpudl: hot-path
    (result, n_pad) = entry
    for i, r in enumerate(result):
        # tpudl: ignore[hot-sync] — this fetch IS the d2h stage; the
        # copy was started async at dispatch (copy_to_host_async), so
        # this blocks only on the oldest window entry
        r = np.asarray(r)  # device→host; blocks until this batch is done
        outputs[i].append(r[: r.shape[0] - n_pad] if n_pad else r)


def null_mask(col) -> np.ndarray:
    """Per-row null flags: object ``None`` and float ``NaN`` count as
    null, everything else does not. The ONE definition of nullness —
    shared by ``Frame.dropna`` and SQL ``IS NULL`` so the two can never
    disagree. A LazyColumn answers via its cheap ``validity_mask`` probe
    when it has one (no decode at all); otherwise the scan streams in
    CHUNKS (parallel reads, O(chunk) held payloads, each discarded
    before the next chunk)."""
    if isinstance(col, LazyColumn):
        valid = col.validity_mask()
        if valid is not None:
            return ~np.asarray(valid, dtype=bool)
        flags = np.empty(len(col), dtype=bool)
        for start in range(0, len(col), 256):
            stop = min(start + 256, len(col))
            chunk = col[start:stop]
            flags[start:stop] = [v is None for v in chunk]
        return flags
    if col.dtype == object:
        return np.array([v is None for v in col], dtype=bool)
    if np.issubdtype(col.dtype, np.floating):
        return np.isnan(col)
    return np.zeros(len(col), dtype=bool)


def _hash_value(h, v) -> None:
    """One object-column row into a running hash — covers the column
    shapes this frame actually stores (image structs, raw bytes,
    ndarrays, scalars/strings, None); anything else contributes its
    repr (best effort, documented in DATA.md)."""
    if v is None:
        h.update(b"\x00none")
    elif isinstance(v, bytes):
        h.update(b"\x00b")
        h.update(v)
    elif isinstance(v, dict):
        for k in sorted(v):
            h.update(f"\x00k{k}=".encode())
            _hash_value(h, v[k])
    elif isinstance(v, np.ndarray):
        h.update(f"\x00a{v.dtype}{v.shape}".encode())
        h.update(np.ascontiguousarray(v).tobytes())
    else:
        h.update(f"\x00r{v!r}".encode())


def _default_pack(sl: np.ndarray) -> np.ndarray:
    if sl.dtype == object:
        return np.stack([np.asarray(v) for v in sl])
    return np.asarray(sl)


def concat(frames: Sequence[Frame]) -> Frame:
    if not frames:
        raise ValueError("concat of zero frames")
    names = frames[0].columns
    for i, f in enumerate(frames[1:], start=1):
        if set(f.columns) != set(names):
            raise ValueError(
                f"concat schema mismatch: frame 0 has {names}, "
                f"frame {i} has {f.columns}"
            )
    out = {}
    for n in names:
        cols = [f[n] for f in frames]
        if any(c.dtype == object for c in cols):
            merged = np.empty(sum(len(c) for c in cols), dtype=object)
            i = 0
            for c in cols:
                # a LazyColumn materializes here: concat is an explicit
                # whole-frame operation, not the streaming path
                merged[i : i + len(c)] = c[:] if isinstance(c, LazyColumn) else c
                i += len(c)
            out[n] = merged
        else:
            out[n] = np.concatenate(cols, axis=0)
    return Frame(out, frames[0].num_partitions)
