"""Minimal columnar batch abstraction — the Spark DataFrame stand-in.

SURVEY.md §7.1 item 3: "intentionally small — transport, not a query
engine". A Frame is an ordered dict of equal-length named columns. Numeric
columns are numpy arrays; ragged/struct/string columns are object arrays.
``map_batches`` is the executor: it packs host batches, pads and shards
them over the mesh's data axis, runs ONE jitted function per batch (the
reference's one-native-call-per-block invariant, SURVEY.md §3.2), and
appends the outputs as new columns.

The reference equivalent is the Spark DataFrame + TensorFrames MapBlocks
path (ref: sparkdl graph/tensorframes_udf.py, tf_image.py:_transform).
"""

from __future__ import annotations

from collections.abc import Callable, Iterator, Mapping, Sequence

import numpy as np

__all__ = ["Frame", "concat"]


def _as_column(values) -> np.ndarray:
    if isinstance(values, np.ndarray):
        return values
    values = list(values)
    if values and isinstance(values[0], (dict, bytes, str, type(None))):
        col = np.empty(len(values), dtype=object)
        col[:] = values
        return col
    try:
        return np.asarray(values)
    except Exception:
        col = np.empty(len(values), dtype=object)
        col[:] = values
        return col


class Frame:
    """Ordered named columns of equal length."""

    def __init__(self, columns: Mapping[str, object], num_partitions: int | None = None):
        self._cols: dict[str, np.ndarray] = {}
        n = None
        for name, values in columns.items():
            col = _as_column(values)
            if n is None:
                n = len(col)
            elif len(col) != n:
                raise ValueError(
                    f"column {name!r} has length {len(col)}, expected {n}"
                )
            self._cols[str(name)] = col
        self._n = n or 0
        self.num_partitions = num_partitions

    # -- schema/access ----------------------------------------------------
    @property
    def columns(self) -> list[str]:
        return list(self._cols)

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, name: str) -> np.ndarray:
        return self._cols[name]

    def __contains__(self, name: str) -> bool:
        return name in self._cols

    def __repr__(self) -> str:
        cols = ", ".join(f"{k}:{v.dtype}" for k, v in self._cols.items())
        return f"Frame[{self._n} rows]({cols})"

    # -- relational-lite --------------------------------------------------
    def select(self, *names: str) -> "Frame":
        missing = [n for n in names if n not in self._cols]
        if missing:
            raise KeyError(f"unknown columns {missing}; have {self.columns}")
        return Frame({n: self._cols[n] for n in names}, self.num_partitions)

    def with_column(self, name: str, values) -> "Frame":
        col = _as_column(values)
        if len(col) != self._n:
            raise ValueError(f"column length {len(col)} != frame length {self._n}")
        out = dict(self._cols)
        out[name] = col
        return Frame(out, self.num_partitions)

    def with_column_renamed(self, old: str, new: str) -> "Frame":
        if new != old and new in self._cols:
            raise ValueError(f"cannot rename {old!r} to existing column {new!r}")
        return Frame(
            {new if k == old else k: v for k, v in self._cols.items()},
            self.num_partitions,
        )

    def drop(self, *names: str) -> "Frame":
        return Frame(
            {k: v for k, v in self._cols.items() if k not in names},
            self.num_partitions,
        )

    def filter_rows(self, mask) -> "Frame":
        mask = np.asarray(mask, dtype=bool)
        return Frame({k: v[mask] for k, v in self._cols.items()}, self.num_partitions)

    def dropna(self, subset: Sequence[str] | None = None) -> "Frame":
        names = list(subset) if subset else self.columns
        mask = np.ones(self._n, dtype=bool)
        for n in names:
            col = self._cols[n]
            if col.dtype == object:
                mask &= np.array([v is not None for v in col], dtype=bool)
            elif np.issubdtype(col.dtype, np.floating):
                mask &= ~np.isnan(col)
        return self.filter_rows(mask)

    def head(self, n: int = 5) -> "Frame":
        return Frame({k: v[:n] for k, v in self._cols.items()}, self.num_partitions)

    def limit(self, n: int) -> "Frame":
        return self.head(n)

    def to_dict(self) -> dict[str, np.ndarray]:
        return dict(self._cols)

    def rows(self) -> Iterator[dict]:
        for i in range(self._n):
            yield {k: v[i] for k, v in self._cols.items()}

    def collect(self) -> list[dict]:
        return list(self.rows())

    # -- executor ---------------------------------------------------------
    def iter_batches(self, batch_size: int) -> Iterator[tuple[int, int]]:
        for start in range(0, self._n, batch_size):
            yield start, min(start + batch_size, self._n)

    def map_batches(
        self,
        fn: Callable,
        input_cols: Sequence[str],
        output_cols: Sequence[str],
        *,
        batch_size: int | None = None,
        mesh=None,
        pack: Callable | None = None,
        check_finite: bool = False,
    ) -> "Frame":
        """Run ``fn`` over the frame in device-sized batches; append outputs.

        ``fn`` maps packed input arrays → one array or a tuple matching
        ``output_cols``. ``pack`` converts a column slice (object arrays
        included) to a stacked numpy batch; defaults to ``np.stack``-like
        coercion. When ``mesh`` is given, batches are padded to the data-axis
        size and sharded before the call (the infeed edge); outputs are
        fetched and unpadded. This is the rebuild of the reference's
        per-partition TensorFrames MapBlocks execution, minus the JVM.

        ``batch_size`` defaults to the frame's ``num_partitions`` hint
        (``ceil(rows / num_partitions)`` — the Spark-side meaning of a
        partition as the unit of executor dispatch), else 256.
        """
        if batch_size is None:
            if self.num_partitions:
                batch_size = max(1, -(-self._n // int(self.num_partitions)))
            else:
                batch_size = 256
        if mesh is not None:
            from tpudl import mesh as M  # jax import only on the mesh path

            multiple = mesh.shape[M.DATA_AXIS]
        missing = [c for c in input_cols if c not in self._cols]
        if missing:
            raise KeyError(f"unknown input columns {missing}")
        outputs: list[list[np.ndarray]] = [[] for _ in output_cols]
        acc: list[list] = [[] for _ in output_cols]  # device-resident results
        segs: list[tuple[int, int]] = []  # (padded_len, n_pad) per batch
        pending: list[tuple[tuple, int]] = []
        mode = None  # "acc" (fetch once at end) or "window" (bounded drain)
        est_batches = max(1, -(-self._n // max(1, batch_size)))
        for start, stop in self.iter_batches(batch_size):
            packed = []
            for c in input_cols:
                sl = self._cols[c][start:stop]
                arr = pack(sl) if pack is not None else _default_pack(sl)
                if check_finite and np.issubdtype(arr.dtype, np.floating):
                    # input-pipeline sanitizer (SURVEY.md §5.2): catch bad
                    # rows host-side before they enter a fused program
                    bad = ~np.isfinite(arr).reshape(arr.shape[0], -1).all(1)
                    if bad.any():
                        rows = (np.nonzero(bad)[0][:8] + start).tolist()
                        raise ValueError(
                            f"non-finite values in column {c!r}, rows "
                            f"{rows} (batch {start}:{stop})")
                packed.append(arr)
            n_pad = 0
            if mesh is not None:
                # every column slices the same rows, so one pad count serves
                padded = [M.pad_batch(arr, multiple) for arr in packed]
                n_pad = padded[0][1] if padded else 0
                packed = [M.shard_batch(p, mesh) for p, _ in padded]
            # (mesh=None: host arrays go straight into the jitted fn — the
            # runtime's own arg transfer pipelines far better than an
            # explicit device_put through tunneled backends)
            result = fn(*packed)
            if not isinstance(result, (tuple, list)):
                result = (result,)
            if len(result) != len(output_cols):
                raise ValueError(
                    f"fn returned {len(result)} outputs, expected {len(output_cols)}"
                )
            if mode is None:
                mode = _pick_fetch_mode(result, est_batches)
            if mode == "acc":
                # Keep results device-resident and fetch ONCE per column at
                # the end: device→host fetch has a large fixed cost per
                # round-trip on tunneled/remote PJRT backends, so per-batch
                # fetching serializes the pipeline (round-1 bottleneck).
                for i, r in enumerate(result):
                    acc[i].append(r)
                segs.append((stop - start + n_pad, n_pad))
            else:
                # Large outputs (e.g. outputMode='image'): bounded window so
                # device memory stays O(window · batch), with the host copy
                # started at dispatch so it overlaps later batches' compute.
                for r in result:
                    if hasattr(r, "copy_to_host_async"):
                        r.copy_to_host_async()
                pending.append((tuple(result), n_pad))
                if len(pending) > _PIPELINE_WINDOW:
                    _drain(pending.pop(0), outputs)
        while pending:
            _drain(pending.pop(0), outputs)
        if mode == "acc":
            _fetch_accumulated(acc, segs, outputs)
        out = self
        for name, chunks in zip(output_cols, outputs):
            col = np.concatenate(chunks, axis=0) if chunks else np.empty((0,))
            if col.ndim > 1:
                obj = np.empty(len(col), dtype=object)
                obj[:] = list(col)
                col = obj
            out = out.with_column(name, col)
        return out


_PIPELINE_WINDOW = 2  # in-flight device batches retained before fetch
_ACC_FETCH_CAP = 512 * 1024 * 1024  # max bytes held on device in "acc" mode


def _pick_fetch_mode(result, est_batches: int) -> str:
    """Device-resident accumulation for small outputs (features, scores),
    windowed drain for big ones (image-sized tensors) or host results."""
    if not all(hasattr(r, "copy_to_host_async") for r in result):
        return "window"  # fn returned host arrays; drain is free
    per_batch = sum(r.nbytes for r in result)
    return "acc" if per_batch * est_batches <= _ACC_FETCH_CAP else "window"


def _fetch_accumulated(acc, segs, outputs):
    """Concatenate per-column device results and fetch each ONCE; strip
    per-batch mesh padding host-side."""
    import jax.numpy as jnp

    for i, chunks in enumerate(acc):
        if not chunks:
            continue
        cat = jnp.concatenate(chunks, axis=0) if len(chunks) > 1 else chunks[0]
        host = np.asarray(cat)
        if any(n_pad for _, n_pad in segs):
            parts, pos = [], 0
            for padded_len, n_pad in segs:
                parts.append(host[pos: pos + padded_len - n_pad])
                pos += padded_len
            outputs[i].extend(parts)
        else:
            outputs[i].append(host)


def _drain(entry, outputs):
    (result, n_pad) = entry
    for i, r in enumerate(result):
        r = np.asarray(r)  # device→host; blocks until this batch is done
        outputs[i].append(r[: r.shape[0] - n_pad] if n_pad else r)


def _default_pack(sl: np.ndarray) -> np.ndarray:
    if sl.dtype == object:
        return np.stack([np.asarray(v) for v in sl])
    return np.asarray(sl)


def concat(frames: Sequence[Frame]) -> Frame:
    if not frames:
        raise ValueError("concat of zero frames")
    names = frames[0].columns
    for i, f in enumerate(frames[1:], start=1):
        if set(f.columns) != set(names):
            raise ValueError(
                f"concat schema mismatch: frame 0 has {names}, "
                f"frame {i} has {f.columns}"
            )
    out = {}
    for n in names:
        cols = [f[n] for f in frames]
        if any(c.dtype == object for c in cols):
            merged = np.empty(sum(len(c) for c in cols), dtype=object)
            i = 0
            for c in cols:
                merged[i : i + len(c)] = c
                i += len(c)
            out[n] = merged
        else:
            out[n] = np.concatenate(cols, axis=0)
    return Frame(out, frames[0].num_partitions)
