"""Minimal columnar batch abstraction — the Spark DataFrame stand-in.

SURVEY.md §7.1 item 3: "intentionally small — transport, not a query
engine". A Frame is an ordered dict of equal-length named columns. Numeric
columns are numpy arrays; ragged/struct/string columns are object arrays.
``map_batches`` is the executor: it packs host batches, pads and shards
them over the mesh's data axis, runs ONE jitted function per batch (the
reference's one-native-call-per-block invariant, SURVEY.md §3.2), and
appends the outputs as new columns.

The reference equivalent is the Spark DataFrame + TensorFrames MapBlocks
path (ref: sparkdl graph/tensorframes_udf.py, tf_image.py:_transform).
"""

from __future__ import annotations

from collections.abc import Callable, Iterator, Mapping, Sequence

import numpy as np

__all__ = ["Frame", "concat"]


def _as_column(values) -> np.ndarray:
    if isinstance(values, np.ndarray):
        return values
    values = list(values)
    if values and isinstance(values[0], (dict, bytes, str, type(None))):
        col = np.empty(len(values), dtype=object)
        col[:] = values
        return col
    try:
        return np.asarray(values)
    except Exception:
        col = np.empty(len(values), dtype=object)
        col[:] = values
        return col


class Frame:
    """Ordered named columns of equal length."""

    def __init__(self, columns: Mapping[str, object], num_partitions: int | None = None):
        self._cols: dict[str, np.ndarray] = {}
        n = None
        for name, values in columns.items():
            col = _as_column(values)
            if n is None:
                n = len(col)
            elif len(col) != n:
                raise ValueError(
                    f"column {name!r} has length {len(col)}, expected {n}"
                )
            self._cols[str(name)] = col
        self._n = n or 0
        self.num_partitions = num_partitions

    # -- schema/access ----------------------------------------------------
    @property
    def columns(self) -> list[str]:
        return list(self._cols)

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, name: str) -> np.ndarray:
        return self._cols[name]

    def __contains__(self, name: str) -> bool:
        return name in self._cols

    def __repr__(self) -> str:
        cols = ", ".join(f"{k}:{v.dtype}" for k, v in self._cols.items())
        return f"Frame[{self._n} rows]({cols})"

    # -- relational-lite --------------------------------------------------
    def select(self, *names: str) -> "Frame":
        missing = [n for n in names if n not in self._cols]
        if missing:
            raise KeyError(f"unknown columns {missing}; have {self.columns}")
        return Frame({n: self._cols[n] for n in names}, self.num_partitions)

    def with_column(self, name: str, values) -> "Frame":
        col = _as_column(values)
        if len(col) != self._n:
            raise ValueError(f"column length {len(col)} != frame length {self._n}")
        out = dict(self._cols)
        out[name] = col
        return Frame(out, self.num_partitions)

    def with_column_renamed(self, old: str, new: str) -> "Frame":
        if new != old and new in self._cols:
            raise ValueError(f"cannot rename {old!r} to existing column {new!r}")
        return Frame(
            {new if k == old else k: v for k, v in self._cols.items()},
            self.num_partitions,
        )

    def drop(self, *names: str) -> "Frame":
        return Frame(
            {k: v for k, v in self._cols.items() if k not in names},
            self.num_partitions,
        )

    def filter_rows(self, mask) -> "Frame":
        mask = np.asarray(mask, dtype=bool)
        return Frame({k: v[mask] for k, v in self._cols.items()}, self.num_partitions)

    def dropna(self, subset: Sequence[str] | None = None) -> "Frame":
        names = list(subset) if subset else self.columns
        mask = np.ones(self._n, dtype=bool)
        for n in names:
            col = self._cols[n]
            if col.dtype == object:
                mask &= np.array([v is not None for v in col], dtype=bool)
            elif np.issubdtype(col.dtype, np.floating):
                mask &= ~np.isnan(col)
        return self.filter_rows(mask)

    def head(self, n: int = 5) -> "Frame":
        return Frame({k: v[:n] for k, v in self._cols.items()}, self.num_partitions)

    def limit(self, n: int) -> "Frame":
        return self.head(n)

    def to_dict(self) -> dict[str, np.ndarray]:
        return dict(self._cols)

    def rows(self) -> Iterator[dict]:
        for i in range(self._n):
            yield {k: v[i] for k, v in self._cols.items()}

    def collect(self) -> list[dict]:
        return list(self.rows())

    # -- executor ---------------------------------------------------------
    def iter_batches(self, batch_size: int) -> Iterator[tuple[int, int]]:
        for start in range(0, self._n, batch_size):
            yield start, min(start + batch_size, self._n)

    def map_batches(
        self,
        fn: Callable,
        input_cols: Sequence[str],
        output_cols: Sequence[str],
        *,
        batch_size: int = 256,
        mesh=None,
        pack: Callable | None = None,
    ) -> "Frame":
        """Run ``fn`` over the frame in device-sized batches; append outputs.

        ``fn`` maps packed input arrays → one array or a tuple matching
        ``output_cols``. ``pack`` converts a column slice (object arrays
        included) to a stacked numpy batch; defaults to ``np.stack``-like
        coercion. When ``mesh`` is given, batches are padded to the data-axis
        size and sharded before the call (the infeed edge); outputs are
        fetched and unpadded. This is the rebuild of the reference's
        per-partition TensorFrames MapBlocks execution, minus the JVM.
        """
        if mesh is not None:
            from tpudl import mesh as M  # jax import only on the mesh path

            multiple = mesh.shape[M.DATA_AXIS]
        missing = [c for c in input_cols if c not in self._cols]
        if missing:
            raise KeyError(f"unknown input columns {missing}")
        outputs: list[list[np.ndarray]] = [[] for _ in output_cols]
        pending: list[tuple[tuple, int]] = []
        for start, stop in self.iter_batches(batch_size):
            packed = []
            for c in input_cols:
                sl = self._cols[c][start:stop]
                arr = pack(sl) if pack is not None else _default_pack(sl)
                packed.append(arr)
            n_pad = 0
            if mesh is not None:
                # every column slices the same rows, so one pad count serves
                padded = [M.pad_batch(arr, multiple) for arr in packed]
                n_pad = padded[0][1] if padded else 0
                packed = [M.shard_batch(p, mesh) for p, _ in padded]
            result = fn(*packed)
            if not isinstance(result, (tuple, list)):
                result = (result,)
            if len(result) != len(output_cols):
                raise ValueError(
                    f"fn returned {len(result)} outputs, expected {len(output_cols)}"
                )
            # pipeline window: dispatch is async, so deferring the host
            # copy lets batch k's compute overlap batch k+1's host pack
            # (SURVEY.md §3.2); the window is bounded so device memory
            # stays O(window · batch), not O(rows).
            pending.append((tuple(result), n_pad))
            if len(pending) > _PIPELINE_WINDOW:
                _drain(pending.pop(0), outputs)
        while pending:
            _drain(pending.pop(0), outputs)
        out = self
        for name, chunks in zip(output_cols, outputs):
            col = np.concatenate(chunks, axis=0) if chunks else np.empty((0,))
            if col.ndim > 1:
                obj = np.empty(len(col), dtype=object)
                obj[:] = list(col)
                col = obj
            out = out.with_column(name, col)
        return out


_PIPELINE_WINDOW = 2  # in-flight device batches retained before fetch


def _drain(entry, outputs):
    (result, n_pad) = entry
    for i, r in enumerate(result):
        r = np.asarray(r)  # device→host; blocks until this batch is done
        outputs[i].append(r[: r.shape[0] - n_pad] if n_pad else r)


def _default_pack(sl: np.ndarray) -> np.ndarray:
    if sl.dtype == object:
        return np.stack([np.asarray(v) for v in sl])
    return np.asarray(sl)


def concat(frames: Sequence[Frame]) -> Frame:
    if not frames:
        raise ValueError("concat of zero frames")
    names = frames[0].columns
    for i, f in enumerate(frames[1:], start=1):
        if set(f.columns) != set(names):
            raise ValueError(
                f"concat schema mismatch: frame 0 has {names}, "
                f"frame {i} has {f.columns}"
            )
    out = {}
    for n in names:
        cols = [f[n] for f in frames]
        if any(c.dtype == object for c in cols):
            merged = np.empty(sum(len(c) for c in cols), dtype=object)
            i = 0
            for c in cols:
                merged[i : i + len(c)] = c
                i += len(c)
            out[n] = merged
        else:
            out[n] = np.concatenate(cols, axis=0)
    return Frame(out, frames[0].num_partitions)
