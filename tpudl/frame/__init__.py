from tpudl.frame.frame import Frame, concat  # noqa: F401
from tpudl.frame.sql import sql  # noqa: F401
