"""Deliberately tiny SQL SELECT layer for model-as-UDF parity.

The reference registers Keras models as Spark SQL UDFs and users write
``SELECT my_udf(image) FROM images`` (ref: sparkdl udf/keras_image_model.py
~L30, graph/tensorframes_udf.py ~L20; SURVEY.md §3.4). We are explicitly
NOT a query engine (SURVEY.md §7.1 item 3), so this module implements only
the projection shape that contract needs:

    SELECT <item> [, <item>...] FROM <table> [LIMIT n]
    item := col | fn(col) | fn(col) AS alias

Registered UDFs come from :mod:`tpudl.udf.registry`; execution of a model
UDF is a batched jitted call, not per-row Python.
"""

from __future__ import annotations

import re

from tpudl.frame.frame import Frame

__all__ = ["sql"]

_SELECT_RE = re.compile(
    r"^\s*select\s+(?P<items>.+?)\s+from\s+(?P<table>\w+)"
    r"(?:\s+limit\s+(?P<limit>\d+))?\s*;?\s*$",
    re.IGNORECASE | re.DOTALL,
)
_ITEM_RE = re.compile(
    r"^\s*(?:(?P<fn>\w+)\s*\(\s*(?P<arg>\w+)\s*\)|(?P<col>\w+))"
    r"(?:\s+as\s+(?P<alias>\w+))?\s*$",
    re.IGNORECASE,
)


def sql(query: str, tables: dict[str, Frame]) -> Frame:
    m = _SELECT_RE.match(query)
    if not m:
        raise ValueError(
            f"unsupported SQL (only 'SELECT items FROM table [LIMIT n]'): {query!r}"
        )
    table = m.group("table")
    if table not in tables:
        raise KeyError(f"unknown table {table!r}; registered: {sorted(tables)}")
    frame = tables[table]
    limit = m.group("limit")
    if limit is not None:
        frame = frame.limit(int(limit))

    out: dict[str, object] = {}
    for raw in _split_items(m.group("items")):
        if raw == "*":
            raise ValueError("SELECT * not supported; name columns explicitly")
        im = _ITEM_RE.match(raw)
        if not im:
            raise ValueError(f"unsupported select item: {raw!r}")
        if im.group("col"):
            name = im.group("alias") or im.group("col")
            if name in out:
                raise ValueError(f"duplicate output column {name!r}")
            out[name] = frame[im.group("col")]
        else:
            from tpudl.udf import registry

            fn_name, arg = im.group("fn"), im.group("arg")
            name = im.group("alias") or f"{fn_name}({arg})"
            if name in out:
                raise ValueError(f"duplicate output column {name!r}")
            udf = registry.get_udf(fn_name)
            result = udf(frame.select(arg).with_column_renamed(arg, udf.input_col))
            out[name] = result[udf.output_col]
    return Frame(out)


def _split_items(items: str) -> list[str]:
    # split on top-level commas (no nested parens in our grammar)
    return [p for p in (s.strip() for s in items.split(",")) if p]
