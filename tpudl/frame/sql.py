"""Deliberately tiny SQL SELECT layer for model-as-UDF parity.

The reference registers Keras models as Spark SQL UDFs and users write
``SELECT my_udf(image) FROM images`` (ref: sparkdl udf/keras_image_model.py
~L30, graph/tensorframes_udf.py ~L20; SURVEY.md §3.4). We are explicitly
NOT a query engine (SURVEY.md §7.1 item 3), so this module implements only
the shapes that contract and its surrounding examples need — plus the
single-table analytics a migrating sparkdl user reaches for right after
featurizing (round-4 verdict weak #7):

    SELECT <item> [, <item>...] FROM <table>
        [WHERE <pred> [AND <pred>...]]
        [GROUP BY col [, col...]]
        [ORDER BY ocol [ASC|DESC] [, ...]] [LIMIT n]
    item := * | col | fn(col) | agg | <any of those> AS alias
    agg  := COUNT(*) | COUNT(col) | SUM(col) | AVG(col)
            | MIN(col) | MAX(col)
    pred := col <op> literal | col IS [NOT] NULL
    op   := = | != | <> | < | <= | > | >=      literal := number | 'text'

Semantics (the SQL ones, scoped to one table):
- WHERE runs before everything, so filtered rows are never featurized.
- Aggregates skip NULL/NaN inputs; ``COUNT(*)`` counts rows; an empty
  group yields NULL (``COUNT`` yields 0). Without GROUP BY, aggregates
  collapse the table to one row and may not mix with plain columns.
- With GROUP BY, every non-aggregate item must be a grouping column;
  NULL keys form one group (SQL GROUP BY semantics).
- ORDER BY names OUTPUT columns (aliases included), NULLs last in both
  directions; it runs after grouping, LIMIT last.
- Still NOT here (use a real engine): JOIN, HAVING, subqueries,
  DISTINCT, expressions beyond a single column/UDF/aggregate call.

Registered UDFs come from :mod:`tpudl.udf.registry`; execution of a model
UDF is a batched jitted call, not per-row Python. Aggregate names are
reserved words and win over a same-named registered UDF.
"""

from __future__ import annotations

import re

import numpy as np

from tpudl.frame.frame import Frame, null_mask

__all__ = ["sql"]

# position-is-outside-quotes guard (even number of quotes remaining) —
# the same trick _AND_SPLIT_RE uses, so clause keywords inside WHERE
# string literals ('a order by b') never terminate the WHERE group
_Q = r"(?=(?:[^']*'[^']*')*[^']*$)"
_SELECT_RE = re.compile(
    r"^\s*select\s+(?P<items>.+?)\s+from\s+(?P<table>\w+)"
    rf"(?:\s+where\s+{_Q}(?P<where>.+?))?"
    rf"(?:\s+group\s+by\s+{_Q}(?P<group>.+?))?"
    rf"(?:\s+order\s+by\s+{_Q}(?P<order>.+?))?"
    rf"(?:\s+limit\s+{_Q}(?P<limit>\d+))?\s*;?\s*$",
    re.IGNORECASE | re.DOTALL,
)
_ITEM_RE = re.compile(
    r"^\s*(?:(?P<fn>\w+)\s*\(\s*(?P<arg>\w+)\s*\)|(?P<col>\w+))"
    r"(?:\s+as\s+(?P<alias>\w+))?\s*$",
    re.IGNORECASE,
)
_CMP_RE = re.compile(
    r"^\s*(?P<col>\w+)\s*(?P<op><=|>=|!=|<>|=|<|>)\s*"
    r"(?P<lit>-?\d+(?:\.\d+)?|'[^']*')\s*$")
_NULL_RE = re.compile(
    r"^\s*(?P<col>\w+)\s+is\s+(?P<neg>not\s+)?null\s*$", re.IGNORECASE)


_AGG_FNS = ("count", "sum", "avg", "mean", "min", "max")
_AGG_RE = re.compile(
    r"^\s*(?P<agg>" + "|".join(_AGG_FNS) + r")\s*\(\s*(?P<arg>\*|\w+)\s*\)"
    r"(?:\s+as\s+(?P<alias>\w+))?\s*$",
    re.IGNORECASE,
)


def sql(query: str, tables: dict[str, Frame]) -> Frame:
    m = _SELECT_RE.match(query)
    if not m:
        raise ValueError(
            "unsupported SQL (only 'SELECT items FROM table [WHERE preds] "
            f"[GROUP BY cols] [ORDER BY cols] [LIMIT n]'): {query!r}")
    table = m.group("table")
    if table not in tables:
        raise KeyError(f"unknown table {table!r}; registered: {sorted(tables)}")
    frame = tables[table]
    if m.group("where"):
        frame = frame.filter_rows(_where_mask(frame, m.group("where")))

    items = [_parse_item(raw) for raw in _split_items(m.group("items"))]
    group_cols = ([c.strip() for c in m.group("group").split(",")]
                  if m.group("group") else None)
    has_agg = any(kind == "agg" for kind, *_ in items)
    limit = int(m.group("limit")) if m.group("limit") is not None else None
    if group_cols is not None or has_agg:
        out = _aggregate(frame, items, group_cols or [])
    else:
        if limit is not None and not m.group("order"):
            # LIMIT pushdown: without ORDER BY the first n rows ARE the
            # answer, so a limited featurize query must only run the
            # UDF over n rows (the 'dropped rows are never featurized'
            # contract extends to rows past the limit)
            frame = frame.limit(limit)
            limit = None
        out = _project(frame, items)

    if m.group("order"):
        out = out.take(_order_perm(out, m.group("order")))
    if limit is not None:
        out = out.limit(limit)
    return out


def _parse_item(raw: str):
    """→ ("star", None, None) | ("col", col, name) |
    ("udf", (fn, arg), name) | ("agg", (fn, arg), name)."""
    if raw == "*":
        return ("star", None, None)
    am = _AGG_RE.match(raw)
    if am:
        fn = am.group("agg").lower()
        fn = "avg" if fn == "mean" else fn
        arg = am.group("arg")
        if arg == "*" and fn != "count":
            raise ValueError(f"{fn.upper()}(*) is not SQL; name a column")
        name = am.group("alias") or f"{fn}({arg})"
        return ("agg", (fn, arg), name)
    im = _ITEM_RE.match(raw)
    if not im:
        raise ValueError(f"unsupported select item: {raw!r}")
    if im.group("col"):
        return ("col", im.group("col"),
                im.group("alias") or im.group("col"))
    fn, arg = im.group("fn"), im.group("arg")
    return ("udf", (fn, arg), im.group("alias") or f"{fn}({arg})")


def _project(frame: Frame, items) -> Frame:
    out: dict[str, object] = {}

    def put(name, value):
        if name in out:
            raise ValueError(f"duplicate output column {name!r}")
        out[name] = value

    for kind, spec, name in items:
        if kind == "star":
            for col in frame.columns:
                put(col, frame[col])
        elif kind == "col":
            put(name, _col(frame, spec))
        else:  # udf
            from tpudl.udf import registry

            fn, arg = spec
            udf = registry.get_udf(fn)
            result = udf(frame.select(arg)
                         .with_column_renamed(arg, udf.input_col))
            put(name, result[udf.output_col])
    return Frame(out)


def _aggregate(frame: Frame, items, group_cols: list[str]) -> Frame:
    for kind, spec, name in items:
        if kind == "star":
            raise ValueError("SELECT * cannot be combined with aggregates")
        if kind == "udf":
            raise ValueError(
                f"UDF {spec[0]!r} inside an aggregate query is "
                "unsupported; featurize first, then aggregate")
        if kind == "col" and spec not in group_cols:
            raise ValueError(
                f"column {spec!r} must appear in GROUP BY or inside an "
                "aggregate")
    # group keys → row indices, first-appearance order; NULL/NaN keys
    # normalize to one sentinel so they form a single group
    if group_cols:
        key_cols = [_col(frame, g) for g in group_cols]
        nulls = [null_mask(c) for c in key_cols]
        groups: dict[tuple, list[int]] = {}
        for i in range(len(frame)):
            key = tuple(None if n[i] else _hashable(c[i])
                        for c, n in zip(key_cols, nulls))
            groups.setdefault(key, []).append(i)
    else:
        groups = {(): list(range(len(frame)))}

    out: dict[str, list] = {}
    for kind, spec, name in items:
        if name in out:
            raise ValueError(f"duplicate output column {name!r}")
        out[name] = []
    for key, rows in groups.items():
        for kind, spec, name in items:
            if kind == "col":
                out[name].append(key[group_cols.index(spec)])
            else:
                fn, arg = spec
                out[name].append(_agg_one(frame, fn, arg, rows))
    return Frame({n: np.asarray(v) if _all_numeric(v) else
                  np.asarray(v, dtype=object)
                  for n, v in out.items()})


def _hashable(v):
    return v.item() if isinstance(v, np.generic) else v


def _all_numeric(vals) -> bool:
    return all(isinstance(v, (int, float, np.number)) and v is not None
               for v in vals)


def _agg_one(frame: Frame, fn: str, arg: str, rows: list[int]):
    if fn == "count" and arg == "*":
        return len(rows)
    col = _col(frame, arg)
    sub = col[rows] if len(rows) else col[:0]
    valid = ~null_mask(sub)
    vals = sub[valid]
    if fn == "count":
        return int(valid.sum())
    if len(vals) == 0:
        return None  # SQL: aggregate over empty/all-NULL is NULL
    pyvals = [(v.item() if isinstance(v, np.generic) else v) for v in vals]
    if fn == "min":
        return min(pyvals)
    if fn == "max":
        return max(pyvals)
    total = sum(pyvals)  # raises TypeError on non-numeric — correct
    return total / len(pyvals) if fn == "avg" else total


_ORDER_RE = re.compile(
    r"^\s*(?P<col>\w+)(?:\s+(?P<dir>asc|desc))?\s*$", re.IGNORECASE)


def _order_perm(frame: Frame, order: str) -> np.ndarray:
    """Row permutation for ORDER BY over OUTPUT columns: stable
    multi-key sort, NULL/NaN rows last in both directions."""
    perm = np.arange(len(frame))
    for part in reversed(order.split(",")):  # stable: minor keys first
        om = _ORDER_RE.match(part)
        if not om:
            raise ValueError(f"unsupported ORDER BY term {part!r} "
                             "(use col [ASC|DESC])")
        col = _col(frame, om.group("col"))[perm]
        desc = (om.group("dir") or "asc").lower() == "desc"
        nulls = null_mask(col)
        if not np.issubdtype(col.dtype, np.number):
            # object AND plain-string ('<U') columns: python-level sort
            # (astype(float) on '<U' would raise, not sort)
            keyed = sorted(
                range(len(col)),
                key=lambda i: (nulls[i],
                               _neg_key(col[i], desc) if not nulls[i]
                               else 0))
            idx = np.asarray(keyed, dtype=int)
        else:
            vals = col.astype(float, copy=True)
            # two-key stable sort, null flag primary: real ±inf values
            # keep their order and NULL/NaN rows still land last (a
            # ±inf SENTINEL for nulls would interleave them with real
            # infinities)
            vals[nulls] = 0.0
            idx = np.lexsort((-vals if desc else vals, nulls))
        perm = perm[idx]
    return perm


class _Reversed:
    """Total-order inverter for python-object sort keys (DESC on object
    columns without assuming numeric negation works)."""

    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, other):
        return other.v < self.v


def _neg_key(v, desc: bool):
    return _Reversed(v) if desc else v


# split on AND only OUTSIDE single-quoted literals (even-quote lookahead)
_AND_SPLIT_RE = re.compile(
    r"\s+and\s+(?=(?:[^']*'[^']*')*[^']*$)", re.IGNORECASE)


def _where_mask(frame: Frame, where: str) -> np.ndarray:
    """AND-conjunction of simple predicates → boolean row mask.

    NULL semantics follow SQL three-valued logic for both column kinds:
    object ``None`` and float ``NaN`` rows fail EVERY comparison
    (including ``!=``) and are selected only by ``IS NULL``."""
    mask = np.ones(len(frame), dtype=bool)
    for pred in _AND_SPLIT_RE.split(where.strip()):
        nm = _NULL_RE.match(pred)
        if nm:
            isnull = null_mask(_col(frame, nm.group("col")))
            mask &= ~isnull if nm.group("neg") else isnull
            continue
        cm = _CMP_RE.match(pred)
        if not cm:
            raise ValueError(
                f"unsupported WHERE predicate {pred!r} (use col <op> "
                "literal or col IS [NOT] NULL)")
        col = _col(frame, cm.group("col"))
        lit_raw = cm.group("lit")
        lit = lit_raw[1:-1] if lit_raw.startswith("'") else float(lit_raw)
        op = cm.group("op")
        if col.dtype == object:
            # per-row compare: None and type-mismatched values (e.g.
            # 'text' < 5) both fail the predicate, like SQL NULL
            mask &= np.array([_row_cmp(v, op, lit) for v in col], dtype=bool)
        else:
            if isinstance(lit, str):
                # numpy would broadcast a scalar False here, silently
                # selecting nothing; name the predicate instead
                raise ValueError(
                    f"WHERE predicate {pred!r} compares numeric column "
                    f"{cm.group('col')!r} against string literal {lit_raw}")
            res = np.asarray(_cmp(col, op, lit), dtype=bool)
            if np.issubdtype(col.dtype, np.floating):
                res &= ~np.isnan(col)  # NaN fails != too, not just ==/<
            mask &= res
    return mask


def _col(frame: Frame, name: str) -> np.ndarray:
    if name not in frame:
        raise KeyError(f"unknown column {name!r}; have {frame.columns}")
    return frame[name]


def _row_cmp(v, op: str, lit) -> bool:
    if v is None:
        return False
    try:
        return bool(_cmp(v, op, lit))
    except TypeError:
        return False  # 'text' < 5 etc: fails the predicate, not the query


def _cmp(a, op: str, b):
    if op == "=":
        return a == b
    if op in ("!=", "<>"):
        return a != b
    if op == "<":
        return a < b
    if op == "<=":
        return a <= b
    if op == ">":
        return a > b
    return a >= b


def _split_items(items: str) -> list[str]:
    # split on top-level commas (no nested parens in our grammar)
    return [p for p in (s.strip() for s in items.split(",")) if p]
