"""Deliberately tiny SQL SELECT layer for model-as-UDF parity.

The reference registers Keras models as Spark SQL UDFs and users write
``SELECT my_udf(image) FROM images`` (ref: sparkdl udf/keras_image_model.py
~L30, graph/tensorframes_udf.py ~L20; SURVEY.md §3.4). We are explicitly
NOT a query engine (SURVEY.md §7.1 item 3), so this module implements only
the shapes that contract and its surrounding examples need:

    SELECT <item> [, <item>...] FROM <table>
        [WHERE <pred> [AND <pred>...]] [LIMIT n]
    item := * | col | fn(col) | col AS alias | fn(col) AS alias
    pred := col <op> literal | col IS [NOT] NULL
    op   := = | != | <> | < | <= | > | >=      literal := number | 'text'

Registered UDFs come from :mod:`tpudl.udf.registry`; execution of a model
UDF is a batched jitted call, not per-row Python. WHERE runs before the
UDF projection, so filtered rows are never featurized.
"""

from __future__ import annotations

import re

import numpy as np

from tpudl.frame.frame import Frame, null_mask

__all__ = ["sql"]

_SELECT_RE = re.compile(
    r"^\s*select\s+(?P<items>.+?)\s+from\s+(?P<table>\w+)"
    r"(?:\s+where\s+(?P<where>.+?))?"
    r"(?:\s+limit\s+(?P<limit>\d+))?\s*;?\s*$",
    re.IGNORECASE | re.DOTALL,
)
_ITEM_RE = re.compile(
    r"^\s*(?:(?P<fn>\w+)\s*\(\s*(?P<arg>\w+)\s*\)|(?P<col>\w+))"
    r"(?:\s+as\s+(?P<alias>\w+))?\s*$",
    re.IGNORECASE,
)
_CMP_RE = re.compile(
    r"^\s*(?P<col>\w+)\s*(?P<op><=|>=|!=|<>|=|<|>)\s*"
    r"(?P<lit>-?\d+(?:\.\d+)?|'[^']*')\s*$")
_NULL_RE = re.compile(
    r"^\s*(?P<col>\w+)\s+is\s+(?P<neg>not\s+)?null\s*$", re.IGNORECASE)


def sql(query: str, tables: dict[str, Frame]) -> Frame:
    m = _SELECT_RE.match(query)
    if not m:
        raise ValueError(
            "unsupported SQL (only 'SELECT items FROM table [WHERE preds] "
            f"[LIMIT n]'): {query!r}")
    table = m.group("table")
    if table not in tables:
        raise KeyError(f"unknown table {table!r}; registered: {sorted(tables)}")
    frame = tables[table]
    if m.group("where"):
        frame = frame.filter_rows(_where_mask(frame, m.group("where")))
    limit = m.group("limit")
    if limit is not None:
        frame = frame.limit(int(limit))

    out: dict[str, object] = {}
    for raw in _split_items(m.group("items")):
        if raw == "*":
            for col in frame.columns:
                if col in out:
                    raise ValueError(f"duplicate output column {col!r}")
                out[col] = frame[col]
            continue
        im = _ITEM_RE.match(raw)
        if not im:
            raise ValueError(f"unsupported select item: {raw!r}")
        if im.group("col"):
            name = im.group("alias") or im.group("col")
            if name in out:
                raise ValueError(f"duplicate output column {name!r}")
            out[name] = frame[im.group("col")]
        else:
            from tpudl.udf import registry

            fn_name, arg = im.group("fn"), im.group("arg")
            name = im.group("alias") or f"{fn_name}({arg})"
            if name in out:
                raise ValueError(f"duplicate output column {name!r}")
            udf = registry.get_udf(fn_name)
            result = udf(frame.select(arg).with_column_renamed(arg, udf.input_col))
            out[name] = result[udf.output_col]
    return Frame(out)


# split on AND only OUTSIDE single-quoted literals (even-quote lookahead)
_AND_SPLIT_RE = re.compile(
    r"\s+and\s+(?=(?:[^']*'[^']*')*[^']*$)", re.IGNORECASE)


def _where_mask(frame: Frame, where: str) -> np.ndarray:
    """AND-conjunction of simple predicates → boolean row mask.

    NULL semantics follow SQL three-valued logic for both column kinds:
    object ``None`` and float ``NaN`` rows fail EVERY comparison
    (including ``!=``) and are selected only by ``IS NULL``."""
    mask = np.ones(len(frame), dtype=bool)
    for pred in _AND_SPLIT_RE.split(where.strip()):
        nm = _NULL_RE.match(pred)
        if nm:
            isnull = null_mask(_col(frame, nm.group("col")))
            mask &= ~isnull if nm.group("neg") else isnull
            continue
        cm = _CMP_RE.match(pred)
        if not cm:
            raise ValueError(
                f"unsupported WHERE predicate {pred!r} (use col <op> "
                "literal or col IS [NOT] NULL)")
        col = _col(frame, cm.group("col"))
        lit_raw = cm.group("lit")
        lit = lit_raw[1:-1] if lit_raw.startswith("'") else float(lit_raw)
        op = cm.group("op")
        if col.dtype == object:
            # per-row compare: None and type-mismatched values (e.g.
            # 'text' < 5) both fail the predicate, like SQL NULL
            mask &= np.array([_row_cmp(v, op, lit) for v in col], dtype=bool)
        else:
            if isinstance(lit, str):
                # numpy would broadcast a scalar False here, silently
                # selecting nothing; name the predicate instead
                raise ValueError(
                    f"WHERE predicate {pred!r} compares numeric column "
                    f"{cm.group('col')!r} against string literal {lit_raw}")
            res = np.asarray(_cmp(col, op, lit), dtype=bool)
            if np.issubdtype(col.dtype, np.floating):
                res &= ~np.isnan(col)  # NaN fails != too, not just ==/<
            mask &= res
    return mask


def _col(frame: Frame, name: str) -> np.ndarray:
    if name not in frame:
        raise KeyError(f"unknown column {name!r}; have {frame.columns}")
    return frame[name]


def _row_cmp(v, op: str, lit) -> bool:
    if v is None:
        return False
    try:
        return bool(_cmp(v, op, lit))
    except TypeError:
        return False  # 'text' < 5 etc: fails the predicate, not the query


def _cmp(a, op: str, b):
    if op == "=":
        return a == b
    if op in ("!=", "<>"):
        return a != b
    if op == "<":
        return a < b
    if op == "<=":
        return a <= b
    if op == ">":
        return a > b
    return a >= b


def _split_items(items: str) -> list[str]:
    # split on top-level commas (no nested parens in our grammar)
    return [p for p in (s.strip() for s in items.split(",")) if p]
