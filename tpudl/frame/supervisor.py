"""Fault-contained executor supervision: taxonomy + degradation ladder.

PRs 10-12 built a deep fast path — D-deep async dispatch windows, fused
mesh transfers, buffer donation, HBM residency — where a single device
OOM, transfer failure or wedged stage today propagates as a raw XLA
error through the pool unwind: the whole ``map_batches`` run dies for a
fault the executor could have absorbed. This module is the containment
layer (FAULTS.md is the operator guide):

1. **taxonomy** — :func:`classify_exception` sorts an executor-stage
   exception into a typed kind, anchored on the XLA runtime-error
   message (``RESOURCE_EXHAUSTED`` → device OOM), the failing stage
   (the ``tpudl_stage`` tag :meth:`PipelineReport.stage` leaves on an
   escaping exception), the shared retry classifier
   (:mod:`tpudl.jobs.retry`: IO-shaped = transient, programming errors
   and ``tpudl_fatal`` = never retried), and the traceck sentinel's
   storm counter. The typed exceptions (:class:`DeviceOOM`,
   :class:`TransferError`, :class:`RecompileStorm`, :class:`StageFault`,
   :class:`Fatal`) are what a supervised run raises when recovery is
   exhausted — always chained to the original error;

2. **degradation ladder** — a :class:`Supervisor` retries the whole run
   under a bounded, ORDERED sequence of rungs instead of dying:

   - device OOM first evicts every unpinned HBM-cache entry
     (``evict_hbm``) and retries with the wire path intact;
   - transient transfer/IO faults route through the ONE shared
     :class:`tpudl.jobs.retry.RetryPolicy` (``io_policy()`` — same
     attempts/backoff knobs as every other IO retry in the tree,
     every attempt in ``retry.frame.transfer`` + the flight error
     ring);
   - repeated stage faults walk the ladder: halve ``dispatch_depth``
     (repeatedly, to 1), then drop ``fuse_steps`` to 1, then disable
     donation, then fall back to the conservative serial arm (the
     ``TPUDL_MESH_FAST_PATH=0`` shape: no prefetch, no window, no
     fusion, no donation, no residency).

   Every rung preserves the bitwise-parity contract the
   depth×donate×fuse matrices already pin (tests/test_frame_async.py,
   test_mesh_executor.py): a degraded run returns the SAME bytes as a
   healthy one, only slower. Rungs are bounded by
   ``TPUDL_FRAME_DEGRADE_MAX_RUNGS``; exhaustion writes a flight dump
   and raises the typed error for the last fault kind.

Supervision is an OPERATOR decision, off by default
(``TPUDL_FRAME_DEGRADE=1`` or ``map_batches(supervise=True)`` arms it):
retrying a run re-executes user code, so the layer that owns the
process — the serving subsystem, a long bench, a production job — arms
it deliberately, exactly like ``device_cache``. Unarmed cost is one env
read per run (the executor overhead guard in tests/test_supervisor.py
pins it under the same <5% envelope as the recorder+watchdog).

Observability: every rung files a ``frame.degraded`` event into the
flight recorder's error ring and bumps ``frame.degraded.rungs``;
recovery lands ``degraded_to`` + ``recovered_batches`` on the
PipelineReport (surfaced by ``obs top``); exhaustion bumps
``frame.degraded.exhausted`` and leaves a schema-valid
``tpudl-dump-*`` whose death ``obs doctor`` classifies as
``degraded_run``.
"""

from __future__ import annotations

import logging
import os
import time

from tpudl.jobs import retry as _retry

__all__ = ["FaultError", "DeviceOOM", "TransferError", "RecompileStorm",
           "StageFault", "Fatal", "Supervisor", "classify_exception",
           "enabled", "LADDER"]

log = logging.getLogger("tpudl.frame.supervisor")

# the ordered ladder (FAULTS.md): generic stage faults walk these rungs
# top to bottom; "dispatch_depth" repeats (halving) until depth is 1
LADDER = ("dispatch_depth", "fuse_steps", "donate", "serial")

# covers evict_hbm + depth 8 -> 1 (3 halvings) + fuse + donate; the
# serial rung is guaranteed ONE attempt even past this budget (the
# last resort is never left untried), so total rungs <= max_rungs + 1
DEFAULT_MAX_RUNGS = 6


# -- the typed taxonomy ------------------------------------------------------
class FaultError(RuntimeError):
    """Base of the executor fault taxonomy. Raised by a supervised run
    when the degradation ladder is exhausted — always ``raise ... from``
    the original exception, so the raw XLA/IO error stays attached.
    ``stage`` is the executor stage the last fault escaped from (the
    ``tpudl_stage`` tag), ``rungs`` the ladder rungs that were tried."""

    kind = "stage"

    def __init__(self, message: str, *, stage: str | None = None,
                 rungs=()):
        super().__init__(message)
        self.stage = stage
        self.rungs = tuple(rungs)


class DeviceOOM(FaultError):
    """Device memory exhausted (XLA ``RESOURCE_EXHAUSTED``): recovery
    evicts unpinned HBM-cache entries and retries; shrinking rungs
    (smaller window, no fusion) follow if it recurs."""

    kind = "oom"


class TransferError(FaultError):
    """Host→device transfer / IO fault at the infeed edge: transient
    ones ride the shared RetryPolicy; a persistent one degrades and
    eventually raises this."""

    kind = "transfer"


class RecompileStorm(FaultError):
    """The traceck sentinel counted new storms during the failed
    attempt: the run was recompiling instead of computing. Recovery
    pins fuse_steps/autotune down to stop the program-shape churn."""

    kind = "recompile_storm"


class StageFault(FaultError):
    """An executor stage failed for no more specific reason — the
    generic ladder (depth, fusion, donation, serial) handles it."""

    kind = "stage"


class Fatal(FaultError):
    """Not recoverable by ANY rung (programming error, preemption,
    interpreter shutdown). ``tpudl_fatal`` keeps every retry layer —
    this one, gang restarts, trial retries — from fighting it."""

    kind = "fatal"
    tpudl_fatal = True


_TYPE_FOR = {"oom": DeviceOOM, "transfer": TransferError,
             "recompile_storm": RecompileStorm, "stage": StageFault,
             "fatal": Fatal}

# device-OOM anchoring: jaxlib raises XlaRuntimeError whose text leads
# with the grpc-style status name. The bare "out of memory" wording
# only counts on that TYPE — a user fn raising RuntimeError('CUDA out
# of memory') from some other library must not trigger a process-wide
# HBM eviction (classified "stage", handled by the generic ladder)
_OOM_STATUS = "RESOURCE_EXHAUSTED"
# exception kinds that never benefit from a retry at ANY rung: the
# shared contract with tpudl.jobs.retry (PROGRAMMING_ERRORS, tpudl_fatal
# and the interpreter-shutdown set), plus schema errors the executor
# raises before any batch runs (unknown columns, bad output arity)
_SETUP_ERRORS = (KeyError,)


def classify_exception(exc: BaseException, *, stage: str | None = None,
                       storm: bool = False) -> str:
    """One executor-attempt exception → a taxonomy kind
    (``oom`` / ``transfer`` / ``recompile_storm`` / ``stage`` /
    ``fatal``). ``stage`` is the ``tpudl_stage`` tag the innermost
    :meth:`PipelineReport.stage` block left on the exception; ``storm``
    says whether the traceck sentinel counted new storms during the
    attempt (the supervisor samples ``traceck.storms`` around it)."""
    if (_retry.is_fatal(exc)
            or isinstance(exc, _retry.PROGRAMMING_ERRORS)
            or isinstance(exc, _SETUP_ERRORS)):
        return "fatal"
    msg = str(exc)
    if _OOM_STATUS in msg or (
            type(exc).__name__ == "XlaRuntimeError"
            and "out of memory" in msg.lower()):
        return "oom"
    if storm:
        return "recompile_storm"
    # the transfer edge: either the fault escaped the h2d stage, or it
    # is IO-shaped per the ONE retry classifier's transient default
    if stage == "h2d" or isinstance(
            exc, (OSError, ConnectionError, TimeoutError,
                  InterruptedError)):
        return "transfer"
    return "stage"


def enabled(kwarg=None) -> bool:
    """Is supervision armed for this run? The explicit ``supervise=``
    kwarg wins; else ``TPUDL_FRAME_DEGRADE`` (default OFF — arming
    changes which exception TYPE a failing run raises and re-executes
    user code on retry, so the process owner opts in)."""
    if kwarg is not None:
        return bool(kwarg)
    return os.environ.get("TPUDL_FRAME_DEGRADE", "0") == "1"


def _storms() -> float:
    """Current traceck storm count (0 when the sentinel is unarmed —
    the counter simply never moves)."""
    from tpudl.obs import metrics as _m

    return float(_m.counter("traceck.storms").value)


class Supervisor:
    """One supervised run's ladder state. Single-consumer by design:
    the supervise loop, classification and rung bookkeeping all run on
    the thread that called ``map_batches`` (pool threads only ever
    RAISE into it via the infeed/window unwind), so no lock is
    needed."""

    def __init__(self, *, max_rungs: int | None = None):
        self.max_rungs = (int(max_rungs) if max_rungs is not None
                          else max(1, _retry._env_int(
                              "TPUDL_FRAME_DEGRADE_MAX_RUNGS",
                              DEFAULT_MAX_RUNGS)))
        self.rungs: list[str] = []      # applied rung labels, in order
        self.overrides: dict = {}       # kwargs for the next attempt
        self.recovered_batches = 0
        self.transfer_attempts = 0      # shared-RetryPolicy budget used
        self.hbm_evicted = False        # the OOM evict rung fired
        self._ladder_pos = 0            # index into LADDER
        self._report = None             # current attempt's PipelineReport
        self._hb = None

    # -- executor hooks ------------------------------------------------------
    def note_report(self, report) -> None:
        """Called by the executor once per attempt, right after the
        attempt's PipelineReport config is resolved — the ladder reads
        the RESOLVED knob values (env/autotune included) to know what
        to halve, and recovery stamps its outcome onto this report."""
        self._report = report
        if self.rungs:
            report.config["degraded_to"] = self.degraded_to

    @property
    def degraded_to(self) -> str | None:
        """The deepest rung applied so far (what the run degraded TO),
        e.g. ``dispatch_depth=1``, ``serial`` — the PipelineReport /
        ``obs top`` field."""
        return self.rungs[-1] if self.rungs else None

    # -- the supervise loop --------------------------------------------------
    def supervise(self, attempt):
        """Run ``attempt(overrides)`` under the ladder: classified
        recoverable faults apply a rung and re-run; fatal faults and
        ladder exhaustion re-raise (typed). The whole-run retry is what
        keeps recovery bitwise-honest: partial outputs of a failed
        attempt are discarded, and the surviving attempt's outputs are
        exactly what a healthy run of that config produces."""
        from tpudl.obs import watchdog as _watchdog

        attempt_no = 0
        # one heartbeat for the whole supervised run, PARENT of each
        # attempt's executor heartbeat (nested registration): it is
        # re-armed by every attempt start, every rung and every backoff
        # slice, so a stage the supervisor is actively retrying is
        # never double-flagged as a stall
        with _watchdog.heartbeat("frame.supervisor",
                                 max_rungs=self.max_rungs) as hb:
            self._hb = hb
            while True:
                attempt_no += 1
                hb.beat(attempt=attempt_no, rungs=len(self.rungs))
                storms0 = _storms()
                try:
                    result = attempt(dict(self.overrides))
                except BaseException as e:
                    kind = classify_exception(
                        e, stage=getattr(e, "tpudl_stage", None),
                        storm=_storms() > storms0)
                    if kind == "fatal":
                        raise
                    self._on_fault(e, kind, attempt_no)  # raises typed
                    continue                             # ... or rung'd
                if self.rungs:
                    # only a RUNG'D run records recovery: a transfer
                    # retry changed no knob and already left its trail
                    # as retry.frame.transfer — stamping it here would
                    # over-report degradation (the frame.degraded.*
                    # registry contract)
                    self._record_recovery()
                return result

    # -- fault handling ------------------------------------------------------
    def _on_fault(self, exc: BaseException, kind: str,
                  attempt_no: int) -> None:
        """Pick and apply the next rung for ``kind`` — or, when the
        ladder is exhausted, dump the black box and raise the typed
        error chained to ``exc``."""
        stage = getattr(exc, "tpudl_stage", None)
        if kind == "transfer" and self._retry_transfer(exc, stage):
            return
        if (kind == "oom" and not self.hbm_evicted
                and len(self.rungs) < self.max_rungs):
            # budget-checked like every ladder rung, so the documented
            # "total rungs <= max_rungs + 1" bound holds (only the
            # guaranteed serial attempt may exceed the budget)
            self.hbm_evicted = True
            freed = self._evict_hbm()
            self._apply_rung("evict_hbm", exc, stage, attempt_no,
                             freed_bytes=freed)
            return
        if kind == "recompile_storm" and "fuse_steps" not in [
                r.split("=")[0] for r in self.rungs]:
            # stop the program-shape churn first: one fused variant
            # fewer per retrace beats shrinking the window
            if self._ladder_pos < 1:
                self._ladder_pos = 1  # skip ahead to the fuse rung
        over_budget = len(self.rungs) >= self.max_rungs
        label = None if over_budget else self._next_ladder_rung()
        if label is None:
            # out of budget, or out of intermediate rungs: the
            # conservative serial arm is the documented LAST RESORT
            # and always gets its one attempt before the typed raise —
            # an eviction or a deep halving sequence consuming the
            # budget must not leave the rung most likely to survive
            # untried
            if "serial" in self.rungs:
                self._exhausted(exc, kind, stage)
            label = self._jump_to_serial()
        self._apply_rung(label, exc, stage, attempt_no)

    def _cfg(self, key, default):
        """The knob value the NEXT attempt will run: an override this
        ladder already applied wins over the last attempt's resolved
        report config (consecutive halvings must see each other)."""
        if key in self.overrides:
            return self.overrides[key]
        cfg = self._report.config if self._report is not None else {}
        v = cfg.get(key)
        return default if v is None else v

    def _next_ladder_rung(self) -> str | None:
        """The next applicable rung label, advancing ``_ladder_pos``
        past rungs the current config makes a no-op (depth already 1,
        fusion already off, ...)."""
        while self._ladder_pos < len(LADDER):
            rung = LADDER[self._ladder_pos]
            if rung == "dispatch_depth":
                depth = int(self._cfg("dispatch_depth", 1))
                if depth > 1:
                    half = max(1, depth // 2)
                    self.overrides["dispatch_depth"] = half
                    return f"dispatch_depth={half}"  # stay on this rung
                self._ladder_pos += 1
            elif rung == "fuse_steps":
                self._ladder_pos += 1
                if int(self._cfg("fuse_steps", 1)) > 1:
                    self.overrides["fuse_steps"] = 1
                    self.overrides["autotune"] = False
                    return "fuse_steps=1"
            elif rung == "donate":
                self._ladder_pos += 1
                if bool(self._cfg("donate", False)):
                    self.overrides["donate"] = False
                    return "donate=off"
            else:  # serial: the conservative arm, always applicable once
                return self._jump_to_serial()
        return None

    def _jump_to_serial(self) -> str:
        """Apply the last-resort rung (the ``TPUDL_MESH_FAST_PATH=0``
        shape) and close the ladder behind it."""
        self._ladder_pos = len(LADDER)
        self.overrides.update(
            prefetch=False, fuse_steps=1, dispatch_depth=1,
            donate=False, autotune=False, device_cache=False)
        return "serial"

    def _retry_transfer(self, exc: BaseException,
                        stage: str | None) -> bool:
        """Route one transfer/IO fault through the ONE shared
        RetryPolicy (``tpudl.jobs.retry.io_policy`` — the same
        attempts/backoff budget as every file read in the tree). True =
        a retry attempt was paid for (no knob change); False = the
        policy's budget is spent and the fault falls through to the
        ladder."""
        pol = _retry.io_policy()
        self.transfer_attempts += 1
        if self.transfer_attempts >= pol.max_attempts:
            return False
        delay = pol.backoff_s(self.transfer_attempts)
        # a retry is NOT a degradation: no knob changed, so it must
        # not touch frame.degraded.* nor the frame.degraded ring (the
        # doctor's degraded_run evidence would over-report). The
        # policy's own record() already files retry.frame.transfer
        # into the metrics + the flight error ring — the same trail as
        # every other io_policy consumer
        pol.record("frame.transfer", exc,
                   attempt=self.transfer_attempts, backoff_s=delay)
        log.warning(
            "frame.supervisor: transfer fault %s in stage %s — retry "
            "%d/%d via the shared io_policy (backoff %.3fs)",
            type(exc).__name__, stage or "?", self.transfer_attempts,
            pol.max_attempts - 1, delay)
        self._sleep_with_beats(delay)
        return True

    def _evict_hbm(self) -> int:
        """The OOM rung's action: evict every unpinned device-cache
        entry, freeing HBM for the retry (pinned entries — buffers an
        in-flight dispatch of ANOTHER run still reads — stay)."""
        try:
            from tpudl.data import device_cache as _dc

            _n, freed = _dc.get_device_cache().evict_unpinned()
            return freed
        # best-effort recovery action: a cache that cannot evict (torn
        # import mid-OOM) just means the retry runs against the same
        # memory pressure, and the ladder's shrinking rungs still follow
        except Exception:
            return 0

    def _apply_rung(self, label: str, exc: BaseException,
                    stage: str | None, attempt_no: int, **extra) -> None:
        self.rungs.append(label)
        self._record_rung(label, exc, stage, attempt=attempt_no,
                          **extra)

    def _record_rung(self, label: str, exc: BaseException,
                     stage: str | None, **extra) -> None:
        """One degradation event: flight error ring (kind
        ``frame.degraded``) + ``frame.degraded.rungs`` + a warning —
        recovery is silent for the caller, loud for the operator."""
        try:
            from tpudl.obs import attribution as _attr
            from tpudl.obs import flight as _flight
            from tpudl.obs import metrics as _m

            _m.counter("frame.degraded.rungs").inc()
            # attribution pairing with frame.degraded.rungs (same
            # best-effort guard: both sides charge or neither does)
            _attr.charge("degradations")
            _flight.record_error(
                "frame.degraded", exc, rung=label, stage=stage,
                rungs_applied=len(self.rungs), **extra)
        # tpudl: ignore[swallowed-except] — the observer must never
        # take down the recovery it narrates
        except Exception:
            pass
        log.warning(
            "frame.supervisor: %s after %s in stage %s — retrying "
            "(rung %d/%d)", label, type(exc).__name__, stage or "?",
            len(self.rungs), self.max_rungs)

    def _record_recovery(self) -> None:
        """The run survived on a degraded rung: stamp the outcome onto
        the surviving attempt's report + the process-wide counters."""
        batches = 0
        if self._report is not None:
            calls = self._report.report().get("stage_calls") or {}
            batches = int(calls.get("dispatch", 0))
            if self.degraded_to is not None:
                self._report.config["degraded_to"] = self.degraded_to
            self._report.config["recovered_batches"] = batches
        self.recovered_batches = batches
        try:
            from tpudl.obs import metrics as _m

            _m.counter("frame.degraded.recovered_batches").inc(batches)
        # tpudl: ignore[swallowed-except] — the observer must never
        # take down the recovery it narrates
        except Exception:
            pass

    def _exhausted(self, exc: BaseException, kind: str,
                   stage: str | None) -> None:
        """Out of rungs: file the counters, write the black box, and
        raise the TYPED error chained to the original — the acceptance
        contract is "recovers bitwise or exits typed with a dump",
        never a raw pool-unwind error and never a hang."""
        try:
            from tpudl.obs import flight as _flight
            from tpudl.obs import metrics as _m

            _m.counter("frame.degraded.exhausted").inc()
            # ctx key is fault_kind, NOT kind: record_error's first
            # positional is already named kind (the PR-7 kwarg-collision
            # class, regression-tested in test_supervisor.py)
            _flight.record_error(
                "frame.degraded.exhausted", exc, fault_kind=kind,
                stage=stage, rungs=",".join(self.rungs) or None)
            _flight.dump(reason="degraded_exhausted", error=exc)
        # tpudl: ignore[swallowed-except] — forensics are best-effort;
        # the typed raise below must happen regardless
        except Exception:
            pass
        cls = _TYPE_FOR.get(kind, StageFault)
        raise cls(
            f"map_batches fault not recoverable after "
            f"{len(self.rungs)} degradation rung(s) "
            f"({', '.join(self.rungs) or 'none applicable'}): "
            f"{type(exc).__name__}: {exc}",
            stage=stage, rungs=self.rungs) from exc

    def _sleep_with_beats(self, seconds: float) -> None:
        """Backoff that stays visibly ALIVE: slept in slices with a
        heartbeat beat per slice, so the watchdog never flags a
        supervised retry's deliberate pause as a stall (the
        heartbeat-re-arm contract, tests/test_obs_flight.py)."""
        deadline = time.monotonic() + max(0.0, float(seconds))
        while True:
            if self._hb is not None:
                self._hb.beat(backing_off=True)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            time.sleep(min(0.05, remaining))
