"""Model-as-UDF registry.

The reference registers frozen graphs as Spark SQL UDFs through
TensorFrames' JVM layer (ref: sparkdl graph/tensorframes_udf.py:makeGraphUDF
~L20). Here a UDF is a named callable ``Frame → Frame`` (batched, jitted
inside) plus the input/output column names the SQL layer binds to.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

__all__ = ["UDF", "register_udf", "get_udf", "list_udfs", "unregister_udf"]


@dataclasses.dataclass(frozen=True)
class UDF:
    name: str
    fn: Callable  # Frame -> Frame, reading input_col, appending output_col
    input_col: str
    output_col: str

    def __call__(self, frame):
        return self.fn(frame)


_REGISTRY: dict[str, UDF] = {}


def register_udf(name: str, fn: Callable, input_col: str, output_col: str) -> UDF:
    udf = UDF(str(name), fn, input_col, output_col)
    _REGISTRY[udf.name] = udf
    return udf


def get_udf(name: str) -> UDF:
    if name not in _REGISTRY:
        raise KeyError(f"no UDF registered as {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def list_udfs() -> list[str]:
    return sorted(_REGISTRY)


def unregister_udf(name: str) -> None:
    _REGISTRY.pop(name, None)
