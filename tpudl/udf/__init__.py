from tpudl.udf import registry  # noqa: F401
from tpudl.udf.registry import get_udf, list_udfs, register_udf  # noqa: F401
from tpudl.udf.tensorframes_udf import makeGraphUDF  # noqa: F401
from tpudl.udf.text_udf import register_text_udfs  # noqa: F401
