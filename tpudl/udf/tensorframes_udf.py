"""makeGraphUDF — register an ingested graph as a SQL UDF.

Rebuild of ref: python/sparkdl/graph/tensorframes_udf.py (makeGraphUDF
~L20): the reference hands a frozen GraphDef to TensorFrames' Scala
layer, which registers a Spark SQL UDF executing the graph per
row-block. Here the graph is already a jax-traceable fn (GraphFunction
or TFInputGraph from :mod:`tpudl.ingest`), so registration wraps it in
ONE jitted batched call per block and files it with
:mod:`tpudl.udf.registry`, callable from ``tpudl.frame.sql``:

    gin = TFInputGraph.fromKeras("model.keras")
    makeGraphUDF(gin, "my_udf")
    sql("SELECT my_udf(x) AS y FROM t", {"t": frame})

The reference's ``blocked`` flag chose row-at-a-time vs block execution;
batched-block execution IS this framework's only execution model (one
native call per block, SURVEY.md §3.2), so ``blocked`` is accepted for
signature parity and ignored.
"""

from __future__ import annotations

from tpudl.obs import metrics as _obs_metrics
from tpudl.obs import tracer as _obs_tracer
from tpudl.obs import watchdog as _obs_watchdog
from tpudl.udf.registry import UDF, register_udf

__all__ = ["makeGraphUDF"]


def makeGraphUDF(graph, udf_name: str, fetches=None,
                 feeds_to_fields_map: dict[str, str] | None = None,
                 blocked: bool = True, register: bool = True, *,
                 batch_size: int = 256, mesh=None,
                 prefetch_depth: int | None = None,
                 prepare_workers: int | None = None,
                 fuse_steps: int | None = None,
                 dispatch_depth: int | None = None,
                 wire_codec=None,
                 cache_dir: str | None = None,
                 device_cache: bool | None = None) -> UDF:
    """Register ``graph`` as a SQL UDF named ``udf_name``.

    ``graph``: a :class:`tpudl.ingest.TFInputGraph` (any factory route,
    trainable included — params close over) or a
    :class:`tpudl.ingest.builder.GraphFunction`. ``fetches`` optionally
    restricts/reorders a TFInputGraph's outputs (tensor names, reference
    semantics); the first fetch is the UDF's output column value.
    ``feeds_to_fields_map`` maps graph input name → frame column name
    (default: the input's own op name). ``register=False`` builds and
    returns the UDF without filing it in the registry.
    ``prefetch_depth`` / ``prepare_workers`` / ``fuse_steps`` /
    ``dispatch_depth`` plumb the ``Frame.map_batches``
    pipelined-executor knobs (None = the ``TPUDL_FRAME_*`` env /
    autotune defaults), so SQL-registered models ride the
    same staged pipeline as the ml transformers; ``wire_codec`` /
    ``cache_dir`` / ``device_cache`` plumb the tpudl.data knobs the
    same way (DATA.md — wire-encoded uploads, the sharded
    prepared-batch cache, and HBM-tier batch residency), so a repeated
    SQL query over the same frame replays its prepared batches — from
    device memory, with zero wire bytes, when the device cache is
    armed.

    SQL's ``fn(col)`` grammar binds single-input graphs; multi-input
    graphs still register and are callable as ``udf(frame)`` with every
    mapped column present.
    """
    import jax  # deferred: registry-only users of tpudl.udf stay jax-free

    from tpudl.ingest.builder import GraphFunction
    from tpudl.ingest.input import TFInputGraph

    if fetches is not None and isinstance(fetches, str):
        # a bare string would be list()-split into characters below and
        # surface as a baffling unknown-node error deep in the ingest
        # layer; a single fetch is still a one-element list
        raise TypeError(
            f"fetches must be a sequence of tensor names, got the "
            f"string {fetches!r} — wrap it: fetches=[{fetches!r}]")
    if isinstance(graph, TFInputGraph):
        fn = graph.make_fn(fetches=list(fetches) if fetches else None)
        if graph.trainable:
            params, base = graph.params, fn
            fn = lambda *xs: base(params, *xs)  # noqa: E731
        input_names = graph.input_names
    elif isinstance(graph, GraphFunction):
        if fetches is not None:
            raise ValueError(
                "fetches selection applies to TFInputGraph; a "
                "GraphFunction already fixes its outputs")
        fn, input_names = graph.fn, graph.input_names
    else:
        raise TypeError(
            f"graph must be TFInputGraph or GraphFunction, got "
            f"{type(graph).__name__}")

    def _field(name: str) -> str:
        op = name.split(":")[0]
        if feeds_to_fields_map:
            return feeds_to_fields_map.get(name,
                                           feeds_to_fields_map.get(op, op))
        return op

    in_cols = [_field(n) for n in input_names]
    out_col = f"{udf_name}_out"

    def first_fetch(*xs):
        y = fn(*xs)
        if isinstance(y, (tuple, list)):
            y = y[0]
        return y

    # tpudl: ignore[jit-cache-churn] — makeGraphUDF runs once per
    # registered UDF; the returned frame_fn closure retains jfn, so
    # the one trace here is the program's lifetime cost
    jfn = jax.jit(first_fetch)

    def frame_fn(frame):
        # per-UDF observability: calls/rows counters + a latency
        # histogram + a host span, named by the registered udf_name so
        # a SQL query's cost is attributable from one snapshot
        # the watchdog heartbeat beats once per call; a wedged UDF is a
        # stall named after the registered udf (the executor's own
        # per-stage heartbeat runs underneath for stage attribution)
        with _obs_watchdog.heartbeat(f"udf.{udf_name}",
                                     rows=len(frame),
                                     batch_size=batch_size), \
                _obs_metrics.timed(f"udf.{udf_name}.seconds"), \
                _obs_tracer.span(f"udf.{udf_name}", rows=len(frame)):
            # map_batches's default pack already stacks numeric and
            # object-of-array columns (frame._default_pack)
            out = frame.map_batches(
                jfn, in_cols, [out_col], batch_size=batch_size, mesh=mesh,
                prefetch_depth=prefetch_depth,
                prepare_workers=prepare_workers, fuse_steps=fuse_steps,
                dispatch_depth=dispatch_depth,
                wire_codec=wire_codec, cache_dir=cache_dir,
                device_cache=device_cache)
        _obs_metrics.counter(f"udf.{udf_name}.calls").inc()
        _obs_metrics.counter(f"udf.{udf_name}.rows").inc(len(frame))
        return out

    if register:
        return register_udf(udf_name, frame_fn, in_cols[0], out_col)
    return UDF(str(udf_name), frame_fn, in_cols[0], out_col)
