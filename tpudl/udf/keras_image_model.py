"""registerKerasImageUDF — Keras model as a SQL UDF.

Rebuild of ref: python/sparkdl/udf/keras_image_model.py (~L30): the
reference splices [spImageConverter → optional preprocessor → frozen
Keras graph] and registers it with TensorFrames' JVM UDF layer
(graph/tensorframes_udf.py makeGraphUDF ~L20). Here the same composition
is a single jitted function — image-struct column in, prediction vector
column out — registered with :mod:`tpudl.udf.registry` and callable
from ``tpudl.frame.sql``:

    registerKerasImageUDF("inception_udf", "/path/model.keras")
    sql("SELECT inception_udf(image) AS preds FROM images", {"images": frame})

``preprocessor`` is an optional jax-traceable ``batch(B,H,W,C) float32 →
batch`` applied between decode and model (the reference traces a python
fn through an IsolatedSession; ours just composes into the same jit).
"""

from __future__ import annotations

import numpy as np

import jax

from tpudl.image import ops as image_ops
from tpudl.udf.registry import UDF, register_udf

__all__ = ["registerKerasImageUDF"]


def registerKerasImageUDF(udf_name: str, keras_model_or_file,
                          preprocessor=None, *, channel_order: str = "RGB",
                          batch_size: int = 64, mesh=None) -> UDF:
    from tpudl.ingest import TFInputGraph
    from tpudl.ml.tf_image import _pack_image_structs

    gin = TFInputGraph.fromKeras(keras_model_or_file)
    model_fn = gin.make_fn()

    def fused(batch):
        x = image_ops.sp_image_converter(batch, "BGR", channel_order)
        if preprocessor is not None:
            x = preprocessor(x)
        y = model_fn(x)
        if isinstance(y, tuple):
            y = y[0]
        return y.reshape(y.shape[0], -1)

    # tpudl: ignore[jit-cache-churn] — UDF registration runs once per
    # name; the registered frame_fn closure retains jfn, so the one
    # trace here is the program's lifetime cost
    jfn = jax.jit(fused)

    def frame_fn(frame):
        return frame.map_batches(
            jfn, ["image"], [f"{udf_name}_out"], batch_size=batch_size,
            mesh=mesh, pack=_pack_image_structs)

    return register_udf(udf_name, frame_fn, "image", f"{udf_name}_out")
