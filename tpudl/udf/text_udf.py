"""register_text_udfs — the LM trio as SQL UDFs.

The text analogue of :func:`tpudl.udf.tensorframes_udf.makeGraphUDF`:
one call registers ``generate`` / ``embed`` / ``classify`` (optionally
prefixed) over a string column, each backed by the corresponding
:mod:`tpudl.ml.lm` transformer built ONCE at registration — the
transformer instance retains its compiled-program cache, so repeated
SQL queries reuse the same bucketed XLA programs:

    register_text_udfs(model=lm, weights=params, tokenizer=tok,
                       max_new=8)
    sql("SELECT generate(prompt) AS story FROM t", {"t": frame})

Instrumentation matches makeGraphUDF exactly: per-UDF ``udf.<name>``
heartbeat + latency histogram + host span, ``udf.<name>.calls`` /
``udf.<name>.rows`` counters, so a SQL query's LM cost is attributable
from one metrics snapshot.
"""

from __future__ import annotations

from tpudl.obs import metrics as _obs_metrics
from tpudl.obs import tracer as _obs_tracer
from tpudl.obs import watchdog as _obs_watchdog
from tpudl.udf.registry import UDF, register_udf

__all__ = ["register_text_udfs"]


def _wrap(udf_name: str, transformer, input_col: str, out_col: str,
          batch_size: int, register: bool) -> UDF:
    def frame_fn(frame):
        with _obs_watchdog.heartbeat(f"udf.{udf_name}",
                                     rows=len(frame),
                                     batch_size=batch_size), \
                _obs_metrics.timed(f"udf.{udf_name}.seconds"), \
                _obs_tracer.span(f"udf.{udf_name}", rows=len(frame)):
            out = transformer.transform(frame)
        _obs_metrics.counter(f"udf.{udf_name}.calls").inc()
        _obs_metrics.counter(f"udf.{udf_name}.rows").inc(len(frame))
        return out

    if register:
        return register_udf(udf_name, frame_fn, input_col, out_col)
    return UDF(str(udf_name), frame_fn, input_col, out_col)


def register_text_udfs(*, model, weights, tokenizer,
                       input_col: str = "text", prefix: str = "",
                       max_new: int = 16, temperature: float = 0.0,
                       seed: int = 0, classes=None, max_len=None,
                       prompt_buckets="pow2", batch_size: int = 32,
                       mesh=None, tp: bool = False,
                       register: bool = True) -> list[UDF]:
    """Register the LM UDF family over ``model``/``weights``/``tokenizer``.

    Always registers ``{prefix}generate`` (→ completion string,
    :class:`~tpudl.ml.lm.LMGenerator`) and ``{prefix}embed`` (→ pooled
    hidden vector, :class:`~tpudl.ml.lm.LMFeaturizer`); with
    ``classes=[...]`` also ``{prefix}classify`` (→ label string,
    :class:`~tpudl.ml.lm.LMClassifier`). ``input_col`` names the string
    column the transformers read — SQL's ``fn(col)`` grammar renames
    the bound column to it, so any column name works at the call site.
    ``register=False`` builds and returns the UDFs without filing them.
    Returns the UDF list in registration order.
    """
    from tpudl.ml.lm import LMClassifier, LMFeaturizer, LMGenerator

    out = []
    name = f"{prefix}generate"
    gen = LMGenerator(inputCol=input_col, outputCol=f"{name}_out",
                      model=model, weights=weights, tokenizer=tokenizer,
                      maxNew=max_new, temperature=temperature, seed=seed,
                      promptBuckets=prompt_buckets, batchSize=batch_size,
                      mesh=mesh, tp=tp)
    out.append(_wrap(name, gen, input_col, f"{name}_out", batch_size,
                     register))
    name = f"{prefix}embed"
    feat = LMFeaturizer(inputCol=input_col, outputCol=f"{name}_out",
                        model=model, weights=weights,
                        tokenizer=tokenizer, maxLen=max_len,
                        promptBuckets=prompt_buckets,
                        batchSize=batch_size, mesh=mesh, tp=tp)
    out.append(_wrap(name, feat, input_col, f"{name}_out", batch_size,
                     register))
    if classes:
        name = f"{prefix}classify"
        clf = LMClassifier(inputCol=input_col, outputCol=f"{name}_out",
                           model=model, weights=weights,
                           tokenizer=tokenizer, classes=classes,
                           maxLen=max_len, promptBuckets=prompt_buckets,
                           batchSize=batch_size, mesh=mesh, tp=tp)
        out.append(_wrap(name, clf, input_col, f"{name}_out",
                         batch_size, register))
    return out
