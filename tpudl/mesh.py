"""Device-mesh and sharding core for tpudl.

This is the TPU-native replacement for the reference's entire distribution
substrate (Spark driver/executor dispatch, torrent broadcast, and the
HorovodRunner NCCL ring — see SURVEY.md §2.3/§5.8). One logical ``Mesh``
abstraction carries every parallelism the framework offers:

- ``data``  axis — data-parallel inference/training (the reference's Spark
  partition map and Horovod allreduce; ref: sparkdl ``tf_image.py:_transform``
  and HorovodRunner contract).
- ``model`` axis — reserved for tensor parallelism (absent in the reference,
  kept open per SURVEY.md §2.4 so it bolts on without redesign).

All helpers are mesh-size-agnostic: they run unchanged on 1 real TPU chip,
a v5e-8 slice, or an 8-device simulated CPU mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``).
"""

from __future__ import annotations

import contextlib
import math
from collections.abc import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudl.testing import faults as _faults

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "build_mesh",
    "replicated",
    "batch_sharding",
    "stacked_batch_sharding",
    "shard_batch",
    "transfer_batch",
    "replicate",
    "pad_batch",
    "unpad_batch",
    "local_device_count",
    "use_mesh",
]

DATA_AXIS = "data"
MODEL_AXIS = "model"


def local_device_count() -> int:
    return jax.local_device_count()


def build_mesh(
    n_data: int | None = None,
    n_model: int = 1,
    *,
    devices: Sequence[jax.Device] | None = None,
    axis_names: tuple[str, ...] = (DATA_AXIS, MODEL_AXIS),
) -> Mesh:
    """Build a 2-D logical mesh ``(data, model)`` over the available devices.

    ``n_data`` defaults to ``len(devices) // n_model``. A ``model`` axis of
    size 1 costs nothing and keeps tensor-parallel shardings expressible
    without re-tracing user code when the axis later grows.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if n_data is None:
        n_data = len(devs) // n_model
    want = n_data * n_model
    if want > len(devs):
        raise ValueError(
            f"mesh {n_data}x{n_model} needs {want} devices, have {len(devs)}"
        )
    grid = np.asarray(devs[:want]).reshape(n_data, n_model)
    return Mesh(grid, axis_names)


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding — the moral equivalent of Spark broadcast."""
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = DATA_AXIS, ndim: int = 1) -> NamedSharding:
    """Shard the leading (batch) dimension over ``axis``; replicate the rest."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def stacked_batch_sharding(mesh: Mesh, axis: str = DATA_AXIS,
                           ndim: int = 2) -> NamedSharding:
    """Shard dim 1 (the batch dim of a stacked ``(M, B, ...)`` fused
    group) over ``axis``; the microbatch dim and the rest replicate.
    This is the in-sharding of the executor's fused mesh dispatch: a
    ``lax.scan`` over dim 0 hands each microbatch to the model already
    carrying ``P(axis, ...)``."""
    return NamedSharding(mesh, P(None, axis, *([None] * (ndim - 2))))


def replicate(tree, mesh: Mesh):
    """Place every leaf on-device fully replicated (Spark broadcast
    analogue) — ONE batched ``device_put`` for the whole tree."""
    sh = replicated(mesh)
    return jax.device_put(tree, jax.tree.map(lambda _: sh, tree))


def pad_batch(arr: np.ndarray, multiple: int) -> tuple[np.ndarray, int]:
    """Pad the leading dim up to a multiple; returns (padded, n_pad).

    SPMD over a mesh needs batch % n_data == 0; the reference never faced
    this (Spark partitions are ragged) so this is new, deliberate surface.
    Padding repeats row 0 to keep dtype/scale realistic for compiled kernels.
    """
    n = arr.shape[0]
    target = math.ceil(n / multiple) * multiple if n else multiple
    n_pad = target - n
    if n_pad == 0:
        return arr, 0
    pad = np.repeat(arr[:1] if n else np.zeros_like(arr, shape=(1, *arr.shape[1:])), n_pad, axis=0)
    return np.concatenate([arr, pad], axis=0), n_pad


def unpad_batch(arr, n_pad: int):
    return arr if n_pad == 0 else arr[: arr.shape[0] - n_pad]


def transfer_batch(tree, mesh: Mesh, axis: str = DATA_AXIS, *,
                   batch_dim: int = 0):
    """THE infeed transfer edge: host numpy batches → device-sharded
    arrays, as ONE batched asynchronous ``jax.device_put`` call for the
    whole tree (no per-leaf put, and — deliberately — no barrier: the
    returned arrays are futures, like every other jax dispatch, so the
    copies ride under whatever the caller does next; the executor's
    dispatch window and the runtime hide the wait).

    ``batch_dim`` selects which dim shards over ``axis``: 0 for a plain
    batch (``P(axis, ...)``), 1 for a stacked fused group
    (``P(None, axis, ...)`` — see :func:`stacked_batch_sharding`).
    Leaves must already be padded to a multiple of the axis size at
    that dim. Every mesh transfer in the codebase goes through here
    (``Frame.map_batches``, the estimator's sub-mesh trials,
    ``Trainer.fit`` — one path, no second ``device_put`` route to
    drift).

    A leaf ALREADY resident under the requested sharding (an HBM-tier
    device-cache hit — DATA.md "Cache hierarchy") passes through
    untouched: zero wire bytes, and crucially no ``np.asarray`` — the
    old unconditional host staging would have GATHERED the resident
    shard back to host just to re-ship it."""
    # THE transfer fault point (tpudl.testing.faults): the chaos suite
    # injects transfer failures at the one edge every mesh H2D crosses;
    # unarmed this is a global None-check
    _faults.fire("mesh.transfer")
    leaves, treedef = jax.tree.flatten(tree)
    shardings = [
        (stacked_batch_sharding(mesh, axis, np.ndim(x)) if batch_dim == 1
         else batch_sharding(mesh, axis, np.ndim(x)))
        for x in leaves]
    out: list = [None] * len(leaves)
    to_put, to_put_sh, to_put_idx = [], [], []
    for i, (x, sh) in enumerate(zip(leaves, shardings)):
        if isinstance(x, jax.Array) and x.sharding == sh:
            out[i] = x  # resident replay: no transfer, no host bounce
        else:
            to_put.append(np.asarray(x))
            to_put_sh.append(sh)
            to_put_idx.append(i)
    if to_put:
        placed = jax.device_put(to_put, to_put_sh)
        for i, p in zip(to_put_idx, placed):
            out[i] = p
    return jax.tree.unflatten(treedef, out)


def shard_batch(tree, mesh: Mesh, axis: str = DATA_AXIS):
    """``transfer_batch`` with the leading dim sharded — kept as the
    short spelling every training/estimator call site uses."""
    return transfer_batch(tree, mesh, axis)


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Context manager installing ``mesh`` for sharding-annotated jit code."""
    with jax.sharding.set_mesh(mesh):
        yield mesh
