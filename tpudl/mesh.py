"""Device-mesh and sharding core for tpudl.

This is the TPU-native replacement for the reference's entire distribution
substrate (Spark driver/executor dispatch, torrent broadcast, and the
HorovodRunner NCCL ring — see SURVEY.md §2.3/§5.8). One logical ``Mesh``
abstraction carries every parallelism the framework offers:

- ``data``  axis — data-parallel inference/training (the reference's Spark
  partition map and Horovod allreduce; ref: sparkdl ``tf_image.py:_transform``
  and HorovodRunner contract).
- ``model`` axis — reserved for tensor parallelism (absent in the reference,
  kept open per SURVEY.md §2.4 so it bolts on without redesign).

All helpers are mesh-size-agnostic: they run unchanged on 1 real TPU chip,
a v5e-8 slice, or an 8-device simulated CPU mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``).
"""

from __future__ import annotations

import contextlib
import math
import os
import warnings
from collections.abc import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudl.testing import faults as _faults

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "build_mesh",
    "model_axis_size",
    "replicated",
    "batch_sharding",
    "stacked_batch_sharding",
    "shard_batch",
    "transfer_batch",
    "replicate",
    "pad_batch",
    "unpad_batch",
    "require_hbm_fit",
    "bytes_per_device",
    "local_device_count",
    "use_mesh",
]

DATA_AXIS = "data"
MODEL_AXIS = "model"


def local_device_count() -> int:
    return jax.local_device_count()


def model_axis_size() -> int:
    """The process-default tensor-parallel degree: ``TPUDL_MESH_MODEL``
    (ANALYSIS.md), floor 1. Consumed wherever a mesh is built WITHOUT an
    explicit ``n_model`` (HorovodRunner, the estimator's sub-mesh
    trials), so one env knob turns a whole job tensor-parallel without
    touching call sites."""
    try:
        return max(1, int(os.environ.get("TPUDL_MESH_MODEL", "1")))
    except ValueError:
        return 1


_warned_idle_devices = False


def _warn_idle_devices_once(n_data: int, n_model: int, idle: int,
                            total: int) -> None:
    global _warned_idle_devices
    # the gauge updates every build (a later, correctly-sized mesh
    # clears it); the warning fires once per process
    try:
        from tpudl.obs import metrics as _metrics

        _metrics.gauge("frame.mesh.idle_devices").set(idle)
    # tpudl: ignore[swallowed-except] — obs may be unimportable in a
    # minimal subprocess; the warning below still fires
    except Exception:
        pass
    if idle == 0 or _warned_idle_devices:
        return
    _warned_idle_devices = True
    warnings.warn(
        f"build_mesh({n_data}x{n_model}) uses {n_data * n_model} of "
        f"{total} visible devices — {idle} device(s) sit IDLE. Size the "
        f"grid to cover the slice (n_data * n_model == device count) or "
        f"pass devices= explicitly; frame.mesh.idle_devices gauges the "
        f"stranded count.", RuntimeWarning, stacklevel=3)


def build_mesh(
    n_data: int | None = None,
    n_model: int | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
    axis_names: tuple[str, ...] = (DATA_AXIS, MODEL_AXIS),
) -> Mesh:
    """Build a 2-D logical mesh ``(data, model)`` over the available devices.

    ``n_model`` defaults to ``TPUDL_MESH_MODEL`` (1 when unset) and
    ``n_data`` to ``len(devices) // n_model``. A ``model`` axis of
    size 1 costs nothing and keeps tensor-parallel shardings expressible
    without re-tracing user code when the axis later grows. A grid that
    covers fewer devices than are visible strands the rest — loud
    warn-once + the ``frame.mesh.idle_devices`` gauge.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if n_model is None:
        n_model = model_axis_size()
    if n_data is None:
        n_data = len(devs) // n_model
    want = n_data * n_model
    if want > len(devs):
        raise ValueError(
            f"mesh {n_data}x{n_model} needs {want} devices, have {len(devs)}"
        )
    _warn_idle_devices_once(n_data, n_model, len(devs) - want, len(devs))
    grid = np.asarray(devs[:want]).reshape(n_data, n_model)
    return Mesh(grid, axis_names)


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding — the moral equivalent of Spark broadcast."""
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = DATA_AXIS, ndim: int = 1) -> NamedSharding:
    """Shard the leading (batch) dimension over ``axis``; replicate the rest."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def stacked_batch_sharding(mesh: Mesh, axis: str = DATA_AXIS,
                           ndim: int = 2) -> NamedSharding:
    """Shard dim 1 (the batch dim of a stacked ``(M, B, ...)`` fused
    group) over ``axis``; the microbatch dim and the rest replicate.
    This is the in-sharding of the executor's fused mesh dispatch: a
    ``lax.scan`` over dim 0 hands each microbatch to the model already
    carrying ``P(axis, ...)``."""
    return NamedSharding(mesh, P(None, axis, *([None] * (ndim - 2))))


def replicate(tree, mesh: Mesh):
    """Place every leaf on-device fully replicated (Spark broadcast
    analogue) — ONE batched ``device_put`` for the whole tree. Under an
    explicit ``TPUDL_DATA_HBM_BUDGET_MB`` the placement is budget-
    checked first (:func:`require_hbm_fit`): replicating a model that
    only fits sharded must die typed, not as an allocator fault."""
    require_hbm_fit(tree, None, what="replicated tree")
    sh = replicated(mesh)
    return jax.device_put(tree, jax.tree.map(lambda _: sh, tree))


def pad_batch(arr: np.ndarray, multiple: int) -> tuple[np.ndarray, int]:
    """Pad the leading dim up to a multiple; returns (padded, n_pad).

    SPMD over a mesh needs batch % n_data == 0; the reference never faced
    this (Spark partitions are ragged) so this is new, deliberate surface.
    Padding repeats row 0 to keep dtype/scale realistic for compiled kernels.
    """
    n = arr.shape[0]
    target = math.ceil(n / multiple) * multiple if n else multiple
    n_pad = target - n
    if n_pad == 0:
        return arr, 0
    pad = np.repeat(arr[:1] if n else np.zeros_like(arr, shape=(1, *arr.shape[1:])), n_pad, axis=0)
    return np.concatenate([arr, pad], axis=0), n_pad


def unpad_batch(arr, n_pad: int):
    return arr if n_pad == 0 else arr[: arr.shape[0] - n_pad]


def transfer_batch(tree, mesh: Mesh, axis: str = DATA_AXIS, *,
                   batch_dim: int = 0):
    """THE infeed transfer edge: host numpy batches → device-sharded
    arrays, as ONE batched asynchronous ``jax.device_put`` call for the
    whole tree (no per-leaf put, and — deliberately — no barrier: the
    returned arrays are futures, like every other jax dispatch, so the
    copies ride under whatever the caller does next; the executor's
    dispatch window and the runtime hide the wait).

    ``batch_dim`` selects which dim shards over ``axis``: 0 for a plain
    batch (``P(axis, ...)``), 1 for a stacked fused group
    (``P(None, axis, ...)`` — see :func:`stacked_batch_sharding`).
    Leaves must already be padded to a multiple of the axis size at
    that dim. Every mesh transfer in the codebase goes through here
    (``Frame.map_batches``, the estimator's sub-mesh trials,
    ``Trainer.fit`` — one path, no second ``device_put`` route to
    drift).

    A leaf ALREADY resident under the requested sharding (an HBM-tier
    device-cache hit — DATA.md "Cache hierarchy") passes through
    untouched: zero wire bytes, and crucially no ``np.asarray`` — the
    old unconditional host staging would have GATHERED the resident
    shard back to host just to re-ship it. The same pass-through covers
    MODEL-sharded resident leaves (tensor-parallel params/closures on a
    2-D grid, under their ``P(None, "model")``-family shardings):
    batch-resharding a param shard would all-gather 1/tp of the model
    per device just to re-split it, so any leaf whose sharding lives on
    this mesh and references the ``model`` axis stays exactly where it
    is — activations ride the wire, weights never move."""
    # THE transfer fault point (tpudl.testing.faults): the chaos suite
    # injects transfer failures at the one edge every mesh H2D crosses;
    # unarmed this is a global None-check
    _faults.fire("mesh.transfer")
    leaves, treedef = jax.tree.flatten(tree)
    shardings = [
        (stacked_batch_sharding(mesh, axis, np.ndim(x)) if batch_dim == 1
         else batch_sharding(mesh, axis, np.ndim(x)))
        for x in leaves]
    out: list = [None] * len(leaves)
    to_put, to_put_sh, to_put_idx = [], [], []
    for i, (x, sh) in enumerate(zip(leaves, shardings)):
        if isinstance(x, jax.Array) and (
                x.sharding == sh or _model_resident(x, mesh)):
            out[i] = x  # resident replay: no transfer, no host bounce
        else:
            to_put.append(np.asarray(x))
            to_put_sh.append(sh)
            to_put_idx.append(i)
    if to_put:
        placed = jax.device_put(to_put, to_put_sh)
        for i, p in zip(to_put_idx, placed):
            out[i] = p
    return jax.tree.unflatten(treedef, out)


def _model_resident(x: jax.Array, mesh: Mesh) -> bool:
    """True when ``x`` is already device-resident on ``mesh`` under a
    sharding that references the ``model`` axis — the tensor-parallel
    pass-through predicate of :func:`transfer_batch`. Exact-spec
    residency is checked by the caller; this only widens it to
    model-sharded leaves (a stale DATA-axis sharding still re-ships, so
    a wrong ``batch_dim`` can't silently reuse it)."""
    sh = getattr(x, "sharding", None)
    if not isinstance(sh, NamedSharding) or sh.mesh != mesh:
        return False

    def axes(spec):
        for s in spec:
            if isinstance(s, (tuple, list)):
                yield from s
            elif s is not None:
                yield s

    return MODEL_AXIS in set(axes(tuple(sh.spec)))


def shard_batch(tree, mesh: Mesh, axis: str = DATA_AXIS):
    """``transfer_batch`` with the leading dim sharded — kept as the
    short spelling every training/estimator call site uses."""
    return transfer_batch(tree, mesh, axis)


def bytes_per_device(tree, shardings=None) -> int:
    """Per-device resident bytes of placing ``tree`` under
    ``shardings`` (a matching NamedSharding pytree; ``None`` = fully
    replicated). Uses each sharding's own ``shard_shape`` so nested
    axis specs (``P(("data", "model"))`` etc.) divide correctly."""
    total = 0
    leaves = jax.tree.leaves(tree)
    shards = (jax.tree.leaves(shardings) if shardings is not None
              else [None] * len(leaves))
    for leaf, sh in zip(leaves, shards):
        a = np.asarray(leaf) if not hasattr(leaf, "shape") else leaf
        shape = tuple(a.shape)
        if sh is not None and hasattr(sh, "shard_shape"):
            shape = sh.shard_shape(shape)
        total += int(np.prod(shape, dtype=np.int64)) * np.dtype(
            a.dtype).itemsize
    return total


def require_hbm_fit(tree, shardings=None, *, what: str = "params") -> None:
    """Refuse a placement whose PER-DEVICE bytes exceed the declared
    ``TPUDL_DATA_HBM_BUDGET_MB`` budget — typed (``DeviceOOM``), before
    any wire bytes move. Only armed when the budget is EXPLICIT (the
    derived device-cache default stays a cache policy, not a placement
    veto). This is the "models bigger than one chip" gate: a replicated
    (or 1-wide ``model`` axis) placement of params that only fit
    sharded fails HERE with the budget arithmetic in the message,
    instead of as an opaque allocator death mid-transfer."""
    if not os.environ.get("TPUDL_DATA_HBM_BUDGET_MB"):
        return
    from tpudl.data.device_cache import budget_bytes

    budget = budget_bytes(allow_device=False)
    if not budget:
        # explicit 0 means "data-cache residency forbidden" (DATA.md),
        # not a zero-HBM chip — placements stay ungated
        return
    need = bytes_per_device(tree, shardings)
    if need > budget:
        from tpudl.frame.supervisor import DeviceOOM

        raise DeviceOOM(
            f"{what} need {need / 2**20:.1f} MB per device but "
            f"TPUDL_DATA_HBM_BUDGET_MB grants {budget / 2**20:.1f} MB — "
            f"shard over a wider 'model' axis (build_mesh(n_model=...), "
            f"param_shardings) or raise the budget")


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Context manager installing ``mesh`` for sharding-annotated jit code."""
    with jax.sharding.set_mesh(mesh):
        yield mesh
