"""The serve loop: admission → bucketed prefill → slot decode → SLOs.

One server owns one :class:`~tpudl.serve.registry.ModelRegistry` and
one :class:`~tpudl.serve.queue.RequestQueue` and runs the continuous-
batching loop (SERVE.md): each tick sheds expired work (queued AND
mid-decode), admits queued requests into free slots up to the current
admission width, dispatches ONE decode step across every active slot
per model, and harvests completions. The loop's dispatch set is
closed — one step program per model geometry plus O(log n) prefill
rungs — so steady state performs zero retraces (traceck-pinned).

Overload rides the PR-14 degradation ladder instead of dying: under
``supervise=True`` (or ``TPUDL_FRAME_DEGRADE=1``) the whole session
runs as a supervised attempt; a classified fault evicts in-flight
requests back to the FRONT of the queue (partial tokens discarded —
the retry re-decodes from the prompt, bitwise-honest) and re-runs with
the ladder's overrides, ``dispatch_depth`` mapping onto the admission
width. Unrecoverable faults fail every pending request TYPED — a dead
server never parks a client (the zero-hangs contract).

SLO metrics publish through ``tpudl.obs`` (``serve.latency_ms`` /
``serve.ttft_s`` histograms carry p50/p99; queue depth, occupancy and
reject counters land in the same registry ``obs top`` and the flight
recorder read); the session's :class:`PipelineReport` feeds the
roofline and ``obs doctor``.
"""

from __future__ import annotations

import threading
import time

from tpudl.obs import attribution as _attr
from tpudl.obs import flight as _flight
from tpudl.obs import metrics as _metrics
from tpudl.obs import pipeline as _pipeline
from tpudl.obs import slo as _slo
from tpudl.obs import watchdog as _watchdog
from tpudl.serve import reqtrace as _reqtrace
from tpudl.serve.queue import DeadlineExceeded, RequestQueue, \
    ServeRequest
from tpudl.testing import faults as _faults

__all__ = ["Server"]


class Server:
    """Continuous-batching server over a model registry.

    Run synchronously (``run(max_ticks=...)`` — deterministic, the
    acceptance tests' mode) or threaded (``start_async()`` +
    ``close()``, the load-generator's mode)."""

    def __init__(self, registry, queue: RequestQueue | None = None, *,
                 supervise=None):
        self.registry = registry
        self.queue = queue if queue is not None else RequestQueue()
        self._supervise = supervise
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._error: BaseException | None = None
        self._sup = None
        self._max_ticks: int | None = None
        self.summary: dict | None = None

    # -- client surface ----------------------------------------------------
    def submit(self, prompt, max_new: int, *, model: str = "default",
               deadline_s: float | None = None, rng=None) -> ServeRequest:
        """Admit one request (typed reject on queue/budget pressure).
        The model name is validated HERE so an unknown name is an
        immediate ``KeyError``, never a request parked forever."""
        self.registry.get(model)  # raises KeyError for unknown names
        req = ServeRequest(prompt, max_new, model=model,
                           deadline_s=deadline_s, rng=rng)
        return self.queue.submit(req)

    # -- lifecycle ---------------------------------------------------------
    def start_async(self) -> "Server":
        """Run the serve session on a daemon thread (the generic name
        ``start`` is deliberately avoided: concurrency.py resolves
        attribute calls by bare name, and every ``t.start()`` in the
        tree would inherit this loop's blocking closure)."""
        if self._thread is not None:
            raise RuntimeError("server already started")
        self._thread = threading.Thread(target=self._run_guarded,
                                        name="tpudl-serve",
                                        daemon=True)
        self._thread.start()
        return self

    def close(self, timeout: float = 120.0) -> dict:
        """Drain (finish queued + in-flight work), stop, and return the
        session summary; re-raises the loop's error if it died."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise TimeoutError(
                    f"serve loop did not drain within {timeout}s")
            self._thread = None
        if self._error is not None:
            raise self._error
        return self.summary or {}

    def _run_guarded(self):
        try:
            self.summary = self.run()
        except BaseException as e:
            self._error = e
            self._fail_pending(e)

    def _fail_pending(self, error: BaseException):
        """Unblock every waiting client with the typed cause."""
        self.queue.fail_all(error)
        for entry in self.registry.entries():
            entry.engine.evict_all(error)

    # -- the session -------------------------------------------------------
    def run(self, max_ticks: int | None = None) -> dict:
        """Run the serve session to drain (or ``max_ticks``), under the
        degradation ladder when armed."""
        from tpudl.frame import supervisor as _supmod

        self._max_ticks = max_ticks
        if _supmod.enabled(self._supervise):
            sup = _supmod.Supervisor()
            self._sup = sup
            try:
                return sup.supervise(self._attempt)
            finally:
                self._sup = None
        return self._attempt({})

    def _requeue_inflight(self):
        """A retry attempt starts clean: in-flight occupants go back to
        the queue FRONT (oldest first) with partial tokens discarded —
        the surviving attempt re-decodes them from the prompt, so its
        outputs are exactly a healthy run's."""
        for entry in self.registry.entries():
            reqs = entry.engine.evict_all()
            for req in reqs:
                req.tokens = None
            if reqs:
                self.queue.requeue_front(reqs)

    def _attempt(self, overrides: dict) -> dict:
        entries = self.registry.entries()
        width_default = sum(e.engine.slots for e in entries) or 1
        max_active = int(overrides.get("dispatch_depth")
                         or width_default)
        report = _pipeline.PipelineReport()
        report.config.update({
            "serve": True,
            "dispatch_depth": max_active,
            "queue_cap": self.queue.cap,
            "models": len(entries),
        })
        if self._sup is not None:
            self._sup.note_report(report)
        _pipeline.set_last_pipeline(report)
        self._requeue_inflight()
        t0 = time.perf_counter()
        tick = completed = admitted = 0
        with _watchdog.heartbeat("serve.loop",
                                 models=len(entries)) as hb:
            while True:
                tick += 1
                _faults.fire("serve.dispatch", tick=tick)
                self._shed_expired(entries)
                admitted += self._admit(entries, max_active, report)
                stepped = 0
                for entry in entries:
                    if entry.engine.active():
                        with report.stage("dispatch"):
                            stepped += entry.engine.step()
                if stepped:
                    report.count("tokens", stepped)
                completed += self._harvest(entries, report)
                depth = self.queue.depth()
                active = sum(len(e.engine.active()) for e in entries)
                report.gauge("queue_depth", depth)
                report.gauge("slot_occupancy",
                             active / max(width_default, 1))
                hb.beat(tick=tick, depth=depth, active=active)
                if self._max_ticks is not None \
                        and tick >= self._max_ticks:
                    break
                if depth == 0 and active == 0:
                    if self._stop.is_set():
                        break
                    time.sleep(0.0005)  # idle poll, clients may appear
        wall = time.perf_counter() - t0
        report.finish(wall)
        # final gauge refresh so a post-run snapshot/status read shows
        # the session's closing window, not a stale throttled view
        _slo.get_slo_engine().publish(force=True)
        return {"ticks": tick, "completed": completed,
                "admitted": admitted, "wall_s": round(wall, 4),
                "models": len(entries),
                "degraded_to": report.config.get("degraded_to")}

    def _shed_expired(self, entries) -> int:
        """Mid-decode deadline sweep: an expired occupant is evicted
        typed — its slot goes to a request that can still make its
        deadline instead of finishing tokens nobody will read."""
        now = time.monotonic()
        shed = 0
        for entry in entries:
            for slot, req in entry.engine.occupants():
                if req.expired(now):
                    entry.engine.evict(slot, DeadlineExceeded(
                        f"deadline passed {now - req.submitted:.3f}s "
                        f"after submit, mid-decode"))
                    shed += 1
        if shed:
            _metrics.counter("serve.deadline_sheds").inc(shed)
        return shed

    def _admit(self, entries, max_active: int, report) -> int:
        """Move queued requests into free slots, bounded by the
        CURRENT admission width (the degradation ladder shrinks it via
        ``dispatch_depth``)."""
        total_active = sum(len(e.engine.active()) for e in entries)
        budget = max_active - total_active
        admitted = 0
        for entry in entries:
            if budget <= 0:
                break
            nfree = min(len(entry.engine.free()), budget)
            if nfree <= 0:
                continue
            for req in self.queue.take(nfree, model=entry.name):
                with report.stage("dispatch"):
                    entry.engine.insert(req)
                req.ttft_s = time.monotonic() - req.submitted
                _metrics.histogram("serve.ttft_s").observe(req.ttft_s)
                budget -= 1
                admitted += 1
        return admitted

    def _harvest(self, entries, report) -> int:
        done = 0
        slo = _slo.get_slo_engine()
        for entry in entries:
            for req, toks in entry.engine.pop_completed():
                req.finish(toks)
                _metrics.histogram("serve.latency_ms").observe(
                    req.latency_s * 1000.0)
                _metrics.counter("serve.completed").inc()
                # attribution: the loop thread serves every tenant, so
                # per-request charges follow the scope captured at
                # submit — paired 1:1 with serve.completed and the
                # latency observe above (the reconciliation contract)
                skey = (req.scope.key if req.scope is not None
                        else None)
                _attr.charge("serve_completed", key=skey)
                _attr.charge("slo_samples", key=skey)
                _attr.charge("tokens_out",
                             int(getattr(req.tokens, "size", 0)),
                             key=skey)
                # windowed SLO stamp + tail-exemplar check, then the
                # flight recorder's request ring (descriptor only)
                slo.record(req)
                _flight.get_recorder().record_request(
                    _reqtrace.request_record(req))
                report.progress(1)
                done += 1
        return done
