"""Request-scoped lifecycle traces for the serve plane.

Every :class:`~tpudl.serve.queue.ServeRequest` carries one
:class:`ReqTrace`: a trace id plus a BOUNDED list of
``(event, monotonic_t)`` stamps, one per lifecycle transition —
submit, admit/typed-reject, queue-wait end, rung pack, slot insert,
first token, per-N-token decode cadence, complete/fail. The stamps
decompose any request into the four segments an operator reasons in:

- ``queue_wait``  — submit → taken off the admission queue
- ``batching``    — taken → rung chosen + padded (pack cost)
- ``prefill``     — rung pack → first token (the TTFT tail)
- ``decode``      — first token → terminal stamp

The segments telescope: their sum IS the end-to-end latency (same
clock, shared cut points), which is what the segment-sum test pins.

Discipline (the obs contract, OBSERVABILITY.md):

- **lock-free**: a stamp is a plain list append on the thread that
  owns the request at that phase — client thread through submit,
  serve thread after. The queue's own lock orders the handoff, so no
  trace lock exists and no stamp can race.
- **bounded**: at most ``TPUDL_SERVE_TRACE_EVENTS`` stamps; decode
  cadence stamps stop early to reserve room so the terminal stamp
  always lands (``force=True``).
- **armable**: ``TPUDL_SERVE_TRACE=0`` makes :func:`new_trace` return
  ``None`` and every stamp site is gated on ``trace is not None`` —
  the <5% armed-overhead guard measures exactly this toggle.
- **descriptors only**: :func:`request_record` emits lengths, ids and
  millisecond segments for the flight recorder's request ring — never
  prompt tokens (tools/validate_dump.py audits).
"""

from __future__ import annotations

import itertools
import os
import time

__all__ = ["ReqTrace", "new_trace", "trace_armed", "decode_cadence",
           "request_record", "SEGMENTS"]

# the four segments every request decomposes into, in lifecycle order
SEGMENTS = ("queue_wait", "batching", "prefill", "decode")

# room reserved below the event cap so complete/fail always fits even
# after a long decode's cadence stamps
_TERMINAL_RESERVE = 4

_TRACE_SEQ = itertools.count(1)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except (TypeError, ValueError):
        return default


def trace_armed() -> bool:
    """Tracing is on unless ``TPUDL_SERVE_TRACE=0`` (cheap enough to
    default on — the overhead guard pins the cost)."""
    return os.environ.get("TPUDL_SERVE_TRACE", "1") != "0"


def decode_cadence() -> int:
    """Stamp every N-th decoded token (``TPUDL_SERVE_TRACE_CADENCE``)."""
    return max(1, _env_int("TPUDL_SERVE_TRACE_CADENCE", 16))


class ReqTrace:
    """One request's bounded stamp list. Appends only — segment math
    happens off the hot path (:meth:`segments`, harvest time)."""

    __slots__ = ("trace_id", "events", "_cap")

    def __init__(self):
        self.trace_id = f"{os.getpid()}-{next(_TRACE_SEQ)}"
        self.events: list = []  # [(name, monotonic_t), ...]
        self._cap = max(8, _env_int("TPUDL_SERVE_TRACE_EVENTS", 64))

    def stamp(self, name: str, force: bool = False) -> None:
        # cadence stamps leave _TERMINAL_RESERVE slots so the terminal
        # stamp (force=True) always lands inside the cap
        if force:
            if len(self.events) < self._cap:
                self.events.append((name, time.monotonic()))
        elif len(self.events) < self._cap - _TERMINAL_RESERVE:
            self.events.append((name, time.monotonic()))

    def t(self, name: str):
        """Monotonic time of the LAST stamp called ``name`` (a
        requeued request stamps queue_wait_end twice; the last wait is
        the one that fed the slot it completed in)."""
        for n, ts in reversed(self.events):
            if n == name:
                return ts
        return None

    def segments(self):
        """``{segment: seconds}`` or ``None`` when any cut point is
        missing (rejected/unfinished requests don't decompose)."""
        t_submit = self.t("submit")
        t_qend = self.t("queue_wait_end")
        t_pack = self.t("rung_pack")
        t_first = self.t("first_token")
        t_end = self.t("complete")
        if t_end is None:
            t_end = self.t("fail")
        cuts = (t_submit, t_qend, t_pack, t_first, t_end)
        if any(c is None for c in cuts):
            return None
        return {
            "queue_wait": t_qend - t_submit,
            "batching": t_pack - t_qend,
            "prefill": t_first - t_pack,
            "decode": t_end - t_first,
        }


def new_trace():
    """A fresh :class:`ReqTrace`, or ``None`` when tracing is
    disarmed — every stamp site gates on ``trace is not None``."""
    return ReqTrace() if trace_armed() else None


def request_record(req) -> dict:
    """The flight-ring descriptor for a terminal request: ids, sizes
    and millisecond timings — NEVER prompt content."""
    tr = getattr(req, "trace", None)
    segs = tr.segments() if tr is not None else None
    rec = {
        "ts": time.time(),
        "trace_id": tr.trace_id if tr is not None else None,
        "model": str(req.model),
        "prompt_len": int(req.prompt.shape[-1]),
        "max_new": int(req.max_new),
        "outcome": ("complete" if req.error is None
                    else type(req.error).__name__),
        "ttft_ms": (round(req.ttft_s * 1000.0, 3)
                    if req.ttft_s is not None else None),
        "latency_ms": (round(req.latency_s * 1000.0, 3)
                       if req.latency_s is not None else None),
        "events": len(tr.events) if tr is not None else 0,
        "segments": ({k: round(v * 1000.0, 3) for k, v in segs.items()}
                     if segs else None),
    }
    return rec
