"""Admission-controlled request queue with per-request deadlines.

The serve plane's front door (SERVE.md): every request passes ONE
admission decision — queue depth against ``TPUDL_SERVE_QUEUE_CAP`` and
queued payload bytes against the optional ``TPUDL_SERVE_HBM_MB``
budget gate — and is either accepted (``serve.requests``) or rejected
with a TYPED :class:`AdmissionError` (``serve.rejects``). Rejection at
the door is the load-shedding contract: under overload the queue stays
bounded, clients get an immediate typed answer, and the black box
records the pressure (``obs doctor`` classifies a death under
sustained rejects as ``overload_shed``).

Deadlines are absolute (stamped at submit): an expired request is shed
at ``take`` time — BEFORE any device work is spent on it — with the
typed :class:`DeadlineExceeded` filed on the request and
``serve.deadline_sheds`` counting the evidence. The server also sheds
mid-decode (slots.py eviction) under the same type.

Lock discipline: one instance lock (``serve.queue``) covers the deque
and byte ledger; metrics publish OUTSIDE it (tpudl/analysis/locks.py).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

import numpy as np

from tpudl.obs import attribution as _attr
from tpudl.obs import metrics as _metrics
from tpudl.serve import reqtrace as _reqtrace
from tpudl.testing import tsan as _tsan

__all__ = ["AdmissionError", "DeadlineExceeded", "Evicted",
           "RequestQueue", "ServeRequest"]


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str) -> float | None:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else None
    except ValueError:
        return None


class AdmissionError(RuntimeError):
    """Typed admission reject. ``reason`` is machine-checkable:
    ``queue_full`` (depth at cap), ``hbm_budget`` (queued payload bytes
    past ``TPUDL_SERVE_HBM_MB``), or ``slots_full`` (direct engine
    insert with no free slot)."""

    def __init__(self, message: str, *, reason: str):
        super().__init__(message)
        self.reason = reason


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before it completed — shed in the
    queue (before dispatch) or evicted mid-decode."""


class Evicted(RuntimeError):
    """The request's slot was evicted before completion (explicit
    cancel, or a supervised retry discarding in-flight work that was
    NOT requeued)."""


class ServeRequest:
    """One in-flight generation request and its result mailbox.

    The submitting client holds the object and waits on
    :meth:`result`; the server thread fills ``tokens``/``error`` and
    sets the event. ``deadline`` is an absolute ``time.monotonic``
    stamp (or ``None``); ``rng`` an optional per-request PRNG key for
    sampled decode."""

    __slots__ = ("prompt", "max_new", "model", "rng", "submitted",
                 "deadline", "tokens", "error", "ttft_s", "latency_s",
                 "done", "trace", "scope")

    def __init__(self, prompt, max_new: int, *, model: str = "default",
                 deadline_s: float | None = None, rng=None):
        self.prompt = np.asarray(prompt, dtype=np.int32)
        if self.prompt.ndim == 1:
            self.prompt = self.prompt[None, :]
        if self.prompt.ndim != 2 or self.prompt.shape[0] != 1:
            raise ValueError(
                f"prompt must be [plen] or [1, plen], got shape "
                f"{self.prompt.shape}")
        self.max_new = int(max_new)
        if self.max_new < 1:
            raise ValueError("max_new must be >= 1")
        self.model = str(model)
        self.rng = rng
        self.submitted = time.monotonic()
        self.deadline = (self.submitted + float(deadline_s)
                         if deadline_s is not None else None)
        self.tokens: np.ndarray | None = None
        self.error: BaseException | None = None
        self.ttft_s: float | None = None
        self.latency_s: float | None = None
        self.done = threading.Event()
        # lifecycle trace (None when TPUDL_SERVE_TRACE=0); stamps are
        # lock-free appends on whichever thread owns the request at
        # that phase (reqtrace.py)
        self.trace = _reqtrace.new_trace()
        if self.trace is not None:
            self.trace.stamp("submit")
        # attribution scope captured on the CLIENT thread: the loop
        # thread serves many tenants per tick, so per-request charges
        # (completions, tokens, SLO samples) follow the submitter's
        # scope, not the loop's (tpudl.obs.attribution)
        self.scope = _attr.current_scope()

    @property
    def nbytes(self) -> int:
        return int(self.prompt.nbytes)

    def expired(self, now: float | None = None) -> bool:
        if self.deadline is None:
            return False
        return (now if now is not None else time.monotonic()) \
            >= self.deadline

    def finish(self, tokens) -> None:
        self.tokens = np.asarray(tokens, dtype=np.int32)
        self.latency_s = time.monotonic() - self.submitted
        if self.trace is not None:
            self.trace.stamp("complete", force=True)
        self.done.set()

    def fail(self, error: BaseException) -> None:
        self.error = error
        self.latency_s = time.monotonic() - self.submitted
        if self.trace is not None:
            self.trace.stamp("fail", force=True)
        self.done.set()

    def result(self, timeout: float | None = None) -> np.ndarray:
        """Block for completion; raise the typed failure if the server
        shed/evicted/errored the request, raise ``TimeoutError`` if the
        wait itself times out (the zero-hangs contract: a client is
        never parked forever on a dead server)."""
        if not self.done.wait(timeout):
            raise TimeoutError(
                f"serve request not completed within {timeout}s")
        if self.error is not None:
            raise self.error
        return self.tokens


class RequestQueue:
    """Bounded FIFO of :class:`ServeRequest` with admission control.

    ``cap`` defaults to ``TPUDL_SERVE_QUEUE_CAP``; ``hbm_budget_mb``
    (default ``TPUDL_SERVE_HBM_MB``, unset = off) bounds the SUM of
    queued prompt payload bytes — the no-unbounded-growth guarantee
    holds in rows and in bytes. An unset per-request deadline inherits
    ``TPUDL_SERVE_DEADLINE_S`` at submit."""

    def __init__(self, cap: int | None = None, *,
                 hbm_budget_mb: float | None = None):
        self.cap = (int(cap) if cap is not None
                    else _env_int("TPUDL_SERVE_QUEUE_CAP", 64))
        budget = (hbm_budget_mb if hbm_budget_mb is not None
                  else _env_float("TPUDL_SERVE_HBM_MB"))
        self.budget_bytes = (int(float(budget) * (1 << 20))
                             if budget else None)
        self._default_deadline_s = _env_float("TPUDL_SERVE_DEADLINE_S")
        self._lock = _tsan.named_lock("serve.queue")
        self._items: deque[ServeRequest] = deque()
        self._bytes = 0
        _metrics.gauge("serve.queue_cap").set(self.cap)
        _metrics.gauge("serve.queue_depth").set(0)

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def depth(self) -> int:
        return len(self)

    def submit(self, req: ServeRequest) -> ServeRequest:
        """Admit or reject ``req``. Raises :class:`AdmissionError` on
        reject (typed, immediate — load shedding is an ANSWER, not a
        hang); returns the request on admit."""
        if req.deadline is None and self._default_deadline_s:
            req.deadline = req.submitted + self._default_deadline_s
        reject = None
        with self._lock:
            if len(self._items) >= self.cap:
                reject = AdmissionError(
                    f"queue at capacity ({self.cap}); raise "
                    f"TPUDL_SERVE_QUEUE_CAP or add serving capacity",
                    reason="queue_full")
            elif self.budget_bytes is not None \
                    and self._bytes + req.nbytes > self.budget_bytes:
                reject = AdmissionError(
                    f"queued payload budget exceeded "
                    f"({self._bytes + req.nbytes} > "
                    f"{self.budget_bytes} bytes; TPUDL_SERVE_HBM_MB)",
                    reason="hbm_budget")
            else:
                self._items.append(req)
                self._bytes += req.nbytes
                depth = len(self._items)
        # metrics/stamps OUTSIDE the lock (locks.py: publication never
        # nests under a serve lock)
        if reject is not None:
            if req.trace is not None:
                req.trace.stamp(f"reject:{reject.reason}")
            _metrics.counter("serve.rejects").inc()
            raise reject
        if req.trace is not None:
            req.trace.stamp("admit")
        _metrics.counter("serve.requests").inc()
        _metrics.gauge("serve.queue_depth").set(depth)
        # attribution: prompt tokens entering the serve plane, charged
        # to the submitter's captured scope (rejects charge nothing)
        _attr.charge("tokens_in", int(req.prompt.shape[1]),
                     key=req.scope.key if req.scope is not None
                     else None)
        return req

    def take(self, k: int, *, model: str | None = None) -> list:
        """Pop up to ``k`` live requests (optionally only for
        ``model``), shedding every EXPIRED request encountered on the
        way — a dead-on-arrival request must cost zero device work.
        Shed requests are failed typed; the count publishes as
        ``serve.deadline_sheds``."""
        now = time.monotonic()
        taken: list[ServeRequest] = []
        shed: list[ServeRequest] = []
        with self._lock:
            kept: deque[ServeRequest] = deque()
            while self._items:
                req = self._items.popleft()
                if req.expired(now):
                    shed.append(req)
                    self._bytes -= req.nbytes
                elif len(taken) < int(k) and (model is None
                                              or req.model == model):
                    taken.append(req)
                    self._bytes -= req.nbytes
                else:
                    kept.append(req)
            self._items = kept
            depth = len(self._items)
        for req in taken:
            if req.trace is not None:
                req.trace.stamp("queue_wait_end")
        for req in shed:
            req.fail(DeadlineExceeded(
                f"deadline passed {now - req.deadline:.3f}s before "
                f"dispatch (queued {now - req.submitted:.3f}s)"))
        if shed:
            _metrics.counter("serve.deadline_sheds").inc(len(shed))
        _metrics.gauge("serve.queue_depth").set(depth)
        return taken

    def requeue_front(self, reqs) -> None:
        """Return in-flight requests to the FRONT of the queue (oldest
        first) — the supervised whole-attempt retry path: a degraded
        re-run serves them again from their prompts, bitwise-honest.
        Bypasses admission: these rows were already admitted once."""
        reqs = list(reqs)
        with self._lock:
            for req in reversed(reqs):
                self._items.appendleft(req)
                self._bytes += req.nbytes
            depth = len(self._items)
        _metrics.gauge("serve.queue_depth").set(depth)

    def fail_all(self, error: BaseException) -> int:
        """Fail every queued request with ``error`` (server teardown on
        an unrecoverable fault): clients unblock with the typed cause
        instead of hanging on a dead server."""
        with self._lock:
            drained = list(self._items)
            self._items.clear()
            self._bytes = 0
        for req in drained:
            req.fail(error)
        _metrics.gauge("serve.queue_depth").set(0)
        return len(drained)
