"""Closed-loop load generation for the serve plane.

The demand side of the serving SLOs (SERVE.md): ``clients`` threads
each keep exactly one request in flight (the closed-loop discipline —
offered load tracks service rate, so the measured QPS is SUSTAINED
throughput, not an open-loop fantasy), and the run reports the SLO
truths the bench judges: sustained QPS, p50/p99 end-to-end latency,
TTFT percentiles, rejects and deadline sheds.

Two chaos points make overload testable under ``TPUDL_FAULT_PLAN``:

- ``serve.tick`` fires once per client iteration; a ``burst`` rule
  returns a COUNT and the client submits that many extra requests
  back-to-back (fire-and-forget) — the deterministic spike that drives
  admission past queue capacity;
- ``serve.client`` fires before each submit; a ``delay`` rule
  (``FaultPlan.slow_client``) stalls the client so queued requests age
  into their deadlines.

A rejected submit is an ANSWER (typed), recorded and moved past; a
completed/shed request's latency comes from its own stamps. Every wait
is bounded (``timeout``) — the zero-hangs contract holds even when the
server dies mid-run.
"""

from __future__ import annotations

import contextlib
import threading
import time

import numpy as np

from tpudl.obs import attribution as _attr
from tpudl.obs.metrics import percentile
from tpudl.serve.queue import AdmissionError
from tpudl.testing import faults as _faults
from tpudl.testing import tsan as _tsan

__all__ = ["run_closed_loop"]


def _percentile(xs: list, q: float):
    # the one shared nearest-rank implementation (tpudl.obs.metrics):
    # the loadgen's ground truth and the obs plane's windows can never
    # disagree by construction
    return percentile(sorted(xs), q)


def run_closed_loop(server, make_prompt, *, requests: int,
                    clients: int = 4, max_new: int = 8,
                    model: str = "default",
                    deadline_s: float | None = None,
                    timeout: float = 120.0,
                    tenant=None) -> dict:
    """Drive ``requests`` total requests through ``server`` with
    ``clients`` closed-loop threads; returns the SLO summary.

    ``make_prompt(i)`` supplies the i-th prompt (ragged lengths are
    the point — the serve loop buckets them). The server must already
    be started (or be run concurrently by the caller).

    ``tenant`` stamps the generated requests with an attribution scope
    (tpudl.obs.attribution): a string tags every client with that
    tenant; a sequence assigns client ``c`` the ``c % len``-th entry —
    the two-tenant serve sub-bench drives attribution end to end with
    ``tenant=("a", "b")``. None leaves requests unattributed."""
    # one leaf lock for every tally: the critical sections are scalar
    # bumps/list appends and never nest with the server's locks
    lock = _tsan.named_lock("serve.loadgen")
    counter = [0]
    latencies: list = []
    ttfts: list = []
    rejected = [0]
    shed = [0]
    errors: list = []

    def _next_index():
        with lock:
            i = counter[0]
            counter[0] += 1
            return i

    def _submit(i, wait: bool):
        try:
            req = server.submit(np.asarray(make_prompt(i),
                                           dtype=np.int32),
                                max_new, model=model,
                                deadline_s=deadline_s)
        except AdmissionError:
            with lock:
                rejected[0] += 1
            return
        if not wait:
            return
        try:
            req.result(timeout=timeout)
        except Exception as e:
            with lock:
                if type(e).__name__ in ("DeadlineExceeded", "Evicted"):
                    shed[0] += 1
                else:
                    errors.append(e)
            return
        with lock:
            latencies.append(req.latency_s)
            if req.ttft_s is not None:
                ttfts.append(req.ttft_s)

    def _tenant_of(cid: int):
        if tenant is None or isinstance(tenant, str):
            return tenant
        seq = list(tenant)
        return seq[cid % len(seq)] if seq else None

    def _client(cid: int):
        # the client thread IS the submit thread, so entering the
        # scope here is exactly where ServeRequest captures it
        ctx = (_attr.scope(tenant=_tenant_of(cid))
               if _tenant_of(cid) is not None
               else contextlib.nullcontext())
        with ctx:
            while True:
                i = _next_index()
                if i >= int(requests):
                    return
                burst = _faults.fire("serve.tick", tick=i, client=cid)
                if burst:
                    # the injected spike: count extra submits in ONE
                    # tick, fire-and-forget — their fate (served or
                    # typed-rejected) is exactly what the chaos case
                    # asserts on
                    for j in range(int(burst)):
                        _submit(i, wait=False)
                _faults.fire("serve.client", client=cid, i=i)
                _submit(i, wait=True)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=_client, args=(c,),
                                name=f"tpudl-loadgen-{c}", daemon=True)
               for c in range(int(clients))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
    wall = time.perf_counter() - t0
    if errors:
        raise errors[0]
    completed = len(latencies)
    return {
        "requests": int(requests),
        "clients": int(clients),
        "completed": completed,
        "rejected": rejected[0],
        "deadline_shed": shed[0],
        "wall_s": round(wall, 4),
        "qps": round(completed / wall, 3) if wall > 0 else None,
        "p50_ms": (round(_percentile(latencies, 0.50) * 1000, 3)
                   if latencies else None),
        "p99_ms": (round(_percentile(latencies, 0.99) * 1000, 3)
                   if latencies else None),
        "ttft_p50_s": (round(_percentile(ttfts, 0.50), 4)
                       if ttfts else None),
        "ttft_p99_s": (round(_percentile(ttfts, 0.99), 4)
                       if ttfts else None),
    }
