"""Rung-bucketed dynamic batching for ragged serve payloads.

The featurize/UDF/embed half of the serve plane (the LM half lives in
slots.py): in-flight requests carry ragged row counts, and dispatching
each alone is the per-request-dispatch tax the paper's serving surface
exists to kill. :class:`RungBatcher` concatenates whatever is in
flight, pads the row count UP to the PR-15 :class:`BucketLadder` rung
(``pad_to`` — row-0 repeat, bitwise-honest: pad rows are stripped
before results fan back out), and dispatches ONE program. The rung set
is O(log n), so at steady state every dispatch replays an
already-traced signature — traceck-provably zero retraces — and when
the AOT store is armed the programs come from disk, not from jit.

``serve.batches`` counts dispatches; ``serve.batch_occupancy`` gauges
real rows over rung rows (the saturation SLO: > 0.5 under load);
padding cost lands on the shared ``compile.bucket_pad_rows`` counter.
"""

from __future__ import annotations

import numpy as np

from tpudl.obs import metrics as _metrics

__all__ = ["RungBatcher"]


class RungBatcher:
    """Pack ragged per-request payloads onto bucket rungs and dispatch
    one compiled program per batch.

    ``fn`` maps ``[N, ...] -> [N, ...]`` (leading dim preserved — the
    UDF/featurize/embed contract); ``buckets`` resolves through
    :func:`tpudl.compile.resolve_ladder` (``None`` = consult
    ``TPUDL_COMPILE_BUCKETS``, ``True`` = default pow2ish ladder).
    When ``fn`` is jittable and the AOT store is armed, dispatch
    routes through ``ProgramStore.call`` so steady state executes
    precompiled programs."""

    def __init__(self, fn, *, buckets=True):
        from tpudl.compile import resolve_ladder

        self._fn = fn
        self._ladder = resolve_ladder(buckets)

    def rung_for(self, n: int) -> int:
        return self._ladder.pick(int(n)) if self._ladder else int(n)

    def run(self, payloads) -> list:
        """Dispatch one padded batch for ``payloads`` (a list of
        ``[rows_i, ...]`` arrays, ragged in ``rows_i``) and split the
        result back per request, pad rows stripped."""
        from tpudl.compile import (aot_enabled, count_pad_rows,
                                   get_program_store, pad_to)

        payloads = [np.asarray(p) for p in payloads]
        if not payloads:
            return []
        sizes = [int(p.shape[0]) for p in payloads]
        batch = (np.concatenate(payloads, axis=0) if len(payloads) > 1
                 else payloads[0])
        n = int(batch.shape[0])
        rung = self.rung_for(n)
        padded = pad_to(batch, rung)
        count_pad_rows(rung - n)
        if aot_enabled() and hasattr(self._fn, "lower"):
            out = get_program_store().call(self._fn, (padded,))
        else:
            out = self._fn(padded)
        out = np.asarray(out)[:n]
        _metrics.counter("serve.batches").inc()
        _metrics.gauge("serve.batch_occupancy").set(n / max(rung, 1))
        cuts = np.cumsum(sizes)[:-1]
        return [np.asarray(a) for a in np.split(out, cuts)] \
            if len(sizes) > 1 else [out]
