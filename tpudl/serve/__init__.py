"""tpudl.serve — continuous-batching online inference (SERVE.md).

The serving tentpole: an admission-controlled request queue with
per-request deadlines, rung-bucketed dynamic batching for ragged
featurize/UDF payloads, slot-based continuous batch decoding for
``TinyCausalLM`` on a fixed-geometry KV cache (one compiled decode-step
program serves a churning request mix with zero retraces), and a
multi-model registry that warm-starts every model's programs from the
persisted store so time-to-first-token is a deserialization, not a
60-second jit. Overload rides the PR-14 degradation ladder; SLO
metrics (``serve.*``) publish through ``tpudl.obs``.

Request-scoped telemetry (ISSUE 18): every request carries a
:class:`~tpudl.serve.reqtrace.ReqTrace` of lifecycle stamps that
decompose its latency into queue_wait/batching/prefill/decode
segments; completed requests feed the windowed SLO engine
(:mod:`tpudl.obs.slo`) and the flight recorder's request ring.
"""

from tpudl.serve.queue import (AdmissionError, DeadlineExceeded,
                               Evicted, RequestQueue, ServeRequest)
from tpudl.serve.reqtrace import ReqTrace
from tpudl.serve.batching import RungBatcher
from tpudl.serve.slots import SlotDecoder
from tpudl.serve.registry import ModelRegistry
from tpudl.serve.server import Server
from tpudl.serve.loadgen import run_closed_loop

__all__ = ["AdmissionError", "DeadlineExceeded", "Evicted",
           "RequestQueue", "ServeRequest", "ReqTrace", "RungBatcher",
           "SlotDecoder", "ModelRegistry", "Server", "run_closed_loop"]
