"""Multi-model serve registry with warm-start from the program store.

Registration is where TTFT is won (SERVE.md): ``add_model`` builds the
model's :class:`~tpudl.serve.slots.SlotDecoder` and — when the AOT
store is armed — restores the persisted program table
(``ensure_restored(block=True)``) and submits every serve-loop
signature through ``precompile_serve``. A previously-served model's
first token is then a DESERIALIZATION away, not a 60-second jit; the
``bench serve`` warm arm pins the ratio (``serve_warm_ttft_s``).

One instance lock (``serve.registry``) guards the name→entry map;
the ``serve.models`` gauge publishes outside it.
"""

from __future__ import annotations

import time

from tpudl.obs import metrics as _metrics
from tpudl.serve.slots import SlotDecoder
from tpudl.testing import tsan as _tsan

__all__ = ["ModelEntry", "ModelRegistry"]


class ModelEntry:
    """One registered model: its engine plus warm-start forensics.
    ``tokenizer`` is set for text-serving entries (``add_generator``)
    so the request path can encode prompts / decode completions with
    the exact vocab the model was trained against."""

    __slots__ = ("name", "model", "params", "engine",
                 "warm_signatures", "warm_s", "tokenizer")

    def __init__(self, name: str, model, params, engine: SlotDecoder,
                 warm_signatures: int, warm_s: float, tokenizer=None):
        self.name = name
        self.model = model
        self.params = params
        self.engine = engine
        self.warm_signatures = warm_signatures
        self.warm_s = warm_s
        self.tokenizer = tokenizer


class ModelRegistry:
    """Name → :class:`ModelEntry` map shared by one server."""

    def __init__(self):
        self._lock = _tsan.named_lock("serve.registry")
        self._entries: dict[str, ModelEntry] = {}

    def add_model(self, name: str, model, params, *,
                 slots: int | None = None,
                 cache_len: int | None = None,
                 temperature: float = 0.0, prompt_buckets=True,
                 prompt_rungs=None, mesh=None, tp: bool = False,
                 warm: bool = True, tokenizer=None) -> ModelEntry:
        """Build the engine for ``model`` and (``warm=True``, store
        armed) AOT-warm its serve programs. ``prompt_rungs`` overrides
        the warmed prefill signature set; default is every ladder rung
        the fixed cache can admit (an over-approximation costs compile
        time once, never correctness — a rung missed here compiles on
        first use like any store miss)."""
        engine = SlotDecoder(model, params, slots=slots,
                             cache_len=cache_len,
                             temperature=temperature,
                             prompt_buckets=prompt_buckets, mesh=mesh,
                             tp=tp)
        warm_n, warm_s = 0, 0.0
        if warm:
            t0 = time.perf_counter()
            if prompt_rungs is None:
                prompt_rungs = (
                    engine._ladder.rungs_up_to(engine.cache_len - 1)
                    if engine._ladder else [])
            if prompt_rungs:
                warm_n = model.precompile_serve(
                    params, slots=engine.slots,
                    cache_len=engine.cache_len,
                    prompt_rungs=prompt_rungs,
                    temperature=engine.temperature, mesh=mesh, tp=tp,
                    block=True)
            warm_s = time.perf_counter() - t0
        entry = ModelEntry(str(name), model, params, engine, warm_n,
                           warm_s, tokenizer=tokenizer)
        with self._lock:
            self._entries[entry.name] = entry
            count = len(self._entries)
        _metrics.gauge("serve.models").set(count)
        return entry

    def add_generator(self, name: str, generator, *,
                      slots: int | None = None,
                      cache_len: int | None = None,
                      warm: bool = True) -> ModelEntry:
        """Register an :class:`~tpudl.ml.lm.LMGenerator`'s signature for
        online serving: the transformer already binds the model, the
        weights, the sampling temperature, the prompt bucket ladder,
        and the TOKENIZER — this unwraps them into :meth:`add_model`
        (so the registered entry decodes through the continuous-
        batching queue with exactly the offline stage's programs) and
        files the tokenizer on the entry for the request path."""
        missing = [k for k in ("model", "weights", "tokenizer")
                   if getattr(generator, k, None) is None]
        if missing:
            raise ValueError(
                f"add_generator needs a fully-bound LMGenerator "
                f"(missing {missing})")
        return self.add_model(
            str(name), generator.model, generator.weights,
            slots=slots, cache_len=cache_len,
            temperature=float(generator.temperature),
            prompt_buckets=(generator.promptBuckets
                            if generator.promptBuckets is not None
                            else True),
            mesh=generator.mesh, tp=bool(generator.tp), warm=warm,
            tokenizer=generator.tokenizer)

    def get(self, name: str) -> ModelEntry:
        with self._lock:
            try:
                return self._entries[name]
            except KeyError:
                raise KeyError(
                    f"model {name!r} not registered (have: "
                    f"{sorted(self._entries)})") from None

    def names(self) -> list:
        with self._lock:
            return sorted(self._entries)

    def entries(self) -> list:
        with self._lock:
            return list(self._entries.values())
