"""Slot-based continuous batch decoding on a fixed-geometry KV cache.

The LM serving engine (SERVE.md): ONE compiled decode-step program of
static geometry ``(slots, cache_len)`` serves a churning request mix.
Requests are INSERTED into free slots (a bucketed prefill program
scans the prompt on a fresh batch-1 row cache, then writes the whole
row into the slot cache at a TRACED slot index — the full-row write
wipes any stale state of the slot's previous occupant) and EVICTED by
pure host-side bookkeeping: the device program never changes shape, so
after warmup the serve loop performs ZERO retraces no matter how
requests churn (traceck-pinned in tests/test_serve.py).

Correctness contract, validated bitwise: each slot's token stream
equals a serial batch-1 ``generate`` of the same prompt — per-slot
traced positions mask dead cache lanes to ``-inf`` before the softmax
and per-lane zero padding keeps reductions exact, so neighbors and
stale occupants are invisible. Sampling folds each slot's key with its
OWN generation-step index, matching ``_gen_program``'s per-step
``fold_in``.

Host state (tok/pos/steps/keys) lives in writable numpy arrays — the
engine copies device outputs before mutating (device views are
read-only). The engine is single-consumer (the server thread); no lock.
"""

from __future__ import annotations

import os

import numpy as np

from tpudl.obs import metrics as _metrics
from tpudl.serve import reqtrace as _reqtrace
from tpudl.serve.queue import AdmissionError, Evicted

__all__ = ["SlotDecoder"]


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class SlotDecoder:
    """Continuous-batching decode engine for one model's params.

    ``slots`` defaults to ``TPUDL_SERVE_SLOTS``; ``cache_len`` (the
    fixed per-slot KV length) defaults to the model's ``max_len``;
    ``prompt_buckets`` resolves through
    :func:`tpudl.compile.resolve_ladder` so ragged prompt lengths share
    O(log n) prefill programs. ``mesh``/``tp`` thread straight into the
    model's ``_tp_hooks`` — the slot programs are topology-keyed in
    ``_gen_jits`` like every generate program."""

    def __init__(self, model, params, *, slots: int | None = None,
                 cache_len: int | None = None, temperature: float = 0.0,
                 prompt_buckets=True, mesh=None, tp: bool = False):
        import jax
        import jax.numpy as jnp

        from tpudl.compile import resolve_ladder

        self.model = model
        self.params = params
        self.slots = (int(slots) if slots is not None
                      else _env_int("TPUDL_SERVE_SLOTS", 8))
        self.cache_len = int(cache_len if cache_len is not None
                             else model.max_len)
        self.temperature = float(temperature)
        self.mesh = mesh
        self.tp = bool(tp)
        self._ladder = resolve_ladder(prompt_buckets)
        dtype = jnp.asarray(params["embed"]["table"]).dtype
        self._cache = model.init_cache(self.slots, self.cache_len,
                                       dtype=dtype, mesh=mesh, tp=tp)
        self._tok = np.zeros(self.slots, dtype=np.int32)
        self._pos = np.zeros(self.slots, dtype=np.int32)
        self._steps = np.zeros(self.slots, dtype=np.int32)
        key0 = np.asarray(jax.random.PRNGKey(0))
        self._keys = np.stack([key0] * self.slots)
        # per-slot occupant: {"request", "tokens": [ints], "trace"}
        # or None
        self._meta: list[dict | None] = [None] * self.slots
        # decode-cadence stamp stride, resolved once (the step loop is
        # the hot path — no env read per token)
        self._trace_cadence = _reqtrace.decode_cadence()

    # -- host-side bookkeeping --------------------------------------------
    def free(self) -> list:
        return [s for s, m in enumerate(self._meta) if m is None]

    def active(self) -> list:
        return [s for s, m in enumerate(self._meta) if m is not None]

    def occupants(self) -> list:
        """``[(slot, request), ...]`` for every occupied slot — the
        server's mid-decode deadline sweep walks this."""
        return [(s, m["request"]) for s, m in enumerate(self._meta)
                if m is not None]

    def occupancy(self) -> float:
        return len(self.active()) / max(self.slots, 1)

    def rung_for(self, plen: int, max_new: int) -> int:
        """Padded prompt length for one admission: bucketed UP the
        ladder but never past what the fixed cache can hold alongside
        ``max_new`` decode steps (past the cap the exact length is
        used — honest, one extra program for an outlier)."""
        plen, max_new = int(plen), int(max_new)
        if plen + max_new > self.cache_len:
            raise ValueError(
                f"prompt ({plen}) + max_new ({max_new}) exceeds the "
                f"slot cache length {self.cache_len}")
        if self._ladder is None:
            return plen
        return max(plen, min(self._ladder.pick(plen),
                             self.cache_len - max_new))

    def _normalize_key(self, rng):
        import jax

        if rng is None:
            return np.asarray(jax.random.PRNGKey(0))
        if isinstance(rng, (int, np.integer)):
            return np.asarray(jax.random.PRNGKey(int(rng)))
        return np.asarray(rng)

    def _call(self, fn, args):
        from tpudl.compile import aot_enabled, get_program_store

        if aot_enabled():
            return get_program_store().call(fn, args)
        return fn(*args)

    # -- the three verbs ---------------------------------------------------
    def insert(self, request) -> int:
        """Prefill ``request``'s prompt into a free slot; returns the
        slot index with the first token already decoded (the request's
        TTFT moment — the server observes it). Raises the typed
        :class:`AdmissionError` (``slots_full``) when no slot is free:
        direct engine users get the same typed answer the queue gives."""
        import jax.numpy as jnp

        free = self.free()
        if not free:
            raise AdmissionError(
                f"all {self.slots} decode slots occupied; raise "
                f"TPUDL_SERVE_SLOTS or queue the request",
                reason="slots_full")
        slot = free[0]
        trace = getattr(request, "trace", None)
        if trace is not None:
            trace.stamp("slot_insert")
        plen = int(request.prompt.shape[1])
        rung = self.rung_for(plen, request.max_new)
        padded = np.zeros((1, rung), dtype=np.int32)
        padded[:, :plen] = request.prompt
        if trace is not None:
            trace.stamp("rung_pack")
        key = self._normalize_key(request.rng)
        fill = self.model._slot_prefill_program(
            rung, self.slots, self.cache_len, self.temperature,
            mesh=self.mesh, tp=self.tp)
        # tpudl: ignore[daemon-shared-write] — single-consumer engine:
        # insert and step only ever run on the one thread driving the
        # serve loop (the server's daemon thread, or the caller's in
        # synchronous run()); the cache never has two writers
        first, self._cache = self._call(fill, (
            self.params, self._cache, jnp.asarray(padded),
            jnp.asarray(key), jnp.asarray(plen, jnp.int32),
            jnp.asarray(slot, jnp.int32)))
        first_tok = int(np.asarray(first)[0])
        if trace is not None:
            trace.stamp("first_token")
        self._tok[slot] = first_tok
        self._pos[slot] = plen
        self._steps[slot] = 1
        self._keys[slot] = key
        self._meta[slot] = {"request": request, "tokens": [first_tok],
                            "trace": trace}
        _metrics.counter("serve.inserts").inc()
        return slot

    def step(self) -> int:
        """One decode step for EVERY active slot through the single
        compiled step program; returns the number of tokens emitted
        (0 = nothing active, no dispatch). Inactive slots ride along as
        dead lanes (their writes land at pos 0 and are overwritten by
        the next insert's full-row write)."""
        import jax.numpy as jnp

        active = self.active()
        if not active:
            return 0
        step_fn = self.model._slot_step_program(
            self.slots, self.cache_len, self.temperature,
            mesh=self.mesh, tp=self.tp)
        nxt, self._cache = self._call(step_fn, (
            self.params, self._cache, jnp.asarray(self._tok),
            jnp.asarray(self._pos), jnp.asarray(self._keys),
            jnp.asarray(self._steps)))
        nxt = np.asarray(nxt).copy()  # device views are read-only
        cad = self._trace_cadence
        for s in active:
            meta = self._meta[s]
            meta["tokens"].append(int(nxt[s]))
            trace = meta["trace"]
            if trace is not None:
                n = len(meta["tokens"])
                if n % cad == 0:
                    trace.stamp(f"decode_{n}")
        self._tok = nxt.astype(np.int32)
        self._pos[active] += 1
        self._steps[active] += 1
        _metrics.counter("serve.steps").inc()
        _metrics.counter("serve.tokens").inc(len(active))
        _metrics.gauge("serve.batch_occupancy").set(self.occupancy())
        return len(active)

    def evict(self, slot: int, error: BaseException | None = None):
        """Free ``slot`` NOW (host bookkeeping only — the next insert's
        full-row write retires the stale cache state). Returns the
        evicted request; when ``error`` is given the request is failed
        with it (typed: deadline shed, cancel), else the caller owns
        the disposition (e.g. requeue for a supervised retry)."""
        meta = self._meta[int(slot)]
        if meta is None:
            raise KeyError(f"slot {slot} is not occupied")
        self._meta[int(slot)] = None
        _metrics.counter("serve.evictions").inc()
        req = meta["request"]
        trace = meta.get("trace")
        if trace is not None:
            trace.stamp("evict")
        if error is not None:
            req.fail(error)
        return req

    def evict_all(self, error: BaseException | None = None) -> list:
        """Evict every occupant (supervised-retry reset / teardown)."""
        return [self.evict(s, error) for s in self.active()]

    def pop_completed(self) -> list:
        """Harvest ``[(request, tokens), ...]`` for every slot whose
        occupant has emitted ``max_new`` tokens, freeing the slots.
        Completion is NOT an eviction: ``serve.evictions`` counts only
        early removals."""
        out = []
        for s in self.active():
            meta = self._meta[s]
            req = meta["request"]
            if len(meta["tokens"]) >= req.max_new:
                self._meta[s] = None
                out.append((req, np.asarray(meta["tokens"],
                                            dtype=np.int32)))
        return out

    def cancel(self, request) -> bool:
        """Evict ``request`` mid-decode, failing it typed
        :class:`Evicted`; ``False`` when it occupies no slot."""
        for s, req in self.occupants():
            if req is request:
                self.evict(s, Evicted("request cancelled mid-decode"))
                return True
        return False
