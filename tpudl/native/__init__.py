"""Native input-pipeline bindings (ctypes over decode.cpp).

Builds ``libtpudl_decode.so`` on first use with the system toolchain
(g++ + libjpeg; no pip, no pybind11 — SURVEY.md §2.3's contract) and
exposes :func:`decode_resize_batch`. Falls back cleanly: callers check
:func:`available` and use the PIL path otherwise, so the framework works
on hosts without a compiler.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess

import numpy as np

from tpudl.testing import tsan as _tsan

__all__ = ["available", "decode_resize_batch", "build", "lib_path"]

log = logging.getLogger("tpudl.native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "decode.cpp")
_LIB = os.path.join(_DIR, "libtpudl_decode.so")
_lock = _tsan.named_lock("native.build")
_lib = None
_build_failed = False


def lib_path() -> str:
    return _LIB


# tpudl: ignore[lock-held-blocking] — the one-shot native build: the
# lock EXISTS to hold everyone while one cc subprocess (timeout=120)
# compiles; a second concurrent build would race the .so write
def build(force: bool = False) -> bool:
    """Compile decode.cpp → libtpudl_decode.so. Returns success.

    The .so is a build artifact, never committed (round-1 advice): it is
    compiled from source on first use and recompiled whenever decode.cpp
    is newer than the existing library."""
    global _build_failed
    if os.path.exists(_LIB) and not force:
        # no source alongside a shipped .so → trust the .so (the ABI
        # check at load time still guards staleness)
        if (not os.path.exists(_SRC)
                or os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)):
            return True
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC,
           "-ljpeg", "-lpthread", "-o", _LIB]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        log.warning("native build unavailable: %r", e)
        _build_failed = True
        return False
    if proc.returncode != 0:
        log.warning("native build failed:\n%s", proc.stderr[-2000:])
        _build_failed = True
        return False
    return True


def _load():
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        if not build():
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError as e:
            log.warning("native lib load failed: %r", e)
            _build_failed = True
            return None
        if (not hasattr(lib, "tpudl_native_abi_version")
                or lib.tpudl_native_abi_version() != 1):
            log.warning("native ABI mismatch/stale library; rebuilding")
            if not build(force=True):
                _build_failed = True
                return None
            lib = ctypes.CDLL(_LIB)
            if (not hasattr(lib, "tpudl_native_abi_version")
                    or lib.tpudl_native_abi_version() != 1):
                # dlopen may have returned the cached stale mapping
                log.warning("native library still stale after rebuild")
                _build_failed = True
                return None
        lib.tpudl_decode_resize_batch.restype = ctypes.c_int
        lib.tpudl_decode_resize_batch.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_size_t),
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int,
        ]
        _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def decode_resize_batch(blobs: list[bytes], height: int, width: int,
                        n_threads: int | None = None
                        ) -> tuple[np.ndarray, np.ndarray]:
    """Decode a list of encoded JPEGs → ((N, H, W, 3) uint8 BGR batch,
    ok mask). Failed rows are zeroed with ok=False (the reference's
    null-row discipline, imageIO._decodeImage)."""
    lib = _load()
    if lib is None:
        raise RuntimeError(
            "native decoder unavailable (no compiler or libjpeg); use the "
            "PIL path (tpudl.image.imageIO)")
    n = len(blobs)
    out = np.zeros((n, height, width, 3), dtype=np.uint8)
    status = np.zeros((n,), dtype=np.uint8)
    if n == 0:
        return out, status.astype(bool)
    keepalive = [ctypes.create_string_buffer(b, len(b)) for b in blobs]
    datas = (ctypes.c_char_p * n)(
        *[ctypes.cast(buf, ctypes.c_char_p) for buf in keepalive])
    sizes = (ctypes.c_size_t * n)(*[len(b) for b in blobs])
    if n_threads is None:
        n_threads = min(n, os.cpu_count() or 1)
    lib.tpudl_decode_resize_batch(
        datas, sizes, n, height, width,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        status.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        int(n_threads))
    return out, status.astype(bool)
