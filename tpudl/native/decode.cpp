// tpudl native input pipeline: batch JPEG decode + resize + pack.
//
// The reference's image hot loop decodes per row on the executor CPU
// (PIL/libjpeg in Python workers, java.awt in the JVM — SURVEY.md §2.3,
// §3.1 "historically the bottleneck"). This is the TPU-native rebuild's
// one first-party native component (SURVEY.md §7.3): a multithreaded
// libjpeg decoder that goes straight from encoded bytes to the packed
// uint8 BGR batch the device transfer wants, with DCT-domain downscale
// (libjpeg scale_num/denom) so a 4000px photo headed for 299×299 never
// materializes at full size.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 decode.cpp -ljpeg -lpthread
//        -o libtpudl_decode.so   (driven by tpudl/native/__init__.py)
// ABI: plain C, consumed via ctypes — no pybind11 in this image.

#include <atomic>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include <jpeglib.h>

namespace {

struct ErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf jump;
};

void error_exit(j_common_ptr cinfo) {
  ErrorMgr* err = reinterpret_cast<ErrorMgr*>(cinfo->err);
  longjmp(err->jump, 1);
}

void silent_output(j_common_ptr) {}

// Bilinear resize HxWx3 -> out_h x out_w x 3 (uint8), channel-order
// preserving. Matches the semantics (not bit-exactness) of the
// reference's bilinear resizes (PIL BILINEAR / Graphics2D bilinear).
void resize_bilinear(const uint8_t* src, int sh, int sw, uint8_t* dst,
                     int dh, int dw) {
  if (sh == dh && sw == dw) {
    std::memcpy(dst, src, static_cast<size_t>(sh) * sw * 3);
    return;
  }
  const float y_ratio = static_cast<float>(sh) / dh;
  const float x_ratio = static_cast<float>(sw) / dw;
  for (int y = 0; y < dh; ++y) {
    // half-pixel centers
    float sy = (y + 0.5f) * y_ratio - 0.5f;
    if (sy < 0) sy = 0;
    int y0 = static_cast<int>(sy);
    int y1 = y0 + 1 < sh ? y0 + 1 : sh - 1;
    float fy = sy - y0;
    for (int x = 0; x < dw; ++x) {
      float sx = (x + 0.5f) * x_ratio - 0.5f;
      if (sx < 0) sx = 0;
      int x0 = static_cast<int>(sx);
      int x1 = x0 + 1 < sw ? x0 + 1 : sw - 1;
      float fx = sx - x0;
      for (int c = 0; c < 3; ++c) {
        float tl = src[(y0 * sw + x0) * 3 + c];
        float tr = src[(y0 * sw + x1) * 3 + c];
        float bl = src[(y1 * sw + x0) * 3 + c];
        float br = src[(y1 * sw + x1) * 3 + c];
        float top = tl + (tr - tl) * fx;
        float bot = bl + (br - bl) * fx;
        dst[(y * dw + x) * 3 + c] =
            static_cast<uint8_t>(top + (bot - top) * fy + 0.5f);
      }
    }
  }
}

// Decode one JPEG into BGR uint8 at (out_h, out_w). Returns true on
// success. Uses libjpeg DCT scaling to decode at <= 2x the target size.
bool decode_one(const uint8_t* data, size_t size, int out_h, int out_w,
                uint8_t* out) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  jerr.pub.output_message = silent_output;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data),
               static_cast<unsigned long>(size));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  // DCT-domain downscale: pick M/8 (M in 1..8) so the decoded image is
  // the smallest size still >= the resize target in both dims.
  for (int m = 1; m <= 8; ++m) {
    cinfo.scale_num = m;
    cinfo.scale_denom = 8;
    long sh = (static_cast<long>(cinfo.image_height) * m + 7) / 8;
    long sw = (static_cast<long>(cinfo.image_width) * m + 7) / 8;
    if (sh >= out_h && sw >= out_w) break;
  }
  jpeg_start_decompress(&cinfo);
  // out_color_space = JCS_RGB above makes libjpeg emit 3 components for
  // every convertible source (grayscale included); unconvertible color
  // spaces error out through error_exit -> caller's PIL fallback.
  const int sh = cinfo.output_height, sw = cinfo.output_width;
  const int row_stride = sw * cinfo.output_components;
  std::vector<uint8_t> decoded(static_cast<size_t>(sh) * sw * 3);
  std::vector<uint8_t> row(row_stride);
  uint8_t* rowp = row.data();
  for (int y = 0; y < sh; ++y) {
    jpeg_read_scanlines(&cinfo, &rowp, 1);
    std::memcpy(decoded.data() + static_cast<size_t>(y) * sw * 3, rowp,
                static_cast<size_t>(sw) * 3);
  }
  jpeg_finish_decompress(&cinfo);
  // Truncated/corrupt-but-recoverable streams surface as libjpeg
  // warnings (padded gray output), not error_exit. The reference's PIL
  // path rejects such files (null-row discipline) — match it.
  const long warnings = cinfo.err->num_warnings;
  jpeg_destroy_decompress(&cinfo);
  if (warnings > 0) return false;

  std::vector<uint8_t> resized(static_cast<size_t>(out_h) * out_w * 3);
  resize_bilinear(decoded.data(), sh, sw, resized.data(), out_h, out_w);
  // RGB -> BGR pack (Spark image-schema storage order)
  const size_t n = static_cast<size_t>(out_h) * out_w;
  for (size_t i = 0; i < n; ++i) {
    out[i * 3] = resized[i * 3 + 2];
    out[i * 3 + 1] = resized[i * 3 + 1];
    out[i * 3 + 2] = resized[i * 3];
  }
  return true;
}

}  // namespace

extern "C" {

// Decode n JPEGs -> packed (n, out_h, out_w, 3) uint8 BGR batch.
// status[i] = 1 ok, 0 decode failure (row left zeroed).
// Returns the number of successfully decoded images.
int tpudl_decode_resize_batch(const uint8_t** datas, const size_t* sizes,
                              int n, int out_h, int out_w, uint8_t* out,
                              uint8_t* status, int n_threads) {
  if (n <= 0) return 0;
  if (n_threads <= 0) n_threads = 1;
  if (n_threads > n) n_threads = n;
  const size_t img_bytes = static_cast<size_t>(out_h) * out_w * 3;
  std::atomic<int> next(0), ok(0);
  auto worker = [&]() {
    int i;
    while ((i = next.fetch_add(1)) < n) {
      bool good = decode_one(datas[i], sizes[i], out_h, out_w,
                             out + static_cast<size_t>(i) * img_bytes);
      status[i] = good ? 1 : 0;
      if (good) {
        ok.fetch_add(1);
      } else {
        std::memset(out + static_cast<size_t>(i) * img_bytes, 0, img_bytes);
      }
    }
  };
  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker);
  for (auto& th : threads) th.join();
  return ok.load();
}

int tpudl_native_abi_version() { return 1; }

}  // extern "C"
