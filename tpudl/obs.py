"""Observability: profiling hooks + step metrics.

SURVEY.md §5.1/§5.5: the reference has NO first-party tracing or metrics
(observability was inherited from the Spark UI). This layer is the cheap
real win the survey calls for: jax.profiler traces, named scopes around
the pipeline stages (decode/infeed/apply show up as labeled spans in the
trace viewer), and a throughput meter that computes the judged metric
(images/sec/chip) inside the framework itself.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import threading
import time

__all__ = ["profile", "named_scope", "Meter", "load_trace_events",
           "summarize_device_trace", "PipelineReport",
           "last_pipeline_report", "set_last_pipeline"]


@contextlib.contextmanager
def profile(log_dir: str):
    """Capture a jax.profiler trace for the enclosed block; view with
    tensorboard-plugin-profile or xprof against ``log_dir``, or parse
    programmatically with :func:`load_trace_events` +
    :func:`summarize_device_trace`."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def load_trace_events(trace_dir: str) -> list[dict]:
    """Events from the newest trace-viewer JSON under ``trace_dir``
    (written by :func:`profile`; works for tunneled backends too — the
    PJRT plugin populates real device lanes)."""
    paths = glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                      recursive=True)
    if not paths:
        raise FileNotFoundError(f"no trace.json.gz under {trace_dir}")
    with gzip.open(max(paths, key=os.path.getmtime)) as f:
        tr = json.load(f)
    return tr["traceEvents"] if isinstance(tr, dict) else tr


def summarize_device_trace(events: list[dict]) -> dict:
    """Aggregate DEVICE-side time from a trace-viewer event list.

    Returns ``{"module_us": total_us_across_XLA-Module_executions,
    "module_count": n, "ops": {name: {us, count, category, long_name,
    bytes}}}``. The "XLA Modules" lane is the compiled program's
    on-device wall time — the honest chip-side throughput denominator,
    independent of host/tunnel dispatch latency; the "XLA Ops" lane is
    the per-fusion attribution (SURVEY.md §5.1). Empty summary (count 0)
    when the trace has no TPU lanes (CPU backend)."""
    procs, lanes = {}, {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            procs[e["pid"]] = e["args"].get("name", "")
        elif e.get("name") == "thread_name":
            lanes[(e["pid"], e["tid"])] = e["args"].get("name", "")
    device_pids = {p for p, n in procs.items() if "TPU" in (n or "")}
    module_us, module_count = 0.0, 0
    ops: dict[str, dict] = {}
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        lane = lanes.get((e["pid"], e["tid"]), "")
        if lane == "XLA Modules":
            module_us += e.get("dur", 0.0)
            module_count += 1
        elif lane == "XLA Ops":
            a = e.get("args", {})
            rec = ops.setdefault(e["name"], {
                "us": 0.0, "count": 0, "category": "", "long_name": "",
                "bytes": 0})
            rec["us"] += e.get("dur", 0.0)
            rec["count"] += 1
            rec["category"] = a.get("hlo_category", rec["category"])
            rec["long_name"] = a.get("long_name", rec["long_name"])
            rec["bytes"] += int(a.get("bytes_accessed", 0) or 0)
    return {"module_us": module_us, "module_count": module_count,
            "ops": ops}


def named_scope(name: str):
    """Label pipeline stages inside jitted code (jax.named_scope; jax
    imported lazily so host-only Frame pipelines — which report into
    this module every map_batches call — never pay the jax import)."""
    import jax

    return jax.named_scope(name)


class PipelineReport:
    """Per-stage wall time + gauges for ONE ``Frame.map_batches`` run.

    The stage-time model (PIPELINE.md has the reading guide):

    - ``prepare``: worker-thread seconds in decode/pack (summed across
      the prepare pool — N workers can make this exceed wall time);
    - ``h2d``: the explicit shard + host→device transfer inside prepare
      (mesh path only; on the mesh=None tunnel path the transfer rides
      the dispatch, see map_batches);
    - ``dispatch``: consumer-thread seconds in ``fn(...)`` — enqueue
      only for async device fns, enqueue+compute for host fns;
    - ``d2h``: device→host fetch time (windowed drain + the acc-mode
      final fetch);
    - ``infeed_wait``: consumer seconds blocked on the infeed queue —
      the UNHIDDEN remainder of prepare, and the numerator of
      ``overlap_efficiency``.

    Gauges (``gauge``) keep every sample; the report surfaces mean/max
    (``queue_depth`` is sampled at each consumer take: depth K means the
    pool is keeping the device fed). Thread-safe: prepare workers and
    the consumer thread write concurrently.
    """

    def __init__(self):
        self.stages: dict[str, float] = {}
        self.calls: dict[str, int] = {}
        self.gauges: dict[str, list] = {}
        self.wall_seconds = 0.0
        self.config: dict = {}
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def stage(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def add(self, name: str, seconds: float):
        with self._lock:
            self.stages[name] = self.stages.get(name, 0.0) + seconds
            self.calls[name] = self.calls.get(name, 0) + 1

    def count(self, name: str, k: int = 1):
        with self._lock:
            self.calls[name] = self.calls.get(name, 0) + k

    def gauge(self, name: str, value):
        with self._lock:
            self.gauges.setdefault(name, []).append(value)

    def overlap_efficiency(self) -> float | None:
        """Fraction of host prepare work hidden under device compute:
        1 - infeed_wait/prepare, clamped to [0, 1]. 1.0 = the consumer
        never waited (prepare fully overlapped); 0.0 = fully serial.
        None when nothing was prepared (empty frame / no prefetch)."""
        prep = self.stages.get("prepare", 0.0)
        if prep <= 0.0:
            return None
        wait = self.stages.get("infeed_wait", 0.0)
        return max(0.0, min(1.0, 1.0 - wait / prep))

    def report(self) -> dict:
        with self._lock:
            out = {
                "wall_seconds": round(self.wall_seconds, 4),
                "stage_seconds": {k: round(v, 4)
                                  for k, v in sorted(self.stages.items())},
                "stage_calls": dict(sorted(self.calls.items())),
            }
            for name, vals in sorted(self.gauges.items()):
                out[f"{name}_mean"] = round(sum(vals) / len(vals), 2)
                out[f"{name}_max"] = max(vals)
            out.update(self.config)
        eff = self.overlap_efficiency()
        if eff is not None:
            out["overlap_efficiency"] = round(eff, 3)
        return out


_LAST_PIPELINE: PipelineReport | None = None


def set_last_pipeline(report: PipelineReport | None):
    """Filed by ``Frame.map_batches`` at the start of every run, so the
    caller above any transformer stack (bench.py, a notebook) can read
    the executor's stage breakdown without threading a handle through
    the transformer APIs."""
    global _LAST_PIPELINE
    _LAST_PIPELINE = report


def last_pipeline_report() -> dict | None:
    """Stage breakdown of the most recent map_batches run (or None)."""
    return _LAST_PIPELINE.report() if _LAST_PIPELINE is not None else None


class Meter:
    """Throughput/latency meter for the executor hot loop.

    ``with meter.batch(n):`` around each device call; ``meter.report()``
    yields {examples, seconds, examples_per_sec, examples_per_sec_per_chip}.
    Warmup batches (compile) can be excluded via ``skip`` — report both
    cold and warm numbers, never silently drop the compile cost.
    """

    def __init__(self, n_chips: int = 1, skip: int = 0):
        self.n_chips = max(1, int(n_chips))
        self.skip = int(skip)
        self._batches: list[tuple[int, float]] = []

    @contextlib.contextmanager
    def batch(self, n_examples: int):
        t0 = time.perf_counter()
        yield
        self._batches.append((int(n_examples), time.perf_counter() - t0))

    def report(self) -> dict:
        counted = self._batches[self.skip:]
        ex = sum(n for n, _ in counted)
        secs = sum(t for _, t in counted)
        all_ex = sum(n for n, _ in self._batches)
        all_secs = sum(t for _, t in self._batches)
        eps = ex / secs if secs > 0 else 0.0
        return {
            "examples": ex,
            "seconds": round(secs, 4),
            "examples_per_sec": round(eps, 2),
            "examples_per_sec_per_chip": round(eps / self.n_chips, 2),
            "cold_examples_per_sec": round(all_ex / all_secs, 2)
            if all_secs > 0 else 0.0,
            "batches": len(self._batches),
        }

    def json_line(self, metric: str, baseline: float | None = None,
                  extra: dict | None = None) -> str:
        r = self.report()
        value = r["examples_per_sec_per_chip"]
        out = {
            "metric": metric,
            "value": value,
            "unit": "images/sec/chip",
            "vs_baseline": round(value / baseline, 3) if baseline else None,
        }
        if extra:
            out.update(extra)
        return json.dumps(out)
