"""Observability: profiling hooks + step metrics.

SURVEY.md §5.1/§5.5: the reference has NO first-party tracing or metrics
(observability was inherited from the Spark UI). This layer is the cheap
real win the survey calls for: jax.profiler traces, named scopes around
the pipeline stages (decode/infeed/apply show up as labeled spans in the
trace viewer), and a throughput meter that computes the judged metric
(images/sec/chip) inside the framework itself.
"""

from __future__ import annotations

import contextlib
import json
import time

import jax

__all__ = ["profile", "named_scope", "Meter"]


@contextlib.contextmanager
def profile(log_dir: str):
    """Capture a jax.profiler trace for the enclosed block; view with
    tensorboard-plugin-profile or xprof against ``log_dir``."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


named_scope = jax.named_scope  # label pipeline stages inside jitted code


class Meter:
    """Throughput/latency meter for the executor hot loop.

    ``with meter.batch(n):`` around each device call; ``meter.report()``
    yields {examples, seconds, examples_per_sec, examples_per_sec_per_chip}.
    Warmup batches (compile) can be excluded via ``skip`` — report both
    cold and warm numbers, never silently drop the compile cost.
    """

    def __init__(self, n_chips: int = 1, skip: int = 0):
        self.n_chips = max(1, int(n_chips))
        self.skip = int(skip)
        self._batches: list[tuple[int, float]] = []

    @contextlib.contextmanager
    def batch(self, n_examples: int):
        t0 = time.perf_counter()
        yield
        self._batches.append((int(n_examples), time.perf_counter() - t0))

    def report(self) -> dict:
        counted = self._batches[self.skip:]
        ex = sum(n for n, _ in counted)
        secs = sum(t for _, t in counted)
        all_ex = sum(n for n, _ in self._batches)
        all_secs = sum(t for _, t in self._batches)
        eps = ex / secs if secs > 0 else 0.0
        return {
            "examples": ex,
            "seconds": round(secs, 4),
            "examples_per_sec": round(eps, 2),
            "examples_per_sec_per_chip": round(eps / self.n_chips, 2),
            "cold_examples_per_sec": round(all_ex / all_secs, 2)
            if all_secs > 0 else 0.0,
            "batches": len(self._batches),
        }

    def json_line(self, metric: str, baseline: float | None = None,
                  extra: dict | None = None) -> str:
        r = self.report()
        value = r["examples_per_sec_per_chip"]
        out = {
            "metric": metric,
            "value": value,
            "unit": "images/sec/chip",
            "vs_baseline": round(value / baseline, 3) if baseline else None,
        }
        if extra:
            out.update(extra)
        return json.dumps(out)
