"""Observability: profiling hooks + step metrics.

SURVEY.md §5.1/§5.5: the reference has NO first-party tracing or metrics
(observability was inherited from the Spark UI). This layer is the cheap
real win the survey calls for: jax.profiler traces, named scopes around
the pipeline stages (decode/infeed/apply show up as labeled spans in the
trace viewer), and a throughput meter that computes the judged metric
(images/sec/chip) inside the framework itself.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import time

import jax

__all__ = ["profile", "named_scope", "Meter", "load_trace_events",
           "summarize_device_trace"]


@contextlib.contextmanager
def profile(log_dir: str):
    """Capture a jax.profiler trace for the enclosed block; view with
    tensorboard-plugin-profile or xprof against ``log_dir``, or parse
    programmatically with :func:`load_trace_events` +
    :func:`summarize_device_trace`."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def load_trace_events(trace_dir: str) -> list[dict]:
    """Events from the newest trace-viewer JSON under ``trace_dir``
    (written by :func:`profile`; works for tunneled backends too — the
    PJRT plugin populates real device lanes)."""
    paths = glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                      recursive=True)
    if not paths:
        raise FileNotFoundError(f"no trace.json.gz under {trace_dir}")
    with gzip.open(max(paths, key=os.path.getmtime)) as f:
        tr = json.load(f)
    return tr["traceEvents"] if isinstance(tr, dict) else tr


def summarize_device_trace(events: list[dict]) -> dict:
    """Aggregate DEVICE-side time from a trace-viewer event list.

    Returns ``{"module_us": total_us_across_XLA-Module_executions,
    "module_count": n, "ops": {name: {us, count, category, long_name,
    bytes}}}``. The "XLA Modules" lane is the compiled program's
    on-device wall time — the honest chip-side throughput denominator,
    independent of host/tunnel dispatch latency; the "XLA Ops" lane is
    the per-fusion attribution (SURVEY.md §5.1). Empty summary (count 0)
    when the trace has no TPU lanes (CPU backend)."""
    procs, lanes = {}, {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            procs[e["pid"]] = e["args"].get("name", "")
        elif e.get("name") == "thread_name":
            lanes[(e["pid"], e["tid"])] = e["args"].get("name", "")
    device_pids = {p for p, n in procs.items() if "TPU" in (n or "")}
    module_us, module_count = 0.0, 0
    ops: dict[str, dict] = {}
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        lane = lanes.get((e["pid"], e["tid"]), "")
        if lane == "XLA Modules":
            module_us += e.get("dur", 0.0)
            module_count += 1
        elif lane == "XLA Ops":
            a = e.get("args", {})
            rec = ops.setdefault(e["name"], {
                "us": 0.0, "count": 0, "category": "", "long_name": "",
                "bytes": 0})
            rec["us"] += e.get("dur", 0.0)
            rec["count"] += 1
            rec["category"] = a.get("hlo_category", rec["category"])
            rec["long_name"] = a.get("long_name", rec["long_name"])
            rec["bytes"] += int(a.get("bytes_accessed", 0) or 0)
    return {"module_us": module_us, "module_count": module_count,
            "ops": ops}


named_scope = jax.named_scope  # label pipeline stages inside jitted code


class Meter:
    """Throughput/latency meter for the executor hot loop.

    ``with meter.batch(n):`` around each device call; ``meter.report()``
    yields {examples, seconds, examples_per_sec, examples_per_sec_per_chip}.
    Warmup batches (compile) can be excluded via ``skip`` — report both
    cold and warm numbers, never silently drop the compile cost.
    """

    def __init__(self, n_chips: int = 1, skip: int = 0):
        self.n_chips = max(1, int(n_chips))
        self.skip = int(skip)
        self._batches: list[tuple[int, float]] = []

    @contextlib.contextmanager
    def batch(self, n_examples: int):
        t0 = time.perf_counter()
        yield
        self._batches.append((int(n_examples), time.perf_counter() - t0))

    def report(self) -> dict:
        counted = self._batches[self.skip:]
        ex = sum(n for n, _ in counted)
        secs = sum(t for _, t in counted)
        all_ex = sum(n for n, _ in self._batches)
        all_secs = sum(t for _, t in self._batches)
        eps = ex / secs if secs > 0 else 0.0
        return {
            "examples": ex,
            "seconds": round(secs, 4),
            "examples_per_sec": round(eps, 2),
            "examples_per_sec_per_chip": round(eps / self.n_chips, 2),
            "cold_examples_per_sec": round(all_ex / all_secs, 2)
            if all_secs > 0 else 0.0,
            "batches": len(self._batches),
        }

    def json_line(self, metric: str, baseline: float | None = None,
                  extra: dict | None = None) -> str:
        r = self.report()
        value = r["examples_per_sec_per_chip"]
        out = {
            "metric": metric,
            "value": value,
            "unit": "images/sec/chip",
            "vs_baseline": round(value / baseline, 3) if baseline else None,
        }
        if extra:
            out.update(extra)
        return json.dumps(out)
