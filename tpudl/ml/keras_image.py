"""KerasImageFileTransformer — a Keras model over image *files*.

Rebuild of ref: python/sparkdl/transformers/keras_image.py (~L30):
params ``inputCol`` (URI column), ``outputCol``, ``modelFile``,
``imageLoader`` (user callable URI → ndarray), ``outputMode``. The
reference freezes the Keras model and delegates to TFImageTransformer;
here the model is ingested once (TFInputGraph.fromKeras → jax fn) and
URIs are loaded *per batch* inside the Frame executor's pack stage, so
host decode overlaps device compute batch-to-batch.
"""

from __future__ import annotations

import os

import numpy as np

from tpudl.image import imageIO
from tpudl.ml.image_params import CanLoadImage
from tpudl.ml.params import (HasInputCol, HasKerasModel, HasOutputCol,
                             HasOutputMode, keyword_only)
from tpudl.ml.pipeline import Transformer

__all__ = ["KerasImageFileTransformer"]


class KerasImageFileTransformer(Transformer, HasInputCol, HasOutputCol,
                                HasKerasModel, HasOutputMode, CanLoadImage):
    @keyword_only
    def __init__(self, *, inputCol=None, outputCol=None, modelFile=None,
                 imageLoader=None, outputMode="vector", batchSize=64,
                 mesh=None, prefetchDepth=None, prepareWorkers=None,
                 fuseSteps=None, dispatchDepth=None, wireCodec=None,
                 cacheDir=None, deviceCache=None):
        super().__init__()
        self._setDefault(outputMode="vector")
        self.batchSize = int(batchSize)
        self.mesh = mesh
        kwargs = dict(self._input_kwargs)
        kwargs.pop("batchSize", None)
        kwargs.pop("mesh", None)
        self._set_pipeline_opts(kwargs)
        self._set(**kwargs)

    def _transform(self, frame):
        mode = self.getOutputMode()
        loader = self.getImageLoader()
        model_file = self.getModelFile()

        def pack(sl: np.ndarray) -> np.ndarray:
            from tpudl.ml.image_params import load_uri_batch

            return load_uri_batch(loader, sl)

        # the pack's cache identity IS the loader's (geometry, scale,
        # dtype): a different loader over the same URI column must
        # re-key the shard cache, not replay stale decodes
        from tpudl.data.dataset import _loader_token

        pack.cache_token = "uri_pack:" + _loader_token(loader)

        # concurrency is strictly opt-in (the LazyFileColumn contract):
        # only a loader that DECLARES itself thread-safe lets the
        # prepare pool parallelize this pack — createNativeImageLoader
        # is marked; custom loaders (batch_decode or per-URI) keep the
        # safe single-worker default unless marked or prepareWorkers is
        # set explicitly
        pack.thread_safe = bool(getattr(loader, "thread_safe", False))

        def build():
            from tpudl.ingest import TFInputGraph

            model_fn = TFInputGraph.fromKeras(model_file).make_fn()

            def fn(batch):
                y = model_fn(batch)
                if isinstance(y, tuple):
                    y = y[0]
                if mode == "vector":
                    return y.reshape(y.shape[0], -1)
                return y

            return fn

        out_col = self.getOutputCol()
        jfn = self._cached_jit(
            (model_file, os.path.getmtime(model_file), mode), build)
        opts = self._pipeline_opts()
        if getattr(loader, "output_dtype", None) == "uint8":
            # a raw-uint8 loader DEFERS its `* scale` normalize to the
            # device: the u8 codec's fused prologue is what applies it,
            # so it installs by default (DATA.md) — without it the
            # model would see un-normalized pixels. An explicit
            # wireCodec that cannot carry the normalize (identity,
            # bf16, bare 'u8'/'auto' which would infer scale=1) is a
            # misconfiguration that must not silently feed the model
            # 255x-too-large pixels; an explicit U8Codec INSTANCE is
            # the user owning the scale.
            from tpudl.data import U8Codec

            if opts.get("wire_codec") is None:
                opts["wire_codec"] = U8Codec(
                    scale=getattr(loader, "wire_scale", 1.0),
                    offset=getattr(loader, "wire_offset", 0.0))
            elif not isinstance(opts["wire_codec"], U8Codec):
                raise ValueError(
                    f"imageLoader defers its normalize (output_dtype="
                    f"'uint8', wire_scale={getattr(loader, 'wire_scale', 1.0)!r}) "
                    f"but wireCodec={opts['wire_codec']!r} would skip it; "
                    "drop wireCodec (the matching u8 codec installs "
                    "automatically) or pass U8Codec(scale=...) explicitly")
        if opts.get("cache_dir") or os.environ.get("TPUDL_DATA_CACHE_DIR"):
            # URI columns name files the frame fingerprint cannot see
            # into; key the cache on path+size+mtime so a rewritten
            # image re-decodes instead of replaying stale pixels
            from tpudl.data.dataset import _uri_fingerprint

            opts["cache_key"] = _uri_fingerprint(
                frame[self.getInputCol()])
        out = frame.map_batches(
            jfn, [self.getInputCol()], [out_col],
            batch_size=self.batchSize, pack=pack, **opts)
        if mode == "image":
            structs = [
                imageIO.imageArrayToStruct(np.asarray(a, dtype=np.float32))
                for a in out[out_col]
            ]
            out = out.drop(out_col).with_column(out_col, structs)
        return out
