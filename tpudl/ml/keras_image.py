"""KerasImageFileTransformer — a Keras model over image *files*.

Rebuild of ref: python/sparkdl/transformers/keras_image.py (~L30):
params ``inputCol`` (URI column), ``outputCol``, ``modelFile``,
``imageLoader`` (user callable URI → ndarray), ``outputMode``. The
reference freezes the Keras model and delegates to TFImageTransformer;
here the model is ingested once (TFInputGraph.fromKeras → jax fn) and
URIs are loaded *per batch* inside the Frame executor's pack stage, so
host decode overlaps device compute batch-to-batch.
"""

from __future__ import annotations

import os

import numpy as np

from tpudl.image import imageIO
from tpudl.ml.image_params import CanLoadImage
from tpudl.ml.params import (HasInputCol, HasKerasModel, HasOutputCol,
                             HasOutputMode, keyword_only)
from tpudl.ml.pipeline import Transformer

__all__ = ["KerasImageFileTransformer"]


class KerasImageFileTransformer(Transformer, HasInputCol, HasOutputCol,
                                HasKerasModel, HasOutputMode, CanLoadImage):
    @keyword_only
    def __init__(self, *, inputCol=None, outputCol=None, modelFile=None,
                 imageLoader=None, outputMode="vector", batchSize=64,
                 mesh=None, prefetchDepth=None, prepareWorkers=None,
                 fuseSteps=None):
        super().__init__()
        self._setDefault(outputMode="vector")
        self.batchSize = int(batchSize)
        self.mesh = mesh
        kwargs = dict(self._input_kwargs)
        kwargs.pop("batchSize", None)
        kwargs.pop("mesh", None)
        self._set_pipeline_opts(kwargs)
        self._set(**kwargs)

    def _transform(self, frame):
        mode = self.getOutputMode()
        loader = self.getImageLoader()
        model_file = self.getModelFile()

        def pack(sl: np.ndarray) -> np.ndarray:
            from tpudl.ml.image_params import load_uri_batch

            return load_uri_batch(loader, sl)

        # concurrency is strictly opt-in (the LazyFileColumn contract):
        # only a loader that DECLARES itself thread-safe lets the
        # prepare pool parallelize this pack — createNativeImageLoader
        # is marked; custom loaders (batch_decode or per-URI) keep the
        # safe single-worker default unless marked or prepareWorkers is
        # set explicitly
        pack.thread_safe = bool(getattr(loader, "thread_safe", False))

        def build():
            from tpudl.ingest import TFInputGraph

            model_fn = TFInputGraph.fromKeras(model_file).make_fn()

            def fn(batch):
                y = model_fn(batch)
                if isinstance(y, tuple):
                    y = y[0]
                if mode == "vector":
                    return y.reshape(y.shape[0], -1)
                return y

            return fn

        out_col = self.getOutputCol()
        jfn = self._cached_jit(
            (model_file, os.path.getmtime(model_file), mode), build)
        out = frame.map_batches(
            jfn, [self.getInputCol()], [out_col],
            batch_size=self.batchSize, mesh=self.mesh, pack=pack,
            **self._pipeline_opts())
        if mode == "image":
            structs = [
                imageIO.imageArrayToStruct(np.asarray(a, dtype=np.float32))
                for a in out[out_col]
            ]
            out = out.drop(out_col).with_column(out_col, structs)
        return out
