"""TFImageTransformer — arbitrary model graph over an image-struct column.

Rebuild of ref: python/sparkdl/transformers/tf_image.py (~L50 class,
~L120 _transform). The reference splices [spImageConverter → user graph →
flattener] into one frozen GraphDef executed per block by TensorFrames;
here the same composition is [sp_image_converter → ingested jax fn →
flatten/restruct] traced into ONE jit program, executed per batch by
``Frame.map_batches`` with mesh data-parallel sharding (SURVEY.md §3.2's
one-native-call-per-block invariant, now one-XLA-program-per-batch).
"""

from __future__ import annotations

import numpy as np

import jax

from tpudl.image import imageIO
from tpudl.image import ops as image_ops
from tpudl.ml.params import (HasInputCol, HasOutputCol, HasOutputMode, Param,
                             TypeConverters, keyword_only)
from tpudl.ml.pipeline import Transformer

__all__ = ["TFImageTransformer"]

OUTPUT_MODES = ("vector", "image")


class ImageBatchWarmup:
    """Mixin: no-fetch warm path for image-batch transformers.

    Requires ``_get_jfn()`` (the fused jitted program), ``batchSize``
    and ``mesh`` on the host class.
    """

    def warmup(self, height, width, nChannels=3, dtype=np.uint8):
        """Compile and warm the fused program for (height, width,
        nChannels) input images WITHOUT any device→host read.

        On tunneled/remote PJRT backends the process's FIRST device→host
        fetch permanently switches the channel from pipelined streaming
        to per-transfer synchronization (BASELINE.md "two transfer
        modes"). Warming up by running ``transform`` ends with exactly
        such a fetch. This method instead executes the program once on a
        synthetic batch and discards the device result unread —
        executions do not trigger the mode switch — so a fresh process
        that calls ``warmup(...)`` and then ``transform(frame)`` keeps
        every upload pipelined until the transform's single final fetch.

        Call with the shape of the frame's images (pre-resize where the
        on-device pipeline resizes: the traced signature is the *input*
        shape). Only the full-batch signature is warmed; a ragged tail
        batch compiles during the transform (compiles don't fetch, so
        streaming mode survives that too). Returns ``self``.

        With the AOT program store armed (``TPUDL_COMPILE_AOT``,
        COMPILE.md) this becomes a pure AOT warm call: the program is
        ``lower().compile()``-d from declared abstract shapes — no
        synthetic batch, no real-data trace, no device execution at
        all — and lands in the store, so the NEXT process restores it
        serialized and skips even this compile.
        """
        import os as _os

        from tpudl.frame import frame as _frame

        jfn = self._get_jfn()
        x = np.zeros((self.batchSize, height, width, nChannels),
                     dtype=dtype)
        mesh = self.mesh
        fuse = getattr(self, "fuseSteps", None)
        if fuse is None:
            fuse = _frame._env_int("TPUDL_FRAME_FUSE_STEPS", 1)
        warm_fused = (int(fuse) > 1
                      and _frame.mesh_fuse_ok(self.batchSize, mesh)
                      and _os.environ.get("TPUDL_FRAME_PREFETCH", "1")
                      != "0")
        # match the executor's donation setting, or this warms a
        # program variant the timed window never runs
        donate = _os.environ.get("TPUDL_FRAME_DONATE", "1") != "0"
        from tpudl import compile as _compile

        if _compile.aot_enabled():
            # AOT warm call (ISSUE 15): declared-signature compile
            # through the program store — the executor's dispatch hits
            # these exact keys, and the serialized executables make the
            # next process's warmup a deserialization
            store = _compile.get_program_store()
            store.ensure_restored(block=True)
            # mirror the executor's bucket pick EXACTLY: with a ladder
            # armed the dispatch shape is the rung (mesh: rounded up to
            # the data axis), and a non-rung batchSize drops fusion —
            # warming the raw batchSize would compile a program the
            # timed window never runs
            ladder = _compile.resolve_ladder(None)
            rows = int(self.batchSize)
            if ladder is not None:
                rows = ladder.pick(rows)
                if rows != int(self.batchSize):
                    warm_fused = False
            if mesh is not None:
                from tpudl import mesh as M

                axis = mesh.shape[M.DATA_AXIS]
                pad_shape = ((-(-rows // axis)) * axis,) + x.shape[1:]
                aval = jax.ShapeDtypeStruct(
                    pad_shape, dtype,
                    sharding=M.batch_sharding(mesh,
                                              ndim=len(pad_shape)))
            else:
                aval = jax.ShapeDtypeStruct((rows,) + x.shape[1:],
                                            dtype)
            store.compile_signature(
                jfn, [aval], donate=False,
                bucketed=(ladder is not None and mesh is None))
            if warm_fused:
                fused = _frame._fused_wrapper(jfn, int(fuse), n_args=1,
                                              donate=donate)
                stacked_shape = (int(fuse),) + tuple(aval.shape)
                if mesh is not None:
                    sds = jax.ShapeDtypeStruct(
                        stacked_shape, dtype,
                        sharding=M.stacked_batch_sharding(
                            mesh, ndim=len(stacked_shape)))
                else:
                    sds = jax.ShapeDtypeStruct(stacked_shape, dtype)
                store.compile_signature(fused, [sds], donate=donate)
            return self
        if mesh is not None:
            from tpudl import mesh as M

            x_pad, _ = M.pad_batch(x, mesh.shape[M.DATA_AXIS])
            warm_in = M.transfer_batch([x_pad], mesh)[0]
        else:
            warm_in = x
        jax.block_until_ready(jfn(warm_in))  # compile+execute; unfetched
        # the executor will run the FUSED multi-step program when
        # fuse_steps > 1 — warm that compile too (compiles don't
        # fetch, and a mid-transform compile would land inside the
        # timed window). The mesh path fuses only when the batch
        # shards evenly and the fast path is armed (map_batches'
        # own rule) — warm exactly the variant it will run.
        if warm_fused:
            fused = _frame._fused_wrapper(jfn, int(fuse), n_args=1,
                                          donate=donate)
            xs = np.zeros((int(fuse),) + x.shape, dtype=dtype)
            if mesh is not None:
                xs = M.transfer_batch([xs], mesh, batch_dim=1)[0]
            jax.block_until_ready(fused(xs))
        return self


class TFImageTransformer(ImageBatchWarmup, Transformer, HasInputCol,
                         HasOutputCol, HasOutputMode):
    """Applies a model function to an image column.

    Params (ref spelling kept: tf_image.py ~L50):

    - ``graph``: a ``TFInputGraph`` (ingested TF artifact) **or** any
      jax-traceable callable batch(B,H,W,C) float32 → array.
    - ``inputTensor``/``outputTensor``: tensor names when ``graph`` is a
      multi-tensor ``TFInputGraph``; default its declared input/output.
    - ``channelOrder``: channel order the model expects — 'RGB'
      (keras-style), 'BGR' (caffe-style), 'L' (ref: v1.x channelOrder).
    - ``outputMode``: 'vector' (flattened float vector per row) or
      'image' (restructured image struct column).
    """

    graph = Param(None, "graph", "TFInputGraph or jax-callable model")
    inputTensor = Param(None, "inputTensor", "input tensor name",
                        TypeConverters.toString)
    outputTensor = Param(None, "outputTensor", "output tensor name",
                         TypeConverters.toString)
    channelOrder = Param(None, "channelOrder",
                         "channel order the model expects: RGB, BGR or L",
                         TypeConverters.toChannelOrder)

    @keyword_only
    def __init__(self, *, inputCol=None, outputCol=None, graph=None,
                 inputTensor=None, outputTensor=None, channelOrder="RGB",
                 outputMode="vector", batchSize=64, mesh=None,
                 prefetchDepth=None, prepareWorkers=None, fuseSteps=None,
                 dispatchDepth=None, wireCodec=None, cacheDir=None,
                 deviceCache=None):
        super().__init__()
        self._setDefault(channelOrder="RGB", outputMode="vector")
        self.batchSize = int(batchSize)
        self.mesh = mesh
        kwargs = dict(self._input_kwargs)
        kwargs.pop("batchSize", None)
        kwargs.pop("mesh", None)
        self._set_pipeline_opts(kwargs)
        self.setParams(**kwargs)

    def setParams(self, **kwargs):
        return self._set(**kwargs)

    # -- model-fn assembly -------------------------------------------------
    def _model_fn(self):
        g = self.getOrDefault(self.graph)
        from tpudl.ingest import TFInputGraph

        if isinstance(g, TFInputGraph):
            feeds = [self.getOrDefault(self.inputTensor)] if self.isDefined(
                self.inputTensor) and self.isSet(self.inputTensor) else None
            fetches = [self.getOrDefault(self.outputTensor)] if self.isDefined(
                self.outputTensor) and self.isSet(self.outputTensor) else None
            fn = g.make_fn(feeds, fetches)
            if g.trainable:
                params = g.params
                return lambda x: fn(params, x)
            return fn
        if callable(g):
            return g
        raise TypeError(
            f"graph param must be TFInputGraph or callable, got {type(g).__name__}")

    def _get_jfn(self):
        order = self.getOrDefault(self.channelOrder)
        mode = self.getOutputMode()

        def build():
            model = self._model_fn()

            def fn(batch):
                # fused prologue + model + epilogue: one XLA program
                x = image_ops.sp_image_converter(batch, "BGR", order) \
                    if order != "L" else batch.astype(np.float32)
                y = model(x)
                if isinstance(y, tuple):
                    y = y[0]
                return image_ops.flattener(y) if mode == "vector" else y

            return fn

        return self._cached_jit(
            (self.getOrDefault(self.graph),
             self._paramMap.get(self.inputTensor),
             self._paramMap.get(self.outputTensor), order, mode), build)

    def _transform(self, frame):
        in_col = self.getInputCol()
        out_col = self.getOutputCol()
        mode = self.getOutputMode()
        jfn = self._get_jfn()
        out = frame.map_batches(
            jfn, [in_col], [out_col], batch_size=self.batchSize,
            pack=_pack_image_structs, **self._pipeline_opts())
        if mode == "image":
            structs = [
                imageIO.imageArrayToStruct(np.asarray(a, dtype=np.float32))
                for a in out[out_col]
            ]
            out = out.drop(out_col).with_column(out_col, structs)
        return out


def _pack_image_structs(sl: np.ndarray) -> np.ndarray:
    """image-struct column slice → stacked (B, H, W, C) batch.

    The host-side half of the reference's spImageConverter (bytes→tensor);
    the device-side cast/flip lives in image_ops so it fuses into the jit.
    """
    arrays = []
    for row in sl:
        if row is None:
            raise ValueError("null image row; dropna() the frame first")
        if isinstance(row, dict):
            arrays.append(imageIO.imageStructToArray(row, copy=False))
        else:
            arrays.append(np.asarray(row))
    shapes = {a.shape for a in arrays}
    if len(shapes) > 1:
        raise ValueError(
            f"mixed image shapes {sorted(shapes)} in one column; resize "
            "first (imageIO.resizeImage / createResizeImageUDF)")
    return np.stack(arrays)


# pure function of its slice: the executor's prepare pool may run it for
# different batches concurrently (map_batches checks this marker)
_pack_image_structs.thread_safe = True
# stable cache identity: prepared bytes depend only on the struct
# contents, which the frame fingerprint already covers
_pack_image_structs.cache_token = "image_structs_v1"
