"""KerasTransformer — a saved Keras model over a 1-D tensor column.

Rebuild of ref: python/sparkdl/transformers/keras_tensor.py (~L25):
params ``modelFile`` (.keras/.h5), ``inputCol`` (array column),
``outputCol``. Loads the model once on the host, ingests it to a jax fn
(TFInputGraph.fromKeras), and delegates execution to the TFTransformer
path — mirroring the reference's load→GraphFunction→TFTransformer
delegation chain.
"""

from __future__ import annotations

from tpudl.ml.params import (HasInputCol, HasKerasModel, HasOutputCol,
                             keyword_only)
from tpudl.ml.pipeline import Transformer

__all__ = ["KerasTransformer"]


class KerasTransformer(Transformer, HasInputCol, HasOutputCol,
                       HasKerasModel):
    @keyword_only
    def __init__(self, *, inputCol=None, outputCol=None, modelFile=None,
                 batchSize=256, mesh=None, prefetchDepth=None,
                 prepareWorkers=None, fuseSteps=None,
                 dispatchDepth=None):
        super().__init__()
        self.batchSize = int(batchSize)
        self.mesh = mesh
        kwargs = dict(self._input_kwargs)
        kwargs.pop("batchSize", None)
        kwargs.pop("mesh", None)
        self._set_pipeline_opts(kwargs)
        self._set(**kwargs)

    def _transform(self, frame):
        from tpudl.ingest import TFInputGraph
        from tpudl.ml.tf_tensor import TFTransformer

        gin = TFInputGraph.fromKeras(self.getModelFile())
        if len(gin.input_names) != 1 or len(gin.output_names) != 1:
            raise ValueError(
                f"KerasTransformer requires a single-input single-output "
                f"model; got {gin.input_names} -> {gin.output_names}")
        delegate = TFTransformer(
            tfInputGraph=gin,
            inputMapping={self.getInputCol(): gin.input_names[0]},
            outputMapping={gin.output_names[0]: self.getOutputCol()},
            batchSize=self.batchSize, mesh=self.mesh,
            prefetchDepth=self.prefetchDepth,
            prepareWorkers=self.prepareWorkers, fuseSteps=self.fuseSteps,
            dispatchDepth=self.dispatchDepth)
        return delegate.transform(frame)
