"""ML Pipeline API layer — the product surface (SURVEY.md §2.1 L5).

Same public spellings as the reference's ``sparkdl`` package so a
spark-deep-learning user finds every Transformer/Estimator under the
name they know, running as fused XLA programs over the mesh.
"""

from tpudl.ml.classification import (LogisticRegression,
                                     LogisticRegressionModel)
from tpudl.ml.estimator import KerasImageFileEstimator
from tpudl.ml.keras_image import KerasImageFileTransformer
from tpudl.ml.keras_tensor import KerasTransformer
from tpudl.ml.lm import LMClassifier, LMFeaturizer, LMGenerator
from tpudl.ml.named_image import DeepImageFeaturizer, DeepImagePredictor
from tpudl.ml.params import Param, Params, TypeConverters
from tpudl.ml.pipeline import (Estimator, Model, Pipeline, PipelineModel,
                               Transformer)
from tpudl.ml.tf_image import TFImageTransformer
from tpudl.ml.tf_tensor import TFTransformer
from tpudl.ml.tuning import (CrossValidator, CrossValidatorModel, Evaluator,
                             FunctionEvaluator, ParamGridBuilder)

__all__ = [
    "DeepImageFeaturizer",
    "DeepImagePredictor",
    "TFImageTransformer",
    "TFTransformer",
    "KerasTransformer",
    "KerasImageFileTransformer",
    "KerasImageFileEstimator",
    "LMFeaturizer",
    "LMGenerator",
    "LMClassifier",
    "LogisticRegression",
    "LogisticRegressionModel",
    "Transformer",
    "Estimator",
    "Model",
    "Pipeline",
    "PipelineModel",
    "Param",
    "Params",
    "TypeConverters",
    "ParamGridBuilder",
    "CrossValidator",
    "CrossValidatorModel",
    "Evaluator",
    "FunctionEvaluator",
]
