"""LM transformers over STRING columns — the text face of the ml API.

The paper's pitch applied to sequences (ROADMAP item 4): a language
model as a pipeline stage over a DataFrame column, with tokenization,
packing, wire coding, and compiled-program reuse all owned by the
framework. Three stages, same spellings a sparkdl user would guess from
DeepImageFeaturizer/DeepImagePredictor:

- :class:`LMFeaturizer` — string column → mean-pooled final-norm hidden
  states (the transfer-learning feature vector). Rides the FULL
  map_batches fast path: :func:`~tpudl.text.codec.tokenize_pack` on the
  prepare pool, :class:`~tpudl.text.codec.TokenCodec` ids on the wire,
  the pad-mask restore fused into the one compiled program.
- :class:`LMClassifier` — string column → label string, scored as the
  last-position logits gathered at the classes' leading token ids (the
  verbalizer pattern); same fast path, int32 on the wire both ways.
- :class:`LMGenerator` — string column → completion string. Generation
  is host-orchestrated (per-row output lengths), but every device call
  snaps to the PR-15 bucket ladders on BOTH axes — prompts pad to a
  sequence rung inside ``TinyCausalLM.generate`` (real length traced),
  chunks pad to a batch rung here (rows are independent in decode, so
  pad rows change nothing bitwise) — which is what the traceck-armed
  ragged sweep in tests/test_text.py proves: zero retraces across a
  ragged prompt mix after the rung programs are warm.

All three take ``model=`` (a :class:`~tpudl.zoo.transformer.TinyCausalLM`
or compatible), ``weights=`` (its param pytree — named to stay clear of
the ml Params machinery), and ``tokenizer=`` (a fingerprintable
:class:`~tpudl.text.tokenizer.Tokenizer`); they are Transformers, not
Estimators — training stays with tpudl.train (see examples/generate_text.py).
"""

from __future__ import annotations

import numpy as np

from tpudl.ml.params import HasInputCol, HasOutputCol, keyword_only
from tpudl.ml.pipeline import Transformer
from tpudl.obs import metrics as _obs_metrics
from tpudl.text.codec import TokenCodec, pad_mask, tokenize_pack
from tpudl.text.tokenizer import EOS_ID

__all__ = ["LMFeaturizer", "LMGenerator", "LMClassifier"]

_LM_ATTRS = ("model", "weights", "tokenizer", "maxLen", "maxNew",
             "temperature", "seed", "classes", "promptBuckets",
             "batchSize", "mesh", "tp")


class _LMStage(Transformer, HasInputCol, HasOutputCol):
    """Shared ctor plumbing: the LM trio's model/tokenizer/geometry are
    plain attributes (they parameterize the executor and the compiled
    programs, not the Param map — the batchSize/mesh precedent), and
    only inputCol/outputCol go through ``_set``."""

    def _init_lm(self):
        kwargs = dict(self._input_kwargs)
        for k in _LM_ATTRS:
            kwargs.pop(k, None)
        self._set_pipeline_opts(kwargs)
        self._set(**kwargs)

    def _require(self):
        missing = [k for k in ("model", "weights", "tokenizer")
                   if getattr(self, k, None) is None]
        if missing:
            raise ValueError(
                f"{type(self).__name__} needs {missing} — pass the "
                "TinyCausalLM (model=), its param pytree (weights=), and "
                "a tpudl.text Tokenizer (tokenizer=)")
        return self.model, self.weights, self.tokenizer

    def _hidden_mesh(self):
        """The mesh handed to the model's forward: only under ``tp``
        (heads/MLP sharded over the mesh's ``model`` axis, PR-16).
        Without tp, ``self.mesh`` still reaches ``map_batches`` for
        data-parallel batch sharding, but the forward stays dense —
        the ring/SP spelling is a training concern."""
        return self.mesh if self.tp else None

    def _codec_opts(self) -> dict:
        opts = self._pipeline_opts()
        if opts.get("wire_codec") is None:
            opts["wire_codec"] = TokenCodec(
                vocab_size=self.tokenizer.vocab_size)
        return opts


class LMFeaturizer(_LMStage):
    """String column → pooled hidden-state feature vectors [dim]."""

    @keyword_only
    def __init__(self, *, inputCol=None, outputCol=None, model=None,
                 weights=None, tokenizer=None, maxLen=None,
                 promptBuckets="pow2", batchSize=32, mesh=None,
                 tp=False, prefetchDepth=None, prepareWorkers=None,
                 fuseSteps=None, dispatchDepth=None, wireCodec=None,
                 cacheDir=None, deviceCache=None):
        super().__init__()
        self.model = model
        self.weights = weights
        self.tokenizer = tokenizer
        self.maxLen = maxLen
        self.promptBuckets = promptBuckets
        self.batchSize = int(batchSize)
        self.mesh = mesh
        self.tp = bool(tp)
        self._init_lm()

    def _transform(self, frame):
        import jax.numpy as jnp

        model, w, tok = self._require()
        pack = tokenize_pack(tok, seq_len=self.maxLen,
                             buckets=self.promptBuckets, bos=True)
        mesh, tp = self._hidden_mesh(), self.tp

        def build():
            def fn(tokens):
                mask = pad_mask(tokens)                    # [B, S]
                h = model.hidden(w, tokens, mesh=mesh, tp=tp)
                pooled = (h * mask[..., None]).sum(axis=1)
                return pooled / jnp.maximum(
                    mask.sum(axis=1, keepdims=True), 1.0)
            return fn

        jfn = self._cached_jit(
            (model.aot_token, id(w), "featurize", self.tp), build)
        out = frame.map_batches(
            jfn, [self.getInputCol()], [self.getOutputCol()],
            batch_size=self.batchSize, pack=pack, **self._codec_opts())
        _obs_metrics.counter("lm.embed.rows").inc(len(frame))
        return out


class LMClassifier(_LMStage):
    """String column → label string: last-real-position logits gathered
    at each class's LEADING token id (classes must therefore start with
    distinct tokens under the given tokenizer — checked loudly)."""

    @keyword_only
    def __init__(self, *, inputCol=None, outputCol=None, model=None,
                 weights=None, tokenizer=None, classes=None, maxLen=None,
                 promptBuckets="pow2", batchSize=32, mesh=None,
                 tp=False, prefetchDepth=None, prepareWorkers=None,
                 fuseSteps=None, dispatchDepth=None, wireCodec=None,
                 cacheDir=None, deviceCache=None):
        super().__init__()
        self.model = model
        self.weights = weights
        self.tokenizer = tokenizer
        self.classes = list(classes) if classes else None
        self.maxLen = maxLen
        self.promptBuckets = promptBuckets
        self.batchSize = int(batchSize)
        self.mesh = mesh
        self.tp = bool(tp)
        self._init_lm()

    def _class_ids(self, tok) -> list:
        if not self.classes:
            raise ValueError("LMClassifier needs classes=[...] (label "
                             "strings)")
        ids = []
        for c in self.classes:
            enc = tok.encode(c)
            if enc.size == 0:
                raise ValueError(f"class {c!r} tokenizes to nothing "
                                 f"under {tok!r}")
            ids.append(int(enc[0]))
        if len(set(ids)) != len(ids):
            raise ValueError(
                f"classes {self.classes} do not start with distinct "
                f"token ids under {tok!r} (leading ids {ids}); pick "
                "distinguishable label strings")
        return ids

    def _transform(self, frame):
        import jax.numpy as jnp

        model, w, tok = self._require()
        class_ids = self._class_ids(tok)
        pack = tokenize_pack(tok, seq_len=self.maxLen,
                             buckets=self.promptBuckets, bos=True)
        mesh, tp = self._hidden_mesh(), self.tp

        def build():
            def fn(tokens):
                mask = pad_mask(tokens)
                logits = model.apply(w, tokens, mesh=mesh, tp=tp)
                last = jnp.maximum(
                    mask.sum(axis=1).astype(jnp.int32) - 1, 0)
                row = jnp.take_along_axis(
                    logits, last[:, None, None], axis=1)[:, 0, :]
                cls = row[:, jnp.asarray(class_ids, jnp.int32)]
                return jnp.argmax(cls, axis=-1).astype(jnp.int32)
            return fn

        jfn = self._cached_jit(
            (model.aot_token, id(w), "classify", tuple(class_ids),
             self.tp), build)
        out_col = self.getOutputCol()
        out = frame.map_batches(
            jfn, [self.getInputCol()], [out_col],
            batch_size=self.batchSize, pack=pack, check_finite=False,
            **self._codec_opts())
        labels = np.array(self.classes, dtype=object)[
            np.asarray(out[out_col], dtype=np.int64)]
        _obs_metrics.counter("lm.classify.rows").inc(len(frame))
        return out.drop(out_col).with_column(out_col, list(labels))


class LMGenerator(_LMStage):
    """String column → generated completion string (decoded, cut at the
    first EOS). Host-orchestrated batching: rows group by EXACT prompt
    length (``generate``'s traced real length is one scalar per batch),
    chunks pad up to a batch-ladder rung, prompts pad to a sequence
    rung inside ``generate`` — O(log B · log S) compiled programs for
    any ragged workload."""

    @keyword_only
    def __init__(self, *, inputCol=None, outputCol=None, model=None,
                 weights=None, tokenizer=None, maxNew=16,
                 temperature=0.0, seed=0, promptBuckets="pow2",
                 batchSize=8, mesh=None, tp=False):
        super().__init__()
        self.model = model
        self.weights = weights
        self.tokenizer = tokenizer
        self.maxNew = int(maxNew)
        self.temperature = float(temperature)
        self.seed = int(seed)
        self.promptBuckets = promptBuckets
        self.batchSize = max(1, int(batchSize))
        self.mesh = mesh
        self.tp = bool(tp)
        self._init_lm()

    def _transform(self, frame):
        import jax

        from tpudl.compile import resolve_ladder

        model, w, tok = self._require()
        texts = list(frame[self.getInputCol()])
        # bos=True guarantees plen >= 1 (generate refuses an empty
        # prompt — the logits carry would never see the model)
        prompts = tok.encode_batch(texts, bos=True)
        ladder = resolve_ladder(
            self.promptBuckets if self.promptBuckets is not None
            else "pow2")
        groups: dict = {}
        for i, p in enumerate(prompts):
            groups.setdefault(len(p), []).append(i)
        key = (jax.random.PRNGKey(self.seed)
               if self.temperature > 0 else None)
        out_rows: list = [None] * len(texts)
        n_new = 0
        for plen in sorted(groups):
            idxs = groups[plen]
            for c0 in range(0, len(idxs), self.batchSize):
                chunk = idxs[c0:c0 + self.batchSize]
                arr = np.stack([prompts[i] for i in chunk])
                b = len(chunk)
                brung = (min(self.batchSize, max(b, ladder.pick(b)))
                         if ladder is not None else b)
                if brung > b:
                    # decode rows are independent (the per-row softmax
                    # never mixes rows), so repeated pad rows leave the
                    # real rows' tokens bitwise unchanged
                    arr = np.concatenate(
                        [arr, np.repeat(arr[:1], brung - b, axis=0)])
                rng = (jax.random.fold_in(key, plen * 8191 + c0)
                       if key is not None else None)
                toks = model.generate(
                    w, arr, self.maxNew, temperature=self.temperature,
                    rng=rng, prompt_buckets=ladder,
                    mesh=self._hidden_mesh(), tp=self.tp)
                toks = np.asarray(toks)[:b]
                for row, i in zip(toks, chunk):
                    stop = np.flatnonzero(row == EOS_ID)
                    if stop.size:
                        row = row[: stop[0]]
                    out_rows[i] = row
                    n_new += int(row.size)
        _obs_metrics.counter("lm.generate.requests").inc(len(texts))
        _obs_metrics.counter("lm.generate.tokens").inc(n_new)
        completions = [tok.decode(r) for r in out_rows]
        return frame.with_column(self.getOutputCol(), completions)
