"""LogisticRegression — the downstream classifier of the flagship
transfer-learning pipeline.

The reference's headline example (upstream README) is
``Pipeline([DeepImageFeaturizer, LogisticRegression])`` with Spark ML's
LogisticRegression consuming the feature vectors. Users switching from
sparkdl need that downstream stage to exist, so the framework ships a
mesh-native multinomial logistic regression with Spark ML's param
spellings (featuresCol/labelCol/predictionCol, maxIter, regParam,
elasticNetParam-less L2), trained as one jitted full-batch optax loop.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from tpudl.ml.params import (HasLabelCol, Param, TypeConverters,
                             keyword_only)
from tpudl.ml.pipeline import Estimator, Model

__all__ = ["LogisticRegression", "LogisticRegressionModel"]


class _LRParams(HasLabelCol):
    featuresCol = Param(None, "featuresCol", "feature-vector column",
                        TypeConverters.toString)
    predictionCol = Param(None, "predictionCol", "predicted class column",
                          TypeConverters.toString)
    probabilityCol = Param(None, "probabilityCol",
                           "class-probability column",
                           TypeConverters.toString)
    maxIter = Param(None, "maxIter", "training iterations",
                    TypeConverters.toInt)
    regParam = Param(None, "regParam", "L2 regularization strength",
                     TypeConverters.toFloat)
    learningRate = Param(None, "learningRate", "optimizer learning rate",
                         TypeConverters.toFloat)

    def setFeaturesCol(self, v):
        return self.set(self.featuresCol, v)

    def setPredictionCol(self, v):
        return self.set(self.predictionCol, v)


def _stack_features(col) -> np.ndarray:
    if col.dtype == object:
        return np.stack([np.asarray(v, dtype=np.float32) for v in col])
    return np.asarray(col, dtype=np.float32)


class LogisticRegression(_LRParams, Estimator):
    @keyword_only
    def __init__(self, *, featuresCol="features", labelCol="label",
                 predictionCol="prediction", probabilityCol="probability",
                 maxIter=100, regParam=0.0, learningRate=0.1):
        super().__init__()
        self._setDefault(featuresCol="features", labelCol="label",
                         predictionCol="prediction",
                         probabilityCol="probability", maxIter=100,
                         regParam=0.0, learningRate=0.1)
        self._set(**self._input_kwargs)

    def _fit(self, frame):
        import optax

        X = _stack_features(frame[self.getOrDefault(self.featuresCol)])
        y = np.asarray(frame[self.getLabelCol()]).astype(np.int32)
        if len(X) == 0:
            raise ValueError("cannot fit on an empty frame (0 rows)")
        n_classes = int(y.max()) + 1 if len(y) else 2
        n_features = X.shape[1]
        reg = self.getOrDefault(self.regParam)
        opt = optax.adam(self.getOrDefault(self.learningRate))

        def loss_fn(p, xb, yb):
            logits = xb @ p["w"] + p["b"]
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, yb)
            return jnp.mean(ce) + reg * jnp.sum(jnp.square(p["w"]))

        @jax.jit
        def run(p, xb, yb):
            opt_state = opt.init(p)

            def step(carry, _):
                p, opt_state = carry
                loss, grads = jax.value_and_grad(loss_fn)(p, xb, yb)
                updates, opt_state = opt.update(grads, opt_state, p)
                p = jax.tree.map(lambda a, u: a + u, p, updates)
                return (p, opt_state), loss

            (p, _), losses = jax.lax.scan(
                step, (p, opt_state), None,
                length=self.getOrDefault(self.maxIter))
            return p, losses

        p0 = {"w": jnp.zeros((n_features, n_classes)),
              "b": jnp.zeros((n_classes,))}
        params, losses = run(p0, X, y)
        model = LogisticRegressionModel(
            np.asarray(params["w"]), np.asarray(params["b"]))
        model._paramMap = dict(self._paramMap)
        model._defaultParamMap = dict(self._defaultParamMap)
        model.history = np.asarray(losses)
        return model


class LogisticRegressionModel(_LRParams, Model):
    def __init__(self, w: np.ndarray, b: np.ndarray):
        super().__init__()
        self._setDefault(featuresCol="features", labelCol="label",
                         predictionCol="prediction",
                         probabilityCol="probability", maxIter=100,
                         regParam=0.0, learningRate=0.1)
        self.w = w
        self.b = b

    @property
    def numClasses(self) -> int:
        return self.b.shape[0]

    def _transform(self, frame):
        X = _stack_features(frame[self.getOrDefault(self.featuresCol)])
        logits = X @ self.w + self.b
        probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
        pred = probs.argmax(axis=1).astype(np.int64)
        prob_col = np.empty(len(probs), dtype=object)
        prob_col[:] = list(probs)
        return (frame
                .with_column(self.getOrDefault(self.predictionCol), pred)
                .with_column(self.getOrDefault(self.probabilityCol),
                             prob_col))
