"""Model selection: ParamGridBuilder + CrossValidator.

The reference's estimator exists to plug into Spark ML's model-selection
loop: ``CrossValidator(estimator=KerasImageFileEstimator(...),
estimatorParamMaps=ParamGridBuilder()...build(), ...)`` (ref:
keras_image_file_estimator.py class docstring ~L60 shows exactly this
usage; SURVEY.md §4 "integration with CrossValidator", §7.3 fitMultiple
contract). This module is the first-party equivalent, so the tuning loop
exists inside the framework instead of requiring pyspark:

- :class:`ParamGridBuilder` — the cartesian grid over Params, same API
  (``baseOn``/``addGrid``/``build``).
- :class:`CrossValidator` — k-fold CV that consumes
  ``Estimator.fitMultiple``'s COMPLETION-ORDER iterator (the whole point
  of that contract: fast trials evaluate while slow ones still train; on
  a meshed estimator the trials themselves run concurrently on device
  slices).

Evaluation is a pluggable :class:`Evaluator`; :class:`FunctionEvaluator`
adapts any ``fn(frame) -> float``.
"""

from __future__ import annotations

import itertools

import numpy as np

from tpudl.ml.params import Param, Params, keyword_only
from tpudl.ml.pipeline import Estimator, Model
from tpudl.obs import metrics as _obs_metrics
from tpudl.obs import tracer as _obs_tracer

__all__ = ["ParamGridBuilder", "CrossValidator", "CrossValidatorModel",
           "Evaluator", "FunctionEvaluator"]


class ParamGridBuilder:
    """Cartesian parameter grid (pyspark.ml.tuning.ParamGridBuilder API —
    the builder sparkdl's docs tell users to feed the estimator with)."""

    def __init__(self):
        self._param_grid: dict[Param, list] = {}

    def baseOn(self, *args, **kwargs):
        """Fix params across the whole grid. Accepts ``{param: value}``
        dicts / ``(param, value)`` pairs positionally."""
        if kwargs:
            raise TypeError(
                "baseOn takes {Param: value} dicts or (param, value) "
                "pairs, not keywords (Param objects are not identifiers)")
        for arg in args:
            if isinstance(arg, dict):
                for p, v in arg.items():
                    self.addGrid(p, [v])
            else:
                p, v = arg
                self.addGrid(p, [v])
        return self

    def addGrid(self, param: Param, values) -> "ParamGridBuilder":
        if not isinstance(param, Param):
            raise TypeError(f"addGrid needs a Param, got {type(param).__name__}")
        values = list(values)
        if not values:
            raise ValueError(f"empty value list for param {param.name!r}")
        self._param_grid[param] = values
        return self

    def build(self) -> list[dict]:
        keys = list(self._param_grid)
        if not keys:
            return [{}]
        grids = []
        for combo in itertools.product(*(self._param_grid[k] for k in keys)):
            grids.append(dict(zip(keys, combo)))
        return grids


class Evaluator(Params):
    """Scores a transformed frame. ``isLargerBetter`` orients selection
    (accuracy-style → True, loss-style → False), mirroring
    pyspark.ml.evaluation.Evaluator."""

    def evaluate(self, frame) -> float:  # pragma: no cover - abstract
        raise NotImplementedError

    def isLargerBetter(self) -> bool:
        return True


class FunctionEvaluator(Evaluator):
    """Adapter: any ``fn(frame) -> float`` as an Evaluator."""

    def __init__(self, fn, larger_is_better: bool = True):
        super().__init__()
        self._fn = fn
        self._larger = bool(larger_is_better)

    def evaluate(self, frame) -> float:
        return float(self._fn(frame))

    def isLargerBetter(self) -> bool:
        return self._larger


class CrossValidator(Estimator):
    """k-fold cross-validation over an estimator's param grid
    (pyspark.ml.tuning.CrossValidator semantics).

    For each fold, every paramMap is trained via the estimator's
    ``fitMultiple`` — consumed AS TRIALS COMPLETE, so evaluation of
    early-finishing models overlaps the training of slow ones (and, for
    KerasImageFileEstimator with a mesh, the trials themselves run
    concurrently on device slices). Metrics are averaged across folds;
    the best paramMap is refit on the FULL dataset for the returned
    model, exactly as Spark does.
    """

    estimator = Param(None, "estimator", "estimator to cross-validate")
    estimatorParamMaps = Param(None, "estimatorParamMaps",
                               "list of {Param: value} grids")
    evaluator = Param(None, "evaluator", "metric evaluator")
    numFolds = Param(None, "numFolds", "number of folds (>= 2)",
                     typeConverter=int)
    seed = Param(None, "seed", "fold-assignment rng seed",
                 typeConverter=int)

    @keyword_only
    def __init__(self, *, estimator=None, estimatorParamMaps=None,
                 evaluator=None, numFolds=3, seed=0):
        super().__init__()
        self._setDefault(numFolds=3, seed=0)
        self._set(**self._input_kwargs)

    def _folds(self, n: int):
        k = self.getOrDefault(self.numFolds)
        if k < 2:
            raise ValueError(f"numFolds must be >= 2, got {k}")
        if n < k:
            raise ValueError(f"{n} rows cannot be split into {k} folds")
        rng = np.random.default_rng(self.getOrDefault(self.seed))
        perm = rng.permutation(n)
        return [np.sort(part) for part in np.array_split(perm, k)]

    def _fit(self, frame):
        est = self.getOrDefault(self.estimator)
        maps = list(self.getOrDefault(self.estimatorParamMaps))
        ev = self.getOrDefault(self.evaluator)
        if est is None or ev is None or not maps:
            raise ValueError(
                "CrossValidator needs estimator, estimatorParamMaps and "
                "evaluator")
        n = len(frame)
        folds = self._folds(n)
        metrics = np.zeros((len(maps), len(folds)), dtype=np.float64)
        for f, val_idx in enumerate(folds):
            val_mask = np.zeros(n, dtype=bool)
            val_mask[val_idx] = True
            train = frame.filter_rows(~val_mask)
            val = frame.filter_rows(val_mask)
            # completion-order consumption: evaluate each model the
            # moment its trial finishes (SURVEY.md §7.3 contract)
            with _obs_tracer.span("tuning.cv_fold", fold=f,
                                  n_maps=len(maps)):
                for i, model in est.fitMultiple(train, maps):
                    metrics[i, f] = ev.evaluate(model.transform(val))
                    _obs_metrics.counter("tuning.cv_evaluations").inc()
                    _obs_metrics.gauge("tuning.cv_last_metric").set(
                        metrics[i, f])
        _obs_metrics.counter("tuning.cv_folds").inc(len(folds))
        avg = metrics.mean(axis=1)
        best = int(np.argmax(avg) if ev.isLargerBetter()
                   else np.argmin(avg))
        _obs_metrics.gauge("tuning.cv_best_metric").set(avg[best])
        best_model = est.fit(frame, maps[best])  # refit on ALL rows
        return CrossValidatorModel(best_model, avg.tolist(), best)


class CrossValidatorModel(Model):
    """The winning model + the per-paramMap average metrics."""

    def __init__(self, bestModel, avgMetrics, bestIndex):
        super().__init__()
        self.bestModel = bestModel
        self.avgMetrics = list(avgMetrics)
        self.bestIndex = int(bestIndex)

    def _transform(self, frame):
        return self.bestModel.transform(frame)
